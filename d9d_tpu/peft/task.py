"""PEFT ↔ training-loop glue.

``PeftTask`` wraps any ``TrainTask`` so the engine's "params" are just the
adapter tree: the base is closed over (XLA keeps it resident, no copy per
step) and stop-gradiented, so grads/optimizer state exist only for
adapters — the reference achieves the same via a trainable-param predicate
(d9d/loop/component/model_stage_factory.py:25,264).
"""

from typing import Any

import flax.linen as nn
import jax

from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.peft.base import PeftMethod


class PeftTask(TrainTask):
    def __init__(self, inner: TrainTask, method: PeftMethod, base: PyTree):
        self.inner = inner
        self.method = method
        self.base = base

    def prepare_batch(self, batch: PyTree) -> PyTree:
        return self.inner.prepare_batch(batch)

    def loss_fn(
        self,
        module: nn.Module,
        adapters: PyTree,
        microbatch: PyTree,
        rng: Array,
    ) -> tuple[Array, Array, dict[str, Array]]:
        frozen = jax.lax.stop_gradient(self.base)
        params = self.method.materialize(frozen, adapters)
        return self.inner.loss_fn(module, params, microbatch, rng)

    def metrics_postprocess(self, metrics: dict[str, Any]) -> dict[str, Any]:
        return self.inner.metrics_postprocess(metrics)

    def metrics(self) -> dict[str, Any]:
        return self.inner.metrics()

    def update_metrics(self, metric_objs, stats) -> None:
        self.inner.update_metrics(metric_objs, stats)


class PeftStageTask:
    """StageTask wrapper for PEFT under pipeline parallelism: one per
    stage, closing over that stage's frozen base so the executor's
    "params" are the stage's adapter tree (reference trainable-predicate
    PEFT per stage, model_stage_factory.py:25,264).

    Grads/optimizer state exist only for adapters; the base rides each
    stage jit as a closed-over constant.
    """

    def __init__(self, inner, method: PeftMethod, base: PyTree):
        self.inner = inner
        self.method = method
        self.base = base
        # forward the optional forward-only hook only when the wrapped task
        # has it — the executor probes getattr(task, "last_stage_outputs")
        if getattr(inner, "last_stage_outputs", None) is not None:
            self.last_stage_outputs = self._last_stage_outputs

    def _params(self, adapters: PyTree) -> PyTree:
        return self.method.materialize(
            jax.lax.stop_gradient(self.base), adapters
        )

    def _last_stage_outputs(self, module, adapters, carry, kwargs, state):
        return self.inner.last_stage_outputs(
            module, self._params(adapters), carry, kwargs, state
        )

    # -- StageTask surface ---------------------------------------------
    def split_microbatch(self, microbatch):
        return self.inner.split_microbatch(microbatch)

    def sample_microbatch(self, microbatch_size, seq_len):
        return self.inner.sample_microbatch(microbatch_size, seq_len)

    def stage_forward(self, module, adapters, carry, kwargs):
        return self.inner.stage_forward(
            module, self._params(adapters), carry, kwargs
        )

    def last_stage_loss(self, module, adapters, carry, kwargs, state):
        return self.inner.last_stage_loss(
            module, self._params(adapters), carry, kwargs, state
        )

    # host-side task surface used by the Trainer loop --------------------
    def prepare_batch(self, batch):
        return self.inner.prepare_batch(batch)

    def metrics_postprocess(self, metrics):
        return self.inner.metrics_postprocess(metrics)

    def metrics(self):
        return self.inner.metrics()

    def update_metrics(self, metric_objs, stats):
        self.inner.update_metrics(metric_objs, stats)


def adapter_state_dict(adapters: PyTree) -> dict[str, jax.Array]:
    """Flatten adapters to the repo's canonical dotted-name dict
    (model_state.io.module.flatten_params), ready for the safetensors
    writer. PeftStack tuples are namespaced ``method_{i}.``. Adapter keys
    created from param paths keep their '/' separators inside one segment
    (they are opaque names, not re-split on load)."""
    from d9d_tpu.model_state.io.module import flatten_params

    if isinstance(adapters, tuple):
        out = {}
        for i, a in enumerate(adapters):
            for k, v in adapter_state_dict(a).items():
                out[f"method_{i}.{k}"] = v
        return out
    return flatten_params(adapters)


def adapter_from_state_dict(
    adapters_template: PyTree, state: dict[str, jax.Array]
) -> PyTree:
    """Inverse of :func:`adapter_state_dict`, shaped like the template."""
    if isinstance(adapters_template, tuple):
        parts = []
        for i, tmpl in enumerate(adapters_template):
            prefix = f"method_{i}."
            sub = {
                k[len(prefix):]: v for k, v in state.items() if k.startswith(prefix)
            }
            parts.append(adapter_from_state_dict(tmpl, sub))
        return tuple(parts)

    from d9d_tpu.model_state.io.module import flatten_params

    flat_tmpl = flatten_params(adapters_template)
    leaves = {}
    for key, leaf in flat_tmpl.items():
        if key not in state:
            raise KeyError(f"adapter state missing {key}")
        got = state[key]
        if got.shape != leaf.shape:
            raise ValueError(f"{key}: shape {got.shape} != expected {leaf.shape}")
        leaves[key] = got.astype(leaf.dtype)

    from d9d_tpu.model_state.io.module import unflatten_params

    return unflatten_params(leaves)
