"""PeftStack: compose several PEFT methods.

Reference: d9d/peft/all/method.py:14. Adapters are kept per-method (a
tuple); materialize folds each method's adapters over the running params
left-to-right, so e.g. (FullTune(norms), LoRA(attn)) trains norms directly
while LoRA-ing attention.
"""

import dataclasses

import jax

from d9d_tpu.core.types import PyTree
from d9d_tpu.peft.base import PeftMethod


@dataclasses.dataclass(frozen=True)
class PeftStack(PeftMethod):
    methods: tuple[PeftMethod, ...]

    def inject(self, params: PyTree, rng: jax.Array) -> tuple[PyTree, PyTree]:
        adapters = []
        for i, m in enumerate(self.methods):
            params, a = m.inject(params, jax.random.fold_in(rng, i))
            adapters.append(a)
        return params, tuple(adapters)

    def materialize(self, base: PyTree, adapters: PyTree) -> PyTree:
        p = base
        for m, a in zip(self.methods, adapters):
            p = m.materialize(p, a)
        return p

    def merge(self, base: PyTree, adapters: PyTree) -> PyTree:
        p = base
        for m, a in zip(self.methods, adapters):
            p = m.merge(p, a)
        return p
