"""PEFT method protocol.

Reference: d9d/peft/base.py:28 (``PeftMethod`` inject/merge). The torch
version mutates ``nn.Module``s in place (swapping Linear for LoraLinear);
the TPU-native design is a *parameter-tree reparameterization* — modules
never change, methods split the param pytree into a frozen ``base`` and a
trainable ``adapters`` tree, and ``materialize`` recombines them as a pure
jit-safe function inside the loss. This keeps the whole train step a single
XLA program and makes the optimizer state exactly the adapter tree.
"""

import abc

import jax

from d9d_tpu.core.types import PyTree


class PeftMethod(abc.ABC):
    """Splits params into (frozen base, trainable adapters)."""

    @abc.abstractmethod
    def inject(self, params: PyTree, rng: jax.Array) -> tuple[PyTree, PyTree]:
        """→ (base, adapters). ``base`` is frozen; ``adapters`` is trained."""

    @abc.abstractmethod
    def materialize(self, base: PyTree, adapters: PyTree) -> PyTree:
        """Pure: effective params used in forward. Runs under jit; grads
        must flow only through ``adapters`` (callers stop-gradient base)."""

    @abc.abstractmethod
    def merge(self, base: PyTree, adapters: PyTree) -> PyTree:
        """Fold adapters into base weights → a plain param tree for export."""


def path_name(path: tuple) -> str:
    """Stable '/'-joined name for a pytree path (dict keys / indices)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)
