"""FullTune: select a subset of params to train directly.

Reference: d9d/peft/full_tune/method.py. Matched params move into the
adapter tree (trained in place); unmatched params freeze in base.
``target_patterns=('.*',)`` trains everything (the degenerate "no PEFT"
case, useful inside a PeftStack to unfreeze e.g. norms next to LoRA).
"""

import dataclasses
import re

import jax
import jax.numpy as jnp

from d9d_tpu.core.types import PyTree
from d9d_tpu.peft.base import PeftMethod, path_name


@dataclasses.dataclass(frozen=True)
class FullTune(PeftMethod):
    target_patterns: tuple[str, ...] = (r".*",)

    def _matches(self, name: str) -> bool:
        return any(re.fullmatch(p, name) for p in self.target_patterns)

    def inject(self, params: PyTree, rng: jax.Array) -> tuple[PyTree, PyTree]:
        del rng
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        # copy (not alias) matched leaves: the train step donates the adapter
        # buffers, which must never invalidate the frozen base tree. jnp.copy
        # produces a fresh buffer with the source's sharding.
        adapters = {
            path_name(path): jnp.copy(leaf)
            for path, leaf in flat
            if self._matches(path_name(path))
        }
        if not adapters:
            raise ValueError(
                f"FullTune target_patterns {self.target_patterns} matched no params"
            )
        return params, adapters

    def _combine(self, base: PyTree, adapters: PyTree) -> PyTree:
        def fix(path, leaf):
            return adapters.get(path_name(path), leaf)

        return jax.tree_util.tree_map_with_path(fix, base)

    def materialize(self, base: PyTree, adapters: PyTree) -> PyTree:
        return self._combine(base, adapters)

    def merge(self, base: PyTree, adapters: PyTree) -> PyTree:
        return self._combine(base, adapters)
