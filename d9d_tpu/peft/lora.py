"""LoRA as parameter-tree reparameterization.

Reference: d9d/peft/lora/method.py:56, lora/layer.py:9,83 — LoRA for
``nn.Linear`` AND ``GroupedLinear`` (MoE experts). Here both cases are
handled by rank: matching 2-D kernels ``(in, out)`` get ``A (in, r)`` /
``B (r, out)``; matching 3-D grouped-expert kernels ``(E, in, out)`` get
per-expert ``A (E, in, r)`` / ``B (E, r, out)`` — one einsum covers both.

The effective weight is ``W + (alpha / r) * A @ B`` with A ~ Kaiming-ish
normal and B = 0 (so injection is a no-op at step 0), matching standard
LoRA initialization.
"""

import dataclasses
import re

import jax
import jax.numpy as jnp

from jax.sharding import NamedSharding, PartitionSpec

from d9d_tpu.core.types import PyTree
from d9d_tpu.peft.base import PeftMethod, path_name


def _shard_like(
    x: jax.Array, ref: jax.Array, dim_map: tuple[tuple[int, int], ...]
) -> jax.Array:
    """Place an adapter on the mesh of its target param: each
    ``(adapter_dim, ref_dim)`` pair inherits the target dim's partitioning;
    unmapped dims (the LoRA rank) stay replicated. No-op when the target has
    no NamedSharding (single-device tests)."""
    sharding = getattr(ref, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return x
    ref_spec = list(sharding.spec) + [None] * (ref.ndim - len(sharding.spec))
    spec = [None] * x.ndim
    for adapter_dim, ref_dim in dim_map:
        spec[adapter_dim] = ref_spec[ref_dim]
    return jax.device_put(x, NamedSharding(sharding.mesh, PartitionSpec(*spec)))


@dataclasses.dataclass(frozen=True)
class LoRA(PeftMethod):
    """``target_patterns``: regexes matched against the '/'-joined param
    path (e.g. ``r".*attention.*kernel"``). Non-matching params stay in
    base untouched."""

    rank: int
    alpha: float = 1.0
    target_patterns: tuple[str, ...] = (r".*kernel$",)
    init_scale: float = 0.01

    def _matches(self, name: str, leaf) -> bool:
        if not hasattr(leaf, "ndim") or leaf.ndim not in (2, 3):
            return False
        return any(re.fullmatch(p, name) for p in self.target_patterns)

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank

    # -- protocol ------------------------------------------------------

    def inject(self, params: PyTree, rng: jax.Array) -> tuple[PyTree, PyTree]:
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        adapters = {}
        for i, (path, leaf) in enumerate(flat):
            name = path_name(path)
            if not self._matches(name, leaf):
                continue
            leaf_rng = jax.random.fold_in(rng, i)
            if leaf.ndim == 2:
                d_in, _d_out = leaf.shape
                a_shape = (d_in, self.rank)
                b_shape = (self.rank, leaf.shape[1])
            else:  # (E, in, out) grouped experts
                e, d_in, d_out = leaf.shape
                a_shape = (e, d_in, self.rank)
                b_shape = (e, self.rank, d_out)
            a = (
                jax.random.normal(leaf_rng, a_shape, jnp.float32)
                * self.init_scale
            ).astype(leaf.dtype)
            b = jnp.zeros(b_shape, leaf.dtype)
            if leaf.ndim == 2:
                a_map, b_map = ((0, 0),), ((1, 1),)
            else:  # expert dim 0 shared; a keeps 'in', b keeps 'out'
                a_map, b_map = ((0, 0), (1, 1)), ((0, 0), (2, 2))
            adapters[name] = {
                "lora_a": _shard_like(a, leaf, a_map),
                "lora_b": _shard_like(b, leaf, b_map),
            }
        if not adapters:
            raise ValueError(
                f"LoRA target_patterns {self.target_patterns} matched no params"
            )
        return params, adapters

    def _delta(self, ad: dict) -> jax.Array:
        a, b = ad["lora_a"], ad["lora_b"]
        if a.ndim == 2:
            return self.scaling * a @ b
        return self.scaling * jnp.einsum("eir,ero->eio", a, b)

    def _combine(self, params: PyTree, adapters: PyTree) -> PyTree:
        def fix(path, leaf):
            name = path_name(path)
            if name in adapters:
                ad = adapters[name]
                return (leaf.astype(jnp.float32) + self._delta(ad).astype(jnp.float32)).astype(leaf.dtype)
            return leaf

        return jax.tree_util.tree_map_with_path(fix, params)

    def materialize(self, base: PyTree, adapters: PyTree) -> PyTree:
        return self._combine(base, adapters)

    def merge(self, base: PyTree, adapters: PyTree) -> PyTree:
        return self._combine(base, adapters)
