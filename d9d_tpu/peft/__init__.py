"""Parameter-efficient fine-tuning (reference: d9d/peft)."""

from d9d_tpu.peft.base import PeftMethod
from d9d_tpu.peft.full_tune import FullTune
from d9d_tpu.peft.lora import LoRA
from d9d_tpu.peft.stack import PeftStack
from d9d_tpu.peft.task import (
    PeftStageTask,
    PeftTask,
    adapter_from_state_dict,
    adapter_state_dict,
)

__all__ = [
    "PeftMethod",
    "FullTune",
    "LoRA",
    "PeftStack",
    "PeftStageTask",
    "PeftTask",
    "adapter_state_dict",
    "adapter_from_state_dict",
]
