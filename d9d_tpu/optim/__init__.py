"""Optimizers (reference: d9d/optim)."""

from d9d_tpu.optim.stochastic_adamw import StochasticAdamW, StochasticAdamWState

__all__ = ["StochasticAdamW", "StochasticAdamWState"]
