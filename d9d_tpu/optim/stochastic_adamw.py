"""StochasticAdamW: AdamW that keeps bf16 parameters via stochastic rounding.

TPU-native rebuild of the reference ``StochasticAdamW``
(d9d/optim/stochastic/adamw.py:43 + kernel/stochastic/adamw_step.py:97):
parameters live in bfloat16, the step is computed in fp32, and the write-back
rounds stochastically so the *expected* parameter trajectory matches fp32
training — no fp32 master copy needed. The RNG key is part of the optimizer
state (reference keeps its own RNG in state_dict), so checkpoints resume the
exact noise stream.

The object satisfies the trainer's optimizer protocol (``init`` /
``update``) and additionally exposes ``apply_updates`` so the train step can
let the optimizer own the parameter write (required: ``optax.apply_updates``
would round-to-nearest on the final bf16 cast and destroy the stochastic
rounding).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from d9d_tpu.core.types import PyTree
from d9d_tpu.ops.stochastic import stochastic_round_to_bf16


class StochasticAdamWState(NamedTuple):
    count: jax.Array
    mu: PyTree
    nu: PyTree
    key: jax.Array


class StochasticAdamW:
    """AdamW with bf16 params + stochastic-rounding write-back.

    ``learning_rate`` may be a float or an optax schedule. Moments default
    to fp32; pass ``moment_dtype=jnp.bfloat16`` to store them rounded too
    (stochastically, sharing the step's noise stream).
    """

    # the train step must NOT down-cast fp32 grads to param dtype for us
    accepts_fp32_grads = True

    def __init__(
        self,
        learning_rate: optax.ScalarOrSchedule,
        *,
        b1: float = 0.9,
        b2: float = 0.95,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        moment_dtype: jnp.dtype = jnp.float32,
        seed: int = 0,
    ):
        self.learning_rate = learning_rate
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.weight_decay = weight_decay
        self.moment_dtype = moment_dtype
        self.seed = seed

    # -- protocol ------------------------------------------------------

    def init(self, params: PyTree) -> StochasticAdamWState:
        zeros = lambda p: jnp.zeros(p.shape, self.moment_dtype)
        return StochasticAdamWState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
            key=jax.random.PRNGKey(self.seed),
        )

    def update(
        self,
        grads: PyTree,
        state: StochasticAdamWState,
        params: PyTree,
    ) -> tuple[PyTree, StochasticAdamWState]:
        """Returns (new_params, new_state) — the "updates" ARE the new
        parameters; ``apply_updates`` below just substitutes them."""
        count = state.count + 1
        # schedules are evaluated at the 0-based step (optax convention);
        # bias correction uses the 1-based count (Adam convention)
        lr = (
            self.learning_rate(state.count)
            if callable(self.learning_rate)
            else self.learning_rate
        )
        c1 = 1.0 - self.b1**count.astype(jnp.float32)
        c2 = 1.0 - self.b2**count.astype(jnp.float32)

        step_key = jax.random.fold_in(state.key, count)

        def leaf_step(p, g, mu, nu, key):
            g32 = g.astype(jnp.float32)
            mu32 = self.b1 * mu.astype(jnp.float32) + (1 - self.b1) * g32
            nu32 = self.b2 * nu.astype(jnp.float32) + (1 - self.b2) * g32**2
            m_hat = mu32 / c1
            v_hat = nu32 / c2
            p32 = p.astype(jnp.float32)
            upd = m_hat / (jnp.sqrt(v_hat) + self.eps) + self.weight_decay * p32
            new_p32 = p32 - lr * upd

            k_p, k_mu, k_nu = jax.random.split(key, 3)
            new_p = self._round(new_p32, p.dtype, k_p)
            new_mu = self._round(mu32, self.moment_dtype, k_mu)
            new_nu = self._round(nu32, self.moment_dtype, k_nu)
            return new_p, new_mu, new_nu

        # work on flat leaf lists so tuple-structured param pytrees are safe
        p_leaves, treedef = jax.tree.flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        mu_leaves = treedef.flatten_up_to(state.mu)
        nu_leaves = treedef.flatten_up_to(state.nu)

        new_p, new_mu, new_nu = [], [], []
        for i, (p, g, mu, nu) in enumerate(
            zip(p_leaves, g_leaves, mu_leaves, nu_leaves)
        ):
            np_, nmu, nnu = leaf_step(p, g, mu, nu, jax.random.fold_in(step_key, i))
            new_p.append(np_)
            new_mu.append(nmu)
            new_nu.append(nnu)

        return treedef.unflatten(new_p), StochasticAdamWState(
            count=count,
            mu=treedef.unflatten(new_mu),
            nu=treedef.unflatten(new_nu),
            key=state.key,
        )

    @staticmethod
    def apply_updates(params: PyTree, updates: PyTree) -> PyTree:
        del params  # updates already carry the rounded new parameters
        return updates

    # -- helpers -------------------------------------------------------

    @staticmethod
    def _round(x32: jax.Array, dtype: Any, key: jax.Array) -> jax.Array:
        if dtype == jnp.bfloat16:
            return stochastic_round_to_bf16(x32, key)
        return x32.astype(dtype)
