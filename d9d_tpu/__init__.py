"""d9d_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the d9d
reference framework: 6D parallelism (PP x DP-replicate x DP-shard x CP x TP
with an expert-parallel overlay), pipeline schedules (GPipe .. ZeroBubble),
MoE with ragged all-to-all dispatch, DAG-based streaming checkpoints, and a
composable training loop.
"""

__version__ = "0.1.0"
