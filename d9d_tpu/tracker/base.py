"""Experiment tracker abstraction.

Reference: d9d/tracker/base.py:11,81 (BaseTracker/BaseTrackerRun). A
tracker opens a *run*; the run accepts scalars and pre-binned histograms
under hierarchical names, with a context-tag dict (e.g. subset=train)
attached per value. Run-hash persistence lives in ``state_dict`` /
``load_state_dict`` so a resumed job continues the same tracker run.
"""

import abc
from typing import Any


class TrackerRun(abc.ABC):
    """An open logging session."""

    @abc.abstractmethod
    def track_scalar(
        self,
        name: str,
        value: float,
        *,
        step: int,
        context: dict[str, str] | None = None,
    ) -> None: ...

    @abc.abstractmethod
    def track_histogram(
        self,
        name: str,
        counts: Any,
        bin_edges: Any,
        *,
        step: int,
        context: dict[str, str] | None = None,
    ) -> None:
        """Pre-binned histogram: len(bin_edges) == len(counts) + 1."""

    def track_hparams(self, hparams: dict[str, Any]) -> None:
        """Optional one-shot hyperparameter dump."""

    def close(self) -> None: ...

    # resume support ---------------------------------------------------

    def state_dict(self) -> dict[str, Any]:
        return {}

    def load_state_dict(self, state: dict[str, Any]) -> None:
        pass


class Tracker(abc.ABC):
    """Factory for runs (one per training job)."""

    @abc.abstractmethod
    def new_run(self, run_name: str | None = None) -> TrackerRun: ...
