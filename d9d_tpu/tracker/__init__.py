"""Experiment trackers (reference: d9d/tracker)."""

from d9d_tpu.tracker.base import Tracker, TrackerRun
from d9d_tpu.tracker.providers import (
    AimTracker,
    JsonlTracker,
    MemoryTracker,
    MemoryTrackerRun,
    NullTracker,
    build_tracker,
)

__all__ = [
    "Tracker",
    "TrackerRun",
    "AimTracker",
    "JsonlTracker",
    "MemoryTracker",
    "MemoryTrackerRun",
    "NullTracker",
    "build_tracker",
]
