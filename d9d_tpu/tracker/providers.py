"""Tracker providers: Null, in-memory, JSONL file, and Aim (gated).

Reference: d9d/tracker/provider/{null.py:40, aim/tracker.py} and
factory.py:14,31 (import-failure stub). The TPU build adds a JSONL file
tracker (no external service needed on a pod) and keeps Aim behind a
lazy import that degrades to Null with a warning, matching the reference
factory's behavior when the extra isn't installed.
"""

import json
import logging
import time
import uuid
from pathlib import Path
from typing import Any

import numpy as np

from d9d_tpu.tracker.base import Tracker, TrackerRun

logger = logging.getLogger("d9d_tpu.tracker")


class NullTrackerRun(TrackerRun):
    def track_scalar(self, name, value, *, step, context=None):
        pass

    def track_histogram(self, name, counts, bin_edges, *, step, context=None):
        pass


class NullTracker(Tracker):
    def new_run(self, run_name=None):
        return NullTrackerRun()


class MemoryTrackerRun(TrackerRun):
    """Keeps everything in lists — the test/debug tracker."""

    def __init__(self, run_hash: str | None = None, run_name: str | None = None):
        self.run_hash = run_hash or uuid.uuid4().hex
        self.run_name = run_name
        self.scalars: list[dict[str, Any]] = []
        self.histograms: list[dict[str, Any]] = []
        self.hparams: dict[str, Any] = {}
        self.closed = False

    def track_scalar(self, name, value, *, step, context=None):
        self.scalars.append(
            {"name": name, "value": float(value), "step": step, "context": context or {}}
        )

    def track_histogram(self, name, counts, bin_edges, *, step, context=None):
        self.histograms.append(
            {
                "name": name,
                "counts": np.asarray(counts).tolist(),
                "bin_edges": np.asarray(bin_edges).tolist(),
                "step": step,
                "context": context or {},
            }
        )

    def track_hparams(self, hparams):
        self.hparams.update(hparams)

    def close(self):
        self.closed = True

    def state_dict(self):
        return {"run_hash": self.run_hash}

    def load_state_dict(self, state):
        self.run_hash = state.get("run_hash", self.run_hash)


class MemoryTracker(Tracker):
    def __init__(self):
        self.runs: list[MemoryTrackerRun] = []

    def new_run(self, run_name=None):
        run = MemoryTrackerRun(run_name=run_name)
        self.runs.append(run)
        return run


class JsonlTrackerRun(TrackerRun):
    """Appends one JSON object per tracked value to ``{dir}/{hash}.jsonl``."""

    def __init__(
        self,
        directory: Path,
        run_hash: str | None = None,
        run_name: str | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.run_name = run_name
        # the name prefixes the file for humans; the hash keeps it unique
        # and is what resume re-points at (reference threads run_name the
        # same way into the tracker dir)
        self.run_hash = run_hash or (
            f"{run_name}-{uuid.uuid4().hex[:8]}" if run_name else uuid.uuid4().hex
        )
        self._fh = None

    def _file(self):
        if self._fh is None:
            self._fh = open(self.directory / f"{self.run_hash}.jsonl", "a")
        return self._fh

    def _emit(self, obj: dict[str, Any]):
        obj["ts"] = time.time()
        self._file().write(json.dumps(obj) + "\n")
        self._file().flush()

    def track_scalar(self, name, value, *, step, context=None):
        self._emit(
            {"kind": "scalar", "name": name, "value": float(value), "step": step,
             "context": context or {}}
        )

    def track_histogram(self, name, counts, bin_edges, *, step, context=None):
        self._emit(
            {
                "kind": "histogram",
                "name": name,
                "counts": np.asarray(counts).tolist(),
                "bin_edges": np.asarray(bin_edges).tolist(),
                "step": step,
                "context": context or {},
            }
        )

    def track_hparams(self, hparams):
        self._emit({"kind": "hparams", "hparams": hparams})

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def state_dict(self):
        return {"run_hash": self.run_hash}

    def load_state_dict(self, state):
        new_hash = state.get("run_hash", self.run_hash)
        if new_hash != self.run_hash:
            # re-point the (possibly already opened) file at the restored run
            self.close()
            self.run_hash = new_hash


class JsonlTracker(Tracker):
    def __init__(self, directory: str | Path):
        self.directory = Path(directory)

    def new_run(self, run_name=None):
        return JsonlTrackerRun(self.directory, run_name=run_name)


class AimTrackerRun(TrackerRun):  # pragma: no cover - needs aim installed
    def __init__(self, repo: str | None, experiment: str | None, run_hash=None):
        import aim

        self._repo = repo
        self._experiment = experiment
        self._run = aim.Run(run_hash=run_hash, repo=repo, experiment=experiment)
        self._aim = aim

    def track_scalar(self, name, value, *, step, context=None):
        self._run.track(float(value), name=name, step=step, context=context or {})

    def track_histogram(self, name, counts, bin_edges, *, step, context=None):
        counts = np.asarray(counts, dtype=float)
        edges = np.asarray(bin_edges, dtype=float)
        widths = np.diff(edges)
        if len(widths) > 1 and not np.allclose(widths, widths[0]):
            # aim.Distribution assumes UNIFORM bins over bin_range; re-bin
            # non-uniform (e.g. log-spaced latency) histograms by spreading
            # each source bin's mass over the uniform bins it overlaps, so
            # the rendered distribution stays honest (if coarse) instead
            # of silently mislabeling every bin's position
            n = len(counts)
            uni = np.linspace(edges[0], edges[-1], n + 1)
            out = np.zeros(n)
            for c, lo, hi in zip(counts, edges[:-1], edges[1:]):
                if c == 0 or hi <= lo:
                    continue
                i0 = max(int(np.searchsorted(uni, lo, side="right")) - 1, 0)
                i1 = min(int(np.searchsorted(uni, hi, side="left")), n)
                for i in range(i0, i1):
                    overlap = min(hi, uni[i + 1]) - max(lo, uni[i])
                    if overlap > 0:
                        out[i] += c * overlap / (hi - lo)
            counts = out
        dist = self._aim.Distribution(
            hist=counts, bin_range=(edges[0], edges[-1])
        )
        self._run.track(dist, name=name, step=step, context=context or {})

    def track_hparams(self, hparams):
        for k, v in hparams.items():
            self._run[k] = v

    def close(self):
        self._run.close()

    def state_dict(self):
        return {"run_hash": self._run.hash}

    def load_state_dict(self, state):
        run_hash = state.get("run_hash")
        if run_hash and run_hash != self._run.hash:
            # reopen the original run so a resumed job keeps appending to it
            self._run.close()
            self._run = self._aim.Run(
                run_hash=run_hash, repo=self._repo, experiment=self._experiment
            )


class AimTracker(Tracker):  # pragma: no cover - needs aim installed
    def __init__(self, repo: str | None = None, experiment: str | None = None):
        self.repo = repo
        self.experiment = experiment

    def new_run(self, run_name=None):
        return AimTrackerRun(self.repo, self.experiment or run_name)


def build_tracker(kind: str = "null", **kwargs) -> Tracker:
    """Factory (reference tracker/factory.py:14): unknown/unavailable
    providers degrade to NullTracker with a warning instead of failing the
    job."""
    if kind == "null":
        return NullTracker()
    if kind == "memory":
        return MemoryTracker()
    if kind == "jsonl":
        return JsonlTracker(**kwargs)
    if kind == "aim":
        try:
            import aim  # noqa: F401

            return AimTracker(**kwargs)
        except ImportError:
            logger.warning("aim not installed; falling back to NullTracker")
            return NullTracker()
    logger.warning("unknown tracker %r; falling back to NullTracker", kind)
    return NullTracker()
