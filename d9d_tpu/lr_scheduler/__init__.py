from d9d_tpu.lr_scheduler.builder import (
    PiecewiseScheduleBuilder,
    Schedule,
    piecewise_schedule,
)
from d9d_tpu.lr_scheduler.config import (
    AnyCurveConfig,
    PhaseConfig,
    PiecewiseSchedulerConfig,
    curve_from_config,
    piecewise_scheduler_from_config,
)
from d9d_tpu.lr_scheduler.curves import (
    CosineAnneal,
    CurveBase,
    CurveCosine,
    CurveExponential,
    CurveLinear,
    CurvePoly,
    LinearInterp,
    LogSpaceInterp,
    PowerInterp,
    ScheduleCurve,
)
from d9d_tpu.lr_scheduler.engine import PiecewiseScheduleEngine, SchedulePhase
from d9d_tpu.lr_scheduler.visualizer import sample_schedule, visualize_schedule

__all__ = [
    "AnyCurveConfig",
    "CosineAnneal",
    "CurveBase",
    "CurveCosine",
    "CurveExponential",
    "CurveLinear",
    "CurvePoly",
    "LinearInterp",
    "LogSpaceInterp",
    "PowerInterp",
    "ScheduleCurve",
    "PhaseConfig",
    "PiecewiseScheduleBuilder",
    "PiecewiseScheduleEngine",
    "PiecewiseSchedulerConfig",
    "Schedule",
    "SchedulePhase",
    "curve_from_config",
    "piecewise_schedule",
    "piecewise_scheduler_from_config",
    "sample_schedule",
    "visualize_schedule",
]
