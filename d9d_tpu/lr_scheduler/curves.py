"""Interpolation curves for piecewise LR schedules.

Parity: reference d9d/lr_scheduler/piecewise/curves.py (CurveBase and the
linear/cosine/poly/exponential family). TPU-native difference: ``compute``
uses jnp ops on traced scalars so a whole schedule stays inside the jitted
train step (the reference computes factors in Python per step on the host).
"""

import abc
import dataclasses

import jax.numpy as jnp

from d9d_tpu.core.types import Array


class CurveBase(abc.ABC):
    """Interpolates between phase start/end values.

    ``step_p`` is the progress fraction through the phase in [0, 1].
    """

    @abc.abstractmethod
    def compute(self, start: float, end: float, step_p: Array) -> Array:
        ...


class CurveLinear(CurveBase):
    def compute(self, start: float, end: float, step_p: Array) -> Array:
        return start + (end - start) * step_p


class CurveCosine(CurveBase):
    """Half-period cosine annealing from start to end."""

    def compute(self, start: float, end: float, step_p: Array) -> Array:
        cos_out = (1.0 + jnp.cos(jnp.pi * step_p)) / 2.0
        return end + (start - end) * cos_out


@dataclasses.dataclass(frozen=True)
class CurvePoly(CurveBase):
    """Polynomial interpolation; power=1 is linear, 2 quadratic, etc."""

    power: float = 2.0

    def compute(self, start: float, end: float, step_p: Array) -> Array:
        return start + (end - start) * step_p**self.power


class CurveExponential(CurveBase):
    """Log-space linear interpolation (values clamped away from zero)."""

    def compute(self, start: float, end: float, step_p: Array) -> Array:
        eps = 1e-8
        ls = jnp.log(jnp.maximum(start, eps))
        le = jnp.log(jnp.maximum(end, eps))
        return jnp.exp(ls + (le - ls) * step_p)
