"""Interpolation curves for piecewise LR schedules.

Functional parity with the reference d9d piecewise curve family, with a
TPU-native twist: :meth:`ScheduleCurve.blend` uses jnp ops on traced
scalars so a whole schedule stays inside the jitted train step (the
reference computes factors in Python per step on the host).

Each curve maps a phase-progress fraction ``frac`` in [0, 1] to a value
blended between the phase's boundary values ``lo`` (start) and ``hi``
(end).
"""

import abc
import dataclasses

import jax.numpy as jnp

from d9d_tpu.core.types import Array


class ScheduleCurve(abc.ABC):
    """Blends between a phase's start/end values.

    ``frac`` is the progress fraction through the phase in [0, 1].
    Implement :meth:`blend`; subclasses written against the pre-rename
    API that implement only ``compute()`` keep working — each spelling
    forwards to whichever one the subclass actually overrode.
    """

    def blend(self, lo: float, hi: float, frac: Array) -> Array:
        if type(self).compute is not ScheduleCurve.compute:
            return self.compute(lo, hi, frac)
        raise NotImplementedError(
            f"{type(self).__name__} must implement blend()"
        )

    # reference-era spelling, kept callable so older schedules built
    # against compute() keep working
    def compute(self, start: float, end: float, step_p: Array) -> Array:
        return self.blend(start, end, step_p)


class LinearInterp(ScheduleCurve):
    """Straight-line blend from ``lo`` to ``hi``."""

    def blend(self, lo: float, hi: float, frac: Array) -> Array:
        return lo + (hi - lo) * frac


class CosineAnneal(ScheduleCurve):
    """Half-period cosine annealing from ``lo`` to ``hi``."""

    def blend(self, lo: float, hi: float, frac: Array) -> Array:
        cosine_mix = (1.0 + jnp.cos(jnp.pi * frac)) / 2.0
        return hi + (lo - hi) * cosine_mix


@dataclasses.dataclass(frozen=True)
class PowerInterp(ScheduleCurve):
    """Power-law blend; ``power=1`` is linear, 2 quadratic, etc."""

    power: float = 2.0

    def blend(self, lo: float, hi: float, frac: Array) -> Array:
        return lo + (hi - lo) * frac**self.power


class LogSpaceInterp(ScheduleCurve):
    """Log-space linear blend (operands clamped away from zero)."""

    def blend(self, lo: float, hi: float, frac: Array) -> Array:
        tiny = 1e-8
        log_lo = jnp.log(jnp.maximum(lo, tiny))
        log_hi = jnp.log(jnp.maximum(hi, tiny))
        return jnp.exp(log_lo + (log_hi - log_lo) * frac)


# compatibility aliases (pre-rename public names; zero behavior change)
CurveBase = ScheduleCurve
CurveLinear = LinearInterp
CurveCosine = CosineAnneal
CurvePoly = PowerInterp
CurveExponential = LogSpaceInterp
