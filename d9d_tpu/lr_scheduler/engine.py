"""Piecewise schedule engine.

Parity: reference d9d/lr_scheduler/piecewise/engine.py (SchedulePhase +
PiecewiseScheduleEngine.get_factor). The reference walks the phase list in
Python per step; here the engine is an optax-style schedule: a callable
``step -> factor`` built from vectorized phase selection, safe to call with
a traced ``step`` inside jit (and equally fine with a plain int on host).
"""

import dataclasses

import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.lr_scheduler.curves import ScheduleCurve


@dataclasses.dataclass(frozen=True)
class SchedulePhase:
    """One phase: interpolate from start_value to end_value over
    [start_step, end_step) using ``curve``."""

    start_step: int
    end_step: int
    start_value: float
    end_value: float
    curve: ScheduleCurve


class PiecewiseScheduleEngine:
    """Callable mapping a (possibly traced) step to a multiplier.

    Out-of-range steps clamp to the nearest boundary value, matching the
    reference engine.
    """

    def __init__(self, phases: list[SchedulePhase]):
        if len(phases) == 0:
            raise ValueError("Scheduler should contain at least one phase")
        self._phases = list(phases)

    def get_factor(self, step: int | Array) -> Array:
        step = jnp.asarray(step, jnp.float32)
        # Start from the final clamp value; overwrite from last phase to
        # first so earlier phases win where ranges touch.
        out = jnp.asarray(self._phases[-1].end_value, jnp.float32)
        for phase in reversed(self._phases):
            phase_len = max(phase.end_step - phase.start_step, 1)
            progress = (step - phase.start_step) / phase_len
            value = phase.curve.blend(
                phase.start_value, phase.end_value, jnp.clip(progress, 0.0, 1.0)
            )
            inside = (step >= phase.start_step) & (step < phase.end_step)
            out = jnp.where(inside, value, out)
        out = jnp.where(
            step < self._phases[0].start_step,
            self._phases[0].start_value,
            out,
        )
        return out

    def __call__(self, step: int | Array) -> Array:
        return self.get_factor(step)
