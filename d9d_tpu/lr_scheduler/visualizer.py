"""Schedule sampling/visualization helpers.

Parity: reference d9d/lr_scheduler/visualizer.py (plotly figure of the
multiplier over training). Sampling is dependency-free; the plotly render is
optional and gated on import availability.
"""

import numpy as np

from d9d_tpu.lr_scheduler.builder import Schedule


def sample_schedule(schedule: Schedule, total_steps: int) -> np.ndarray:
    """Evaluate the schedule at every step; returns [total_steps] factors."""
    return np.asarray(schedule(np.arange(total_steps)), dtype=np.float64)


def visualize_schedule(schedule: Schedule, total_steps: int):
    """Render the schedule as a plotly line figure (requires plotly)."""
    try:
        import plotly.graph_objects as go
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "plotly is required for visualize_schedule; use sample_schedule "
            "for a dependency-free dump"
        ) from e
    ys = sample_schedule(schedule, total_steps)
    fig = go.Figure(go.Scatter(x=list(range(total_steps)), y=ys.tolist()))
    fig.update_layout(
        title="LR schedule", xaxis_title="step", yaxis_title="multiplier"
    )
    return fig
