"""Fluent builder for multiphase LR schedules.

Parity: reference d9d/lr_scheduler/piecewise/builder.py
(PiecewiseScheduleBuilder.for_steps/until_percentage/fill_rest). The
reference's ``build`` wraps a torch optimizer in LambdaLR; here ``build``
returns an optax schedule (multiplier) and ``build_lr`` a ready-to-use
learning-rate schedule, pluggable into any optax optimizer.
"""

from typing import Callable

from d9d_tpu.core.types import Array
from d9d_tpu.lr_scheduler.curves import CurveBase
from d9d_tpu.lr_scheduler.engine import PiecewiseScheduleEngine, SchedulePhase

Schedule = Callable[[int | Array], Array]


class PiecewiseScheduleBuilder:
    def __init__(self, initial_multiplier: float, total_steps: int | None):
        self._phases: list[SchedulePhase] = []
        self._total_steps = total_steps
        self._last_end_step = 0
        self._last_multiplier = initial_multiplier

    def for_steps(
        self, steps: int, target_multiplier: float, curve: CurveBase
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase lasting ``steps`` steps ending at ``target_multiplier``."""
        self._phases.append(
            SchedulePhase(
                start_step=self._last_end_step,
                end_step=self._last_end_step + steps,
                start_value=self._last_multiplier,
                end_value=target_multiplier,
                curve=curve,
            )
        )
        self._last_end_step += steps
        self._last_multiplier = target_multiplier
        return self

    def until_percentage(
        self, p: float, target_multiplier: float, curve: CurveBase
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase ending at fraction ``p`` of total_steps."""
        if self._total_steps is None:
            raise ValueError(
                "total_steps is required for percentage-based phases"
            )
        if not 0.0 <= p <= 1.0:
            raise ValueError("Percentage should be in range of [0.0, 1.0]")
        target_step_abs = int(self._total_steps * p)
        duration = target_step_abs - self._last_end_step
        if duration < 0:
            raise ValueError(
                f"Target percentage {p} (step {target_step_abs}) is behind "
                f"current cursor (step {self._last_end_step})."
            )
        return self.for_steps(duration, target_multiplier, curve)

    def fill_rest(
        self, target_multiplier: float, curve: CurveBase
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase from the cursor to the end of training."""
        return self.until_percentage(1.0, target_multiplier, curve)

    def build(self) -> Schedule:
        """Finalize into a ``step -> multiplier`` schedule."""
        if self._total_steps is not None and self._last_end_step > self._total_steps:
            raise ValueError(
                f"Schedule defined for {self._last_end_step} steps, but "
                f"total_steps is {self._total_steps}."
            )
        return PiecewiseScheduleEngine(self._phases)

    def build_lr(self, base_lr: float) -> Schedule:
        """Finalize into a ``step -> learning_rate`` schedule."""
        engine = self.build()
        return lambda step: base_lr * engine(step)


def piecewise_schedule(
    initial_multiplier: float, total_steps: int | None = None
) -> PiecewiseScheduleBuilder:
    """Entry point for building a piecewise LR schedule."""
    return PiecewiseScheduleBuilder(
        initial_multiplier=initial_multiplier, total_steps=total_steps
    )
