"""Fluent builder for multiphase LR schedules.

Parity target: reference d9d/lr_scheduler/piecewise/builder.py
(``for_steps`` / ``until_percentage`` / ``fill_rest`` fluent surface). The
reference's ``build`` wraps a torch optimizer in LambdaLR; here ``build``
returns an optax schedule (multiplier) and ``build_lr`` a ready-to-use
learning-rate schedule, pluggable into any optax optimizer.
"""

from typing import Callable

from d9d_tpu.core.types import Array
from d9d_tpu.lr_scheduler.curves import ScheduleCurve
from d9d_tpu.lr_scheduler.engine import PiecewiseScheduleEngine, SchedulePhase

Schedule = Callable[[int | Array], Array]


class PiecewiseScheduleBuilder:
    """Accumulates phases left to right; the cursor (step, multiplier)
    always sits at the end of the last phase added."""

    def __init__(self, initial_multiplier: float, total_steps: int | None):
        self._phases: list[SchedulePhase] = []
        self._total_steps = total_steps
        self._cursor = (0, initial_multiplier)  # (step, multiplier)

    def _push(self, steps: int, target: float, curve: ScheduleCurve) -> None:
        at, value = self._cursor
        self._phases.append(
            SchedulePhase(
                start_step=at,
                end_step=at + steps,
                start_value=value,
                end_value=target,
                curve=curve,
            )
        )
        self._cursor = (at + steps, target)

    def for_steps(
        self, steps: int, target_multiplier: float, curve: ScheduleCurve
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase lasting ``steps`` steps ending at ``target_multiplier``."""
        self._push(steps, target_multiplier, curve)
        return self

    def until_percentage(
        self, p: float, target_multiplier: float, curve: ScheduleCurve
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase ending at fraction ``p`` of total_steps."""
        if self._total_steps is None:
            raise ValueError(
                "percentage-based phases need the builder constructed with "
                "total_steps"
            )
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"phase end fraction {p} outside [0, 1]")
        end_step = int(self._total_steps * p)
        at, _ = self._cursor
        if end_step < at:
            raise ValueError(
                f"phase ending at fraction {p} (step {end_step}) precedes "
                f"the schedule cursor (step {at})"
            )
        self._push(end_step - at, target_multiplier, curve)
        return self

    def fill_rest(
        self, target_multiplier: float, curve: ScheduleCurve
    ) -> "PiecewiseScheduleBuilder":
        """Add a phase from the cursor to the end of training."""
        return self.until_percentage(1.0, target_multiplier, curve)

    def build(self) -> Schedule:
        """Finalize into a ``step -> multiplier`` schedule."""
        at, _ = self._cursor
        if self._total_steps is not None and at > self._total_steps:
            raise ValueError(
                f"phases cover {at} steps but the schedule was declared for "
                f"{self._total_steps}"
            )
        return PiecewiseScheduleEngine(self._phases)

    def build_lr(self, base_lr: float) -> Schedule:
        """Finalize into a ``step -> learning_rate`` schedule."""
        engine = self.build()
        return lambda step: base_lr * engine(step)


def piecewise_schedule(
    initial_multiplier: float, total_steps: int | None = None
) -> PiecewiseScheduleBuilder:
    """Entry point for building a piecewise LR schedule."""
    return PiecewiseScheduleBuilder(
        initial_multiplier=initial_multiplier, total_steps=total_steps
    )
