"""Declarative (pydantic) configuration for piecewise LR schedules.

Parity: reference d9d/lr_scheduler/piecewise/config.py — the same
discriminated unions: curves {linear, cosine, exponential, poly(power)} and
phases {steps, percentage, rest}.
"""

from typing import Annotated, Literal, Union

from pydantic import BaseModel, Field, PositiveInt

from d9d_tpu.lr_scheduler.builder import Schedule, piecewise_schedule
from d9d_tpu.lr_scheduler.curves import (
    CosineAnneal,
    LinearInterp,
    LogSpaceInterp,
    PowerInterp,
    ScheduleCurve,
)


class CurveLinearConfig(BaseModel):
    type: Literal["linear"] = "linear"


class CurveCosineConfig(BaseModel):
    type: Literal["cosine"] = "cosine"


class CurveExponentialConfig(BaseModel):
    type: Literal["exponential"] = "exponential"


class CurvePolyConfig(BaseModel):
    type: Literal["poly"] = "poly"
    power: float = 2.0


AnyCurveConfig = Annotated[
    Union[
        CurveLinearConfig,
        CurveCosineConfig,
        CurveExponentialConfig,
        CurvePolyConfig,
    ],
    Field(discriminator="type"),
]


def curve_from_config(config: AnyCurveConfig) -> ScheduleCurve:
    match config:
        case CurveLinearConfig():
            return LinearInterp()
        case CurvePolyConfig():
            return PowerInterp(config.power)
        case CurveExponentialConfig():
            return LogSpaceInterp()
        case CurveCosineConfig():
            return CosineAnneal()
    raise TypeError(f"unknown curve config: {config!r}")


class StepPhaseConfig(BaseModel):
    mode: Literal["steps"] = "steps"
    steps: PositiveInt
    target_multiplier: float
    curve: AnyCurveConfig


class PercentagePhaseConfig(BaseModel):
    mode: Literal["percentage"] = "percentage"
    percentage: float = Field(..., ge=0.0, le=1.0)
    target_multiplier: float
    curve: AnyCurveConfig


class RestPhaseConfig(BaseModel):
    mode: Literal["rest"] = "rest"
    target_multiplier: float
    curve: AnyCurveConfig


PhaseConfig = Annotated[
    Union[StepPhaseConfig, PercentagePhaseConfig, RestPhaseConfig],
    Field(discriminator="mode"),
]


class PiecewiseSchedulerConfig(BaseModel):
    initial_multiplier: float
    phases: list[PhaseConfig]


def piecewise_scheduler_from_config(
    config: PiecewiseSchedulerConfig, total_steps: int | None
) -> Schedule:
    """Build a ``step -> multiplier`` schedule from config."""
    builder = piecewise_schedule(config.initial_multiplier, total_steps)
    for phase in config.phases:
        curve = curve_from_config(phase.curve)
        match phase:
            case StepPhaseConfig():
                builder.for_steps(phase.steps, phase.target_multiplier, curve)
            case PercentagePhaseConfig():
                builder.until_percentage(
                    phase.percentage, phase.target_multiplier, curve
                )
            case RestPhaseConfig():
                builder.fill_rest(phase.target_multiplier, curve)
    return builder.build()
