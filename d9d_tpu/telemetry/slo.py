"""SLO layer: rolling-window streaming quantile digests + declarative
burn-rate policies (docs/design/observability.md).

The registry's fixed-bin histograms are cumulative-since-start and
log-bin coarse — fine for dashboards, too blunt for tail SLOs ("p99
TTFT over the last minute"). This module adds the missing half:

- :class:`StreamingQuantileDigest` — a time-bucketed merging sketch.
  The window is split into sub-buckets; each holds a bounded set of
  weighted points that is *compressed* (sorted, every other point kept
  at doubled weight) whenever it outgrows its capacity. Quantile
  queries merge the live buckets' points. Memory is
  O(buckets x capacity) regardless of traffic; samples age out with
  their bucket, so the digest always describes the last ``window_s``
  seconds. Rank error stays within a few parts per thousand at the
  default capacity (pinned against exact quantiles in
  ``tests/telemetry/test_slo.py``).
- :class:`SloPolicy` — one declarative rule: a quantile objective
  ("serve/ttft_s p99 <= 300ms") or an error-budget rate objective
  ("deadline misses <= 1% of finished requests"), each with a window
  and a *burn rate* threshold: ``burn = observed / target``; the policy
  is burning when ``burn >= burn_rate`` (the SRE multi-window alerting
  convention — ``burn_rate=1`` pages on any overrun, higher values page
  only on fast budget burn).
- :class:`SloMonitor` — evaluates every policy against the live
  registry. Quantile policies read their digests (fed by
  ``Telemetry.observe`` raw-value observers); rate policies difference
  the named counters over the window (sampled at each evaluation, so no
  instrumented component changes). Each evaluation sets
  ``slo/{name}/observed``, ``slo/{name}/burn`` and
  ``slo/{name}/violating`` gauges plus the fleet-wide ``slo/burning``
  gauge; a policy that is burning bumps ``slo/violations`` (and
  ``slo/{name}/violations``) **once per window** and logs one
  rate-limited warning — a sustained burn pages once per window, not
  once per scrape.

Policies work over replica-labeled instruments too: a rate policy may
name ``serve/r0/expired`` and a quantile policy ``serve/r0/ttft_s``
(the batcher records base rollup AND ``serve/r{i}/...`` — see
docs/design/observability.md), so per-replica objectives see only that
replica's windowed deltas. :meth:`SloMonitor.extend` /
:meth:`SloMonitor.remove` register/retire policies at runtime — the
fleet autopilot's canary comparator (``resilience/autopilot.py``)
scopes temporary per-replica policies this way for exactly one
decision window. ``subscribers`` fire after every evaluation with the
fresh status list: the autopilot's sense→act hook.

Pure host Python, no jax anywhere: evaluation runs inside /metrics
scrapes and telemetry flushes, neither of which may touch the device.
"""

import bisect
import dataclasses
import logging
import math
import threading
import time
from collections import deque
from typing import Callable, Literal, Sequence

__all__ = [
    "SloMonitor",
    "SloPolicy",
    "StreamingQuantileDigest",
]

logger = logging.getLogger("d9d_tpu.telemetry")


def _stratified_compress(
    points: list[tuple[float, float]], m: int
) -> list[tuple[float, float]]:
    """Downsample weighted points to ``m`` representatives placed at the
    centers of ``m`` equal cumulative-weight strata — the weighted
    empirical CDF is preserved to within half a stratum of rank
    (``total/2m``) per compression, so error grows additively with the
    stratum width rather than multiplicatively with weight doubling."""
    points.sort()
    total = sum(w for _, w in points)
    step = total / m
    out: list[float] = []
    cum = 0.0
    ti = 0
    for v, w in points:
        cum += w
        while ti < m and (ti + 0.5) * step <= cum + 1e-12:
            out.append(v)
            ti += 1
    while ti < m:  # float-tail guard: always exactly m representatives
        out.append(points[-1][0])
        ti += 1
    return [(v, step) for v in out]


class _Bucket:
    __slots__ = ("points", "raw")

    def __init__(self):
        self.points: list[tuple[float, float]] = []  # (value, weight)
        self.raw = 0  # raw samples observed (pre-compression count)

    def add(self, value: float, capacity: int) -> None:
        self.points.append((value, 1.0))
        self.raw += 1
        if len(self.points) > capacity:
            self.points = _stratified_compress(self.points, capacity // 2)


class StreamingQuantileDigest:
    """Windowed quantile sketch over a value stream.

    ``record(v)`` is O(1) amortized (an append, occasionally a
    sort-and-halve of one bucket); ``quantile(p)`` merges the live
    buckets — called on the scrape/flush cadence, not the hot path.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        *,
        window_s: float = 60.0,
        buckets: int = 8,
        capacity: int = 256,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0 or buckets < 1 or capacity < 8:
            raise ValueError(
                f"need window_s > 0, buckets >= 1, capacity >= 8; got "
                f"{window_s}, {buckets}, {capacity}"
            )
        self.window_s = float(window_s)
        self._span = self.window_s / buckets
        self._n_buckets = buckets
        self._capacity = capacity
        self._clock = clock
        self._buckets: dict[int, _Bucket] = {}

    def _prune(self, now: float) -> None:
        # live window = the current bucket plus the n-1 before it, i.e.
        # indices > cur - n; anything older has fully aged out
        cur = int(now // self._span)
        dead = [i for i in self._buckets if i <= cur - self._n_buckets]
        for i in dead:
            del self._buckets[i]

    def record(self, value: float) -> None:
        now = self._clock()
        idx = int(now // self._span)
        bucket = self._buckets.get(idx)
        if bucket is None:
            self._prune(now)
            bucket = self._buckets[idx] = _Bucket()
        bucket.add(float(value), self._capacity)

    def count(self) -> int:
        """Raw samples currently inside the window."""
        self._prune(self._clock())
        return sum(b.raw for b in self._buckets.values())

    def quantile(self, p: float) -> float:
        """Approximate ``p``-quantile (p in [0, 1]) of the samples in the
        window; NaN when the window is empty."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        self._prune(self._clock())
        merged: list[tuple[float, float]] = []
        for b in self._buckets.values():
            merged.extend(b.points)
        if not merged:
            return float("nan")
        merged.sort()
        total = sum(w for _, w in merged)
        target = p * total
        cum = 0.0
        for v, w in merged:
            cum += w
            if cum >= target:
                return v
        return merged[-1][0]


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """One declarative SLO rule (see module docstring for semantics).

    ``kind="quantile"``: ``metric`` names the digest stream (a raw-value
    metric recorded through ``Telemetry.observe``, e.g. ``serve/ttft_s``)
    and ``observed = quantile(quantile)`` in seconds; ``target`` is the
    objective in the same unit.

    ``kind="rate"``: ``bad`` names the failure counter (e.g.
    ``serve/expired``) and ``good`` the success counters; over the
    window ``observed = Δbad / (Δbad + ΣΔgood)`` and ``target`` is the
    allowed bad fraction (the error budget, e.g. 0.01 for 1%).
    """

    name: str
    target: float
    window_s: float = 60.0
    burn_rate: float = 1.0
    kind: Literal["quantile", "rate"] = "quantile"
    metric: str = ""
    quantile: float = 0.99
    bad: str = ""
    good: tuple[str, ...] = ()
    min_samples: int = 1

    def __post_init__(self):
        if not self.name:
            raise ValueError("SloPolicy needs a name")
        if self.target <= 0 or self.window_s <= 0 or self.burn_rate <= 0:
            raise ValueError(
                f"{self.name}: target/window_s/burn_rate must be > 0"
            )
        if self.kind == "quantile":
            if not self.metric:
                raise ValueError(f"{self.name}: quantile policy needs metric")
            if not 0.0 <= self.quantile <= 1.0:
                raise ValueError(f"{self.name}: quantile must be in [0, 1]")
        elif self.kind == "rate":
            if not self.bad:
                raise ValueError(f"{self.name}: rate policy needs bad counter")
        else:
            raise ValueError(f"{self.name}: unknown kind {self.kind!r}")


@dataclasses.dataclass
class SloStatus:
    """One policy's evaluation result (also mirrored into gauges)."""

    policy: SloPolicy
    observed: float
    burn: float
    violating: bool
    samples: int


class SloMonitor:
    """Evaluate :class:`SloPolicy` rules against a telemetry registry.

    ``attach(hub)`` subscribes the digests to the hub's raw-value stream
    and registers the monitor for per-flush evaluation; /metrics scrapes
    (``telemetry/export.py``) evaluate it too, so an operator polling
    only the endpoint still gets fresh burn rates.
    """

    def __init__(
        self,
        policies: Sequence[SloPolicy],
        *,
        clock: Callable[[], float] = time.monotonic,
        digest_buckets: int = 8,
        digest_capacity: int = 256,
    ):
        names = [p.name for p in policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate policy names in {names}")
        self.policies: tuple[SloPolicy, ...] = ()
        self._clock = clock
        self._digest_buckets = digest_buckets
        self._digest_capacity = digest_capacity
        # one digest PER (metric, window): two policies with different
        # windows over the same metric must each see their own horizon —
        # a shared widest-window digest would let a 4-minute-old spike
        # keep a 60s policy burning. Isolated extensions get a
        # sequence-suffixed key instead, so a scoped decision window
        # can never alias a standing policy's samples.
        self._digests: dict[tuple, StreamingQuantileDigest] = {}
        self._digests_by_metric: dict[
            str, list[StreamingQuantileDigest]
        ] = {}
        self._policy_digest_key: dict[str, tuple] = {}
        self._isolate_seq = 0
        # counter history rings for rate policies: (t, value) samples
        # appended at each evaluation; the windowed delta is current
        # minus the newest sample at/before (now - window)
        self._counter_rings: dict[str, deque[tuple[float, float]]] = {}
        self._max_window = 60.0
        self._last_violation: dict[str, float] = {}
        # post-evaluation callbacks (fresh status list, called OUTSIDE
        # the evaluation lock): the autopilot's sense→act subscription
        self.subscribers: list[
            Callable[[list["SloStatus"]], None]
        ] = []
        self._subscriber_warned_t = -float("inf")
        # evaluate() runs from scrape threads (MetricsServer) AND the
        # flush path concurrently; the once-per-window violation bump is
        # check-then-set and the counter rings mutate — serialize it
        self._eval_lock = threading.Lock()
        self._hub = None
        self._register(tuple(policies))

    def _register(
        self, policies: tuple[SloPolicy, ...], *, isolate: bool = False
    ) -> None:
        self.policies = self.policies + policies
        for p in policies:
            if p.kind != "quantile":
                continue
            if isolate:
                # never alias a standing policy's digest, even on an
                # exact (metric, window) collision: a scoped decision
                # window must start clean
                self._isolate_seq += 1
                key = (p.metric, p.window_s, self._isolate_seq)
            else:
                key = (p.metric, p.window_s)
            self._policy_digest_key[p.name] = key
            if key not in self._digests:
                d = self._digests[key] = StreamingQuantileDigest(
                    window_s=p.window_s,
                    buckets=self._digest_buckets,
                    capacity=self._digest_capacity,
                    clock=self._clock,
                )
                self._digests_by_metric.setdefault(p.metric, []).append(d)
        self._max_window = max(
            (p.window_s for p in self.policies), default=60.0
        )

    def extend(
        self, policies: Sequence[SloPolicy], *, isolate: bool = False
    ) -> None:
        """Register additional policies at runtime. Digest-backed
        (quantile) additions only observe samples recorded AFTER the
        extension — callers scoping a decision window (the autopilot's
        canary comparator) rely on exactly that: the window starts
        clean at extend time. ``isolate=True`` guarantees it even when
        the new policy's (metric, window) exactly matches a standing
        policy's, by giving the addition its own digest instead of
        sharing."""
        with self._eval_lock:
            have = {p.name for p in self.policies}
            fresh = [p.name for p in policies]
            clash = have.intersection(fresh)
            if clash or len(set(fresh)) != len(fresh):
                raise ValueError(
                    f"duplicate policy names in extend: "
                    f"{sorted(clash) or fresh}"
                )
            self._register(tuple(policies), isolate=isolate)

    def remove(self, names: Sequence[str]) -> None:
        """Retire policies by name (unknown names are ignored). Their
        ``slo/{name}/*`` gauges are cleared (set NaN, which snapshots
        drop) so a retired temporary policy doesn't keep exporting its
        last evaluation forever; digests survive only while some
        remaining policy still reads their (metric, window) key."""
        gone = set(names)
        with self._eval_lock:
            self.policies = tuple(
                p for p in self.policies if p.name not in gone
            )
            live_keys = {
                self._policy_digest_key[p.name]
                for p in self.policies if p.kind == "quantile"
            }
            for key in [k for k in self._digests if k not in live_keys]:
                d = self._digests.pop(key)
                per_metric = self._digests_by_metric.get(key[0], [])
                if d in per_metric:
                    per_metric.remove(d)
                if not per_metric:
                    self._digests_by_metric.pop(key[0], None)
            for n in gone:
                self._last_violation.pop(n, None)
                self._policy_digest_key.pop(n, None)
            self._max_window = max(
                (p.window_s for p in self.policies), default=60.0
            )
        registry = self._hub.registry if self._hub is not None else None
        if registry is not None:
            for n in gone:
                for suffix in ("observed", "burn", "violating"):
                    g = registry.gauges.get(f"slo/{n}/{suffix}")
                    if g is not None:
                        g.set(float("nan"))

    def attach(self, hub) -> "SloMonitor":
        hub.registry.value_observers.append(self._on_value)
        hub.slo_monitor = self
        self._hub = hub
        return self

    def detach(self) -> None:
        if self._hub is None:
            return
        observers = self._hub.registry.value_observers
        if self._on_value in observers:
            observers.remove(self._on_value)
        if self._hub.slo_monitor is self:
            self._hub.slo_monitor = None
        self._hub = None

    def _on_value(self, name: str, value: float) -> None:
        for d in self._digests_by_metric.get(name, ()):
            d.record(value)

    # -- counter windowing ---------------------------------------------

    def _counter_value(self, registry, name: str) -> float:
        c = registry.counters.get(name)
        return float(c.value) if c is not None else 0.0

    def _windowed_delta(
        self, registry, name: str, window_s: float, now: float
    ) -> float:
        cur = self._counter_value(registry, name)
        ring = self._counter_rings.setdefault(name, deque())
        # baseline: the newest sample at/before the window start; if the
        # ring doesn't reach back that far yet (cold start), the oldest
        # sample — best-effort until a full window of history exists
        cutoff = now - window_s
        base = ring[0][1] if ring else cur
        times = [t for t, _ in ring]
        i = bisect.bisect_right(times, cutoff) - 1
        if i >= 0:
            base = ring[i][1]
        ring.append((now, cur))
        while ring and ring[0][0] < now - 2 * self._max_window:
            ring.popleft()
        return max(0.0, cur - base)

    # -- evaluation -----------------------------------------------------

    def evaluate(self, registry=None) -> list[SloStatus]:
        """Evaluate every policy; set the ``slo/*`` gauges; bump
        ``slo/violations`` once per window per burning policy; hand the
        fresh status list to every subscriber (outside the lock — a
        subscriber may re-enter monitor APIs). Thread-safe: scrapes and
        flushes may evaluate concurrently."""
        if registry is None:
            if self._hub is None:
                return []
            registry = self._hub.registry
        with self._eval_lock:
            statuses = self._evaluate_locked(registry)
        for cb in list(self.subscribers):
            try:
                cb(statuses)
            except Exception:  # noqa: BLE001 — a bad subscriber must not
                # kill the flush/scrape that evaluated; rate-limited log
                now = self._clock()
                if now - self._subscriber_warned_t >= 60.0:
                    self._subscriber_warned_t = now
                    logger.exception("SLO evaluation subscriber failed")
        return statuses

    def _evaluate_locked(self, registry) -> list[SloStatus]:
        now = self._clock()
        statuses: list[SloStatus] = []
        burning = 0
        for p in self.policies:
            if p.kind == "quantile":
                digest = self._digests[self._policy_digest_key[p.name]]
                samples = digest.count()
                observed = (
                    digest.quantile(p.quantile)
                    if samples >= p.min_samples else float("nan")
                )
                burn = observed / p.target if math.isfinite(observed) else 0.0
            else:
                bad = self._windowed_delta(registry, p.bad, p.window_s, now)
                den = bad + sum(
                    self._windowed_delta(registry, g, p.window_s, now)
                    for g in p.good
                )
                samples = int(den)
                # den > 0 guards a min_samples=0 policy (the autopilot's
                # promote-unless-observably-bad canary twins) from 0/0
                observed = (
                    bad / den if den >= p.min_samples and den > 0
                    else float("nan")
                )
                burn = observed / p.target if math.isfinite(observed) else 0.0
            violating = burn >= p.burn_rate
            # NaN clears the gauge from snapshots (the registry filters
            # NaN): an emptied window must DROP the observed value, not
            # keep exporting the last spike next to burn=0
            registry.gauge(f"slo/{p.name}/observed").set(
                observed if math.isfinite(observed) else float("nan")
            )
            registry.gauge(f"slo/{p.name}/burn").set(burn)
            registry.gauge(f"slo/{p.name}/violating").set(
                1.0 if violating else 0.0
            )
            if violating:
                burning += 1
                last = self._last_violation.get(p.name)
                if last is None or now - last >= p.window_s:
                    # once per window, however often evaluation runs: a
                    # sustained burn pages once per window, not per scrape
                    self._last_violation[p.name] = now
                    registry.counter("slo/violations").add(1)
                    registry.counter(f"slo/{p.name}/violations").add(1)
                    logger.warning(
                        "SLO %s burning: observed %.6g vs target %.6g "
                        "(burn %.2fx >= %.2fx) over %.0fs window "
                        "[%d sample(s)]",
                        p.name, observed, p.target, burn, p.burn_rate,
                        p.window_s, samples,
                    )
            statuses.append(SloStatus(
                policy=p, observed=observed, burn=burn,
                violating=violating, samples=samples,
            ))
        registry.gauge("slo/burning").set(float(burning))
        return statuses
