"""Training numerics observability plane (docs/design/observability.md
"Training numerics plane").

The anomaly guard (PR 5) sees two scalars — loss and global grad-norm —
so a NaN dump says *that* a step went bad, never *where*. This module is
the per-layer substrate underneath it:

- **Device side** (traced, zero added dispatches/readbacks): per-leaf /
  per-scope tensor statistics — grad RMS and absmax, parameter RMS,
  update-to-parameter ratio, optimizer second-moment health, and a
  per-row finite mask — stacked into ONE flat f32 device array that
  rides the step's ordinary metric dict. The heavy statistics are gated
  by a traced cadence flag (``lax.cond``): off-cadence steps run the
  identical program with the stats branch skipped and the vector left
  all-NaN, and the host only materializes it at the log cadence it was
  already fetching metrics at (``tools/bench_compare.py``'s tiny-train
  leg pins host_dispatches/readbacks byte-identical to a numerics-free
  loop at off-cadence steps).
- **Activation taps** (:func:`tap`): models mark residual-stream points
  (``layers_{gid}`` in the qwen3 backbones, mirroring their
  ``jax.named_scope`` module paths). A tap is a no-op unless a
  :func:`collect_taps` context is active — which only the
  numerics-enabled train step opens around ``task.loss_fn`` — so
  serving/eval/seed training trace byte-identical programs. Taps must
  sit OUTSIDE ``nn.remat`` boundaries (a tracer captured from inside a
  remat body leaks); the backbones tap each layer's *output* at the
  layer-loop call site for exactly this reason.
- **Host side**: :class:`NumericsSpec` (row names in device order),
  :class:`NumericsReport` decode with **NaN provenance** — the finite
  mask names the first offending row, ordered forward activations →
  loss → per-leaf grads → optimizer moments, which is the order the
  NaN was *produced* in — plus :class:`NumericsMonitor`, which feeds
  gauges, the schema-v4 ``numerics`` JSONL event, and the flight
  recorder's last-window context.
- **Drift policies**: :class:`DriftPolicy`/:class:`TrainDriftMonitor` —
  ``SloPolicy``-style declarative rules over training metrics
  (grad-norm drift vs a rolling baseline, update:param ratio out of
  band, loss spike), evaluated at the log cadence, surfacing
  ``train_slo/*`` gauges on ``/metrics`` and bumping
  ``train_slo/violations`` once per window. :class:`RollingBaseline` is
  the ONE windowed-median baseline implementation — the host anomaly
  guard's loss-spike detector (``resilience/anomaly.py``) delegates to
  it rather than keeping a second copy.

No jax at module import (the telemetry package core stays jax-free);
traced helpers defer the import to first use, like ``introspect.py``.
"""

import collections
import contextlib
import dataclasses
import logging
import math
import statistics
import threading
import time
from typing import Any, Iterable, Literal, Sequence

__all__ = [
    "DriftPolicy",
    "NumericsMonitor",
    "NumericsReport",
    "NumericsRow",
    "NumericsSpec",
    "RollingBaseline",
    "TrainDriftMonitor",
    "STAT_COLUMNS",
    "build_spec",
    "collect_taps",
    "default_drift_policies",
    "find_second_moments",
    "param_leaf_names",
    "tap",
]

logger = logging.getLogger("d9d_tpu.telemetry")

# one row of the flat stats array = one scope (activation tap, the loss,
# or one parameter leaf) x these columns. Rows of every kind share the
# layout; columns that don't apply to a kind are NaN.
STAT_COLUMNS = (
    "rms",           # grad RMS (param rows) / activation RMS (act rows) / |loss|
    "absmax",        # max |grad| / max |activation| / loss value
    "param_rms",     # RMS of the post-update parameter leaf
    "update_ratio",  # RMS(new - old) / RMS(new) — the update:param ratio
                     # (post-update denominator: see _leaf_row)
    "moment2_max",   # max of the Adam second-moment leaf (optimizer health)
    "finite",        # finite code: act/loss 0|1; param rows bit0=grads, bit1=moments
)
N_COLS = len(STAT_COLUMNS)

KIND_ACT = "act"
KIND_LOSS = "loss"
KIND_PARAM = "param"


# -- spec: the host-side naming of the device array's rows ---------------


@dataclasses.dataclass(frozen=True)
class NumericsRow:
    name: str
    kind: str  # act | loss | param
    # forward/production rank for provenance ordering. Device row layout
    # follows jax's canonical (sorted) dict order through scan/cond, so
    # for act rows this records the TAP order ("layers_2" fires before
    # "layers_10" even though it sorts after) — _first_nonfinite walks
    # acts by this rank, never by layout position.
    order: int = 0


@dataclasses.dataclass(frozen=True)
class NumericsSpec:
    """Row names/kinds in the exact order the device array stacks them:
    activation taps (forward order) → the loss → parameter leaves (tree
    order). Built at trace time, so the naming can never drift from the
    compiled layout."""

    rows: tuple[NumericsRow, ...]

    @property
    def n_rows(self) -> int:
        return len(self.rows)

    @property
    def flat_size(self) -> int:
        return len(self.rows) * N_COLS


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        parts.append(str(p) if key is None else str(key))
    return "/".join(parts)


def param_leaf_names(params) -> list[str]:
    """Leaf names from the parameter tree's paths (flax module paths:
    ``layers_0/self_attn/q_proj/kernel``), in tree-flatten order — the
    same order the device stats stack in. A common leading ``params/``
    collection is stripped."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    names = [_path_str(path) for path, _ in leaves]
    if names and all(n.startswith("params/") for n in names):
        names = [n[len("params/"):] for n in names]
    return names


def build_spec(
    act_names: Sequence[str], param_names: Sequence[str], *,
    include_loss: bool = True,
    act_rank: dict[str, int] | None = None,
) -> NumericsSpec:
    """``act_names`` in DEVICE layout order (jax's sorted dict order);
    ``act_rank`` maps tap name → forward application rank so provenance
    can walk acts in the order the NaN was produced, not sorted order."""
    rows = [
        NumericsRow(n, KIND_ACT, (act_rank or {}).get(n, i))
        for i, n in enumerate(act_names)
    ]
    if include_loss:
        rows.append(NumericsRow("loss", KIND_LOSS))
    rows.extend(NumericsRow(n, KIND_PARAM) for n in param_names)
    return NumericsSpec(rows=tuple(rows))


def build_param_spec(params) -> NumericsSpec:
    """Param-rows-only spec (the PP per-stage form: stages see grads and
    params, not the global loss or the forward taps)."""
    return build_spec((), param_leaf_names(params), include_loss=False)


# -- activation taps (trace-time collection) -----------------------------

_tls = threading.local()


class _TapCollector:
    """Per-trace accumulator: name → stacked ``[sq_mean, absmax, finite]``
    f32 device values. A re-tapped name (shared module applied N times)
    merges rather than overwrites, so row count stays trace-stable; the
    sq_mean merge weights every application equally (running mean over
    the trace-time application count, not a pairwise average)."""

    __slots__ = ("stats", "_counts")

    def __init__(self):
        self.stats: dict[str, Any] = {}
        self._counts: dict[str, int] = {}

    def add(self, name: str, x) -> None:
        import jax.numpy as jnp

        x32 = jnp.asarray(x).astype(jnp.float32)
        new = jnp.stack([
            jnp.mean(jnp.square(x32)),
            jnp.max(jnp.abs(x32)),
            jnp.all(jnp.isfinite(x32)).astype(jnp.float32),
        ])
        prev = self.stats.get(name)
        if prev is not None:
            k = self._counts[name]
            new = jnp.stack([
                (prev[0] * k + new[0]) / (k + 1),
                jnp.maximum(prev[1], new[1]),
                jnp.minimum(prev[2], new[2]),
            ])
        self._counts[name] = self._counts.get(name, 0) + 1
        self.stats[name] = new


def tap(name: str, x) -> None:
    """Observe an activation for the numerics plane. No-op (not even a
    traced op) unless a :func:`collect_taps` context is active — only the
    numerics-enabled train step opens one, so models can tap
    unconditionally. Call OUTSIDE ``nn.remat`` bodies (see module doc)."""
    col = getattr(_tls, "collector", None)
    if col is not None:
        col.add(name, x)


@contextlib.contextmanager
def collect_taps():
    """Activate tap collection for the enclosed trace region; yields the
    collector whose ``.stats`` maps tap name → ``[3]`` f32 stats."""
    prev = getattr(_tls, "collector", None)
    col = _TapCollector()
    _tls.collector = col
    try:
        yield col
    finally:
        _tls.collector = prev


# -- device-side assembly (traced helpers) -------------------------------


def find_second_moments(opt_state, params):
    """The Adam-family second-moment (``nu``) tree matching ``params``'
    structure, or None. Walks the (possibly wrapped/nested) optimizer
    state for the first node carrying both ``mu`` and ``nu`` — optax's
    ``ScaleByAdamState`` shape, which ``stochastic_adamw`` shares."""
    import jax

    treedef = jax.tree_util.tree_structure(params)
    found: list[Any] = []

    def walk(node):
        if found:
            return
        if hasattr(node, "nu") and hasattr(node, "mu"):
            found.append(node.nu)
            return
        if isinstance(node, (list, tuple)):
            for c in node:
                walk(c)
        elif isinstance(node, dict):
            for c in node.values():
                walk(c)

    walk(opt_state)
    if not found:
        return None
    nu = found[0]
    if jax.tree_util.tree_structure(nu) != treedef:
        return None
    return nu


def _leaf_row(g, p_old, p_new, nu_leaf):
    """One param row: [grad_rms, grad_absmax, param_rms, update_ratio,
    moment2_max, finite_code] as a [N_COLS] f32 stack (all operands may
    be None except ``g``)."""
    import jax.numpy as jnp

    g32 = jnp.asarray(g).astype(jnp.float32)
    grad_rms = jnp.sqrt(jnp.mean(jnp.square(g32)))
    grad_absmax = jnp.max(jnp.abs(g32))
    grad_finite = jnp.all(jnp.isfinite(g32)).astype(jnp.float32)
    nan = jnp.float32(jnp.nan)
    if p_new is not None:
        pn32 = jnp.asarray(p_new).astype(jnp.float32)
        param_rms = jnp.sqrt(jnp.mean(jnp.square(pn32)))
    else:
        param_rms = nan
    if p_old is not None and p_new is not None:
        po32 = jnp.asarray(p_old).astype(jnp.float32)
        upd_rms = jnp.sqrt(jnp.mean(jnp.square(pn32 - po32)))
        # denominator is the POST-update RMS: a zero-initialized leaf
        # (bias at step 0) then reads ~1 instead of 1/eps, and in steady
        # state new ≈ old so the conventional ratio is unchanged
        update_ratio = upd_rms / (param_rms + 1e-8)
    else:
        update_ratio = nan
    if nu_leaf is not None:
        nu32 = jnp.asarray(nu_leaf).astype(jnp.float32)
        moment2_max = jnp.max(nu32)
        moment_finite = jnp.all(jnp.isfinite(nu32)).astype(jnp.float32)
    else:
        moment2_max = nan
        moment_finite = jnp.float32(1.0)
    finite = grad_finite + 2.0 * moment_finite
    return jnp.stack([
        grad_rms, grad_absmax, param_rms, update_ratio, moment2_max, finite,
    ])


def stacked_param_rows(grads, params=None, new_params=None, nu=None):
    """[n_leaves, N_COLS] f32 rows over the grad tree's leaves, in the
    tree order :func:`param_leaf_names` reports. Traced — call inside
    the jitted step (or a per-stage stats executable under PP)."""
    import jax
    import jax.numpy as jnp

    g_leaves = jax.tree.leaves(grads)
    p_leaves = jax.tree.leaves(params) if params is not None else [None] * len(g_leaves)
    n_leaves = (
        jax.tree.leaves(new_params) if new_params is not None
        else [None] * len(g_leaves)
    )
    nu_leaves = jax.tree.leaves(nu) if nu is not None else [None] * len(g_leaves)
    rows = [
        _leaf_row(g, p, pn, v)
        for g, p, pn, v in zip(g_leaves, p_leaves, n_leaves, nu_leaves)
    ]
    return jnp.stack(rows)


def act_rows(act_stats: dict[str, Any], num_microbatches: int):
    """[n_taps, N_COLS] rows from microbatch-aggregated tap stats
    (``[sq_sum, absmax, finite_min]`` per tap, summed/maxed/minned over
    the microbatch scan)."""
    import jax.numpy as jnp

    nan = jnp.float32(jnp.nan)
    rows = []
    for name in act_stats:
        s = act_stats[name]
        rms = jnp.sqrt(s[0] / jnp.float32(max(num_microbatches, 1)))
        rows.append(jnp.stack([rms, s[1], nan, nan, nan, s[2]]))
    return jnp.stack(rows)


def loss_row(loss):
    import jax.numpy as jnp

    loss32 = jnp.asarray(loss).astype(jnp.float32)
    nan = jnp.float32(jnp.nan)
    return jnp.stack([
        jnp.abs(loss32), loss32, nan, nan, nan,
        jnp.isfinite(loss32).astype(jnp.float32),
    ])[None, :]


def merge_tap_stats(acc, new):
    """Scan-carry aggregation of two tap-stat dicts: sq_mean sums (the
    finalize divides by the trip count), absmax maxes, finite mins."""
    import jax.numpy as jnp

    return {
        k: jnp.stack([
            acc[k][0] + new[k][0],
            jnp.maximum(acc[k][1], new[k][1]),
            jnp.minimum(acc[k][2], new[k][2]),
        ])
        for k in acc
    }


def init_tap_stats(shapes: dict[str, Any]):
    """Zero-element of :func:`merge_tap_stats` matching ``shapes``
    (sq_sum 0, absmax -inf, finite 1)."""
    import jax.numpy as jnp

    zero = jnp.stack([
        jnp.float32(0.0), jnp.float32(-jnp.inf), jnp.float32(1.0)
    ])
    return {k: zero for k in shapes}


# -- host-side decode + monitor -----------------------------------------


@dataclasses.dataclass
class NumericsReport:
    """One decoded window: per-row stats keyed by (possibly
    stage-prefixed) scope name, plus the NaN-provenance verdict."""

    step: int
    rows: dict[str, dict[str, Any]]
    # {"site": "act"|"loss"|"grad"|"moment", "name": row name} or None
    first_nonfinite: dict[str, str] | None

    def scalars(self) -> dict[str, float]:
        """Aggregate scalars folded back into the trainer's host metric
        dict (drift policies key off these)."""
        out: dict[str, float] = {}
        grad_rms = [
            r["rms"] for r in self.rows.values()
            if r["kind"] == KIND_PARAM and math.isfinite(r["rms"])
        ]
        ratios = [
            r["update_ratio"] for r in self.rows.values()
            if r["kind"] == KIND_PARAM
            and r["update_ratio"] is not None
            and math.isfinite(r["update_ratio"])
        ]
        if grad_rms:
            out["numerics/grad_rms_max"] = max(grad_rms)
        if ratios:
            out["numerics/update_ratio_max"] = max(ratios)
        out["numerics/nonfinite_rows"] = float(sum(
            1 for r in self.rows.values() if not r["finite_ok"]
        ))
        return out


def decode_window(
    spec: NumericsSpec, vec, *, prefix: str = ""
) -> dict[str, dict[str, Any]] | None:
    """Decode one flat device vector against its spec → row dict, or
    None when the window was off-cadence (all-NaN finite column)."""
    import numpy as np

    arr = np.asarray(vec, dtype=np.float64).reshape(spec.n_rows, N_COLS)
    finite_col = arr[:, 5]
    if not np.isfinite(finite_col).any():
        return None
    rows: dict[str, dict[str, Any]] = {}
    for i, row in enumerate(spec.rows):
        code = finite_col[i]
        if row.kind == KIND_PARAM:
            grad_ok = bool(int(code) & 1) if math.isfinite(code) else False
            moment_ok = bool(int(code) & 2) if math.isfinite(code) else False
            finite_ok = grad_ok and moment_ok
        else:
            grad_ok = moment_ok = finite_ok = bool(
                math.isfinite(code) and code >= 0.5
            )
        rows[prefix + row.name] = {
            "kind": row.kind,
            "order": row.order,
            "rms": float(arr[i, 0]),
            "absmax": float(arr[i, 1]),
            "param_rms": float(arr[i, 2]),
            "update_ratio": float(arr[i, 3]),
            "moment2_max": float(arr[i, 4]),
            "grad_finite": grad_ok,
            "moment_finite": moment_ok,
            "finite_ok": finite_ok,
        }
    return rows


def _first_nonfinite(
    ordered: Iterable[tuple[str, dict[str, Any]]]
) -> dict[str, str] | None:
    """Provenance: the first offending row in production order — forward
    activations (TAP order, via the rows' ``order`` rank — the device
    layout itself is jax's sorted dict order), then the loss, then grads
    (tree order), then moments. A NaN loss with clean activations is
    attributed to the loss (the site that produced it — e.g.
    ``ChaosScaleTask``'s injection)."""
    items = list(ordered)
    acts = [(n, r) for n, r in items if r["kind"] == KIND_ACT]
    acts.sort(key=lambda nr: nr[1].get("order", 0))
    for name, r in acts:
        if not r["finite_ok"]:
            return {"site": "act", "name": name}
    for name, r in items:
        if r["kind"] == KIND_LOSS and not r["finite_ok"]:
            return {"site": "loss", "name": name}
    for name, r in items:
        if r["kind"] == KIND_PARAM and not r["grad_finite"]:
            return {"site": "grad", "name": name}
    for name, r in items:
        if r["kind"] == KIND_PARAM and not r["moment_finite"]:
            return {"site": "moment", "name": name}
    return None


class NumericsMonitor:
    """Host half: decodes the cadence windows the trainer fetched,
    feeds the ``numerics/*`` gauges, streams the schema-v4 ``numerics``
    JSONL event, and keeps the last window for the anomaly guard's
    provenance context and the flight recorder."""

    def __init__(self, telemetry=None):
        if telemetry is None:
            from d9d_tpu.telemetry import get_telemetry

            telemetry = get_telemetry()
        self._tele = telemetry
        self.last: NumericsReport | None = None

    def ingest(
        self,
        step: int,
        windows: Sequence[tuple[str, NumericsSpec, Any]],
    ) -> NumericsReport | None:
        """``windows`` is ``[(prefix, spec, host_vector), ...]`` — one
        entry for the single-program step, one per stage under PP.
        Returns the merged report, or None when every window was
        off-cadence."""
        rows: dict[str, dict[str, Any]] = {}
        for prefix, spec, vec in windows:
            decoded = decode_window(spec, vec, prefix=prefix)
            if decoded is not None:
                rows.update(decoded)
        if not rows:
            return None
        report = NumericsReport(
            step=step,
            rows=rows,
            first_nonfinite=_first_nonfinite(rows.items()),
        )
        self.last = report
        self._tele.gauge("numerics/last_step").set(float(step))
        self._tele.counter("numerics/windows").add(1)
        for k, v in report.scalars().items():
            self._tele.gauge(k).set(v)
        record: dict[str, Any] = {
            "step": step,
            "unix_time": time.time(),
            "rows": {
                name: {
                    stat: (r[stat] if math.isfinite(r[stat]) else None)
                    for stat in STAT_COLUMNS[:-1]
                } | {"kind": r["kind"], "finite": bool(r["finite_ok"])}
                for name, r in rows.items()
            },
        }
        if report.first_nonfinite is not None:
            record["first_nonfinite"] = report.first_nonfinite
        self._tele.record_numerics(record)
        return report

    def guard_context(self) -> dict[str, Any] | None:
        """Provenance context for ``HostAnomalyGuard.observe``: the last
        window's first-offending row (None while everything is finite)."""
        if self.last is None or self.last.first_nonfinite is None:
            return None
        fn = self.last.first_nonfinite
        return {
            "first_nonfinite": f"{fn['site']}:{fn['name']}",
            "numerics_step": self.last.step,
        }

    def reset(self) -> None:
        """Forget the last window (post-rollback: the restored state is
        not the one the window describes)."""
        self.last = None


# -- rolling baseline + drift policies ----------------------------------


class RollingBaseline:
    """THE windowed-median baseline (docs/design/observability.md):
    shared by the host anomaly guard's loss-spike detector and the drift
    policies, so there is exactly one definition of "the recent normal".

    The caller decides what the window absorbs — the guard/policies add
    only non-violating values, so a plateau of spikes can never
    normalize itself into the new baseline (the PR 5 contract, pinned by
    ``tests/resilience/test_anomaly_guard.py``).
    """

    def __init__(self, window: int, *, min_samples: int = 4):
        if window < 1 or min_samples < 1:
            raise ValueError(
                f"need window >= 1 and min_samples >= 1, got "
                f"{window}, {min_samples}"
            )
        self.min_samples = min_samples
        self._values: collections.deque[float] = collections.deque(
            maxlen=max(window, min_samples)
        )

    def __len__(self) -> int:
        return len(self._values)

    def ready(self) -> bool:
        return len(self._values) >= self.min_samples

    def add(self, value: float) -> None:
        self._values.append(float(value))

    def baseline(self) -> float:
        """Windowed median; NaN before ``min_samples`` values exist."""
        if not self.ready():
            return float("nan")
        return statistics.median(self._values)

    def ratio(self, value: float) -> float:
        """``value / baseline`` (guarded denominator); NaN while the
        baseline is not ready."""
        base = self.baseline()
        if not math.isfinite(base):
            return float("nan")
        return float(value) / max(base, 1e-12)

    def clear(self) -> None:
        self._values.clear()


@dataclasses.dataclass(frozen=True)
class DriftPolicy:
    """One declarative rule over a host training metric (the
    ``SloPolicy`` shape, at step cadence instead of wall cadence).

    ``kind="drift"``: violating when ``value > factor x rolling-median
    baseline`` over the last ``window`` observed values (the baseline
    absorbs only non-violating values). ``kind="band"``: violating when
    the value leaves ``[lo, hi]`` (either bound may be None); the first
    ``min_samples`` observations only gauge, never page — a fresh run's
    step-0 transient (zero-initialized leaves take their first real
    update) must not fire the pager.

    ``burn = observed / threshold`` (drift: ``factor x baseline``;
    band: the violated bound), mirroring the serving SLO convention —
    burning at ``burn >= 1``.
    """

    name: str
    metric: str
    kind: Literal["drift", "band"] = "drift"
    factor: float = 10.0
    window: int = 64
    lo: float | None = None
    hi: float | None = None
    min_samples: int = 4

    def __post_init__(self):
        if not self.name or not self.metric:
            raise ValueError("DriftPolicy needs a name and a metric")
        if self.kind == "drift":
            if self.factor <= 1.0 or self.window < self.min_samples:
                raise ValueError(
                    f"{self.name}: drift needs factor > 1 and "
                    f"window >= min_samples"
                )
        elif self.kind == "band":
            if self.lo is None and self.hi is None:
                raise ValueError(f"{self.name}: band needs lo and/or hi")
        else:
            raise ValueError(f"{self.name}: unknown kind {self.kind!r}")


def default_drift_policies() -> tuple[DriftPolicy, ...]:
    """The trainer's stock policy set (``TrainerConfig.numerics_drift``):
    grad-norm drift vs its rolling baseline, update:param ratio out of
    band (an optimizer moving some parameter leaf by > 50% RMS per step
    is pathological at any LR schedule this repo ships — small-norm
    leaves like biases legitimately see 10-20% early in training), and
    the loss-spike rule the host anomaly guard also acts on."""
    return (
        DriftPolicy(name="grad_norm_drift", metric="grad_norm",
                    kind="drift", factor=10.0, window=64),
        DriftPolicy(name="update_ratio_band",
                    metric="numerics/update_ratio_max", kind="band",
                    hi=0.5),
        DriftPolicy(name="loss_spike", metric="loss", kind="drift",
                    factor=10.0, window=64),
    )


class TrainDriftMonitor:
    """Evaluate drift policies against each log-cadence host metric dict;
    surface ``train_slo/*`` gauges (scraped live by ``/metrics``) and
    bump ``train_slo/violations`` at most once per ``window`` steps per
    policy — a sustained drift pages once per window, not per cadence."""

    def __init__(
        self, policies: Sequence[DriftPolicy], *, telemetry=None
    ):
        names = [p.name for p in policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate drift policy names in {names}")
        if telemetry is None:
            from d9d_tpu.telemetry import get_telemetry

            telemetry = get_telemetry()
        self._tele = telemetry
        self.policies = tuple(policies)
        self._baselines = {
            p.name: RollingBaseline(p.window, min_samples=p.min_samples)
            for p in self.policies if p.kind == "drift"
        }
        self._band_seen: dict[str, int] = {}
        self._last_violation: dict[str, int] = {}

    def observe(self, step: int, host_metrics: dict[str, Any]) -> list[str]:
        """→ names of the policies burning at this observation."""
        burning: list[str] = []
        for p in self.policies:
            raw = host_metrics.get(p.metric)
            if raw is None:
                continue
            try:
                value = float(raw)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(value):
                continue
            baseline = float("nan")
            if p.kind == "drift":
                rb = self._baselines[p.name]
                baseline = rb.baseline()
                if not rb.ready():
                    rb.add(value)
                    continue
                threshold = p.factor * max(baseline, 1e-12)
                burn = value / threshold
                violating = burn >= 1.0
                if not violating:
                    rb.add(value)
            else:
                seen = self._band_seen.get(p.name, 0)
                self._band_seen[p.name] = seen + 1
                # guarded ratios: a zero bound (metric expected <= 0) is
                # a legitimate band — burn saturates instead of dividing
                # by zero, and `is not None` keeps hi=0.0 from reading
                # as an absent bound
                if p.hi is not None and value > p.hi:
                    burn = value / p.hi if abs(p.hi) > 1e-12 else math.inf
                    violating = seen >= p.min_samples
                elif p.lo is not None and value < p.lo:
                    burn = p.lo / value if value > 1e-12 else math.inf
                    violating = seen >= p.min_samples
                else:
                    burn = (
                        value / p.hi
                        if p.hi is not None and abs(p.hi) > 1e-12
                        else 0.0
                    )
                    violating = False
            self._tele.gauge(f"train_slo/{p.name}/observed").set(value)
            if math.isfinite(baseline):
                self._tele.gauge(f"train_slo/{p.name}/baseline").set(baseline)
            self._tele.gauge(f"train_slo/{p.name}/burn").set(burn)
            self._tele.gauge(f"train_slo/{p.name}/violating").set(
                1.0 if violating else 0.0
            )
            if violating:
                burning.append(p.name)
                last = self._last_violation.get(p.name)
                if last is None or step - last >= p.window:
                    self._last_violation[p.name] = step
                    self._tele.counter("train_slo/violations").add(1)
                    self._tele.counter(
                        f"train_slo/{p.name}/violations"
                    ).add(1)
                    logger.warning(
                        "train drift policy %s burning at step %d: "
                        "%s=%.6g (burn %.2fx%s)",
                        p.name, step, p.metric, value, burn,
                        f", baseline {baseline:.6g}"
                        if math.isfinite(baseline) else "",
                    )
        self._tele.gauge("train_slo/burning").set(float(len(burning)))
        return burning

    def reset(self) -> None:
        """Forget baselines (post-rollback — the restored run's normal
        is not the exploded run's)."""
        for rb in self._baselines.values():
            rb.clear()
        self._last_violation.clear()
