"""Host sampling profiler: folded controller-thread stacks, stdlib only.

Single-controller JAX means one Python thread owns every dispatch, every
``block_until_ready``, every input-pipeline wait — when a step is slow
for host reasons (the dispatch tax the fused PP runtime attacks, data
stalls, stray Python overhead) the evidence is the controller's call
stack over time, and no device trace shows it. This module is the
in-process answer: a daemon thread polls ``sys._current_frames()`` for
the target thread at a fixed interval during capture windows (the
``JobProfiler`` one-shots driven by ``/debug/profile`` and the
``FlightRecorder`` capture hook), folds each sample into a
``outer;...;leaf`` stack string, and emits one schema-v5 ``host_stacks``
event per window. ``trace_export`` renders the window as a
``host_sampler`` Perfetto track next to the fused-run spans, so
data_wait vs dispatch vs Python overhead is attributable in the same
timeline without py-spy or any external tooling.

Cost model: off-window the sampler does not exist (nothing is started).
In-window it is one daemon thread doing a dict lookup + frame walk per
interval (default 10 ms → ~100 folds/s); ``sys._current_frames()`` holds
the GIL only for the snapshot, so the controller is perturbed by at most
the fold time. Sample counts, not wall time, are the fidelity unit:
``stacks`` maps folded stack → hit count, and consumers scale by
``dur_s / samples``.
"""

import sys
import threading
import time
import traceback
from collections import Counter
from pathlib import Path
from typing import Any

__all__ = ["HostSampler"]

# frames from these files are the sampler observing itself (the target
# thread is never this thread, but a stack can end inside threading
# internals when the controller is between frames) — kept, not filtered:
# honesty beats cosmetics, and the fold depth bound below is the only
# shaping we do
_MAX_DEPTH = 64


def _fold(frame) -> str:
    """``outer;...;leaf`` fold of a frame chain (Brendan Gregg folded
    format, the flamegraph/Perfetto lingua franca), innermost last."""
    parts: list[str] = []
    depth = 0
    while frame is not None and depth < _MAX_DEPTH:
        code = frame.f_code
        parts.append(
            f"{Path(code.co_filename).name}:{code.co_name}:"
            f"{frame.f_lineno}"
        )
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts) if parts else "<no frames>"


class HostSampler:
    """Sample one thread's Python stack on a fixed cadence.

    ``start()`` spawns the daemon sampler thread; ``stop()`` joins it and
    returns the window's ``host_stacks`` event dict (also handed to
    ``telemetry.record_host_stacks`` by the callers that own a capture
    window). Re-startable; never raises from the sampling loop — a
    target thread that exits mid-window simply stops accumulating.
    """

    def __init__(
        self,
        *,
        target_tid: int | None = None,
        interval_s: float = 0.01,
        thread_name: str = "controller",
    ):
        if target_tid is None:
            target_tid = threading.main_thread().ident
        self.target_tid = target_tid
        self.interval_s = interval_s
        self.thread_name = thread_name
        self._stacks: Counter[str] = Counter()
        self._samples = 0
        self._t0 = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stacks = Counter()
        self._samples = 0
        self._stop.clear()
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="d9d-host-sampler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                frame = sys._current_frames().get(self.target_tid)
                if frame is None:
                    continue
                self._stacks[_fold(frame)] += 1
                self._samples += 1
            except Exception:  # pragma: no cover — observability never
                # takes the job down; a single bad sample is dropped
                traceback.clear_frames(sys.exc_info()[2])

    def stop(self) -> dict[str, Any]:
        """Stop sampling and return the window's ``host_stacks`` event
        body (no ``kind`` key — the sink adds it)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        dur = time.perf_counter() - self._t0
        return {
            "t0": self._t0,
            "dur_s": dur,
            "interval_s": self.interval_s,
            "samples": self._samples,
            "thread": self.thread_name,
            "stacks": dict(self._stacks),
        }
