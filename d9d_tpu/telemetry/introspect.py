"""Device-side introspection: compile/recompile accounting and
per-executable HLO cost + HBM attribution.

PR 4's telemetry sees the host (phase timelines, serve latencies, MFU
gauges) but is blind to the device: nothing records compile time,
detects a silent steady-state recompile, or attributes HBM per
executable. This module closes that gap with one wrapper:

``tracked_jit(fn, name=...)`` behaves like ``jax.jit(fn, ...)`` but
routes every distinct abstract input signature through the explicit AOT
path (``lower()`` → ``compile()`` → call), which makes three things
observable for free:

- **compile spans** — lowering + backend-compile wall time land as a
  ``compile/{name}`` span (feeding the same-named histogram) with the
  lower/compile split in its meta;
- **recompile guard** — a compile on a wrapper that already holds a
  compiled signature is a *recompile*; after the configured warmup
  (``RecompileGuard``) each one bumps the ``compile/recompile`` counter
  and emits a rate-limited warning — the silent-recompile tripwire for
  the steady-state training loop;
- **executable inventory** — ``compiled.cost_analysis()`` /
  ``memory_analysis()`` (normalized in ``core/compat.py``; backends may
  return None) are harvested into a process-wide inventory: FLOPs,
  bytes-accessed, and the args/outputs/temps/generated-code HBM
  breakdown per executable, streamed to sinks as schema-v2
  ``executable`` events and summarized by ``tools/trace_summary.py``.

The happy path costs one extra host-side tuple build per call (the
signature key — the same work ``jax.jit``'s own cache-key computation
does) and **zero** extra device dispatches or readbacks: the AOT call
is the very dispatch ``jax.jit`` would have made. If the AOT machinery
raises during lower/compile (exotic argument types, plugin quirks), the
wrapper permanently degrades to the plain jitted function for that
site, logs once, and keeps the program running — introspection must
never take down training.

No jax import at module load: the telemetry package core stays
jax-free; ``tracked_jit`` defers the import to first use.
"""

import dataclasses
import hashlib
import logging
import threading
import time
from typing import Any, Callable

from d9d_tpu.telemetry import audit_capture  # stdlib-only at import

__all__ = [
    "ExecutableRecord",
    "RecompileGuard",
    "TrackedJit",
    "executable_flops",
    "inventory",
    "recompile_guard",
    "reset_inventory",
    "tracked_jit",
]

logger = logging.getLogger("d9d_tpu.telemetry.introspect")


@dataclasses.dataclass
class ExecutableRecord:
    """One compiled executable: identity, compile cost, HLO analyses."""

    name: str
    signature: str  # digest of the abstract input signature
    lower_s: float
    compile_s: float
    recompile: bool  # this wrapper already held a compiled signature
    step: int | None
    flops: float | None = None
    bytes_accessed: float | None = None
    # memory_analysis breakdown (bytes); None where the backend declines
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    generated_code_bytes: int | None = None
    alias_bytes: int | None = None
    calls: int = 0
    # compile-time artifact facts (telemetry/audit_capture.py): only
    # populated when audit capture is opted in — collective census,
    # donation coverage, baked consts, dtype census, host callbacks
    audit: dict[str, Any] | None = None
    # schedule manifest (optional, set by the producing component before
    # first call — the fused PP executor attaches its per-run op list:
    # rank, run index, ordered ops with stage/kind/microbatch and
    # declared read/write value keys). Rides the same executable event
    # as ``audit`` — optional fields need no schema bump.
    manifest: dict[str, Any] | None = None

    @property
    def hbm_peak_bytes(self) -> int | None:
        """Args + outputs + temps + generated code minus aliased (donated
        inputs overlap outputs) — the executable's device-memory claim
        the HBM budget gauge compares against chip capacity."""
        parts = [
            self.argument_bytes,
            self.output_bytes,
            self.temp_bytes,
            self.generated_code_bytes,
        ]
        if all(p is None for p in parts):
            return None
        total = sum(p for p in parts if p is not None)
        if self.alias_bytes is not None:
            total -= self.alias_bytes
        return max(total, 0)

    def event(self) -> dict[str, Any]:
        """The schema-v2 ``executable`` event payload sinks receive."""
        ev: dict[str, Any] = {
            "name": self.name,
            "signature": self.signature,
            "lower_s": self.lower_s,
            "compile_s": self.compile_s,
            "recompile": self.recompile,
        }
        if self.step is not None:
            ev["step"] = self.step
        if self.flops is not None:
            ev["flops"] = self.flops
        if self.bytes_accessed is not None:
            ev["bytes_accessed"] = self.bytes_accessed
        hbm = {
            k: v
            for k, v in (
                ("args", self.argument_bytes),
                ("outputs", self.output_bytes),
                ("temps", self.temp_bytes),
                ("generated_code", self.generated_code_bytes),
                ("alias", self.alias_bytes),
                ("peak", self.hbm_peak_bytes),
            )
            if v is not None
        }
        if hbm:
            ev["hbm"] = hbm
        if self.audit is not None:
            ev["audit"] = self.audit
        if self.manifest is not None:
            ev["manifest"] = self.manifest
        return ev


# -- process-wide executable inventory ----------------------------------

_INVENTORY: list[ExecutableRecord] = []
_INVENTORY_LOCK = threading.Lock()


def inventory() -> tuple[ExecutableRecord, ...]:
    """Every executable compiled through ``tracked_jit`` in this
    process, in compile order."""
    with _INVENTORY_LOCK:
        return tuple(_INVENTORY)


def reset_inventory() -> None:
    """Drop the inventory (tests / bench measurement windows). Wrappers
    keep their compiled executables — only the records are cleared."""
    with _INVENTORY_LOCK:
        _INVENTORY.clear()


def executable_flops(name: str) -> float | None:
    """XLA-reported FLOPs of the newest inventory record for ``name``
    (the cross-check input for ``flops/model_vs_xla_divergence``)."""
    with _INVENTORY_LOCK:
        for rec in reversed(_INVENTORY):
            if rec.name == name and rec.flops is not None:
                return rec.flops
    return None


# -- recompile guard ----------------------------------------------------


class RecompileGuard:
    """Arms the silent-recompile tripwire once warmup is over.

    Warmup is expressed in *loop steps of the current train() session*:
    the trainer calls :meth:`note_step` after each completed step and
    the guard flips steady once ``warmup_steps`` have run — by then
    every legitimate signature variant (ragged last microbatch, guarded
    vs unguarded step, both fused-serve variants in a warmed batcher)
    has compiled. Recompiles during warmup only count toward
    ``compile/recompiles_total``; recompiles in steady state
    additionally bump ``compile/recompile`` and emit a rate-limited
    warning. Harnesses without a step loop (bench sweeps compiling many
    configs on purpose) simply never arm the guard.
    """

    def __init__(self, *, warmup_steps: int = 1, warn_every_s: float = 30.0):
        self.warmup_steps = warmup_steps
        self.warn_every_s = warn_every_s
        self._steady = False
        self._last_warn = -float("inf")
        self._lock = threading.Lock()

    @property
    def steady(self) -> bool:
        return self._steady

    def configure(self, warmup_steps: int) -> None:
        """Re-arm for a fresh session: steady resets, warmup restarts."""
        self.warmup_steps = warmup_steps
        self._steady = False

    def note_step(self, session_steps: int) -> None:
        """Called by the loop after each completed step with the number
        of steps run *this session* (a resumed process re-warms: its
        wrappers start empty regardless of the global step counter)."""
        if not self._steady and session_steps >= self.warmup_steps:
            self._steady = True

    def mark_steady(self) -> None:
        self._steady = True

    def reset(self) -> None:
        self._steady = False
        self._last_warn = -float("inf")

    def on_recompile(self, name: str, signature: str, telemetry) -> None:
        """Account one recompile; warn (rate-limited) iff steady."""
        telemetry.counter("compile/recompiles_total").add(1)
        if not self._steady:
            return
        telemetry.counter("compile/recompile").add(1)
        with self._lock:
            now = time.monotonic()
            warn = now - self._last_warn >= self.warn_every_s
            if warn:
                self._last_warn = now
        if warn:
            logger.warning(
                "steady-state recompile of %r (signature %s): an input "
                "shape/dtype/sharding changed after warmup — every such "
                "step pays a full XLA compile",
                name, signature,
            )


_GUARD = RecompileGuard()


def recompile_guard() -> RecompileGuard:
    """The process-wide guard every ``tracked_jit`` wrapper consults."""
    return _GUARD


# -- signature fingerprinting -------------------------------------------


# sharding → canonical placement token, memoized by the (hashable)
# sharding value. The token must identify PLACEMENT, not the Python
# wrapper type: a jitted step returns GSPMD shardings for arrays that
# went in as NamedShardings, with identical device layout — keying on
# the objects themselves would flag every step-2 call as a recompile
# that jax.jit's own cache never performs.
_SHARDING_TOKENS: dict[Any, Any] = {}


def _sharding_token(sharding, ndim: int) -> Any:
    try:
        key = (sharding, ndim)
        token = _SHARDING_TOKENS.get(key)
        if token is None:
            token = _SHARDING_TOKENS[key] = (
                str(sharding._to_xla_hlo_sharding(ndim)),
                tuple(sorted(d.id for d in sharding.device_set)),
                getattr(sharding, "memory_kind", None),
            )
        return token
    except Exception:  # noqa: BLE001 — exotic sharding: degrade to repr
        return str(sharding)


def _leaf_sig(x) -> Any:
    """Hashable abstract signature of one argument leaf, matching what
    ``jax.jit``'s cache key distinguishes: shape/dtype/placement for
    arrays, weak type-identity for host scalars (different Python int
    *values* share one trace, so the value must not enter the key)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            sharding = _sharding_token(sharding, len(x.shape))
        return (tuple(x.shape), str(x.dtype), sharding)
    if x is None or isinstance(x, (bool, int, float, complex)):
        return type(x).__name__
    return repr(x)


class TrackedJit:
    """``jax.jit`` with compile/recompile/cost/HBM accounting.

    Call-compatible with the jitted function (positional and keyword
    arguments; donation and other jit kwargs pass through). Each
    distinct abstract input signature is lowered and compiled once via
    the AOT path and the resulting executable is cached here — exactly
    the cache ``jax.jit`` keeps internally, made observable.
    """

    def __init__(self, fn: Callable, *, name: str, **jit_kwargs: Any):
        import jax  # deferred: telemetry package core stays jax-free

        self.name = name
        self._fn = fn
        self._jit = jax.jit(fn, **jit_kwargs)
        # kept for the audit-capture donation check (declared donated
        # buffers are counted against the concrete call arguments)
        self._jit_kwargs = dict(jit_kwargs)
        # schedule manifest (ExecutableRecord.manifest): producers that
        # know the program's internal structure (the fused PP executor's
        # per-run op list) set this BEFORE the first call; every record
        # this wrapper files then carries it into the JSONL sidecar and
        # the introspection inventory
        self.manifest: dict[str, Any] | None = None
        self._compiled: dict[Any, Any] = {}
        self._records: dict[Any, ExecutableRecord] = {}
        self._fallback = False
        self._lock = threading.Lock()

    # the plain jitted function, for callers that need jit attributes
    @property
    def jitted(self):
        return self._jit

    def _signature_key(self, args, kwargs):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return (tuple(_leaf_sig(x) for x in leaves), treedef)

    def _compile(self, key, args, kwargs):
        """Lower + compile ``key``'s signature, harvest analyses, file
        the record. Returns the compiled executable, or None after
        degrading to the plain jit path (machinery failure only —
        errors from *running* the computation always propagate)."""
        from d9d_tpu.core import compat
        from d9d_tpu.telemetry import get_telemetry

        tele = get_telemetry()
        recompile = bool(self._compiled)
        # artifact capture (audit_capture.py) is compile-time-only and
        # opt-in: with it off this path is byte-identical to before; with
        # it on, trace()+lower() replace the single lower() call (the
        # same trace jax runs inside lower(), split so the jaxpr is
        # inspectable) — the dispatch path below never changes
        capture = audit_capture.capture_enabled()
        traced = None
        t0 = time.perf_counter()
        try:
            if capture and hasattr(self._jit, "trace"):
                try:
                    traced = self._jit.trace(*args, **kwargs)
                    lowered = traced.lower()
                except Exception:  # noqa: BLE001 — capture must never
                    # degrade the TRACKED path: a quirk specific to the
                    # trace() split falls back to the plain lower()
                    # (facts omitted, accounting kept); a genuinely
                    # untraceable fn re-raises identically from lower()
                    # and lands in the outer fallback as before
                    traced = None
                    logger.warning(
                        "audit capture: trace() failed for %r; "
                        "retrying the plain lower() path (facts "
                        "omitted, compile accounting kept)",
                        self.name, exc_info=True,
                    )
                    lowered = self._jit.lower(*args, **kwargs)
            else:
                lowered = self._jit.lower(*args, **kwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception:  # noqa: BLE001 — degrade, never break the loop
            self._fallback = True
            logger.warning(
                "tracked_jit(%r): AOT lower/compile failed; falling back "
                "to plain jax.jit for this site (compile/HBM accounting "
                "disabled for it)", self.name, exc_info=True,
            )
            return None

        sig = hashlib.sha1(repr(key).encode()).hexdigest()[:10]
        record = ExecutableRecord(
            name=self.name,
            signature=sig,
            lower_s=t1 - t0,
            compile_s=t2 - t1,
            recompile=recompile,
            step=tele.registry.current_step,
        )
        ca = compat.compiled_cost_analysis(compiled)
        if ca:
            record.flops = ca.get("flops")
            record.bytes_accessed = ca.get("bytes accessed")
        ma = compat.compiled_memory_analysis(compiled)
        if ma:
            record.argument_bytes = ma.get("argument_size_in_bytes")
            record.output_bytes = ma.get("output_size_in_bytes")
            record.temp_bytes = ma.get("temp_size_in_bytes")
            record.generated_code_bytes = ma.get(
                "generated_code_size_in_bytes"
            )
            record.alias_bytes = ma.get("alias_size_in_bytes")
        if self.manifest is not None:
            record.manifest = self.manifest

        if capture:
            try:
                record.audit = audit_capture.extract_facts(
                    self.name,
                    closed_jaxpr=getattr(traced, "jaxpr", None),
                    compiled_text=compiled.as_text(),
                    args=args,
                    kwargs=kwargs,
                    jit_kwargs=self._jit_kwargs,
                ).to_dict()
            except Exception:  # noqa: BLE001 — facts are observability,
                # never a reason to fail a compile; the audit gate reads
                # a missing block as "not captured" and fails THERE
                logger.warning(
                    "audit capture failed for %r (facts omitted)",
                    self.name, exc_info=True,
                )

        with _INVENTORY_LOCK:
            _INVENTORY.append(record)
        self._records[key] = record

        # compile/{name} span (feeds the same-named histogram) with the
        # lower/compile split; counters for cheap cross-run aggregation
        tele.registry.record_span(
            f"compile/{self.name}", t0, t2 - t0,
            meta={
                "lower_s": record.lower_s,
                "compile_s": record.compile_s,
                "signature": sig,
                "recompile": recompile,
            },
        )
        tele.counter("compile/count").add(1)
        tele.counter("compile/wall_s").add(t2 - t0)
        if recompile:
            _GUARD.on_recompile(self.name, sig, tele)

        # HBM budget gauges: per-executable claim, plus the fraction of
        # chip capacity where the backend reports one (TPU; CPU rigs
        # have no bytes_limit and skip the fraction)
        peak = record.hbm_peak_bytes
        if peak is not None:
            tele.gauge(f"hbm/{self.name}/peak_bytes").set(peak)
            cap = compat.device_hbm_capacity()
            if cap:
                tele.gauge("hbm/device_capacity_bytes").set(cap)
                tele.gauge(f"hbm/{self.name}/budget_frac").set(peak / cap)

        tele.record_executable(record.event())
        return compiled

    def __call__(self, *args, **kwargs):
        if self._fallback:
            return self._jit(*args, **kwargs)
        key = self._signature_key(args, kwargs)
        compiled = self._compiled.get(key)
        if compiled is None:
            with self._lock:
                compiled = self._compiled.get(key)
                if compiled is None and not self._fallback:
                    compiled = self._compile(key, args, kwargs)
                    if compiled is not None:
                        self._compiled[key] = compiled
            if compiled is None:  # degraded inside _compile
                return self._jit(*args, **kwargs)
        record = self._records.get(key)
        if record is not None:
            record.calls += 1
        return compiled(*args, **kwargs)


def tracked_jit(fn: Callable, *, name: str, **jit_kwargs: Any) -> TrackedJit:
    """Drop-in ``jax.jit`` replacement with device-side introspection
    (see module docstring). ``name`` keys every signal this wrapper
    emits: the ``compile/{name}`` span, ``hbm/{name}/*`` gauges, and
    the executable-inventory rows."""
    return TrackedJit(fn, name=name, **jit_kwargs)
