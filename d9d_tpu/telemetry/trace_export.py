"""Cross-process Perfetto export: merge telemetry JSONL event logs into
one Chrome-trace file.

Each process writes its own JSONL event log (``JsonlSink``, one file per
process) with span timestamps on its *private* monotonic clock
(``perf_counter`` origin). The file's ``meta`` header records the
``(unix_time, perf_counter)`` pair sampled at open, which is exactly the
rebasing constant needed to place every span on a shared wall clock:

    wall(t) = meta.unix_time + (t - meta.perf_counter)

This module reads any number of such files, rebases them onto the
earliest meta wall time across the set, and emits one Chrome-trace /
Perfetto JSON (``{"traceEvents": [...]}``) in which:

- **spans** become ``"X"`` duration events, one *track* (tid) per span
  namespace (``name.rsplit('/', 1)[0]`` — so ``train/phase/*`` phases,
  ``train/step``, ``pp/*``, ``io/*`` and ``compile/*`` each get their
  own lane) under one *process* (pid) per input file;
- **flush** counters and gauges become ``"C"`` counter events at the
  flush's own ``unix_time`` (counter names carry the metric namespace);
- **executable** records (telemetry/introspect.py) become ``"i"``
  instant events on the ``compile`` track with the FLOPs/HBM payload in
  ``args``, so a recompile shows up as a visible pin on the timeline;
- **request_trace** events (schema v3 — per-request serving milestones
  keyed by a fleet-stable trace id) become one track *per request*
  (``req/{trace_id}``): consecutive milestones turn into ``"X"`` state
  spans (``queued`` → ``running@r0`` → ``decoding@r0`` → ``migrating``
  → …) and terminal milestones into ``"i"`` pins, so a request that
  crossed a preemption-driven migration reads as ONE contiguous lane —
  the continuity the fleet's kill-recovery contract promises;
- **numerics** events (schema v4 — per-layer training tensor-statistics
  windows, ``telemetry/numerics.py``) become ``"C"`` counter tracks:
  one ``numerics/{layer}/grad_rms`` series per parameter row, so a
  layer's gradient drifting away from its siblings is visible as a
  diverging counter lane next to the ``train/step`` spans;
- **host_stacks** events (schema v5 — folded controller-thread stack
  samples from ``telemetry/host_sampler.py``, one per profiling capture
  window) become a ``host_sampler`` track: the window is tiled with one
  ``"X"`` span per distinct stack, width proportional to its sample
  count, so host time (data_wait vs dispatch vs Python overhead) reads
  as a flamegraph-like lane next to the fused-run spans;
- process/thread ``"M"`` metadata events name every lane.

The output ordering is deterministic (sorted by timestamp, then pid,
tid, name) so two exports of the same logs are byte-identical — tests
and diff-based tooling rely on that.

Load the result at https://ui.perfetto.dev or chrome://tracing; with
per-stage PP tracks and the serve admission/dispatch spans side by side,
stage bubbles and admission stalls become one visually inspectable
timeline — the observable the MPMD-pipeline work (PAPERS.md,
arxiv 2412.14374) tunes against.

Pure host Python: no jax anywhere (importable by offline tooling).
"""

import json
import logging
import re
from pathlib import Path
from typing import Any, Iterable

from d9d_tpu.telemetry.sinks import validate_event

__all__ = [
    "discover_jsonl",
    "export_perfetto",
    "merge_to_chrome_trace",
]

logger = logging.getLogger("d9d_tpu.telemetry.trace_export")

_PROC_RE = re.compile(r"_proc(\d+)\.jsonl$")

# request_trace rendering: the state a request ENTERS at each milestone
# (the span runs until the next milestone) and the milestones that end
# the request (rendered as instant pins, no outgoing span)
_REQUEST_STATE = {
    "submit": "queued",
    "admit": "running",
    "first_token": "decoding",
    "migrate": "migrating",
    "handoff": "handing_off",
    "continuation": "recovering",
}
_REQUEST_TERMINAL = frozenset({"finish", "expired", "failed", "rejected"})


def _read_events_lenient(path: Path) -> list[dict[str, Any]]:
    """Validated events from one log, tolerating the tail a crashed
    process leaves: JsonlSink buffers span writes between flushes, so a
    killed rank's file typically ends mid-line — a post-mortem merge
    must read everything BEFORE the damage, not die on it. Malformed
    trailing lines are dropped with a warning; damage to the first
    (meta) line is still fatal, since nothing can be aligned without
    the clock pair."""
    events: list[dict[str, Any]] = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
                validate_event(event)
            except ValueError as e:
                if i == 0:
                    raise ValueError(
                        f"{path}: unreadable meta header: {e}"
                    ) from e
                logger.warning(
                    "%s: dropping malformed line %d (truncated by a "
                    "crash?): %s", path, i + 1, e,
                )
                break
            events.append(event)
    if not events or events[0].get("kind") != "meta":
        raise ValueError(f"{path}: no meta header — not a telemetry log")
    return events


def discover_jsonl(path: str | Path) -> list[Path]:
    """Telemetry JSONL files at ``path``: the file itself, or every
    ``*.jsonl`` directly under a directory, sorted for determinism."""
    p = Path(path)
    if p.is_file():
        return [p]
    return sorted(p.glob("*.jsonl"))


def _track_of(span_name: str) -> str:
    """Track (thread lane) for a span: its namespace — everything before
    the last path component. ``train/phase/data_wait`` → ``train/phase``
    (so the enclosing ``train/step`` span sits on its own ``train`` lane
    instead of fighting the phases for nesting), ``pp/s3/bwd`` →
    ``pp/s3``, ``compile/train_step`` → ``compile``."""
    if "/" in span_name:
        return span_name.rsplit("/", 1)[0]
    return span_name


def merge_to_chrome_trace(paths: Iterable[str | Path]) -> dict[str, Any]:
    """Merge telemetry JSONL files into one Chrome-trace dict.

    Each file becomes one trace process; its pid is the file's recorded
    ``process_index`` where unique across the set, else its position in
    the sorted input (two single-process runs merged side by side must
    not collide)."""
    paths = [Path(p) for p in paths]
    if not paths:
        raise ValueError("no telemetry JSONL files to merge")

    loaded = []  # (path, meta, events)
    for path in paths:
        events = _read_events_lenient(path)
        meta = events[0]
        if "perf_counter" not in meta or "unix_time" not in meta:
            raise ValueError(
                f"{path}: meta header lacks the unix_time/perf_counter "
                "clock pair needed for cross-process alignment"
            )
        loaded.append((path, meta, events[1:]))

    indices = [m.get("process_index", 0) for _, m, _ in loaded]
    unique = len(set(indices)) == len(indices)
    origin = min(m["unix_time"] - m["perf_counter"] for _, m, _ in loaded)
    t0_wall = min(m["unix_time"] for _, m, _ in loaded)

    trace_events: list[dict[str, Any]] = []
    meta_events: list[dict[str, Any]] = []
    for slot, (path, meta, events) in enumerate(loaded):
        pid = meta.get("process_index", 0) if unique else slot
        # this process's perf_counter → shared-wall-µs rebase
        epoch = meta["unix_time"] - meta["perf_counter"]

        def wall_us(perf_t: float) -> float:
            return (epoch + perf_t - t0_wall) * 1e6

        meta_events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": f"proc{pid} ({path.stem})"},
        })
        tids: dict[str, int] = {}
        tracks: list[str] = []

        def tid_of(track: str) -> int:
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                tracks.append(track)
            return tid

        req_events: dict[str, list[dict[str, Any]]] = {}
        for ev in events:
            kind = ev["kind"]
            if kind == "request_trace":
                req_events.setdefault(ev["trace_id"], []).append(ev)
            elif kind == "span":
                args: dict[str, Any] = {}
                if "step" in ev:
                    args["step"] = ev["step"]
                if ev.get("meta"):
                    args.update(ev["meta"])
                trace_events.append({
                    "ph": "X", "pid": pid,
                    "tid": tid_of(_track_of(ev["name"])),
                    "ts": wall_us(ev["t0"]),
                    "dur": ev["dur_s"] * 1e6,
                    "name": ev["name"], "cat": "span",
                    **({"args": args} if args else {}),
                })
            elif kind == "flush":
                # flush carries its own wall clock — no rebase needed
                ts = (ev.get("unix_time", t0_wall) - t0_wall) * 1e6
                series = dict(ev.get("counters", {}))
                series.update(ev.get("gauges", {}))
                for name, value in series.items():
                    if value is None:
                        continue
                    trace_events.append({
                        "ph": "C", "pid": pid, "tid": 0, "ts": ts,
                        "name": name, "cat": "counter",
                        "args": {"value": value},
                    })
            elif kind == "numerics":
                # per-layer grad-RMS counter tracks (param rows only —
                # act/loss rows have no grad axis); the event carries
                # its own wall clock like flush events
                ts = (ev.get("unix_time", t0_wall) - t0_wall) * 1e6
                for row_name in sorted(ev.get("rows", {})):
                    row = ev["rows"][row_name]
                    if row.get("kind") != "param":
                        continue
                    value = row.get("rms")
                    if value is None:
                        continue
                    trace_events.append({
                        "ph": "C", "pid": pid, "tid": 0, "ts": ts,
                        "name": f"numerics/{row_name}/grad_rms",
                        "cat": "numerics",
                        "args": {"value": value},
                    })
            elif kind == "host_stacks":
                # folded controller-stack window (schema v5,
                # telemetry/host_sampler.py): render the window as one
                # "X" span per distinct stack on a host_sampler lane,
                # widths proportional to hit counts laid end to end
                # (heaviest first), named by the leaf frame with the
                # full fold in args — a poor man's flamegraph that sits
                # time-aligned next to the fused-run spans
                samples = ev.get("samples", 0)
                stacks = ev.get("stacks", {})
                if samples and stacks:
                    tid = tid_of(
                        f"host_sampler/{ev.get('thread', 'thread')}"
                    )
                    per_sample = ev["dur_s"] / samples
                    cursor = ev["t0"]
                    order = sorted(
                        stacks.items(), key=lambda kv: (-kv[1], kv[0])
                    )
                    for fold, count in order:
                        dur = count * per_sample
                        leaf = fold.rsplit(";", 1)[-1]
                        trace_events.append({
                            "ph": "X", "pid": pid, "tid": tid,
                            "ts": wall_us(cursor), "dur": dur * 1e6,
                            "name": leaf, "cat": "host_stacks",
                            "args": {
                                "stack": fold, "samples": count,
                                "frac": count / samples,
                            },
                        })
                        cursor += dur
            elif kind == "executable":
                # no per-event timestamp: pin to the compile span's lane
                # at the file's own meta time + accumulated order is not
                # recoverable — use the meta wall time so the pins sit at
                # the run's start unless a matching compile span exists
                trace_events.append({
                    "ph": "i", "pid": pid, "tid": tid_of("compile"),
                    "ts": (meta["unix_time"] - t0_wall) * 1e6,
                    "name": f"executable:{ev['name']}",
                    "cat": "executable", "s": "t",
                    "args": {
                        k: v for k, v in ev.items() if k != "kind"
                    },
                })
        # per-request tracks: one lane per trace id, milestones turned
        # into contiguous state spans + terminal pins (request_trace
        # timestamps are perf_counter values — same rebase as spans)
        for trace_id in sorted(req_events):
            evs = sorted(req_events[trace_id], key=lambda e: e["t"])
            tid = tid_of(f"req/{trace_id}")
            for i, ev in enumerate(evs):
                milestone = ev["event"]
                args: dict[str, Any] = {"trace_id": trace_id}
                if ev.get("replica") is not None:
                    args["replica"] = ev["replica"]
                if ev.get("rid") is not None:
                    args["rid"] = ev["rid"]
                if ev.get("meta"):
                    args.update(ev["meta"])
                if milestone in _REQUEST_TERMINAL:
                    trace_events.append({
                        "ph": "i", "pid": pid, "tid": tid,
                        "ts": wall_us(ev["t"]), "name": milestone,
                        "cat": "request", "s": "t", "args": args,
                    })
                    continue
                if i + 1 >= len(evs):
                    continue  # still in flight at log end: no close time
                state = _REQUEST_STATE.get(milestone, milestone)
                label = (
                    f"{state}@{ev['replica']}"
                    if ev.get("replica") is not None else state
                )
                trace_events.append({
                    "ph": "X", "pid": pid, "tid": tid,
                    "ts": wall_us(ev["t"]),
                    "dur": (evs[i + 1]["t"] - ev["t"]) * 1e6,
                    "name": label, "cat": "request", "args": args,
                })
        for track in sorted(tracks):
            meta_events.append({
                "ph": "M", "pid": pid, "tid": tids[track],
                "name": "thread_name", "args": {"name": track},
            })

    # deterministic, stable ordering: two exports of the same logs are
    # byte-identical (metadata first, then events by time/identity)
    trace_events.sort(
        key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"])
    )
    meta_events.sort(
        key=lambda e: (e["pid"], e["name"], e.get("tid", 0))
    )
    return {
        "traceEvents": meta_events + trace_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "source": "d9d_tpu.telemetry.trace_export",
            "origin_unix_time": t0_wall,
            "clock_origin": origin,
            "processes": len(loaded),
        },
    }


def export_perfetto(
    paths: Iterable[str | Path], out_path: str | Path
) -> dict[str, Any]:
    """Merge ``paths`` and write the Chrome-trace JSON to ``out_path``;
    returns the trace dict (callers report event counts)."""
    trace = merge_to_chrome_trace(paths)
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as fh:
        json.dump(trace, fh, sort_keys=True)
    return trace
