"""Roofline FLOPs inventory for live MFU (bench.py's accounting, shared).

``bench.py`` and ``tools/roofline.py`` compute model-FLOPs-per-token
offline; the trainer's live MFU gauge needs the same convention on the
step path: 6 FLOPs per token per active parameter plus the exact
quadratic-attention term (MFU counts remat recompute as overhead, so the
multiplier stays 6 regardless of remat policy — VERDICT r2 Weak #3).
"""

from typing import Any

__all__ = [
    "model_flops_per_token",
    "gdn_flops_per_token",
    "active_param_count",
    "device_peak_flops",
]

# Peak bf16 FLOPs per chip by device-kind substring (bench.py table).
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}
DEFAULT_PEAK = 197e12  # unknown device (CPU rigs): v5e yardstick


def model_flops_per_token(
    active_param_count: int,
    *,
    seq_len: int,
    config: Any | None = None,
) -> float:
    """Model FLOPs per trained token.

    When ``config`` exposes transformer geometry (``num_layers``,
    ``num_heads``, ``head_dim`` — the Qwen3/deepseek config shape) the
    causal-attention term ``6 * L * H * D * T`` is added; hybrid stacks
    restrict it to the quadratic layers via ``linear_attention_layers``.
    Without a recognizable config the 6N term alone is reported (an
    underestimate for long sequences — documented, not guessed at).
    """
    flops = 6.0 * active_param_count
    if config is not None:
        layers = getattr(config, "num_layers", None)
        heads = getattr(config, "num_heads", None)
        head_dim = getattr(config, "head_dim", None)
        if layers and heads and head_dim:
            linear = getattr(config, "linear_attention_layers", None) or ()
            n_attn = layers - len(linear)
            flops += 6.0 * n_attn * heads * head_dim * seq_len
            flops += gdn_flops_per_token(config)
    return flops


def gdn_flops_per_token(config: Any, chunk: int = 64) -> float:
    """Chunked-WY gated-delta FLOPs per token across the GDN layers
    (ops/gated_delta.py matmul inventory): per head per token the forward
    costs ≈ 2·2·C·dk (k·kᵀ, q·kᵀ) + C·dv (triangular solve) + 2·C·dv
    (attn·u) + 3·2·dk·dv (state read ×2 + state update); fwd+bwd ≈ 3×."""
    linear = getattr(config, "linear_attention_layers", None) or ()
    if not linear:
        return 0.0
    dk = getattr(config, "gdn_head_qk_dim", None) or config.head_dim
    dv = getattr(config, "gdn_head_v_dim", None) or config.head_dim
    hv = getattr(config, "gdn_v_heads", None) or config.num_heads
    per_head = 3 * (4 * chunk * dk + 3 * chunk * dv + 6 * dk * dv)
    return len(linear) * hv * per_head


def active_param_count(trees, config: Any | None = None) -> float:
    """Parameters that compute per token, summed over ``trees`` (pytrees
    of arrays): MoE expert weights — any leaf whose path contains
    ``grouped_experts`` — scaled by ``num_experts_per_tok / num_experts``
    from ``config``, everything else counted once. The single accounting
    bench.py and the trainer's live-MFU gauge both use, so the two MFU
    numbers cannot drift apart."""
    import jax  # deferred: the telemetry package core stays jax-free
    import numpy as np

    n_exp = getattr(config, "num_experts", None)
    top_k = getattr(config, "num_experts_per_tok", None)
    expert_scale = (top_k / n_exp) if (n_exp and top_k) else 1.0
    total = 0.0
    for tree in trees:
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            n = int(np.prod(leaf.shape))
            if expert_scale != 1.0 and "grouped_experts" in "/".join(
                str(p) for p in path
            ):
                n *= expert_scale
            total += n
    return total


def device_peak_flops() -> float:
    """Peak bf16 FLOPs of the first local device (DEFAULT_PEAK when the
    device kind is unrecognized — live MFU is a trend signal, and on CPU
    rigs an arbitrary-but-fixed yardstick keeps the gauge plottable)."""
    import jax  # deferred: the telemetry package core stays jax-free

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — backend not initialized/available
        return DEFAULT_PEAK
    return next(
        (v for k, v in PEAK_FLOPS.items() if k in kind), DEFAULT_PEAK
    )
