"""Always-on runtime telemetry: registry + sinks + the process hub.

Usage shape (see docs/design/observability.md):

- Instrumented components (trainer, pipeline executor, serving batcher,
  checkpointer, data loader) call :func:`get_telemetry` and record into
  its registry. The hub always exists; with no sinks attached the cost
  is a few host-clock reads per region and in-memory accumulation.
- A driver (``Trainer`` via its config, bench harnesses via
  ``D9D_TELEMETRY_DIR``) attaches sinks — JSONL event log, tracker
  bridge, console summary — and calls :meth:`Telemetry.flush` on its
  metric cadence.
- Tests and embedders may install a fresh hub with :func:`set_telemetry`
  to isolate their measurements.

Metric namespace (enforced by convention, documented in the design doc):
``train/*`` trainer loop, ``pp/*`` pipeline executor, ``serve/*``
continuous batching, ``io/*`` checkpoint + data IO.
"""

import contextlib
import logging
import threading
import time as _time
from typing import Any

from d9d_tpu.telemetry.flops import (
    active_param_count,
    device_peak_flops,
    model_flops_per_token,
)
from d9d_tpu.telemetry.registry import (
    SCHEMA_VERSION,
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    PhaseTimeline,
    Span,
    exp_edges,
)
from d9d_tpu.telemetry.sinks import (
    ConsoleSink,
    JsonlSink,
    TelemetrySink,
    TrackerBridge,
    iter_events,
    validate_event,
)

__all__ = [
    "SCHEMA_VERSION",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "PhaseTimeline",
    "Span",
    "Telemetry",
    "TelemetrySink",
    "JsonlSink",
    "TrackerBridge",
    "ConsoleSink",
    "exp_edges",
    "get_telemetry",
    "set_telemetry",
    "attached_jsonl_sink",
    "iter_events",
    "validate_event",
    "model_flops_per_token",
    "active_param_count",
    "device_peak_flops",
    "tracked_jit",
    "recompile_guard",
    # monitoring plane (docs/design/observability.md)
    "MetricsServer",
    "render_prometheus",
    "SloMonitor",
    "SloPolicy",
    "StreamingQuantileDigest",
    "FlightRecorder",
    # host sampling profiler (telemetry/host_sampler.py)
    "HostSampler",
    # training numerics plane (telemetry/numerics.py)
    "DriftPolicy",
    "NumericsMonitor",
    "RollingBaseline",
    "TrainDriftMonitor",
    "default_drift_policies",
]


class Telemetry:
    """One registry + its attached sinks.

    Spans stream to sinks as they complete (via a registry observer);
    counters/gauges/histograms reach sinks only on :meth:`flush` — the
    metric-collector cadence, so the hot loop never serializes a
    snapshot per step.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self.registry.span_observers.append(self._on_span)
        self._sinks: list[TelemetrySink] = []
        self._lock = threading.Lock()
        # monitoring plane attachments (both optional): the SLO monitor
        # is evaluated on every flush (and by /metrics scrapes); the
        # flight recorder makes dump_flight_record a real dump instead of
        # a no-op (telemetry/flight_recorder.py)
        self.slo_monitor = None
        self.flight_recorder = None
        # last numerics window (telemetry/numerics.py): kept so flight-
        # recorder dumps carry the per-layer stats + first-non-finite
        # verdict of the moment things went wrong
        self.last_numerics = None
        self._slo_eval_warned_t = -float("inf")

    # -- instrument passthrough (the API components actually use) ------

    def counter(self, name: str) -> Counter:
        return self.registry.counter(name)

    def gauge(self, name: str) -> Gauge:
        return self.registry.gauge(name)

    def gauge_fn(self, name: str, fn) -> None:
        self.registry.gauge_fn(name, fn)

    def histogram(self, name: str, edges=None) -> Histogram:
        return self.registry.histogram(name, edges)

    def observe(self, name: str, value: float, edges=None) -> None:
        """Record one raw latency/value sample: the fixed-bin histogram
        plus every value observer (SLO streaming digests)."""
        self.registry.record_value(name, value, edges)

    def span(self, name: str, *, step: int | None = None, **meta: Any):
        return self.registry.span(name, step=step, **meta)

    def phases(self, prefix: str, *, step: int | None = None) -> PhaseTimeline:
        return self.registry.phases(prefix, step=step)

    def set_step(self, step: int | None) -> None:
        """Tag subsequent spans from step-unaware components (executor,
        checkpointer IO) with the loop's current step."""
        self.registry.current_step = step

    def reset_instruments(self) -> None:
        """Drop all counters/gauges/histograms (sinks stay attached) —
        bench harnesses call this between measurement windows so each
        flush snapshot covers exactly one window."""
        self.registry.reset_instruments()

    # -- sinks ---------------------------------------------------------

    def add_sink(self, sink: TelemetrySink) -> TelemetrySink:
        with self._lock:
            self._sinks.append(sink)
        return sink

    def remove_sink(self, sink: TelemetrySink, *, close: bool = True) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
        if close:
            sink.close()

    @property
    def sinks(self) -> tuple[TelemetrySink, ...]:
        with self._lock:
            return tuple(self._sinks)

    def _on_span(self, span: Span) -> None:
        for sink in self.sinks:
            sink.on_span(span)

    def record_executable(self, record: dict[str, Any]) -> None:
        """Stream one per-executable introspection record (compile cost,
        FLOPs, HBM breakdown — telemetry/introspect.py) to every sink as
        a schema-v2 ``executable`` event."""
        for sink in self.sinks:
            sink.on_executable(record)

    def record_request_trace(self, record: dict[str, Any]) -> None:
        """Stream one per-request milestone (schema v3 ``request_trace``,
        docs/design/observability.md) to every sink. With no sinks
        attached this is a loop over an empty tuple — the serving hot
        path pays nothing for tracing it isn't exporting."""
        for sink in self.sinks:
            sink.on_request_trace(record)

    def record_numerics(self, record: dict[str, Any]) -> None:
        """Stream one per-layer numerics window (schema v4 ``numerics``,
        telemetry/numerics.py) to every sink, and keep it as the hub's
        ``last_numerics`` so flight-recorder dumps carry the window."""
        self.last_numerics = record
        for sink in self.sinks:
            sink.on_numerics(record)

    def record_host_stacks(self, record: dict[str, Any]) -> None:
        """Stream one folded controller-stack window (schema v5
        ``host_stacks``, telemetry/host_sampler.py) to every sink —
        emitted once per profiling capture window, never on the step
        path."""
        for sink in self.sinks:
            sink.on_host_stacks(record)

    def flush(self, step: int | None = None) -> dict[str, Any]:
        """Snapshot every instrument and hand it to each sink; returns
        the snapshot (callers fold headline values into their own logs).
        Each flush also (a) evaluates the attached SLO monitor first, so
        slo/* instruments in the snapshot are current, and (b) appends
        the snapshot to the registry's flight-recorder ring."""
        if self.slo_monitor is not None:
            try:
                self.slo_monitor.evaluate()
            except Exception:  # noqa: BLE001 — SLO eval must not kill flush
                # rate-limited log: a broken policy silently freezing the
                # slo/* surface would be invisible on scraper-less jobs
                now = _time.monotonic()
                if now - self._slo_eval_warned_t >= 60.0:
                    self._slo_eval_warned_t = now
                    logging.getLogger("d9d_tpu.telemetry").exception(
                        "SLO evaluation failed during flush; slo/* "
                        "instruments are stale until this is fixed"
                    )
        snapshot = self.registry.snapshot()
        self.registry.flush_ring.append({
            "unix_time": _time.time(),
            "step": step,
            "snapshot": snapshot,
        })
        for sink in self.sinks:
            sink.on_flush(snapshot, step)
        return snapshot

    def dump_flight_record(self, event: str, *, extra=None):
        """Dump the flight-recorder ring (recent flush windows + span
        tail + executable inventory) as ``flight_recorder_{event}.json``
        — a no-op returning None until a recorder is configured
        (:meth:`configure_flight_recorder`). Never raises: the recorder
        exists to observe failures, not to cause new ones."""
        if self.flight_recorder is None:
            return None
        try:
            return self.flight_recorder.dump(
                event, self.registry, extra=extra,
                numerics=self.last_numerics,
            )
        except Exception:  # noqa: BLE001 — see docstring
            return None

    def configure_flight_recorder(self, directory, **kwargs):
        """Install a :class:`FlightRecorder` writing into ``directory``;
        returns it. Idempotent per directory: re-configuring the same
        directory keeps the existing recorder (and its per-event
        rate-limit state — a second Trainer over the same telemetry dir
        must not reset the one-dump-per-interval guarantee)."""
        from pathlib import Path

        from d9d_tpu.telemetry.flight_recorder import FlightRecorder

        if (
            self.flight_recorder is not None
            and self.flight_recorder.directory == Path(directory)
        ):
            return self.flight_recorder
        self.flight_recorder = FlightRecorder(directory, **kwargs)
        return self.flight_recorder

    def close(self) -> None:
        for sink in self.sinks:
            self.remove_sink(sink)


_default: Telemetry | None = None
_default_lock = threading.Lock()


def get_telemetry() -> Telemetry:
    """The process-local hub every instrumented component records into."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Telemetry()
    return _default


def set_telemetry(hub: Telemetry) -> Telemetry:
    """Replace the process hub (tests, embedders); returns the new hub."""
    global _default
    with _default_lock:
        _default = hub
    return hub


# imported AFTER get_telemetry exists: introspect records through the hub
# (deferred inside its methods), and re-exporting here keeps the public
# surface one import wide
from d9d_tpu.telemetry.introspect import (  # noqa: E402
    recompile_guard,
    tracked_jit,
)
from d9d_tpu.telemetry.export import (  # noqa: E402
    MetricsServer,
    render_prometheus,
)
from d9d_tpu.telemetry.flight_recorder import FlightRecorder  # noqa: E402
from d9d_tpu.telemetry.host_sampler import HostSampler  # noqa: E402
from d9d_tpu.telemetry.slo import (  # noqa: E402
    SloMonitor,
    SloPolicy,
    StreamingQuantileDigest,
)
from d9d_tpu.telemetry.numerics import (  # noqa: E402
    DriftPolicy,
    NumericsMonitor,
    RollingBaseline,
    TrainDriftMonitor,
    default_drift_policies,
)


@contextlib.contextmanager
def attached_jsonl_sink(directory, *, run_name: str):
    """Attach a :class:`JsonlSink` for ``directory`` to the process hub
    for the duration and remove it on exit; flush cadence stays with the
    caller. Yields ``(hub, sink)`` — ``sink`` is ``None`` and nothing is
    attached when ``directory`` is falsy, so env-gated bench harnesses
    share one code path either way."""
    hub = get_telemetry()
    if not directory:
        yield hub, None
        return
    import jax  # deferred (process_index): the package core stays jax-free

    sink = hub.add_sink(JsonlSink(
        directory, run_name=run_name, process_index=jax.process_index(),
    ))
    try:
        yield hub, sink
    finally:
        hub.remove_sink(sink)
