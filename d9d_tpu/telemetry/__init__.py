"""Always-on runtime telemetry: registry + sinks + the process hub.

Usage shape (see docs/design/observability.md):

- Instrumented components (trainer, pipeline executor, serving batcher,
  checkpointer, data loader) call :func:`get_telemetry` and record into
  its registry. The hub always exists; with no sinks attached the cost
  is a few host-clock reads per region and in-memory accumulation.
- A driver (``Trainer`` via its config, bench harnesses via
  ``D9D_TELEMETRY_DIR``) attaches sinks — JSONL event log, tracker
  bridge, console summary — and calls :meth:`Telemetry.flush` on its
  metric cadence.
- Tests and embedders may install a fresh hub with :func:`set_telemetry`
  to isolate their measurements.

Metric namespace (enforced by convention, documented in the design doc):
``train/*`` trainer loop, ``pp/*`` pipeline executor, ``serve/*``
continuous batching, ``io/*`` checkpoint + data IO.
"""

import contextlib
import threading
from typing import Any

from d9d_tpu.telemetry.flops import (
    active_param_count,
    device_peak_flops,
    model_flops_per_token,
)
from d9d_tpu.telemetry.registry import (
    SCHEMA_VERSION,
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    PhaseTimeline,
    Span,
    exp_edges,
)
from d9d_tpu.telemetry.sinks import (
    ConsoleSink,
    JsonlSink,
    TelemetrySink,
    TrackerBridge,
    iter_events,
    validate_event,
)

__all__ = [
    "SCHEMA_VERSION",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "PhaseTimeline",
    "Span",
    "Telemetry",
    "TelemetrySink",
    "JsonlSink",
    "TrackerBridge",
    "ConsoleSink",
    "exp_edges",
    "get_telemetry",
    "set_telemetry",
    "attached_jsonl_sink",
    "iter_events",
    "validate_event",
    "model_flops_per_token",
    "active_param_count",
    "device_peak_flops",
    "tracked_jit",
    "recompile_guard",
]


class Telemetry:
    """One registry + its attached sinks.

    Spans stream to sinks as they complete (via a registry observer);
    counters/gauges/histograms reach sinks only on :meth:`flush` — the
    metric-collector cadence, so the hot loop never serializes a
    snapshot per step.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self.registry.span_observers.append(self._on_span)
        self._sinks: list[TelemetrySink] = []
        self._lock = threading.Lock()

    # -- instrument passthrough (the API components actually use) ------

    def counter(self, name: str) -> Counter:
        return self.registry.counter(name)

    def gauge(self, name: str) -> Gauge:
        return self.registry.gauge(name)

    def gauge_fn(self, name: str, fn) -> None:
        self.registry.gauge_fn(name, fn)

    def histogram(self, name: str, edges=None) -> Histogram:
        return self.registry.histogram(name, edges)

    def span(self, name: str, *, step: int | None = None, **meta: Any):
        return self.registry.span(name, step=step, **meta)

    def phases(self, prefix: str, *, step: int | None = None) -> PhaseTimeline:
        return self.registry.phases(prefix, step=step)

    def set_step(self, step: int | None) -> None:
        """Tag subsequent spans from step-unaware components (executor,
        checkpointer IO) with the loop's current step."""
        self.registry.current_step = step

    def reset_instruments(self) -> None:
        """Drop all counters/gauges/histograms (sinks stay attached) —
        bench harnesses call this between measurement windows so each
        flush snapshot covers exactly one window."""
        self.registry.reset_instruments()

    # -- sinks ---------------------------------------------------------

    def add_sink(self, sink: TelemetrySink) -> TelemetrySink:
        with self._lock:
            self._sinks.append(sink)
        return sink

    def remove_sink(self, sink: TelemetrySink, *, close: bool = True) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
        if close:
            sink.close()

    @property
    def sinks(self) -> tuple[TelemetrySink, ...]:
        with self._lock:
            return tuple(self._sinks)

    def _on_span(self, span: Span) -> None:
        for sink in self.sinks:
            sink.on_span(span)

    def record_executable(self, record: dict[str, Any]) -> None:
        """Stream one per-executable introspection record (compile cost,
        FLOPs, HBM breakdown — telemetry/introspect.py) to every sink as
        a schema-v2 ``executable`` event."""
        for sink in self.sinks:
            sink.on_executable(record)

    def flush(self, step: int | None = None) -> dict[str, Any]:
        """Snapshot every instrument and hand it to each sink; returns
        the snapshot (callers fold headline values into their own logs)."""
        snapshot = self.registry.snapshot()
        for sink in self.sinks:
            sink.on_flush(snapshot, step)
        return snapshot

    def close(self) -> None:
        for sink in self.sinks:
            self.remove_sink(sink)


_default: Telemetry | None = None
_default_lock = threading.Lock()


def get_telemetry() -> Telemetry:
    """The process-local hub every instrumented component records into."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Telemetry()
    return _default


def set_telemetry(hub: Telemetry) -> Telemetry:
    """Replace the process hub (tests, embedders); returns the new hub."""
    global _default
    with _default_lock:
        _default = hub
    return hub


# imported AFTER get_telemetry exists: introspect records through the hub
# (deferred inside its methods), and re-exporting here keeps the public
# surface one import wide
from d9d_tpu.telemetry.introspect import (  # noqa: E402
    recompile_guard,
    tracked_jit,
)


@contextlib.contextmanager
def attached_jsonl_sink(directory, *, run_name: str):
    """Attach a :class:`JsonlSink` for ``directory`` to the process hub
    for the duration and remove it on exit; flush cadence stays with the
    caller. Yields ``(hub, sink)`` — ``sink`` is ``None`` and nothing is
    attached when ``directory`` is falsy, so env-gated bench harnesses
    share one code path either way."""
    hub = get_telemetry()
    if not directory:
        yield hub, None
        return
    import jax  # deferred (process_index): the package core stays jax-free

    sink = hub.add_sink(JsonlSink(
        directory, run_name=run_name, process_index=jax.process_index(),
    ))
    try:
        yield hub, sink
    finally:
        hub.remove_sink(sink)
