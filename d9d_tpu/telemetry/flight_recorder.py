"""Anomaly flight recorder: dump the recent telemetry history when
something goes wrong (docs/design/observability.md).

The registry keeps a bounded ring of recent flush snapshots
(``MetricRegistry.flush_ring``, appended by ``Telemetry.flush``) and a
bounded span timeline. When a failure path fires — the anomaly guard
sees a non-finite step, the serving drain-stall watchdog trips, a fleet
replica dies mid-drain — the recorder serializes that history as
``flight_recorder_{event}.json`` next to the telemetry directory: the
last N metric windows, the span tail, the instruments' current values,
and the executable inventory (``telemetry/introspect.py``) *at the
moment things went wrong*. Post-mortem starts from the crash site's own
black box instead of re-running the failure under instrumentation.

Dumps are rate-limited per event kind (a NaN storm produces one dump per
interval, not one per step) and never raise into the failing code path
— the recorder observes failures, it must not compound them.
"""

import json
import logging
import time
from pathlib import Path
from typing import Any

__all__ = ["FlightRecorder"]

logger = logging.getLogger("d9d_tpu.telemetry")


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON coercion: telemetry snapshots are plain dicts of
    floats already; anything exotic (inf, numpy scalars) degrades to
    ``repr`` rather than failing the dump."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        try:
            return float(obj)
        except (TypeError, ValueError):
            return repr(obj)


class FlightRecorder:
    """Serialize the registry's recent history on failure events.

    ``directory`` is where the dumps land (the trainer points this next
    to its telemetry dir — ``Path(telemetry_dir).parent``); it is
    created on first dump, not at construction, so configuring the
    recorder costs nothing on healthy runs.
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        span_tail: int = 256,
        min_interval_s: float = 30.0,
    ):
        self.directory = Path(directory)
        self.span_tail = int(span_tail)
        self.min_interval_s = float(min_interval_s)
        self._last_dump: dict[str, float] = {}
        # optional profiling capture hook (``(event) -> Path | None``,
        # trainer-wired to ``JobProfiler.capture``): when set, each dump
        # additionally kicks off a short on-demand profile so an
        # SLO-burn or anomaly record carries device+host timeline
        # evidence, not just metric windows. Best-effort like everything
        # here — a hook failure or a busy profiler degrades to "no
        # capture", never to a failed dump.
        self.capture_hook = None

    def dump(
        self,
        event: str,
        registry,
        *,
        extra: dict | None = None,
        numerics: dict | None = None,
    ) -> Path | None:
        """Write ``flight_recorder_{event}.json``; returns the path, or
        None when rate-limited. Never raises (logged instead)."""
        now = time.monotonic()
        last = self._last_dump.get(event)
        if last is not None and now - last < self.min_interval_s:
            return None
        self._last_dump[event] = now
        try:
            capture = None
            if self.capture_hook is not None:
                try:
                    capture = self.capture_hook(event)
                except Exception:  # noqa: BLE001 — the dump proceeds
                    logger.exception(
                        "flight recorder: capture hook for %r failed",
                        event,
                    )
            spans = list(registry.spans)[-self.span_tail:]
            try:
                from d9d_tpu.telemetry.introspect import inventory

                executables = [r.event() for r in inventory()]
            except Exception:  # noqa: BLE001 — inventory is best-effort
                executables = []
            record = {
                "kind": "flight_record",
                "event": event,
                "unix_time": time.time(),
                "windows": _jsonable(list(registry.flush_ring)),
                "current": _jsonable(registry.snapshot()),
                "spans": [
                    {
                        "name": s.name, "t0": s.t0, "dur_s": s.dur_s,
                        **({"step": s.step} if s.step is not None else {}),
                        **({"meta": _jsonable(s.meta)} if s.meta else {}),
                    }
                    for s in spans
                ],
                "executables": _jsonable(executables),
                # last numerics window (telemetry/numerics.py): the
                # per-layer stats + first-non-finite verdict of the
                # moment things went wrong — the "where", next to the
                # flush ring's "when"
                **({"numerics": _jsonable(numerics)} if numerics else {}),
                **({"extra": _jsonable(extra)} if extra else {}),
                **(
                    {"profile_capture": str(capture)}
                    if capture is not None else {}
                ),
            }
            self.directory.mkdir(parents=True, exist_ok=True)
            path = self.directory / f"flight_recorder_{event}.json"
            with open(path, "w") as fh:
                json.dump(record, fh, indent=1, sort_keys=True)
            logger.warning("flight recorder: dumped %s -> %s", event, path)
            return path
        except Exception:  # noqa: BLE001 — see module docstring
            logger.exception("flight recorder: dump for %r failed", event)
            return None
