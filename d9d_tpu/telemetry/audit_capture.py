"""Compile-time artifact capture for the d9d-audit contract checker.

``d9d-lint`` (tools/lint/) enforces invariants the *source* can show;
the bugs that have cost this repo the most only become checkable facts
in the **lowered artifact**: params baked as jit constants, a donation
XLA silently dropped (double-buffered KV pool), a sharding constraint
whose collective schedule drifted, an f64 op smuggled in by a Python
float. This module harvests those facts at the one moment they exist
and cost nothing to read — inside ``TrackedJit._compile``, between
``lower()`` and the first dispatch:

- **collective census** — every all-reduce / all-gather / reduce-scatter
  / all-to-all / collective-permute op in the *post-SPMD optimized* HLO
  (``compiled.as_text()``), i.e. the schedule XLA actually runs, not the
  one the source hoped for;
- **donation coverage** — declared donated buffers (from the wrapper's
  ``donate_argnums``/``donate_argnames`` against the concrete call
  arguments) vs the ``input_output_alias`` pairs the compiled module
  header actually carries;
- **baked constants** — the closed jaxpr's ``consts`` (closure-captured
  arrays the trace embedded into the program) with byte sizes;
- **dtype census** — per-primitive output dtypes from the jaxpr, plus
  the two disciplined classes: any f64 aval, and f32 matmuls
  (``dot_general``/conv) that a bf16-compute program must not contain;
- **host callbacks** — callback primitives in the jaxpr (a hot
  executable with a host round-trip is a dispatch-contract breach).

Capture is **opt-in** (``D9D_AUDIT_CAPTURE=1`` or :func:`enable`) and
runs at compile time only: the steady-state call path is byte-identical
with it on or off — zero added dispatches, zero readbacks (pinned in
tests/tools/test_audit_clean.py). With capture on, the only delta is
that the AOT path goes ``trace() → lower()`` instead of ``lower()``
directly (the same trace jax performs inside ``lower()``, split so the
jaxpr is inspectable).

Facts ride the inventory (``ExecutableRecord.audit``) and the schema
``executable`` JSONL event as an optional ``audit`` block; the checker
in ``tools/audit/`` turns them into violations against the committed
``AUDIT_BASELINE.json``. A process-wide *context label*
(:func:`context`) tags which harness leg compiled an executable, so one
name ("train_step") can carry different contracts under different
configurations (plain vs ZeRO).

Stdlib-only at module load (the telemetry package core stays jax-free);
jax types are only touched through the objects handed in.
"""

import contextlib
import dataclasses
import math
import os
import re
import threading
from typing import Any

__all__ = [
    "AuditFacts",
    "capture_enabled",
    "context",
    "current_context",
    "enable",
    "extract_facts",
]

# collective op kinds as they appear in optimized HLO text. Async pairs
# count once via the -start half; -done is bookkeeping for the same op.
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

# op-definition lines: `%name = <type> <kind>[-start](...)`. The result
# type is a single token for sync ops but a parenthesized tuple WITH
# SPACES for async (-start) and variadic collectives — `(f32[2]{0},
# f32[4]{0}) all-gather-start(` — and on TPU HLO the tuple carries
# NESTED parens from tiled-layout/memory-space annotations
# (`bf16[1024,8192]{1,0:T(8,128)}`), so the tuple alternative tolerates
# one nesting level. The `\(` anchor right after the kind keeps `-done`
# halves (and operand references like `%all-reduce.3,`) out of the
# count.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?:\((?:[^()]|\([^()]*\))*\)|\S+)\s+("
    + "|".join(re.escape(k) for k in COLLECTIVE_KINDS)
    + r")(-start)?\("
)

# jaxpr primitives that round-trip through the host
CALLBACK_PRIMITIVES = (
    "pure_callback",
    "io_callback",
    "debug_callback",
    "host_callback_call",
    "outside_call",
)

# the f32-disciplined op class: under a bf16_compute policy the heavy
# contractions must run in bf16 — f32 is allowlisted only for the cheap
# elementwise/reduction classes (grad accumulation, norms, masters)
MATMUL_PRIMITIVES = ("dot_general", "conv_general_dilated")


# -- opt-in flag + context label ----------------------------------------

_lock = threading.Lock()
_state: dict[str, Any] = {"enabled": None, "context": "default"}


def capture_enabled() -> bool:
    """True when artifact capture is on: programmatic :func:`enable`
    wins; otherwise the ``D9D_AUDIT_CAPTURE`` env var (bench legs)."""
    with _lock:
        if _state["enabled"] is not None:
            return _state["enabled"]
    return os.environ.get("D9D_AUDIT_CAPTURE", "") not in ("", "0")


def enable(on: bool = True) -> None:
    """Force capture on/off for this process (None-able via
    :func:`reset` semantics: ``enable(None)`` restores env control)."""
    with _lock:
        _state["enabled"] = on


@contextlib.contextmanager
def context(label: str):
    """Tag executables compiled inside the block with ``label`` — the
    audit manifest keys expectations by (context, executable name), so
    the same name can carry per-configuration contracts."""
    with _lock:
        prev = _state["context"]
        _state["context"] = label
    try:
        yield
    finally:
        with _lock:
            _state["context"] = prev


def current_context() -> str:
    """The active context label (``D9D_AUDIT_CONTEXT`` seeds the
    default for bench legs that can't wrap their compiles)."""
    with _lock:
        label = _state["context"]
    if label == "default":
        return os.environ.get("D9D_AUDIT_CONTEXT", "default")
    return label


# -- facts ---------------------------------------------------------------


@dataclasses.dataclass
class AuditFacts:
    """Artifact-level facts of one compiled executable (see module
    docstring for what each block witnesses)."""

    name: str
    context: str
    # post-SPMD optimized-HLO collective census: kind → op count
    collectives: dict[str, int]
    num_partitions: int
    # donation: declared at the call site vs aliased by the compiler
    donated_declared: int
    donated_bytes: int
    aliased_pairs: int
    # closed-jaxpr consts (closure-baked arrays), largest first
    consts: list[dict]  # {"bytes", "shape", "dtype"}, top _MAX_CONSTS
    const_bytes_total: int
    n_consts: int
    # jaxpr dtype census: dtype string → eqn-output count
    dtype_ops: dict[str, int]
    f64_ops: list[str]  # primitive names with an f64 operand/output
    f32_matmuls: int  # dot/conv eqns carrying f32
    callbacks: list[str]  # host-callback primitive names

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


_MAX_CONSTS = 8  # largest consts kept per executable (facts stay small)


def _collective_census(hlo_text: str) -> dict[str, int]:
    counts: dict[str, int] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        kind = m.group(1)
        counts[kind] = counts.get(kind, 0) + 1
    return counts


def _module_header(hlo_text: str) -> str:
    head = hlo_text.lstrip()
    nl = head.find("\n")
    return head if nl < 0 else head[:nl]


def _alias_pairs(hlo_text: str) -> int:
    """Number of input→output alias entries in the compiled module
    header (``input_output_alias={ {0}: (1, {}, may-alias), ... }``) —
    the donations XLA actually honored."""
    header = _module_header(hlo_text)
    start = header.find("input_output_alias={")
    if start < 0:
        return 0
    i = start + len("input_output_alias=")
    depth = 0
    end = i
    for j, ch in enumerate(header[i:], i):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    block = header[i:end]
    return block.count("-alias")  # may-alias | must-alias, one per pair


def _num_partitions(hlo_text: str) -> int:
    m = re.search(r"num_partitions=(\d+)", _module_header(hlo_text))
    return int(m.group(1)) if m else 1


def _array_leaves(tree) -> list:
    """Shape/dtype-bearing leaves of a pytree, without importing jax at
    module scope (deferred import; capture only runs when jax exists)."""
    import jax

    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    ]


def _leaf_bytes(leaf) -> int:
    itemsize = getattr(leaf.dtype, "itemsize", None)
    if itemsize is None:
        return 0
    return math.prod(leaf.shape) * itemsize if leaf.shape else itemsize


def _donated(args, kwargs, jit_kwargs) -> tuple[int, int]:
    """(buffer count, bytes) the call site declared donated — the
    coverage the compiled aliasing is checked against."""
    donate_argnums = jit_kwargs.get("donate_argnums", ())
    if isinstance(donate_argnums, int):
        donate_argnums = (donate_argnums,)
    donate_argnames = jit_kwargs.get("donate_argnames", ())
    if isinstance(donate_argnames, str):
        donate_argnames = (donate_argnames,)
    count = 0
    total = 0
    for i in donate_argnums:
        if i < len(args):
            for leaf in _array_leaves(args[i]):
                count += 1
                total += _leaf_bytes(leaf)
    for name in donate_argnames:
        if name in kwargs:
            for leaf in _array_leaves(kwargs[name]):
                count += 1
                total += _leaf_bytes(leaf)
    return count, total


def _iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and its sub-jaxprs (scan bodies, cond
    branches, pjit calls — anything an eqn param smuggles in)."""
    stack = [jaxpr]
    seen: set[int] = set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for value in eqn.params.values():
                stack.extend(_sub_jaxprs(value))


def _sub_jaxprs(value) -> list:
    out = []
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        out.append(value.jaxpr)  # ClosedJaxpr
    elif hasattr(value, "eqns"):
        out.append(value)  # raw Jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            out.extend(_sub_jaxprs(v))
    return out


def _eqn_dtypes(eqn) -> list:
    dts = []
    for var in list(eqn.invars) + list(eqn.outvars):
        dtype = getattr(getattr(var, "aval", None), "dtype", None)
        if dtype is not None:
            dts.append(dtype)
    return dts


def _jaxpr_census(closed_jaxpr) -> dict[str, Any]:
    """Const / dtype / callback facts from the closed jaxpr (the traced
    program before XLA touches it — platform-independent, so dtype
    discipline can't be confused by a backend's internal upcasts)."""
    consts = sorted(
        (
            {
                "bytes": _leaf_bytes(c),
                "shape": list(getattr(c, "shape", ())),
                "dtype": str(getattr(c, "dtype", "?")),
            }
            for c in closed_jaxpr.consts
            if hasattr(c, "shape") and hasattr(c, "dtype")
        ),
        key=lambda d: -d["bytes"],
    )
    dtype_ops: dict[str, int] = {}
    f64_ops: list[str] = []
    f32_matmuls = 0
    callbacks: list[str] = []
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        prim = eqn.primitive.name
        dts = _eqn_dtypes(eqn)
        for var in eqn.outvars:
            dtype = getattr(getattr(var, "aval", None), "dtype", None)
            if dtype is not None:
                key = str(dtype)
                dtype_ops[key] = dtype_ops.get(key, 0) + 1
        if any(str(dt) == "float64" for dt in dts):
            if prim not in f64_ops:
                f64_ops.append(prim)
        if prim in MATMUL_PRIMITIVES and any(
            str(dt) == "float32" for dt in dts
        ):
            f32_matmuls += 1
        if prim in CALLBACK_PRIMITIVES or "callback" in prim:
            if prim not in callbacks:
                callbacks.append(prim)
    return {
        "consts": consts[:_MAX_CONSTS],
        "const_bytes_total": sum(c["bytes"] for c in consts),
        "n_consts": len(consts),
        "dtype_ops": dtype_ops,
        "f64_ops": sorted(f64_ops),
        "f32_matmuls": f32_matmuls,
        "callbacks": sorted(callbacks),
    }


def extract_facts(
    name: str,
    *,
    closed_jaxpr,
    compiled_text: str,
    args=(),
    kwargs=None,
    jit_kwargs=None,
) -> AuditFacts:
    """Assemble one executable's :class:`AuditFacts`.

    ``closed_jaxpr`` may be None (a runtime without the ``trace()``
    stage): the jaxpr-derived blocks degrade to empty, the HLO-derived
    ones (collectives, aliasing) still land.
    """
    kwargs = kwargs or {}
    jit_kwargs = jit_kwargs or {}
    declared, donated_bytes = _donated(args, kwargs, jit_kwargs)
    jx = (
        _jaxpr_census(closed_jaxpr)
        if closed_jaxpr is not None
        else {
            "consts": [],
            "const_bytes_total": 0,
            "n_consts": 0,
            "dtype_ops": {},
            "f64_ops": [],
            "f32_matmuls": 0,
            "callbacks": [],
        }
    )
    return AuditFacts(
        name=name,
        context=current_context(),
        collectives=_collective_census(compiled_text),
        num_partitions=_num_partitions(compiled_text),
        donated_declared=declared,
        donated_bytes=donated_bytes,
        aliased_pairs=_alias_pairs(compiled_text),
        **jx,
    )
