"""Process-local runtime-metric primitives: counters, gauges, fixed-bin
histograms, and a monotonic span timeline.

The reference d9d design treats metric collection as a first-class loop
component; this package is its always-on runtime half — cheap enough to
stay enabled in production (a span costs two ``perf_counter`` calls, one
bisect, and a deque append; no jax import anywhere in the package). The
profiler traces (``core/tracing.py`` + ``JobProfiler``) remain the
capture-window microscope; this registry is the continuous signal an
operator watches and alerts on between captures.

Everything here is plain host Python. Device work is NEVER synchronized
to take a measurement — instrumented components time their *host*
interactions (dispatch, readback, staging, IO waits) and derive device
signals from values that were already coming back to the host anyway
(loss fetches, serving token readbacks).

Thread safety: one lock guards the instrument maps and the span
timeline (prefetch producers, checkpoint IO threads, and the main loop
share the registry); individual instrument updates ride the GIL —
telemetry tolerates a lost increment under contention, a lock per
``record`` would not be low-overhead.
"""

import bisect
import collections
import dataclasses
import math
import threading
import time
from typing import Any, Callable, Iterable

__all__ = [
    "SCHEMA_VERSION",
    "Counter",
    "Gauge",
    "Histogram",
    "Span",
    "MetricRegistry",
    "PhaseTimeline",
    "exp_edges",
]

# JSONL event-log schema version (docs/design/observability.md) — bump on
# any breaking change to event shapes emitted by sinks.JsonlSink.
# v2: adds the ``executable`` event kind (per-executable compile/HBM/FLOPs
# records from telemetry/introspect.py); v1 files remain readable.
# v3: adds the ``request_trace`` event kind (per-request serving
# milestones keyed by a fleet-stable trace id); v1/v2 files remain
# readable.
# v4: adds the ``numerics`` event kind (per-layer training tensor
# statistics windows from telemetry/numerics.py); v1-v3 files remain
# readable.
# v5: adds the ``host_stacks`` event kind (folded controller-thread
# stack samples from telemetry/host_sampler.py, one event per capture
# window); v1-v4 files remain readable.
SCHEMA_VERSION = 5


def exp_edges(lo: float, hi: float, bins: int) -> tuple[float, ...]:
    """``bins + 1`` log-spaced edges from ``lo`` to ``hi`` — the default
    shape for latency histograms (latencies span decades; linear bins
    waste resolution where it matters)."""
    if lo <= 0 or hi <= lo or bins < 1:
        raise ValueError(f"need 0 < lo < hi and bins >= 1, got {lo}, {hi}, {bins}")
    ratio = (hi / lo) ** (1.0 / bins)
    return tuple(lo * ratio**i for i in range(bins + 1))


# 1 µs .. 1000 s, 36 log bins: covers a fused-decode dispatch on a tiny
# CPU model through a multi-minute first-step compile in one shape
DEFAULT_LATENCY_EDGES = exp_edges(1e-6, 1e3, 36)


class Counter:
    """Monotonic accumulator (events, tokens, bytes)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def add(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Last-write-wins instantaneous value (tokens/s, MFU, queue depth)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = float("nan")

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed-bin histogram with running count/sum/min/max.

    ``edges`` are the ``len(counts) + 1`` bin boundaries (the shape the
    ``TrackerRun.track_histogram`` API takes). Values below the first
    edge land in bin 0, values at/above the last edge in the final bin —
    nothing is dropped, so ``sum(counts) == count`` always holds.
    """

    __slots__ = ("name", "edges", "counts", "count", "total", "min", "max")

    def __init__(self, name: str, edges: Iterable[float] = DEFAULT_LATENCY_EDGES):
        self.name = name
        self.edges = tuple(float(e) for e in edges)
        if len(self.edges) < 2 or any(
            b <= a for a, b in zip(self.edges, self.edges[1:])
        ):
            raise ValueError("edges must be >= 2 strictly increasing values")
        self.counts = [0] * (len(self.edges) - 1)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, v: float) -> None:
        v = float(v)
        # bisect over interior edges: < edges[1] -> bin 0, >= edges[-2] -> last
        i = bisect.bisect_right(self.edges, v, 1, len(self.edges) - 1) - 1
        self.counts[i] += 1
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else float("nan")

    def percentile(self, p: float) -> float:
        """Approximate percentile (``p`` in [0, 1]) by linear interpolation
        within the containing bin; exact at the recorded min/max ends."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        if self.count == 0:
            return float("nan")
        target = p * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                lo = max(self.edges[i], self.min)
                hi = min(self.edges[i + 1], self.max)
                frac = (target - seen) / c
                # clamp into [min, max]: samples land in the edge bins
                # even when they fall outside the edge range entirely,
                # where the bin-bounds interpolation runs backwards
                return min(max(lo + frac * (hi - lo), self.min), self.max)
            seen += c
        return self.max

    def snapshot(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "mean": self.total / self.count if self.count else None,
            "p50": self.percentile(0.5) if self.count else None,
            "p99": self.percentile(0.99) if self.count else None,
            "counts": list(self.counts),
            "edges": list(self.edges),
        }


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed timed region on the monotonic timeline."""

    name: str
    t0: float  # perf_counter seconds (monotonic, process-local origin)
    dur_s: float
    step: int | None = None
    meta: dict[str, Any] | None = None


class _SpanContext:
    __slots__ = ("_registry", "_name", "_step", "_meta", "_t0")

    def __init__(self, registry, name, step, meta):
        self._registry = registry
        self._name = name
        self._step = step
        self._meta = meta

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._registry.record_span(
            self._name, self._t0, t1 - self._t0, step=self._step,
            meta=self._meta,
        )
        return False


class MetricRegistry:
    """Named instruments + a bounded span timeline.

    ``span_observers`` fire synchronously on every completed span (the
    JSONL sink streams the timeline through one); keep observers cheap.
    """

    def __init__(
        self, *, timeline_capacity: int = 8192, flush_ring_capacity: int = 16
    ):
        self._lock = threading.Lock()
        self.counters: dict[str, Counter] = {}
        self.gauges: dict[str, Gauge] = {}
        self.gauge_fns: dict[str, Callable[[], float]] = {}
        self.histograms: dict[str, Histogram] = {}
        self.spans: collections.deque[Span] = collections.deque(
            maxlen=timeline_capacity
        )
        self.span_observers: list[Callable[[Span], None]] = []
        # raw-value observers, fired by record_value (the SLO layer's
        # streaming digests subscribe here — the fixed-bin histograms
        # are too coarse for tail SLOs, so digests need the raw samples)
        self.value_observers: list[Callable[[str, float], None]] = []
        # flight-recorder ring (docs/design/observability.md): the last N
        # flush snapshots, appended by Telemetry.flush — what the anomaly
        # flight recorder dumps when something goes wrong
        self.flush_ring: collections.deque[dict[str, Any]] = (
            collections.deque(maxlen=flush_ring_capacity)
        )
        # loop-global step tag: the trainer advances it; components that
        # have no step plumbed through (executor, checkpointer) stamp
        # their spans with it
        self.current_step: int | None = None

    # -- instruments ---------------------------------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self.counters.get(name)
            if c is None:
                c = self.counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self.gauges.get(name)
            if g is None:
                g = self.gauges[name] = Gauge(name)
            return g

    def gauge_fn(self, name: str, fn: Callable[[], float]) -> None:
        """Register a callable evaluated at snapshot time — for live
        rates that must stay honest when the instrumented component goes
        quiet (a last-write-wins gauge would freeze at its last healthy
        value through a stall). NaN return = absent; exceptions skip the
        gauge for that snapshot. Registrations survive
        ``reset_instruments`` (they are wiring, not accumulated state)."""
        with self._lock:
            self.gauge_fns[name] = fn

    def unregister_gauge_fn(self, name: str, fn=None) -> None:
        """Remove a callback gauge registration. With ``fn`` given, the
        removal only happens if the registration still points at that
        exact callable — a component renaming its gauge (replica
        labelling) must not tear down a different component's later
        registration under the same name."""
        with self._lock:
            cur = self.gauge_fns.get(name)
            if cur is not None and (fn is None or cur is fn):
                del self.gauge_fns[name]

    def histogram(
        self, name: str, edges: Iterable[float] | None = None
    ) -> Histogram:
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(
                    name, edges if edges is not None else DEFAULT_LATENCY_EDGES
                )
            return h

    def record_value(
        self, name: str, value: float, edges: Iterable[float] | None = None
    ) -> None:
        """Record one raw sample: feeds the fixed-bin histogram AND every
        registered value observer (the SLO layer's streaming quantile
        digests). Components whose latencies may carry tail SLOs record
        through this instead of ``histogram(...).record``."""
        self.histogram(name, edges).record(value)
        for obs in list(self.value_observers):
            obs(name, value)

    # -- timeline ------------------------------------------------------

    def span(
        self, name: str, *, step: int | None = None, **meta: Any
    ) -> _SpanContext:
        """Context manager timing one region; records a Span (and feeds
        the same-named histogram) on exit."""
        return _SpanContext(self, name, step, meta or None)

    def record_span(
        self,
        name: str,
        t0: float,
        dur_s: float,
        *,
        step: int | None = None,
        meta: dict[str, Any] | None = None,
    ) -> None:
        if step is None:
            step = self.current_step
        span = Span(name=name, t0=t0, dur_s=dur_s, step=step, meta=meta)
        self.histogram(name).record(dur_s)
        with self._lock:
            self.spans.append(span)
            observers = list(self.span_observers)
        for obs in observers:
            obs(span)

    def phases(self, prefix: str, *, step: int | None = None) -> "PhaseTimeline":
        return PhaseTimeline(self, prefix, step=step)

    def reset_instruments(self) -> None:
        """Drop every counter/gauge/histogram (the span timeline and
        observers stay). Bench harnesses call this between measurement
        windows so each flush snapshot covers exactly one window —
        instruments are re-looked-up by name on every record, so they
        simply reappear empty on next use."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()

    # -- snapshot ------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time copy of every instrument (cumulative values) —
        what sinks flush. Spans are NOT included (they stream through
        observers / stay on the in-memory timeline)."""
        with self._lock:
            counters = {n: c.value for n, c in self.counters.items()}
            gauges = {
                n: g.value
                for n, g in self.gauges.items()
                if not math.isnan(g.value)
            }
            histograms = {n: h.snapshot() for n, h in self.histograms.items()}
            fns = list(self.gauge_fns.items())
        for n, fn in fns:  # outside the lock: fns may touch the registry
            try:
                v = float(fn())
            except Exception:  # noqa: BLE001 — one bad fn must not kill flush
                continue
            if not math.isnan(v):
                gauges[n] = v
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }


class PhaseTimeline:
    """Contiguous named phases partitioning one interval — gap-free by
    construction, so the per-step phase breakdown always accounts for
    100% of the wall time between construction and ``close()``.

    ``mark(phase)`` closes the currently open phase at *now* and opens
    the next; ``close()`` ends the last phase and emits the enclosing
    ``{prefix}/step`` span.
    """

    def __init__(self, registry: MetricRegistry, prefix: str, *, step=None):
        self._registry = registry
        self._prefix = prefix
        self._step = step
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._closed = False

    def mark(self, phase: str) -> None:
        now = time.perf_counter()
        self._registry.record_span(
            # d9d-lint: disable=D9D006 — caller-prefixed ({train,bench}/phase/*, documented)
            f"{self._prefix}/phase/{phase}", self._last, now - self._last,
            step=self._step,
        )
        self._last = now

    def cancel(self) -> None:
        """Abandon the timeline without emitting anything — for intervals
        that turn out not to be a step at all (e.g. the data iterator
        raised StopIteration before any work ran), so span consumers
        never see a phantom ``{prefix}/step``."""
        self._closed = True

    def close(self, tail_phase: str | None = None) -> float:
        """Finish the timeline; returns the total wall seconds. Any time
        since the last ``mark`` is attributed to ``tail_phase`` (default
        ``other``) so nothing is left unaccounted."""
        if self._closed:
            return 0.0
        self._closed = True
        if tail_phase is None:
            tail_phase = "other"
        now = time.perf_counter()
        if now > self._last:
            self._registry.record_span(
                # d9d-lint: disable=D9D006 — caller-prefixed ({train,bench}/phase/*, documented)
                f"{self._prefix}/phase/{tail_phase}", self._last,
                now - self._last, step=self._step,
            )
        total = now - self._t0
        self._registry.record_span(
            # d9d-lint: disable=D9D006 — caller-prefixed ({train,bench}/step, documented)
            f"{self._prefix}/step", self._t0, total, step=self._step
        )
        return total
