"""Telemetry sinks: JSONL event log, tracker bridge, console summary.

Three composable consumers of one ``MetricRegistry`` (see
docs/design/observability.md for how they layer with trackers and
profiler traces):

- :class:`JsonlSink` — one schema-versioned event file per process.
  Spans stream in as they complete; instrument snapshots land as
  ``flush`` events on the flush cadence. Writes stay on the Python
  buffered-IO layer (no per-event fsync/flush) so a span costs ~a dict
  + one buffered ``write``.
- :class:`TrackerBridge` — flushes counters/gauges as scalars and
  histograms through the existing ``TrackerRun`` scalar/histogram API,
  on the metric-collector cadence. Values are cumulative-since-start
  (the tracker UI differentiates; the JSONL log carries the same
  snapshots for offline rate computation).
- :class:`ConsoleSink` — a periodic one-line summary through
  ``logging`` for operators tailing the job log, rate-limited by wall
  seconds so a tight flush cadence cannot spam the console.
"""

import json
import logging
import math
import os
import time
from pathlib import Path
from typing import Any, Iterator, TextIO

from d9d_tpu.telemetry.registry import SCHEMA_VERSION, Span

__all__ = [
    "TelemetrySink",
    "JsonlSink",
    "TrackerBridge",
    "ConsoleSink",
    "iter_events",
    "validate_event",
]

logger = logging.getLogger("d9d_tpu.telemetry")


class TelemetrySink:
    """Interface; all hooks optional."""

    def on_span(self, span: Span) -> None: ...

    def on_flush(self, snapshot: dict[str, Any], step: int | None) -> None: ...

    def on_executable(self, record: dict[str, Any]) -> None: ...

    def on_request_trace(self, record: dict[str, Any]) -> None: ...

    def on_numerics(self, record: dict[str, Any]) -> None: ...

    def on_host_stacks(self, record: dict[str, Any]) -> None: ...

    def close(self) -> None: ...


def _finite_or_none(v):
    if v is None:
        return None
    v = float(v)
    return v if math.isfinite(v) else None


class JsonlSink(TelemetrySink):
    """Appends one JSON object per line to ``{dir}/{run}_proc{i}.jsonl``.

    The first line is a ``meta`` event carrying the schema version and
    process identity; every subsequent event is ``span`` or ``flush``.
    ``process_index`` is injected by the caller (the hub) so this module
    never imports jax.
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        run_name: str = "telemetry",
        process_index: int = 0,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.path = self.directory / f"{run_name}_proc{process_index}.jsonl"
        self._process_index = process_index
        self._fh: TextIO | None = None

    def _file(self) -> TextIO:
        if self._fh is None:
            self._fh = open(self.path, "a")
            self._write(
                {
                    "kind": "meta",
                    "schema": SCHEMA_VERSION,
                    "process_index": self._process_index,
                    "pid": os.getpid(),
                    "unix_time": time.time(),
                    "perf_counter": time.perf_counter(),
                }
            )
        return self._fh

    def _write(self, obj: dict[str, Any]) -> None:
        fh = self._fh if self._fh is not None else self._file()
        fh.write(json.dumps(obj) + "\n")

    def on_span(self, span: Span) -> None:
        ev: dict[str, Any] = {
            "kind": "span",
            "name": span.name,
            "t0": span.t0,
            "dur_s": span.dur_s,
        }
        if span.step is not None:
            ev["step"] = span.step
        if span.meta:
            ev["meta"] = span.meta
        self._write(ev)

    def on_executable(self, record: dict[str, Any]) -> None:
        # compiles are rare and expensive — flush immediately so a crash
        # right after a multi-minute compile still leaves its record
        self._write({"kind": "executable", **record})
        self._fh.flush()

    def on_request_trace(self, record: dict[str, Any]) -> None:
        # per-request milestones (schema v3): buffered like spans — a
        # handful of events per request, flushed on the flush cadence
        self._write({"kind": "request_trace", **record})

    def on_numerics(self, record: dict[str, Any]) -> None:
        # per-layer numerics windows (schema v4): one event per cadence
        # window, buffered like spans (the flush cadence bounds loss)
        self._write({"kind": "numerics", **record})

    def on_host_stacks(self, record: dict[str, Any]) -> None:
        # folded controller-stack windows (schema v5): captures are rare
        # operator actions — flush immediately so a crash right after a
        # capture still leaves its samples on disk
        self._write({"kind": "host_stacks", **record})
        self._fh.flush()

    def on_flush(self, snapshot: dict[str, Any], step: int | None) -> None:
        self._file()  # ensure the meta header exists even for span-free runs
        self._write(
            {
                "kind": "flush",
                "step": step,
                "unix_time": time.time(),
                "counters": snapshot["counters"],
                "gauges": {
                    k: _finite_or_none(v)
                    for k, v in snapshot["gauges"].items()
                },
                "histograms": {
                    k: {
                        "count": h["count"],
                        "sum": h["sum"],
                        "min": h["min"],
                        "max": h["max"],
                        "p50": h["p50"],
                        "p99": h["p99"],
                    }
                    for k, h in snapshot["histograms"].items()
                },
            }
        )
        self._fh.flush()  # flush events bound how much a crash can lose

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class TrackerBridge(TelemetrySink):
    """Pushes registry snapshots into an open ``TrackerRun``.

    Scalars land as ``{name}`` (names already carry the ``train/ pp/
    serve/ io/`` namespace); histograms go through
    ``track_histogram`` with their fixed bin edges, plus a ``{name}/p50``
    scalar so percentile trends are plottable without histogram support.
    """

    def __init__(self, run, *, context: dict[str, str] | None = None):
        self.run = run
        self.context = context or {"subset": "telemetry"}

    def on_flush(self, snapshot: dict[str, Any], step: int | None) -> None:
        step = step if step is not None else 0
        for name, value in snapshot["counters"].items():
            self.run.track_scalar(name, value, step=step, context=self.context)
        for name, value in snapshot["gauges"].items():
            if math.isfinite(value):
                self.run.track_scalar(
                    name, value, step=step, context=self.context
                )
        for name, h in snapshot["histograms"].items():
            if h["count"] == 0:
                continue
            self.run.track_histogram(
                name, h["counts"], h["edges"], step=step, context=self.context
            )
            if h["p50"] is not None:
                self.run.track_scalar(
                    f"{name}/p50", h["p50"], step=step, context=self.context
                )

    def close(self) -> None:
        pass  # the run is owned by the trainer, not the bridge


class ConsoleSink(TelemetrySink):
    """One-line operator summary per flush, at most every ``min_interval_s``
    wall seconds. Picks the handful of headline values an operator wants
    on a tailing terminal; the full detail lives in the JSONL/tracker."""

    _HEADLINE_GAUGES = (
        "train/tokens_per_s",
        "train/mfu",
        "serve/tokens_per_s",
        "serve/slot_utilization",
        # fleet rollups (resilience/elastic.ServingFleet): present only
        # while a fleet is active, so single-batcher jobs pay no line width
        "serve/fleet_replicas",
        "serve/fleet_queue_depth",
        "serve/fleet_tokens_per_s",
    )
    _HEADLINE_HISTS = (
        "train/step",
        "serve/ttft_s",
        "serve/tpot_s",
    )

    def __init__(self, *, min_interval_s: float = 30.0):
        self.min_interval_s = min_interval_s
        # first flush always emits; the interval only rate-limits repeats
        self._last_emit = -math.inf

    def on_flush(self, snapshot: dict[str, Any], step: int | None) -> None:
        now = time.monotonic()
        if now - self._last_emit < self.min_interval_s:
            return
        self._last_emit = now
        parts = [f"step={step}" if step is not None else "step=?"]
        gauges = snapshot["gauges"]
        for name in self._HEADLINE_GAUGES:
            v = gauges.get(name)
            if v is not None and math.isfinite(v):
                parts.append(f"{name.split('/', 1)[1]}={v:.4g}")
        hists = snapshot["histograms"]
        for name in self._HEADLINE_HISTS:
            h = hists.get(name)
            if h and h["count"]:
                parts.append(
                    f"{name.split('/', 1)[1]}"
                    f"[p50={h['p50']:.4g}s p99={h['p99']:.4g}s]"
                )
        # SLO status (telemetry/slo.py): one word on the headline — the
        # operator's console must say "burning" without a dashboard
        burning = gauges.get("slo/burning")
        if burning is not None and math.isfinite(burning):
            violations = snapshot["counters"].get("slo/violations", 0)
            parts.append(
                "slo=ok" if burning == 0
                else f"slo=BURNING({int(burning)} policy(ies), "
                     f"{int(violations)} violation(s))"
            )
        logger.info("telemetry %s", " ".join(parts))


# -- JSONL schema helpers (shared by tests and offline tooling) ---------

_REQUIRED = {
    "meta": ("schema", "process_index"),
    "span": ("name", "t0", "dur_s"),
    "flush": ("step", "counters", "gauges", "histograms"),
    "executable": ("name", "signature", "lower_s", "compile_s"),
    "request_trace": ("trace_id", "event", "t"),
    "numerics": ("step", "rows"),
    "host_stacks": ("t0", "dur_s", "stacks"),
}


def validate_event(event: dict[str, Any]) -> None:
    """Raise ``ValueError`` if ``event`` is not a well-formed telemetry
    event (the contract bench harness tests pin). Files written by any
    schema version up to the current one stay readable — v2 added the
    ``executable`` kind, v3 the ``request_trace`` kind, v4 the
    ``numerics`` kind and v5 the ``host_stacks`` kind, which older
    files simply never contain."""
    kind = event.get("kind")
    if kind not in _REQUIRED:
        raise ValueError(f"unknown event kind {kind!r}")
    missing = [k for k in _REQUIRED[kind] if k not in event]
    if missing:
        raise ValueError(f"{kind} event missing fields {missing}")
    if kind == "meta" and not (
        isinstance(event["schema"], int)
        and 1 <= event["schema"] <= SCHEMA_VERSION
    ):
        raise ValueError(
            f"schema {event['schema']} not in supported range "
            f"[1, {SCHEMA_VERSION}]"
        )
    if kind == "span" and not (
        isinstance(event["dur_s"], (int, float)) and event["dur_s"] >= 0
    ):
        raise ValueError("span dur_s must be a non-negative number")


def iter_events(path: str | Path) -> Iterator[dict[str, Any]]:
    """Parse + validate a telemetry JSONL file; the first event must be
    the schema ``meta`` header."""
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            event = json.loads(line)
            if i == 0 and event.get("kind") != "meta":
                raise ValueError("first event must be the meta header")
            validate_event(event)
            yield event
