"""Live metrics endpoint: Prometheus text rendering of the registry +
a stdlib ``http.server`` background thread serving ``/metrics``,
``/healthz`` and ``/readyz`` (docs/design/observability.md).

Until now every telemetry signal was process-local and post-hoc (JSONL
files, tracker runs, a rate-limited console line) — an operator could
not *scrape* a live replica. This module is the pull side of the
monitoring plane:

- :func:`render_prometheus` renders one registry snapshot in the
  Prometheus text exposition format (``text/plain; version=0.0.4``):
  counters and gauges become samples, fixed-bin histograms become
  cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.
  Replica-namespaced serving metrics (``serve/r{i}/...`` — the
  fleet's per-replica instruments) are folded into one metric family
  with a ``replica`` label, so fleet dashboards aggregate with ordinary
  PromQL instead of regexes.
- :class:`MetricsServer` serves it from a daemon thread. The scrape
  path is pure host work — a registry snapshot, gauge-fn evaluation and
  string formatting; it never touches jax, so the serving loop's
  zero-added-readbacks contract is structurally safe (and additionally
  gated by ``tools/bench_compare.py``'s exporter leg). ``/metrics``
  first evaluates the hub's attached SLO monitor (``telemetry/slo.py``)
  so scraped burn rates are current even if nothing has flushed.

Readiness contract (``/readyz``): the endpoint answers 503 until the
owning component reports ready — a ``ContinuousBatcher`` past its first
readback, a ``Trainer`` past its introspection warmup steps, a
``ServingFleet`` with at least one ready live replica (per-replica
detail rides ``/healthz``). Load balancers and schedulers gate traffic
on this, so "compiling" never reads as "serving".

Health contract (``/healthz``): liveness plus owner detail — a fleet
reports per-replica ``{live, retired, dead, ready, active}`` and, with
an ``FleetAutopilot`` bound, an ``autopilot`` block (burning policies,
burn/idle ages, pending canary, last decision) — one scrape explains
both what the fleet looks like and what the control loop is about to
do about it (docs/design/elasticity.md "SLO autopilot").

Lifecycle: opt-in via ``TrainerConfig.metrics_port``,
``ContinuousBatcher(metrics_port=...)`` or
``ServingFleet(metrics_port=...)``; ``port=0`` binds an ephemeral port
(tests; read it back from :attr:`MetricsServer.port`). Owners close the
server in their ``finally``/``close()`` paths.
"""

import json
import logging
import math
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

__all__ = [
    "MetricsServer",
    "render_prometheus",
]

logger = logging.getLogger("d9d_tpu.telemetry")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
# any path-free replica label (ContinuousBatcher._validate_label's
# contract), not just the fleet's r{i} — a custom "east1" label must
# fold into the same metric family as everyone else, or fleet PromQL
# aggregations silently exclude that replica
_REPLICA_RE = re.compile(r"^serve/([^/]+)/(.+)$")


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _family(name: str) -> tuple[str, dict[str, str]]:
    """Metric family + labels for a registry instrument name: the
    per-replica namespace ``serve/{label}/x`` folds into family
    ``serve/x`` with a ``replica`` label (the fleet's ``r{i}`` labels
    shorten to the index); everything else is label-free."""
    m = _REPLICA_RE.match(name)
    if m:
        label = m.group(1)
        if re.fullmatch(r"r\d+", label):
            label = label[1:]
        return f"serve/{m.group(2)}", {"replica": label}
    return name, {}


def _fmt_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.10g}"


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(
    snapshot: dict[str, Any], *, prefix: str = "d9d"
) -> str:
    """Render one ``MetricRegistry.snapshot()`` as Prometheus text
    exposition format. Deterministic ordering (sorted families) so two
    renders of the same snapshot are byte-identical."""
    # family → (type, [(sanitized sample suffix, labels, value)])
    families: dict[str, tuple[str, list]] = {}

    def fam(name: str, kind: str):
        base, labels = _family(name)
        key = f"{prefix}_{_sanitize(base)}" if prefix else _sanitize(base)
        entry = families.get(key)
        if entry is None:
            entry = families[key] = (kind, [])
        return key, labels, entry[1]

    for name, value in snapshot.get("counters", {}).items():
        key, labels, samples = fam(name, "counter")
        samples.append((key, labels, float(value)))
    for name, value in snapshot.get("gauges", {}).items():
        key, labels, samples = fam(name, "gauge")
        samples.append((key, labels, float(value)))
    for name, h in snapshot.get("histograms", {}).items():
        key, labels, samples = fam(name, "histogram")
        cum = 0
        # the registry's FINAL bin absorbs samples >= its upper edge
        # (nothing is dropped), so that edge cannot be claimed as a
        # `le` bound — a 10s latency in a 2s-top histogram must not
        # render as `le="2"`. The last finite bucket emitted is the
        # second-to-last edge; the final bin's contents are only
        # representable under +Inf.
        for edge, count in zip(h["edges"][1:-1], h["counts"][:-1]):
            cum += count
            samples.append((
                f"{key}_bucket",
                {**labels, "le": _fmt_value(float(edge))},
                float(cum),
            ))
        samples.append((f"{key}_bucket", {**labels, "le": "+Inf"},
                        float(h["count"])))
        samples.append((f"{key}_sum", labels, float(h["sum"])))
        samples.append((f"{key}_count", labels, float(h["count"])))

    lines: list[str] = []
    for key in sorted(families):
        kind, samples = families[key]
        lines.append(f"# TYPE {key} {kind}")
        for sample_name, labels, value in samples:
            lines.append(
                f"{sample_name}{_fmt_labels(labels)} {_fmt_value(value)}"
            )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Background HTTP exporter over one telemetry hub.

    ``readiness`` is a callable returning ``bool`` or ``(bool, detail
    dict)``; ``health`` a callable returning a JSON-serializable detail
    dict (per-replica status for a fleet). Both run inside scrape
    handling — keep them host-only and cheap. Exceptions in either
    degrade to unhealthy/unready responses, never to a dead endpoint.

    ``profile`` is the optional on-demand capture backend behind
    ``GET /debug/profile?duration_s=``: a callable taking the duration
    and returning the capture path, or ``None`` while a capture is
    already live (``JobProfiler.capture``'s exact contract). Without a
    backend the endpoint answers 404; requests are rate-limited to one
    per ``profile_min_interval_s`` (429), errors degrade to 500 — the
    endpoint never raises and never touches the step path.
    """

    def __init__(
        self,
        telemetry=None,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        readiness: Callable[[], Any] | None = None,
        health: Callable[[], dict] | None = None,
        profile: Callable[[float], Any] | None = None,
        profile_min_interval_s: float = 30.0,
        prefix: str = "d9d",
    ):
        if telemetry is None:
            from d9d_tpu.telemetry import get_telemetry

            telemetry = get_telemetry()
        self._tele = telemetry
        self._host = host
        self._want_port = int(port)
        self._readiness = readiness
        self._health = health
        self.profile = profile
        self.profile_min_interval_s = profile_min_interval_s
        self._profile_last_t = -math.inf
        self._prefix = prefix
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- endpoint bodies (shared with tests via direct calls) ----------

    def metrics_text(self) -> str:
        """The /metrics body: evaluate the attached SLO monitor (scraped
        burn rates stay current without a flush), then render."""
        monitor = getattr(self._tele, "slo_monitor", None)
        if monitor is not None:
            try:
                monitor.evaluate()
            except Exception:  # noqa: BLE001 — a bad policy must not 500
                logger.exception("SLO evaluation failed during scrape")
        return render_prometheus(
            self._tele.registry.snapshot(), prefix=self._prefix
        )

    def health_body(self) -> tuple[int, dict]:
        try:
            detail = self._health() if self._health is not None else {}
            return 200, {"status": "ok", **detail}
        except Exception as e:  # noqa: BLE001 — report, don't die
            return 500, {"status": "error", "error": repr(e)}

    def ready_body(self) -> tuple[int, dict]:
        try:
            out = self._readiness() if self._readiness is not None else True
        except Exception as e:  # noqa: BLE001 — not ready, with a reason
            return 503, {"ready": False, "error": repr(e)}
        ready, detail = (
            out if isinstance(out, tuple) else (out, {})
        )
        return (200 if ready else 503), {"ready": bool(ready), **detail}

    def profile_body(self, query: str) -> tuple[int, dict]:
        """The /debug/profile body. Status codes are the operator
        contract: 404 no backend wired, 400 bad duration, 429 rate
        limited, 503 a capture is already live, 500 backend error, 200
        with the capture path on success."""
        if self.profile is None:
            return 404, {"error": "no profiling backend wired"}
        try:
            params = urllib.parse.parse_qs(query)
            duration = float(params.get("duration_s", ["2.0"])[0])
        except (ValueError, TypeError):
            return 400, {"error": "duration_s must be a number"}
        if not (0.0 < duration <= 60.0):
            return 400, {
                "error": "duration_s must be in (0, 60]",
                "duration_s": duration,
            }
        now = time.monotonic()
        if now - self._profile_last_t < self.profile_min_interval_s:
            return 429, {
                "error": "rate limited",
                "retry_after_s": round(
                    self.profile_min_interval_s
                    - (now - self._profile_last_t), 1
                ),
            }
        try:
            out = self.profile(duration)
        except Exception as e:  # noqa: BLE001 — report, don't die
            logger.exception("on-demand profile capture failed")
            return 500, {"error": repr(e)}
        if out is None:
            return 503, {"busy": True, "error": "a capture is live"}
        self._profile_last_t = now
        return 200, {"capture": str(out), "duration_s": duration}

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "MetricsServer":
        if self._server is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: scrapes are periodic
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                try:
                    if path == "/metrics":
                        self._send(
                            200, outer.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        code, body = outer.health_body()
                        self._send(
                            code, json.dumps(body).encode(),
                            "application/json",
                        )
                    elif path == "/readyz":
                        code, body = outer.ready_body()
                        self._send(
                            code, json.dumps(body).encode(),
                            "application/json",
                        )
                    elif path == "/debug/profile":
                        code, body = outer.profile_body(query)
                        self._send(
                            code, json.dumps(body).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found", "text/plain")
                except BrokenPipeError:  # scraper went away mid-response
                    pass

        self._server = ThreadingHTTPServer(
            (self._host, self._want_port), Handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="d9d-metrics-server",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "metrics endpoint up at http://%s:%d/metrics",
            self._host, self.port,
        )
        return self

    @property
    def port(self) -> int:
        """The bound port (resolves ``port=0`` ephemeral binds)."""
        if self._server is None:
            return self._want_port
        return self._server.server_address[1]

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self._host}:{self.port}{path}"

    def close(self) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
