"""Checkpoint integrity manifests: write-after-finalize, validate-on-restore.

Orbax finalizes a step atomically (tmp dir → rename) but says nothing
about what is *inside* the directory: a machine that dies mid-write of
one tensorstore chunk, a filesystem that truncates on quota, or a stray
``rm`` leaves a step that lists as restorable and explodes (or worse,
half-loads) at restore time. The manifest closes that gap:

- After a save is durably finalized, :func:`write_manifest` records the
  full file inventory of the step directory — relative path + byte size
  for every file, plus a SHA-256 content checksum for small files (the
  JSON meta item, orbax/tensorstore index metadata). The manifest itself
  is written atomically (tmp + rename) *after* everything it describes.
- On restore, :func:`validate_checkpoint_dir` re-walks the directory and
  raises :class:`CheckpointIntegrityError` on any missing file, size
  mismatch, or checksum mismatch. The checkpointer walks back through
  the rotation history to the newest step that validates AND restores,
  instead of crashing on the newest directory.

A step directory without a manifest (pre-manifest checkpoints, or a
save whose process died between finalize and manifest write) is treated
as *unverified*, not invalid: restore still attempts it inside the same
walk-back guard, so a corrupt unverified step degrades to a fallback,
not a crash.

Schema v2 (docs/design/checkpointing.md, elasticity.md): the manifest
additionally records the **saving mesh** under ``"mesh"`` — MeshSpec
axis sizes (incl. ``dp_replicate``), device count, the
``zero_sharding`` setting and per-leaf sharding specs — so restore can
detect a topology mismatch *before* loading and route through the
resharding path. Versioning follows the telemetry schema's ≤-current
rule: v1 files (no ``version``-gated fields beyond the inventory) stay
fully readable; a manifest from a *newer* writer raises
:class:`ManifestVersionError` — which the restore walk-back treats as
"skip this step", never as confirmed corruption (a newer format must
not get an intact checkpoint pruned).
"""

import hashlib
import json
import logging
import os
from pathlib import Path
from typing import Any

logger = logging.getLogger("d9d_tpu.resilience")

MANIFEST_NAME = "d9d_manifest.json"
MANIFEST_VERSION = 2

# files at or under this size get full content checksums (the meta item
# and the orbax/tensorstore index files all qualify); bigger array chunk
# files are inventoried by size — truncation and deletion are caught,
# and the array payloads don't pay a full re-read on every save/restore
_CHECKSUM_MAX_BYTES = 4 * 1024 * 1024


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint step directory failed manifest validation."""


class ManifestVersionError(RuntimeError):
    """The manifest was written by a newer schema than this reader.

    Deliberately NOT a :class:`CheckpointIntegrityError`: the restore
    walk-back prunes integrity-confirmed corrupt steps, and a
    format-from-the-future checkpoint is (presumably) intact — it must
    be skipped, never deleted.
    """


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _inventory(step_dir: Path) -> list[dict[str, Any]]:
    files = []
    for path in sorted(step_dir.rglob("*")):
        if not path.is_file() or path.name == MANIFEST_NAME:
            continue
        size = path.stat().st_size
        entry: dict[str, Any] = {
            "path": path.relative_to(step_dir).as_posix(),
            "size": size,
        }
        if size <= _CHECKSUM_MAX_BYTES:
            entry["sha256"] = _sha256(path)
        files.append(entry)
    return files


def write_manifest(
    step_dir: str | Path, *, step: int, mesh: dict[str, Any] | None = None
) -> Path:
    """Inventory a *finalized* step directory and write its manifest
    atomically. ``mesh`` is the saving-topology block (v2 — see
    :func:`d9d_tpu.resilience.elastic.job_mesh_spec`). Returns the
    manifest path."""
    step_dir = Path(step_dir)
    manifest = {
        "version": MANIFEST_VERSION,
        "step": step,
        "files": _inventory(step_dir),
    }
    if mesh is not None:
        manifest["mesh"] = mesh
    path = step_dir / MANIFEST_NAME
    tmp = step_dir / (MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def read_manifest(step_dir: str | Path) -> dict[str, Any] | None:
    """The parsed manifest, or None when the step has none (unverified).

    Accepts any version ≤ :data:`MANIFEST_VERSION` (the telemetry
    schema's rule); raises :class:`ManifestVersionError` on a manifest
    from a newer writer.
    """
    path = Path(step_dir) / MANIFEST_NAME
    if not path.exists():
        return None
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointIntegrityError(
            f"unreadable checkpoint manifest {path}: {e}"
        ) from e
    version = int(manifest.get("version", 1))
    if version > MANIFEST_VERSION:
        raise ManifestVersionError(
            f"checkpoint manifest {path} has schema version {version}; "
            f"this reader supports <= {MANIFEST_VERSION}"
        )
    return manifest


def manifest_mesh(step_dir: str | Path) -> dict[str, Any] | None:
    """The saving-mesh block of a step's manifest, or None (pre-v2
    manifest, no manifest at all, or unreadable/newer manifest — mesh
    detection is best-effort; integrity validation stays strict)."""
    try:
        manifest = read_manifest(step_dir)
    except (CheckpointIntegrityError, ManifestVersionError):
        return None
    if manifest is None:
        return None
    return manifest.get("mesh")


def validate_checkpoint_dir(step_dir: str | Path) -> bool:
    """Validate a step directory against its manifest.

    Returns True when the manifest exists and every inventoried file
    matches (path present, size equal, checksum equal where recorded);
    False when no manifest exists (unverified — caller may still try
    it). Raises :class:`CheckpointIntegrityError` naming every problem
    when validation *fails*.
    """
    step_dir = Path(step_dir)
    if not step_dir.is_dir():
        raise CheckpointIntegrityError(f"checkpoint dir {step_dir} missing")
    manifest = read_manifest(step_dir)
    if manifest is None:
        return False
    problems: list[str] = []
    for entry in manifest["files"]:
        path = step_dir / entry["path"]
        if not path.is_file():
            problems.append(f"missing file {entry['path']}")
            continue
        size = path.stat().st_size
        if size != entry["size"]:
            problems.append(
                f"size mismatch {entry['path']}: "
                f"{size} != recorded {entry['size']}"
            )
            continue
        digest = entry.get("sha256")
        if digest is not None and _sha256(path) != digest:
            problems.append(f"checksum mismatch {entry['path']}")
    if problems:
        raise CheckpointIntegrityError(
            f"checkpoint {step_dir.name} failed integrity validation: "
            + "; ".join(problems)
        )
    return True
