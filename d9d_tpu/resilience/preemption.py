"""Preemption-safe exit: signal flag → boundary checkpoint → clean exit.

Cloud TPU pools preempt with SIGTERM and a grace window; operators
interrupt with SIGINT. Either way the right move is the same and the
single-controller host is the one place to make it: finish the step in
flight, write a *synchronous* (durable-on-return) checkpoint, and exit
with a distinct code so the job scheduler can tell "preempted, resume
me" from "crashed, investigate". The existing ``resume`` path picks the
emergency checkpoint up unchanged.

``PreemptionGuard`` only sets a flag from the handler (async-signal
safe); all real work happens at the trainer's step boundary.
``TrainingPreempted`` subclasses ``SystemExit``: uncaught, it terminates
the process with the documented code and no traceback; embedders that
drive ``Trainer.train()`` themselves can catch it like any exception
(the trainer's cleanup — telemetry flush, checkpoint barrier — has
already run by the time it propagates).
"""

import logging
import signal
import threading
import time

from d9d_tpu.telemetry import get_telemetry

logger = logging.getLogger("d9d_tpu.resilience")

# documented defaults for the exit-code contract (configurable on
# TrainerConfig; docs/design/resilience.md)
EXIT_PREEMPTED = 83
EXIT_WATCHDOG = 42


class TrainingPreempted(SystemExit):
    """Raised by the trainer after the emergency checkpoint is durable.

    ``code`` is the process exit code (``SystemExit`` semantics);
    ``step`` is the step the checkpoint was written at.
    """

    def __init__(self, code: int, *, step: int | None = None):
        super().__init__(code)
        self.step = step

    def __str__(self) -> str:
        return (
            f"training preempted (exit code {self.code}, "
            f"checkpoint at step {self.step})"
        )


class PreemptionGuard:
    """Context manager installing SIGTERM/SIGINT flag-setting handlers.

    Handlers chain nowhere on the first signal — they record it and
    return, letting the step in flight finish. A *second* SIGINT falls
    through to an immediate ``KeyboardInterrupt`` (the operator really
    means it). Signal handlers are only installable on the main thread;
    elsewhere (tests driving a trainer from a worker thread, embedders)
    the guard degrades to an inert no-op with a warning.
    """

    def __init__(
        self,
        *,
        signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
        enabled: bool = True,
        telemetry=None,
    ):
        self._signals = signals
        self._enabled = enabled
        self._previous: dict[int, object] = {}
        self._triggered_at: float | None = None
        self._signum: int | None = None
        self._tele = telemetry if telemetry is not None else get_telemetry()

    # -- flag surface ---------------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._triggered_at is not None

    @property
    def signum(self) -> int | None:
        return self._signum

    def trip(self, signum: int = signal.SIGTERM) -> None:
        """Set the flag programmatically (chaos injection, tests)."""
        self._handle(signum, None)

    def _handle(self, signum, frame) -> None:
        if self._triggered_at is not None and signum == signal.SIGINT:
            # second Ctrl-C: stop waiting for the boundary
            raise KeyboardInterrupt
        first = self._triggered_at is None
        self._triggered_at = time.monotonic()
        self._signum = signum
        if first:
            # counters are async-signal tolerant (plain float adds); the
            # heavyweight work (checkpoint, flush) stays at the boundary
            self._tele.counter("resilience/preempt_signals").add(1)
            logger.warning(
                "received signal %d: will checkpoint and exit at the "
                "next step boundary", signum,
            )

    # -- install/restore ------------------------------------------------

    def __enter__(self) -> "PreemptionGuard":
        if not self._enabled:
            return self
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption guard disabled: signal handlers need the "
                "main thread (trainer is running on %s)",
                threading.current_thread().name,
            )
            self._enabled = False
            return self
        for signum in self._signals:
            self._previous[signum] = signal.signal(signum, self._handle)
        return self

    def __exit__(self, *exc) -> bool:
        for signum, prev in self._previous.items():
            signal.signal(signum, prev)
        self._previous.clear()
        return False
