"""Host half of the step anomaly guard.

The device half lives inside the jitted step (``loop/train_step.py`` for
the single-program path, ``pipelining/training.py`` for PP): it computes
``ok = isfinite(loss) & isfinite(grad_norm)`` from values the step
already materializes, freezes the parameter/optimizer update via an
in-device select when the policy calls for it, and carries a consecutive
-anomaly streak plus a cumulative total as device-resident state. None
of that costs a dispatch or a readback — the guard state rides the step
call, and the flags surface as ordinary metric-dict entries.

This module is the host side: it inspects those flags whenever the
trainer fetches metrics anyway (the log cadence — the guard never forces
an extra sync), layers a rolling loss-spike detector on top (finite but
exploding losses pass the device finiteness check — the windowed-median
baseline is :class:`~d9d_tpu.telemetry.numerics.RollingBaseline`, the
ONE implementation shared with the drift policies), counts everything
into ``resilience/*`` telemetry, and decides when a ``rollback`` policy
should actually restore the last checkpoint.

With the numerics plane enabled (``TrainerConfig.numerics_every_steps``,
``telemetry/numerics.py``), the trainer passes ``observe`` a provenance
``context`` naming the first non-finite layer of the last numerics
window (fwd activation vs grad vs optimizer moment): the one-line
warning and the flight-recorder dump then say *where* the NaN was
produced, not just that step N went bad.

Latency contract: device-side anomalies are *acted on* (skipped/frozen)
the step they happen; the host *notices* them — and can trigger a
rollback — only at the next metric fetch, i.e. within ``log_every``
steps. Chaos tests run with ``log_every=1`` to make this exact.
"""

import logging
import math
from typing import Any, Literal

from d9d_tpu.telemetry import get_telemetry
from d9d_tpu.telemetry.numerics import RollingBaseline

logger = logging.getLogger("d9d_tpu.resilience")

AnomalyPolicy = Literal["warn", "skip_step", "rollback"]
ANOMALY_POLICIES = ("warn", "skip_step", "rollback")

# metric-dict keys the device half publishes (both step backends)
METRIC_ANOMALY = "resilience/anomaly"
METRIC_STREAK = "resilience/anomaly_streak"
METRIC_TOTAL = "resilience/anomaly_total"


class HostAnomalyGuard:
    """Cadence-rate observer over the device guard's flags + host losses.

    ``observe()`` returns the action the trainer should take *now*:
    ``"ok"``, ``"warn"`` (anomaly seen, update policy already handled it
    on device), or ``"rollback"`` (restore the last checkpoint and
    rewind). The caller resets the guard (``reset()``) after acting on a
    rollback so one burst cannot trigger twice.
    """

    def __init__(
        self,
        *,
        policy: AnomalyPolicy,
        rollback_after: int = 3,
        spike_factor: float | None = 10.0,
        spike_window: int = 32,
        telemetry=None,
    ):
        if policy not in ANOMALY_POLICIES:
            raise ValueError(
                f"anomaly policy must be one of {ANOMALY_POLICIES}, "
                f"got {policy!r}"
            )
        if rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        self.policy = policy
        self.rollback_after = rollback_after
        self.spike_factor = spike_factor
        # the shared windowed-median baseline (telemetry/numerics.py):
        # one definition of "the recent normal" for the spike detector
        # and the drift policies alike
        self._baseline = RollingBaseline(spike_window, min_samples=4)
        self._spike_streak = 0
        self._last_device_total = 0.0
        self._tele = telemetry if telemetry is not None else get_telemetry()

    # -- detectors -----------------------------------------------------

    def _is_spike(self, loss: float) -> bool:
        """Rolling-median spike test. The baseline window only ever
        absorbs non-spiking losses, so a plateau of spikes cannot
        normalize itself into the new baseline."""
        if self.spike_factor is None or not math.isfinite(loss):
            return False
        if not self._baseline.ready():
            self._baseline.add(loss)
            return False
        if loss > self.spike_factor * max(self._baseline.baseline(), 1e-12):
            return True
        self._baseline.add(loss)
        return False

    # -- the cadence hook ----------------------------------------------

    def observe(
        self,
        step: int,
        host_metrics: dict[str, Any],
        context: dict[str, Any] | None = None,
    ) -> str:
        """Feed one fetched metric dict; returns ``ok|warn|rollback``.

        ``context`` (optional) is the numerics plane's provenance — the
        first non-finite layer of the last window — folded into the
        warning line and the flight-recorder dump's ``extra``."""
        device_flag = float(host_metrics.get(METRIC_ANOMALY, 0.0) or 0.0)
        device_streak = float(host_metrics.get(METRIC_STREAK, 0.0) or 0.0)
        device_total = float(host_metrics.get(METRIC_TOTAL, 0.0) or 0.0)
        loss = host_metrics.get("loss")

        # the device total is cumulative across the run: counter-ize the
        # delta so anomalies between cadences are not lost, only late
        delta = max(0.0, device_total - self._last_device_total)
        self._last_device_total = device_total
        if delta:
            self._tele.counter("resilience/anomalies").add(delta)

        spike = loss is not None and self._is_spike(float(loss))
        if spike:
            self._spike_streak += 1
            self._tele.counter("resilience/loss_spikes").add(1)
            logger.warning(
                "loss spike at step %d: loss=%.6g (rolling median %.6g)",
                step, loss, self._baseline.baseline(),
            )
        elif device_flag == 0.0:
            self._spike_streak = 0

        anomalous = spike or device_flag > 0.0 or delta > 0.0
        if anomalous and not spike:
            provenance = ""
            if context and context.get("first_nonfinite"):
                # numerics-plane attribution: the first offending layer
                # (site:name — fwd act vs grad vs optimizer moment)
                provenance = (
                    f", first non-finite: {context['first_nonfinite']}"
                )
            logger.warning(
                "non-finite step anomaly observed at step %d "
                "(streak=%d, total=%d, policy=%s%s)",
                step, int(device_streak), int(device_total), self.policy,
                provenance,
            )
        if not anomalous:
            return "ok"

        # black-box dump at the first sight of the anomaly (no-op until
        # a flight recorder is configured on the hub; rate-limited there
        # so a NaN storm dumps once per interval, not once per step)
        dump = getattr(self._tele, "dump_flight_record", None)
        if dump is not None:
            dump("anomaly", extra={
                "step": step,
                "loss": float(loss) if loss is not None else None,
                "spike": bool(spike),
                "device_streak": device_streak,
                "device_total": device_total,
                "policy": self.policy,
                **(context or {}),
            })

        if self.policy == "rollback" and (
            device_streak >= self.rollback_after
            or self._spike_streak >= self.rollback_after
        ):
            return "rollback"
        return "warn"

    def reset(self) -> None:
        """Forget streak state (after a rollback restored a checkpoint
        the pre-rollback history no longer describes the live run)."""
        self._baseline.clear()
        self._spike_streak = 0
        self._last_device_total = 0.0
