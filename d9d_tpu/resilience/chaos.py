"""Deterministic fault injectors driving ``tests/resilience/``.

Every injector is reproducible from explicit indices — no randomness, no
timing races — so a chaos test asserts exact recovery behavior, not
"usually survives". The catalogue (docs/design/resilience.md):

- :class:`ChaosScaleTask` — multiply the training loss of chosen host
  batches by a factor (``float("nan")`` ⇒ NaN loss AND NaN grads through
  the whole backward; ``1000.0`` ⇒ a finite loss spike). Works through
  both step backends: the factor rides the batch pytree as a
  ``chaos_scale`` leaf, so the jitted step stays trace-stable and the
  injection point is an ordinary host decision.
- :class:`FlakyDataset` — raise on chosen ``__getitem__`` *call
  indices* (retries advance the call counter, so transient-vs-fatal is
  expressed exactly), or permanently from a call index on
  (``dead_from`` ⇒ prefetch-producer death once retries exhaust).
- :func:`truncate_latest_checkpoint` — physically truncate the largest
  payload file of a finalized save directory (the on-disk state of a
  machine that died mid-write after the finalize rename).
- :func:`sigterm_at_step` — deliver a real SIGTERM to this process when
  a chosen trainer step begins (event-bus hook).
- :func:`wedge_batcher` — replace a serving batcher's harvest with a
  long sleep: a deterministic stand-in for a wedged device readback.
- :func:`shrink_at_step` — arm a :class:`~d9d_tpu.resilience.elastic.
  ServingFleet` to shrink a chosen replica at an exact scheduling
  round (the deterministic form of a preemption landing mid-traffic).
- :func:`kill_replica_mid_drain` — make a replica die partway through
  its shrink drain (after an exact number of grace chunks): the fleet
  must recover its unfinished requests onto survivors.
- :func:`kill_prefill_mid_handoff` — make a prefill replica die at its
  next handoff with pages exported but not yet imported: the shipment
  is lost in flight, the request must recover via continuation.
- :func:`corrupt_handoff_payload` — flip a byte of the next handoff
  shipment so the per-page checksum must catch it: the import is
  refused wholesale and the request re-prefills, token-identically.
- :func:`ramp_arrivals` — a scripted arrival-rate ramp: phases of
  (steps, arrivals-per-step) compiled into an exact arrival schedule.
  Arrival *times* carry zero randomness (fractional rates are spread
  by an error accumulator), so an overload ramp reproduces the same
  queue depths, rejections and autopilot decisions on every run; the
  same builder shapes ``tools/bench_serve.py`` ramp workloads.

Queue overflow needs no injector: submit past ``max_queue`` and assert
:class:`~d9d_tpu.loop.serve.QueueFullError`.

This module imports the loop task surface; import it on demand (tests,
harnesses), not from ``d9d_tpu.resilience.__init__``.
"""

import os
import signal
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import PyTree
from d9d_tpu.loop.control.task import PipelineTrainTask
from d9d_tpu.resilience.manifest import MANIFEST_NAME

CHAOS_SCALE_KEY = "chaos_scale"


class ChaosScaleTask(PipelineTrainTask):
    """Wrap a task; scale the loss of chosen prepared batches.

    ``scale_at`` maps *prepared-batch call index* (0-based, counted on
    the host in ``prepare_batch`` — under prefetch that is the
    producer's order, which equals consumption order) to a loss factor.
    Unlisted batches are untouched (factor 1). The factor is injected as
    a per-sample ``chaos_scale`` batch leaf and applied as
    ``loss_sum * mean(scale)`` inside the jitted loss — NaN propagates
    into every gradient leaf, a finite factor spikes the loss and scales
    grads without breaking finiteness.

    Implements the full :class:`PipelineTrainTask` surface by
    delegation, routing the leaf through the last stage's ``state``
    tree, so the same injector drives the non-PP and the PP step
    backends. (PP note: ``state`` leaves are staged with the last
    stage's [batch, seq] sharding — the [B, 1] scale leaf requires the
    context-parallel axis to be trivial, which chaos rigs satisfy.)
    """

    def __init__(self, inner, scale_at: dict[int, float]):
        self.inner = inner
        self.scale_at = {int(k): float(v) for k, v in scale_at.items()}
        self.calls = 0

    # -- non-PP surface ------------------------------------------------

    def prepare_batch(self, batch: PyTree) -> PyTree:
        prepared = dict(self.inner.prepare_batch(batch))
        n = np.shape(jax.tree.leaves(prepared)[0])[0]
        factor = self.scale_at.get(self.calls, 1.0)
        self.calls += 1
        prepared[CHAOS_SCALE_KEY] = np.full((n, 1), factor, np.float32)
        return prepared

    def loss_fn(self, module, params, mb, rng):
        mb = dict(mb)
        scale = mb.pop(CHAOS_SCALE_KEY)
        loss_sum, weight, metrics = self.inner.loss_fn(
            module, params, mb, rng
        )
        return loss_sum * jnp.mean(scale), weight, metrics

    def metrics_postprocess(self, metrics):
        return self.inner.metrics_postprocess(metrics)

    def metrics(self):
        return self.inner.metrics()

    def update_metrics(self, metric_objs, stats):
        return self.inner.update_metrics(metric_objs, stats)

    # -- PP surface (delegated; the scale leaf rides `state`) ----------

    def sample_microbatch(self, microbatch_size: int, seq_len: int):
        mb = dict(self.inner.sample_microbatch(microbatch_size, seq_len))
        mb[CHAOS_SCALE_KEY] = np.ones((microbatch_size, 1), np.float32)
        return mb

    def split_microbatch(self, microbatch):
        mb = dict(microbatch)
        scale = mb.pop(CHAOS_SCALE_KEY)
        carry, kwargs, state = self.inner.split_microbatch(mb)
        state = dict(state)
        state[CHAOS_SCALE_KEY] = scale
        return carry, kwargs, state

    def stage_forward(self, module, params, carry, kwargs):
        return self.inner.stage_forward(module, params, carry, kwargs)

    def last_stage_loss(self, module, params, carry, kwargs, state):
        state = dict(state)
        scale = state.pop(CHAOS_SCALE_KEY)
        loss_sum, weight, metrics = self.inner.last_stage_loss(
            module, params, carry, kwargs, state
        )
        return loss_sum * jnp.mean(scale), weight, metrics

    def stage_init(self, module, rng, carry, kwargs, state, is_last):
        state = dict(state)
        state.pop(CHAOS_SCALE_KEY, None)
        return self.inner.stage_init(
            module, rng, carry, kwargs, state, is_last
        )


class FlakyDataset:
    """Map-style dataset wrapper that fails on exact fetch-call indices.

    ``fail_calls`` — the global ``__getitem__`` call indices that raise
    (a retry is a new call, so ``fail_calls={3, 4}`` with
    ``retry_attempts>=2`` is a transient fault the loader survives);
    ``dead_from`` — every call at/after this index raises (a permanent
    source outage: retries exhaust, the error must surface cleanly).
    """

    def __init__(
        self,
        inner,
        *,
        fail_calls=frozenset(),
        dead_from: int | None = None,
        exc_type: type[Exception] = ConnectionError,
    ):
        self.inner = inner
        self.fail_calls = frozenset(int(c) for c in fail_calls)
        self.dead_from = dead_from
        self.exc_type = exc_type
        self.calls = 0
        self.failures = 0

    def __len__(self) -> int:
        return len(self.inner)

    def __getitem__(self, i):
        call = self.calls
        self.calls += 1
        if (self.dead_from is not None and call >= self.dead_from) or (
            call in self.fail_calls
        ):
            self.failures += 1
            raise self.exc_type(
                f"chaos: injected fetch failure (call {call}, item {i})"
            )
        return self.inner[i]


def checkpoint_steps(directory: str | Path) -> list[int]:
    """Finalized ``save_{N}`` steps under a checkpoint dir, ascending."""
    steps = []
    for p in Path(directory).glob("save_*"):
        tail = p.name.split("_", 1)[1]
        if p.is_dir() and tail.isdigit():
            steps.append(int(tail))
    return sorted(steps)


def truncate_latest_checkpoint(
    directory: str | Path, *, step: int | None = None
) -> tuple[int, Path]:
    """Truncate the largest payload file of the newest (or given) save
    directory to half its size — the post-crash disk state of an
    interrupted array write. Returns (step, truncated file path).

    The step's integrity manifest (written before the damage) now
    records the original size, so restore-time validation must reject
    the step and fall back.
    """
    steps = checkpoint_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no save_* directories under {directory}")
    target = steps[-1] if step is None else step
    step_dir = Path(directory) / f"save_{target}"
    files = [
        p for p in step_dir.rglob("*")
        if p.is_file() and p.name != MANIFEST_NAME and p.stat().st_size > 0
    ]
    victim = max(files, key=lambda p: p.stat().st_size)
    size = victim.stat().st_size
    with open(victim, "r+b") as fh:
        fh.truncate(size // 2)
    return target, victim


def sigterm_at_step(
    event_bus, step: int, *, signum: int = signal.SIGTERM
) -> None:
    """Deliver ``signum`` to this process when trainer step ``step``
    begins (EVENT_STEP.pre hook) — a real mid-run preemption, raced
    against nothing: the flag is checked at the same step's boundary."""
    from d9d_tpu.loop import event as ev

    def hook(**payload):
        if payload.get("step") == step:
            os.kill(os.getpid(), signum)

    event_bus.subscribe(ev.EVENT_STEP.pre, hook)


def shrink_at_step(fleet, replica_idx: int, step: int) -> None:
    """Shrink ``replica_idx`` out of ``fleet`` when its scheduling-round
    counter reaches ``step`` — a preemption arriving mid-traffic, raced
    against nothing (the trigger is consumed at the exact round, before
    that round's chunk dispatches)."""
    fleet._chaos_shrink = (int(replica_idx), int(step))


def kill_replica_mid_drain(
    fleet, replica_idx: int, *, after_chunks: int = 1
) -> None:
    """Make ``replica_idx`` die after ``after_chunks`` grace chunks of
    its shrink drain: the fleet must resubmit the replica's unfinished
    requests to survivors as continuation prompts (prompt + tokens
    already emitted), losing no committed work."""
    fleet._chaos_kill = (int(replica_idx), int(after_chunks))


def kill_prefill_mid_handoff(fleet, replica_idx: int) -> None:
    """Make ``replica_idx`` die at its NEXT prefill→decode handoff, at
    the worst instant: pages exported but not yet imported anywhere.
    The shipment is lost with the replica; the fleet must recover every
    in-flight request (including the one mid-handoff) via the
    continuation fallback — token-identically, with zero leaked pages
    on every survivor (``check_invariants``)."""
    fleet._chaos_kill_handoff = int(replica_idx)


def corrupt_handoff_payload(fleet) -> None:
    """Flip one byte of the NEXT handoff shipment's page payload after
    export. The importer's per-page checksum must detect it and refuse
    the import wholesale (no partially-written pool pages); the request
    falls back to continuation re-prefill — fallback, not failure."""
    fleet._chaos_corrupt_handoff = True


def ramp_arrivals(
    schedule,
    *,
    vocab: int,
    seed: int = 0,
    prompt_lo: int = 1,
    prompt_hi: int = 4,
    gen_lo: int = 2,
    gen_hi: int = 8,
    start_step: int = 0,
) -> list[tuple[int, list[int], int]]:
    """Compile a scripted arrival-rate ramp into an exact workload.

    ``schedule`` is a sequence of ``(steps, rate)`` phases: for
    ``steps`` scheduling steps, ``rate`` requests arrive per step
    (fractional rates are spread deterministically by an error
    accumulator — rate 0.5 lands one arrival every second step, never a
    random draw). Returns ``[(arrival_step, prompt, max_new_tokens)]``
    in the exact tuple shape ``tools/bench_serve.py`` workloads use, so
    one builder drives both the autopilot chaos tests and the bench
    harness ramp legs. Prompt contents and budgets come from the
    seeded RNG (``prompt_hi``/``gen_hi`` exclusive, matching
    ``make_workload``); arrival *times* carry no randomness at all.
    """
    rng = np.random.RandomState(seed)
    arrivals: list[tuple[int, list[int], int]] = []
    step = int(start_step)
    acc = 0.0
    for steps, rate in schedule:
        if steps < 0 or rate < 0:
            raise ValueError(
                f"schedule phases need steps >= 0 and rate >= 0, got "
                f"({steps}, {rate})"
            )
        for s in range(int(steps)):
            acc += float(rate)
            while acc >= 1.0 - 1e-9:
                acc -= 1.0
                prompt = rng.randint(
                    0, vocab, rng.randint(prompt_lo, prompt_hi)
                ).tolist()
                arrivals.append(
                    (step + s, prompt, int(rng.randint(gen_lo, gen_hi)))
                )
        step += int(steps)
    return arrivals


def wedge_batcher(batcher, *, seconds: float = 3600.0) -> None:
    """Make the batcher's next harvest block for ``seconds`` — a
    deterministic stand-in for a device/runtime wedge, used to prove the
    drain stall watchdog converts a hang into ``ServeStalledError``."""

    def wedged_harvest():
        time.sleep(seconds)
        return {}

    batcher._harvest_one = wedged_harvest
