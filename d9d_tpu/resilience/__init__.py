"""Fault tolerance for the training/serving loops.

The reference d9d's recovery model is fail-fast restart-and-resume:
two-phase NCCL timeouts kill a hung job, the scheduler restarts it, and
the checkpointer resumes from the latest rotation entry. This package is
the TPU rebuild's full version of that story, caught uniformly at the
single-controller host (docs/design/resilience.md):

- :mod:`~d9d_tpu.resilience.anomaly` — step anomaly guard. Non-finite
  loss/grad-norm is detected *inside* the jitted step (reusing the
  already-computed global grad norm: zero extra device dispatches or
  readbacks on the happy path) and optionally frozen out via an
  in-device select; a host-side rolling detector additionally catches
  finite-but-exploding loss spikes at the metric cadence. Policies:
  ``warn`` / ``skip_step`` / ``rollback``.
- :mod:`~d9d_tpu.resilience.preemption` — SIGTERM/SIGINT set a flag the
  trainer checks at step boundaries; an emergency synchronous checkpoint
  is written and the process exits with a distinct, documented code that
  the existing ``resume`` path picks up.
- :mod:`~d9d_tpu.resilience.manifest` — per-save integrity manifests
  (meta-item checksums + array file inventory) and validation, so
  restore can walk back through the rotation history to the newest
  intact step instead of crashing on a truncated one.
- :mod:`~d9d_tpu.resilience.chaos` — deterministic fault injectors (NaN
  grads, loss spikes, checkpoint truncation, prefetch-thread death,
  SIGTERM mid-run, queue overflow, fleet shrink/kill) driving
  ``tests/resilience/``. Imported on demand only; it pulls in the loop
  task surface.
- :mod:`~d9d_tpu.resilience.elastic` — elastic topology
  (docs/design/elasticity.md): cross-mesh checkpoint restore (manifest
  v2 saving-mesh block, memory-bounded chunked redistribution), live
  train→serve weight publish (:class:`WeightPublisher`), and
  preemption-driven serving-fleet shrink/grow (:class:`ServingFleet`).
  The fleet/publisher import the serve surface lazily.
- :mod:`~d9d_tpu.resilience.autopilot` — the SLO autopilot
  (docs/design/elasticity.md "SLO autopilot"): a burn-rate-driven
  control loop (:class:`FleetAutopilot`) connecting the monitoring
  plane's senses to the fleet's actuators — autoscaling with
  hysteresis, priority-tiered admission shedding under burn, and
  canaried weight publish with automatic rollback, every action
  decision-logged and flight-recorded.

Exit-code contract (see docs/design/resilience.md):

- ``EXIT_PREEMPTED`` (83): preemption signal received, emergency
  checkpoint durable on disk, resume will continue from it.
- ``EXIT_WATCHDOG`` (42): hang watchdog fired (no step heartbeat);
  state is whatever the last rotation checkpoint holds.

Both are configurable knobs on ``TrainerConfig``
(``preemption_exit_code`` / ``watchdog_exit_code``); the constants are
the documented defaults.
"""

from d9d_tpu.resilience.anomaly import (
    ANOMALY_POLICIES,
    AnomalyPolicy,
    HostAnomalyGuard,
)
from d9d_tpu.resilience.autopilot import (
    AutopilotConfig,
    DecisionLog,
    FleetAutopilot,
    read_decisions,
)
from d9d_tpu.resilience.elastic import (
    ServingFleet,
    WeightPublisher,
    job_mesh_spec,
    redistribute_tree,
    topology_mismatch,
    tree_mesh_summary,
)
from d9d_tpu.resilience.manifest import (
    MANIFEST_NAME,
    CheckpointIntegrityError,
    ManifestVersionError,
    manifest_mesh,
    validate_checkpoint_dir,
    write_manifest,
)
from d9d_tpu.resilience.preemption import (
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
    PreemptionGuard,
    TrainingPreempted,
)

__all__ = [
    "ANOMALY_POLICIES",
    "AnomalyPolicy",
    "AutopilotConfig",
    "DecisionLog",
    "FleetAutopilot",
    "HostAnomalyGuard",
    "read_decisions",
    "MANIFEST_NAME",
    "CheckpointIntegrityError",
    "ManifestVersionError",
    "ServingFleet",
    "WeightPublisher",
    "job_mesh_spec",
    "manifest_mesh",
    "redistribute_tree",
    "topology_mismatch",
    "tree_mesh_summary",
    "validate_checkpoint_dir",
    "write_manifest",
    "EXIT_PREEMPTED",
    "EXIT_WATCHDOG",
    "PreemptionGuard",
    "TrainingPreempted",
]
