"""Elastic topology: cross-mesh restore, live weight publish, fleet
shrink/grow (docs/design/elasticity.md).

Production means the chip count changes under you: a job trains on N
chips and resumes on M after a preemption, a serving fleet loses a
replica mid-drain and must not lose its requests, and freshly trained
weights must reach live batcher replicas without a restart. PR 5 made
the failure *exits* safe; this module is the recovery half (ROADMAP
item 4):

- **Topology-independent restore.** Checkpoints record the saving mesh
  (manifest v2 ``mesh`` block — :func:`job_mesh_spec`); restore
  compares it against the live job's mesh (:func:`tree_mesh_summary` /
  :func:`topology_mismatch`) and reshard-on-loads across the mismatch.
  The memory-bounded leg (PAPERS.md, arxiv 2112.01075's bounded
  collective redistribution, in its load-time form):
  :func:`bounded_restore_shardings` stages oversized leaves sharded
  flat across the new mesh's devices, and :func:`redistribute_tree`
  moves them to their final placement in chunks — never gathering more
  than ``hbm_budget_bytes`` of any array at once. The chunked path is
  SINGLE-CONTROLLER: its per-chunk host round-trip would touch
  non-addressable shards on a multi-process mesh, so under
  ``jax.process_count() > 1`` it degrades to direct placement —
  orbax's tensorstore reads stay shard-local and per-rank there (the
  arxiv 2412.14374 per-rank constraint), just not budget-capped for a
  huge replicated leaf.
- **Live train→serve weight publish.** :class:`WeightPublisher`
  snapshots trainer params at a step boundary and installs them into
  attached ``ContinuousBatcher`` replicas; each batcher swaps at its
  next chunk boundary (``install_weights``) with generation-stamped
  versioning — already-dispatched chunks complete on the weights they
  were dispatched with, and ``defer_to_idle`` holds the swap until
  in-flight *requests* finish. The batcher's jitted executables take
  params as a traced argument with an unchanged ``tracked_jit``
  fingerprint, so a publish causes zero steady-state recompiles
  (gated by ``tools/bench_compare.py``).
- **Preemption-driven shrink/grow.** :class:`ServingFleet` routes
  requests across N batcher replicas under the PR 5 backpressure
  contract (``QueueFullError`` cascades replica → fleet). ``shrink``
  — wired to PR 5's preemption signal via :meth:`bind_preemption` —
  drains the dying replica: queued requests migrate into survivors,
  running rows finish inside the grace window. If the replica dies
  mid-drain (``chaos.kill_replica_mid_drain``), its unfinished
  requests are resubmitted to survivors as *continuation prompts*
  (original prompt + tokens already emitted), which the serving loop's
  teacher-forced prompt consumption replays bit-identically to an
  uninterrupted decode under greedy sampling. A PAGED replica
  (``page_size`` set — docs/design/generation.md) needs nothing extra:
  a continuation is an ordinary fresh submit on the survivor, so it
  allocates pages like any request and may even prefix-hit the
  original prompt's cached pages there; the dead replica's pool dies
  with its device state. ``grow`` cold-starts a replacement replica
  from the latest published weights.

Import note: like :mod:`~d9d_tpu.resilience.chaos`, anything that
touches the loop/serve surface is imported lazily — the module itself
only needs jax + telemetry.
"""

import dataclasses
import logging
import math
import time
import weakref
from collections import deque
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.core.types import PyTree
from d9d_tpu.telemetry import get_telemetry

logger = logging.getLogger("d9d_tpu.resilience")

__all__ = [
    "ServingFleet",
    "WeightPublisher",
    "bounded_restore_shardings",
    "job_mesh_spec",
    "redistribute_tree",
    "topology_mismatch",
    "tree_mesh_summary",
]

# staging axis name for the bounded restore path; underscore-prefixed so
# it can never collide with the framework's mesh axis vocabulary
_STAGING_AXIS = "_elastic"


# ---------------------------------------------------------------------------
# mesh specs: what a checkpoint records about the topology that wrote it


def _leaf_nbytes(leaf: Any) -> int:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return math.prod(shape) * jnp.dtype(dtype).itemsize


def _committed_mesh(tree: PyTree) -> Mesh | None:
    """The mesh of the first NamedSharding-placed leaf, or None."""
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return sh.mesh
    return None


def tree_mesh_summary(tree: PyTree) -> dict[str, Any] | None:
    """``{"device_count", "axes"}`` of the mesh placing ``tree``'s leaves
    (read off the first NamedSharding), or None for an unplaced tree."""
    mesh = _committed_mesh(tree)
    if mesh is None:
        return None
    return {
        "device_count": int(mesh.devices.size),
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
    }


def leaf_sharding_specs(tree: PyTree) -> dict[str, str | None]:
    """Per-leaf PartitionSpec strings keyed by tree path — the manifest's
    record of how the save was laid out (diagnostic; restore placement is
    driven by the live target, never by these)."""
    out: dict[str, str | None] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sh = getattr(leaf, "sharding", None)
        key = jax.tree_util.keystr(path)
        out[key] = str(sh.spec) if isinstance(sh, NamedSharding) else None
    return out


def job_mesh_spec(
    *,
    ctx=None,
    mesh: Mesh | None = None,
    zero_sharding: bool = False,
    arrays: PyTree | None = None,
) -> dict[str, Any]:
    """The saving-topology block a checkpoint records (manifest v2
    ``mesh``): MeshParameters axis sizes (incl. ``dp_r``), device count,
    the ``zero_sharding`` setting, and per-leaf sharding specs.

    ``ctx`` is a :class:`~d9d_tpu.core.mesh.MeshContext`; a bare ``mesh``
    also works (axis sizes read off ``mesh.shape``).
    """
    spec: dict[str, Any] = {"zero_sharding": bool(zero_sharding)}
    if ctx is not None:
        spec["mesh_parameters"] = ctx.params.as_dict()
        mesh = ctx.mesh
    if mesh is not None:
        spec["device_count"] = int(mesh.devices.size)
        spec["axes"] = {str(k): int(v) for k, v in mesh.shape.items()}
    if arrays is not None:
        spec["leaf_shardings"] = leaf_sharding_specs(arrays)
    return spec


def topology_mismatch(
    saved: dict[str, Any] | None, target: dict[str, Any] | None
) -> bool:
    """Did the checkpoint's saving mesh differ from the restore target's?

    Conservative: unknown on either side (pre-v2 manifest, unplaced
    target tree) reads as "no mismatch" — the plain restore path is
    always correct, the elastic path is an optimization + telemetry.
    """
    if not saved or not target:
        return False
    if "device_count" in saved and (
        int(saved["device_count"]) != int(target["device_count"])
    ):
        return True
    if saved.get("axes") and dict(saved["axes"]) != dict(target["axes"]):
        return True
    return False


# ---------------------------------------------------------------------------
# memory-bounded redistribution (chunked gather → re-place)


def _shard_slice_shape(
    idx: tuple[slice, ...], shape: tuple[int, ...]
) -> tuple[int, ...]:
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        out.append(max(0, (stop - start + step - 1) // step))
    return tuple(out)


def _zeros_on(shape, dtype, sharding) -> jax.Array:
    """An all-zeros array materialized shard-by-shard on ``sharding`` —
    never a full host or single-device copy."""
    return jax.make_array_from_callback(
        shape,
        sharding,
        lambda idx: np.zeros(_shard_slice_shape(idx, shape), dtype),
    )


def _chunked_place(
    leaf: jax.Array, target: NamedSharding, budget: int
) -> tuple[jax.Array, int]:
    """Move ``leaf`` onto ``target`` without ever gathering more than
    ``budget`` bytes of it at once: slice dim-0 chunks off the source,
    round-trip each through the host, and write it into a
    target-sharded accumulator via a donated dynamic_update_slice.
    Peak transient footprint per device: the target shard (required)
    plus one replicated ≤ budget chunk. Returns (placed, n_chunks)."""
    rows = leaf.shape[0]
    row_bytes = max(1, _leaf_nbytes(leaf) // max(rows, 1))
    chunk_rows = max(1, int(budget // row_bytes))
    repl = NamedSharding(target.mesh, P())
    out = _zeros_on(leaf.shape, leaf.dtype, target)

    def write(buf, chunk, start):
        zeros = (jnp.int32(0),) * (buf.ndim - 1)
        return lax.dynamic_update_slice(buf, chunk, (start,) + zeros)

    write_j = jax.jit(write, donate_argnums=0, out_shardings=target)
    n = 0
    for a in range(0, rows, chunk_rows):
        b = min(rows, a + chunk_rows)
        host_chunk = np.asarray(leaf[a:b])  # gather: ≤ budget bytes
        dev_chunk = jax.device_put(host_chunk, repl)
        out = write_j(out, dev_chunk, jnp.int32(a))
        n += 1
    return out, n


def redistribute_tree(
    tree: PyTree,
    target_shardings: PyTree,
    *,
    hbm_budget_bytes: int | None = None,
    telemetry=None,
) -> PyTree:
    """Re-place ``tree``'s leaves onto ``target_shardings`` (None leaves
    pass through untouched), moving any leaf larger than
    ``hbm_budget_bytes`` through the chunked gather→re-place path so no
    more than the budget of it is ever materialized outside its source
    and destination shards. Bumps ``resilience/reshard_chunks`` and
    returns the re-placed tree; with no budget this degrades to plain
    ``device_put`` per leaf (still one transfer, just unbounded)."""
    tele = telemetry if telemetry is not None else get_telemetry()
    moved = 0
    chunks = 0

    def place(sh, leaf):
        nonlocal moved, chunks
        if sh is None or not isinstance(leaf, jax.Array):
            return leaf
        cur = getattr(leaf, "sharding", None)
        try:
            if cur is not None and cur.is_equivalent_to(sh, leaf.ndim):
                return leaf
        except Exception:  # noqa: BLE001 — exotic sharding: fall through
            pass
        nbytes = _leaf_nbytes(leaf)
        moved += nbytes
        if (
            hbm_budget_bytes is None
            or nbytes <= hbm_budget_bytes
            or leaf.ndim == 0
            or leaf.shape[0] < 2
            or not isinstance(sh, NamedSharding)
            # chunking round-trips through THIS host: on a multi-process
            # mesh the slice would span non-addressable shards — degrade
            # to direct placement (shard-local, just not budget-capped)
            or jax.process_count() > 1
        ):
            chunks += 1
            return jax.device_put(leaf, sh)
        placed, n = _chunked_place(leaf, sh, hbm_budget_bytes)
        chunks += n
        return placed

    out = jax.tree.map(
        place, target_shardings, tree, is_leaf=lambda x: x is None
    )
    if chunks:
        tele.counter("resilience/reshard_chunks").add(chunks)
        tele.counter("resilience/reshard_bytes_total").add(moved)
    return out


def bounded_restore_shardings(
    target_tree: PyTree, *, hbm_budget_bytes: int | None
) -> PyTree:
    """Staging shardings for a cross-topology restore under an HBM
    budget: a tree of NamedShardings (or None = restore directly).

    A leaf stages when restoring it straight into its final placement
    would materialize more than the budget *per device* (a big
    replicated leaf) and dim 0 divides over the new mesh's device
    count: orbax then reads it 1/ndev-sharded (shard-local byte
    ranges), and :func:`redistribute_tree` re-places it chunked.
    Leaves whose final shard already fits the budget restore directly —
    tensorstore reads are shard-local and thus already bounded.
    """
    none_tree = jax.tree.map(lambda _: None, target_tree)
    if hbm_budget_bytes is None:
        return none_tree
    if jax.process_count() > 1:
        # the chunked re-place behind this staging is single-controller
        # (see redistribute_tree); multi-process restores go direct
        logger.warning(
            "elastic restore: HBM-budgeted staging is single-process "
            "only; restoring directly on %d processes",
            jax.process_count(),
        )
        return none_tree
    mesh = _committed_mesh(target_tree)
    if mesh is None or mesh.devices.size <= 1:
        return none_tree
    devs = mesh.devices.reshape(-1)
    flat = Mesh(devs, (_STAGING_AXIS,))
    staged = NamedSharding(flat, P(_STAGING_AXIS))

    def plan(leaf):
        sh = getattr(leaf, "sharding", None)
        shape = getattr(leaf, "shape", None)
        if not isinstance(sh, NamedSharding) or not shape or len(shape) == 0:
            return None
        nbytes = _leaf_nbytes(leaf)
        if nbytes <= hbm_budget_bytes:
            return None
        try:
            per_dev = (
                math.prod(sh.shard_shape(tuple(shape)))
                * jnp.dtype(leaf.dtype).itemsize
            )
        except Exception:  # noqa: BLE001 — odd sharding: assume worst
            per_dev = nbytes
        if per_dev <= hbm_budget_bytes:
            return None
        if shape[0] % devs.size != 0:
            # can't stage evenly over the devices: restore direct — the
            # budget is best-effort per-leaf, so say which leaf escaped
            logger.warning(
                "elastic restore: leaf of shape %s (%d bytes) exceeds "
                "the %d-byte HBM budget but dim 0 does not divide over "
                "%d devices; restoring unbounded",
                tuple(shape), nbytes, hbm_budget_bytes, devs.size,
            )
            return None
        return staged

    return jax.tree.map(plan, target_tree)


def normalize_published_params(params: PyTree) -> PyTree:
    """Pin uncommitted leaves of a to-be-published param tree to a
    mesh-replicated placement — the same latent-placement class as the
    PR 5 resume bug: params coming out of a restored checkpoint (or a
    fresh ``jit(init)``) can carry uncommitted scalars whose placement
    conflicts with the batcher's mesh-placed cache at the first
    post-publish dispatch. No-op when the tree has no committed mesh to
    normalize against. Delegates to the batcher's own helper so the
    two can never drift; ``install_weights`` re-running it on an
    already-normalized tree is a pure traversal (no transfers)."""
    from d9d_tpu.loop.serve import _normalize_params

    return _normalize_params(params)


# ---------------------------------------------------------------------------
# live train→serve weight publish


@dataclasses.dataclass
class _CanaryPublish:
    """One in-flight canary generation: the candidate tree, its version
    stamp, and the single replica it was installed on (weakref — a dead
    canary replica must not be pinned by the pending decision)."""

    params: PyTree
    version: int
    target: weakref.ref
    unix_time: float


class WeightPublisher:
    """Fan a trainer's step-boundary param snapshot out to live serving
    replicas, generation-stamped.

    ``publish(params)`` normalizes placement, bumps the generation, and
    stages the tree into every attached batcher via
    ``ContinuousBatcher.install_weights`` — each swaps at its own next
    chunk boundary (no restart, no steady-state recompile; see
    serve.py). The publisher retains the newest fleet-wide published
    tree so a grown replica (:meth:`ServingFleet.grow`) can cold-start
    from it.

    Canaried publish (docs/design/elasticity.md "SLO autopilot"):
    :meth:`publish_canary` installs a candidate generation on exactly
    ONE replica and leaves :attr:`latest_params` (and every other
    replica) on the retained prior tree — grows and restarts during the
    canary stay on known-good weights. :meth:`promote_canary` fans the
    candidate out fleet-wide under the same generation stamp;
    :meth:`rollback_canary` re-installs the retained prior tree on the
    canary replica under a fresh stamp (a rollback is itself an
    auditable generation — two trees never share a stamp). The
    ``FleetAutopilot`` drives the promote/rollback decision from the
    canary replica's per-replica SLO deltas; a plain :meth:`publish`
    while a canary is pending supersedes (clears) it.

    Batchers are held by weakref: a retired replica must not be pinned
    (with its device cache) by the publish fan-out list.
    """

    def __init__(self, *, telemetry=None):
        self._targets: list[weakref.ref] = []
        self._tele = telemetry if telemetry is not None else get_telemetry()
        self.version = 0
        # version stamp of latest_params — diverges from ``version``
        # while a canary is pending (the canary takes a stamp without
        # becoming the fleet-wide tree until promoted)
        self.latest_version = 0
        self.latest_params: PyTree | None = None
        self.canary: _CanaryPublish | None = None

    def attach(self, batcher) -> None:
        self._targets.append(weakref.ref(batcher))

    def _live_targets(self) -> list[weakref.ref]:
        live = [ref for ref in self._targets if ref() is not None]
        self._targets = live
        return live

    def publish(self, params: PyTree, *, defer_to_idle: bool = False) -> int:
        """Install ``params`` into every live attached batcher; returns
        the new generation number. ``defer_to_idle`` asks each batcher
        to hold the swap until its in-flight requests finish. A pending
        canary is superseded: the fleet converges on THIS generation
        and the autopilot abandons the stale decision."""
        params = normalize_published_params(params)
        self.version += 1
        self.latest_version = self.version
        self.latest_params = params
        self.canary = None
        fanned = 0
        for ref in self._live_targets():
            b = ref()
            if b is None:  # died between the liveness scan and here
                continue
            b.install_weights(
                params, version=self.version, defer_to_idle=defer_to_idle
            )
            fanned += 1
        if fanned:
            self._tele.counter("serve/weight_publish_fanout").add(fanned)
        return self.version

    def publish_from(self, trainer, **kwargs) -> int:
        """Snapshot ``trainer.merged_params()`` (PEFT adapters folded,
        PP stages merged) and publish it. Call between trainer steps —
        the step boundary is what makes the snapshot consistent."""
        return self.publish(trainer.merged_params(), **kwargs)

    # -- canaried publish (decision loop: resilience/autopilot.py) -----

    def publish_canary(self, params: PyTree, *, batcher=None) -> int:
        """Install a candidate generation on ONE replica (``batcher``,
        or the first live attached one) and record it as the pending
        canary; returns its generation stamp. ``latest_params`` stays
        on the prior retained tree until :meth:`promote_canary` — the
        rollback target is therefore always at hand, and a concurrent
        ``grow()`` cold-starts on known-good weights.

        One canary at a time: a second ``publish_canary`` while one is
        pending raises — silently replacing it would strand the first
        canary replica on abandoned candidate weights with nothing left
        to roll it back. Resolve the pending one first
        (promote/rollback, or a fleet-wide :meth:`publish`, which
        supersedes by converging every replica on the new tree)."""
        if self.canary is not None:
            raise RuntimeError(
                f"a canary (generation {self.canary.version}) is already "
                "pending; promote/rollback it (or publish fleet-wide) "
                "before staging another"
            )
        if self.latest_params is None:
            # nothing retained = nothing to roll back to: a "canary"
            # with no known-good prior tree is just a publish that
            # cannot be undone — make the caller publish one first
            raise RuntimeError(
                "publish_canary needs a prior fleet-wide publish: the "
                "retained tree is the rollback target"
            )
        params = normalize_published_params(params)
        if batcher is None:
            live = self._live_targets()
            if not live:
                raise RuntimeError(
                    "publish_canary needs at least one live attached "
                    "batcher (attach one, or pass batcher=)"
                )
            batcher = live[0]()
        self.version += 1
        batcher.install_weights(params, version=self.version)
        self.canary = _CanaryPublish(
            params=params, version=self.version,
            target=weakref.ref(batcher), unix_time=time.time(),
        )
        self._tele.counter("serve/weight_canary").add(1)
        return self.version

    def promote_canary(self) -> int:
        """Fan the pending canary generation out to every OTHER live
        replica (the canary replica already runs it, same stamp) and
        make it the retained fleet-wide tree; returns its version."""
        c = self.canary
        if c is None:
            raise RuntimeError("no canary publish is pending")
        self.canary = None
        self.latest_params = c.params
        self.latest_version = c.version
        canary_b = c.target()
        fanned = 0
        for ref in self._live_targets():
            b = ref()
            if b is None or b is canary_b:
                continue
            b.install_weights(c.params, version=c.version)
            fanned += 1
        if fanned:
            self._tele.counter("serve/weight_publish_fanout").add(fanned)
        return c.version

    def rollback_canary(self) -> int:
        """Re-install the retained prior tree on the canary replica
        under a FRESH generation stamp (the audit trail must show the
        rollback as its own generation, never reuse the bad stamp);
        returns that stamp. A dead canary replica (killed mid-canary)
        just clears the pending state — its device tree died with it."""
        c = self.canary
        if c is None:
            raise RuntimeError("no canary publish is pending")
        self.canary = None
        b = c.target()
        if b is None or self.latest_params is None:
            return self.version
        self.version += 1
        b.install_weights(self.latest_params, version=self.version)
        return self.version


# ---------------------------------------------------------------------------
# serving fleet: preemption-driven shrink/grow


@dataclasses.dataclass
class _FleetRequest:
    prompt: list[int]
    max_new_tokens: int
    # ABSOLUTE perf_counter deadline, fixed at fleet submit time: a
    # migration resubmits with the REMAINING budget, so shrink/kill
    # recovery can never extend a request's lifetime past its contract
    deadline_t: float | None
    replica: int | None = None
    local_rid: int | None = None
    # tokens already emitted on replicas that died before finishing this
    # request; resubmission feeds prompt + prefix as a continuation
    prefix: list[int] = dataclasses.field(default_factory=list)
    migrations: int = 0
    # fleet-stable trace id (docs/design/observability.md): minted once
    # at the fleet front door and re-submitted verbatim across every
    # migration and kill-recovery continuation, so the request is ONE
    # continuous track however many replicas it crosses
    trace_id: str | None = None
    # admission tier (higher = more important): what the autopilot's
    # burn-driven shedding orders on — see ServingFleet.shed_queued
    priority: int = 0
    # disaggregated-serving stage: "direct" (unified fleet — the whole
    # request runs where it lands), "prefill" (awaiting its prefill leg
    # on a prefill-role replica: budget clamped to the first token),
    # "decode" (post-handoff or post-fallback: the continuation runs
    # out the remaining budget on a decode-capable replica)
    stage: str = "direct"


class ServingFleet:
    """Route requests over N ``ContinuousBatcher`` replicas; shrink on
    preemption, grow from published weights.

    Admission rides the PR 5 backpressure contract: :meth:`submit`
    tries live replicas least-loaded-first and lets each replica's
    bounded queue reject (``QueueFullError``); when every replica
    rejects, the fleet re-raises — overload stays an explicit,
    retryable signal end to end. Internal *migrations* (shrink/kill
    recovery) are never dropped on backpressure: they wait in a
    fleet-level overflow queue and re-place at each step boundary.

    Deterministic chaos hooks (``resilience/chaos.py``): the
    ``shrink_at_step`` / ``kill_replica_mid_drain`` injectors arm
    ``_chaos_shrink`` / ``_chaos_kill``, consumed at exact step-round /
    drain-chunk indices.
    """

    def __init__(self, *, publisher: WeightPublisher | None = None,
                 telemetry=None, metrics_port: int | None = None):
        self._replicas: dict[int, Any] = {}
        self._live: set[int] = set()
        self._next_idx = 0
        self._reqs: dict[int, _FleetRequest] = {}
        self._by_replica: dict[tuple[int, int], int] = {}
        self._next_frid = 0
        self._overflow: deque[int] = deque()
        self._publisher = publisher
        self._tele = telemetry if telemetry is not None else get_telemetry()
        self._preemption: tuple[Any, int] | None = None
        self._chaos_shrink: tuple[int, int] | None = None
        self._chaos_kill: tuple[int, int] | None = None
        # disaggregated-serving chaos arms (resilience/chaos.py):
        # kill_prefill_mid_handoff arms the replica idx to die with
        # exported-but-unimported pages in flight; corrupt_handoff_payload
        # arms a byte flip on the next shipment (the checksum must catch)
        self._chaos_kill_handoff: int | None = None
        self._chaos_corrupt_handoff: bool = False
        self._rounds = 0
        # replica roles (docs/design/elasticity.md "Disaggregated
        # serving"): "prefill" replicas take new requests' first-token
        # leg, "decode" replicas run continuations; "unified" (default)
        # does both — an all-unified fleet behaves exactly as before
        self._roles: dict[int, str] = {}
        # fleet-wide prefix directory: content-chain block key → live
        # replica idx whose allocator holds it READY. Rebuilt each
        # scheduling round from the live replicas (a dead owner drops
        # out on the next sync; a stale entry is harmless — export
        # returns None and the request falls back to local prefill),
        # cleared fleet-wide whenever the publisher's generation moves
        self._prefix_dir: dict[bytes, int] = {}
        self._dir_seen_version: int | None = None
        # bound by FleetAutopilot.attach (resilience/autopilot.py):
        # polled once per scheduling round, BEFORE any chunk dispatches
        # — the control loop acts only at this boundary cadence
        self._autopilot = None
        # fleet-level rollup gauges (the per-replica gauges are
        # namespaced serve/r{i}/* — last-write-wins gauges cannot share
        # a name across replicas, so the fleet computes explicit sums);
        # weakref'd so the hub never pins a discarded fleet + replicas
        fleet_ref = weakref.ref(self)
        self._gauge_fns = {
            "serve/fleet_queue_depth":
                lambda: f._queue_depth() if (f := fleet_ref()) is not None
                else float("nan"),
            "serve/fleet_tokens_per_s":
                lambda: f._fleet_rate() if (f := fleet_ref()) is not None
                else float("nan"),
            # paged-KV rollups (docs/design/generation.md): fleet-wide
            # page-pool headroom; NaN while no live replica is paged
            "serve/fleet_kv_pages_free":
                lambda: f._kv_pages("pages_free")
                if (f := fleet_ref()) is not None else float("nan"),
            "serve/fleet_kv_pages_in_use":
                lambda: f._kv_pages("pages_in_use")
                if (f := fleet_ref()) is not None else float("nan"),
            # fleet prefix directory size (disaggregated serving)
            "serve/fleet_prefix_entries":
                lambda: float(len(f._prefix_dir))
                if (f := fleet_ref()) is not None else float("nan"),
        }
        for name, fn in self._gauge_fns.items():
            self._tele.gauge_fn(name, fn)
        # opt-in fleet metrics endpoint (telemetry/export.py): /metrics
        # aggregates every replica's namespaced instruments + the fleet
        # rollups from the shared registry; /healthz reports per-replica
        # status; /readyz = at least one live replica past its first
        # readback. close() shuts it down.
        self.metrics_server = None
        if metrics_port is not None:
            from d9d_tpu.telemetry import MetricsServer

            self.metrics_server = MetricsServer(
                self._tele,
                port=metrics_port,
                readiness=lambda: (
                    (f.ready, {"live_replicas": list(f.live_replicas)})
                    if (f := fleet_ref()) is not None else (False, {})
                ),
                health=lambda: (
                    f.replica_health() if (f := fleet_ref()) is not None
                    else {"gone": True}
                ),
            ).start()
        self.retired: set[int] = set()  # drained cleanly
        self.dead: set[int] = set()     # killed mid-drain
        # fleet-level retirement without completion (mirrors the PR 5
        # batcher surface): frid → reason, partial output kept
        self.failed: dict[int, str] = {}
        # finished requests retire out of _reqs into a bounded-FIFO
        # output snapshot: a long-lived fleet must not grow host memory
        # with total requests served, and finished() must not depend on
        # the replicas' own bounded done-FIFO staying warm (the same
        # retention invariant ContinuousBatcher._retire protects)
        self._finished_outputs: dict[int, list[int]] = {}
        self._finished_fifo: deque[int] = deque()

    # -- monitoring plane ----------------------------------------------

    def _queue_depth(self) -> float:
        """Waiting requests across the fleet: every live replica's
        admission queue plus the fleet-level overflow queue."""
        depth = len(self._overflow)
        for i in self._live:
            depth += len(self._replicas[i]._queue)
        return float(depth)

    def _fleet_rate(self) -> float:
        return float(sum(
            self._replicas[i]._live_rate() for i in self._live
        ))

    def _kv_pages(self, attr: str) -> float:
        """Sum a paged-KV pool counter over live PAGED replicas (a
        mixed or unpaged fleet reports NaN rather than a misleading 0
        — absence of paging is not an empty pool)."""
        total, any_paged = 0.0, False
        for i in self._live:
            kv = getattr(self._replicas[i], "_kv", None)
            if kv is not None:
                any_paged = True
                total += float(getattr(kv, attr))
        return total if any_paged else float("nan")

    @property
    def ready(self) -> bool:
        """At least one live replica past its first readback — the
        fleet /readyz contract (a cold fleet mid-compile is not ready,
        a fleet that lost one replica but still serves is)."""
        return any(
            getattr(self._replicas[i], "ready", False) for i in self._live
        )

    def replica_health(self) -> dict[str, Any]:
        """Per-replica status block for the fleet /healthz endpoint —
        with an autopilot bound, its control-loop state (burning
        policies, pending canary, last decision) rides along so one
        scrape explains both what the fleet looks like and what the
        controller is about to do about it."""
        replicas = {}
        for idx, b in self._replicas.items():
            replicas[str(idx)] = {
                "live": idx in self._live,
                "retired": idx in self.retired,
                "dead": idx in self.dead,
                "ready": bool(getattr(b, "ready", False)),
                "active": int(b.active),
                "role": self._role(idx),
            }
        roles: dict[str, int] = {}
        for i in self._live:
            roles[self._role(i)] = roles.get(self._role(i), 0) + 1
        out = {
            "replicas": replicas,
            "overflow": len(self._overflow),
            "ready": self.ready,
            # live-replica count per fleet role: the disaggregated
            # provisioning view (what the role-aware autopilot scales)
            "roles": roles,
        }
        if self._autopilot is not None:
            out["autopilot"] = self._autopilot.status()
        return out

    def close(self) -> None:
        """Release the fleet's host-side attachments (metrics endpoint,
        the fleet rollup gauges, every replica's)."""
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        for name, fn in self._gauge_fns.items():
            # fn-guarded: a newer fleet's registration under the same
            # name must survive this (older) fleet's close
            self._tele.registry.unregister_gauge_fn(name, fn)
        for b in self._replicas.values():
            close = getattr(b, "close", None)
            if close is not None:
                close()

    def _trace(self, trace_id: str | None, event: str, **meta) -> None:
        """Fleet-side request_trace event (migrations, continuations —
        milestones no single replica can see)."""
        if trace_id is None:
            return
        rec: dict[str, Any] = {
            "trace_id": trace_id, "event": event, "t": time.perf_counter(),
        }
        if meta:
            rec["meta"] = meta
        self._tele.record_request_trace(rec)

    # -- replica lifecycle ---------------------------------------------

    _ROLES = ("prefill", "decode", "unified")

    def add_replica(self, batcher, *, role: str = "unified") -> int:
        """Register a replica under a fleet role. ``prefill`` replicas
        take new requests' first-token leg and hand off via KV page
        shipment; ``decode`` replicas run the continuations; ``unified``
        (the default) does both — a fleet of unified replicas behaves
        exactly as before this distinction existed."""
        if role not in self._ROLES:
            raise ValueError(
                f"role must be one of {self._ROLES}, got {role!r}"
            )
        idx = self._next_idx
        self._next_idx += 1
        self._replicas[idx] = batcher
        self._roles[idx] = role
        self._live.add(idx)
        # replica conflation fix (docs/design/observability.md): each
        # replica's serve instruments get a fleet-assigned namespace
        # (serve/r{i}/...) unless the embedder labeled it already
        if (
            getattr(batcher, "_replica_label", None) is None
            and hasattr(batcher, "set_replica_label")
        ):
            batcher.set_replica_label(f"r{idx}")
        if self._publisher is not None:
            self._publisher.attach(batcher)
            if self._publisher.latest_params is not None:
                # latest_version, not version: while a canary is pending
                # the version counter belongs to the canary generation —
                # a replica added mid-canary runs the RETAINED tree and
                # must carry that tree's stamp
                batcher.install_weights(
                    self._publisher.latest_params,
                    version=self._publisher.latest_version,
                )
        self._tele.gauge("serve/fleet_replicas").set(len(self._live))
        return idx

    def grow(
        self, make_batcher: Callable[[PyTree], Any], *,
        role: str = "unified",
    ) -> int:
        """Cold-start a replacement replica from the latest *published*
        weights — the recovery half of a preemption shrink. The factory
        receives the published param tree and returns a batcher;
        ``role`` assigns the new replica's fleet pool (the role-aware
        autopilot grows prefill and decode pools independently)."""
        if self._publisher is None or self._publisher.latest_params is None:
            raise RuntimeError(
                "grow() cold-starts replicas from the latest published "
                "weights; attach a WeightPublisher and publish first"
            )
        idx = self.add_replica(
            make_batcher(self._publisher.latest_params), role=role
        )
        self._tele.counter("serve/fleet_grows").add(1)
        return idx

    def bind_preemption(self, guard, replica_idx: int) -> None:
        """Wire PR 5's preemption signal as the shrink trigger: once
        ``guard.triggered`` (SIGTERM landed), the next :meth:`step`
        drains ``replica_idx`` into the survivors."""
        self._preemption = (guard, int(replica_idx))

    # -- admission ------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: int,
        deadline_s: Optional[float] = None,
        priority: int = 0,
    ) -> int:
        """Queue a request on the least-loaded live replica; returns the
        fleet-level request id. Raises ``QueueFullError`` when every
        live replica's bounded queue rejects (fleet-level backpressure:
        shed or retry, exactly like the single-replica contract).

        ``priority`` tiers admission for the autopilot's burn-driven
        shedding (higher = protected longer; admission order itself
        stays FIFO — see ``ContinuousBatcher.submit``).

        The fleet front door mints the request's trace id here; every
        placement (including migrations and kill-recovery continuations)
        re-submits with the same id, so the request's schema-v3
        ``request_trace`` stream is one continuous track."""
        from d9d_tpu.loop.serve import QueueFullError, mint_trace_id

        frid = self._next_frid
        self._next_frid += 1
        # with any live prefill-role replica the request runs its
        # first-token leg there and hands off (docs/design/elasticity.md
        # "Disaggregated serving"); an all-unified/decode fleet serves
        # it in one place, exactly as before roles existed
        disagg = any(self._role(i) == "prefill" for i in self._live)
        req = _FleetRequest(
            [int(x) for x in prompt], int(max_new_tokens),
            time.perf_counter() + deadline_s
            if deadline_s is not None else None,
            trace_id=mint_trace_id(),
            priority=int(priority),
            stage="prefill" if disagg else "direct",
        )
        self._reqs[frid] = req
        # front-door placements consult the fleet prefix directory, so
        # refresh it HERE, not just at step boundaries — a shared prompt
        # submitted right after its twin finished must still ship pages
        # instead of recomputing ("once per fleet", not "once per round")
        self._sync_prefix_dir()
        try:
            placed = self._try_place(frid)
        except BaseException:
            # a replica-side validation error (bad budget, prompt over
            # decode_max_length, ...) must not leave a ghost request
            # that can never finish and wedges every later drain()
            del self._reqs[frid]
            raise
        if not placed:
            del self._reqs[frid]
            # the fleet owns the terminal rejection event: individual
            # replica rejections during placement are not terminal (a
            # survivor may still accept), this is
            self._trace(req.trace_id, "rejected",
                        live_replicas=len(self._live))
            raise QueueFullError(
                f"all {len(self._live)} live replicas rejected the "
                "request (bounded queues full); retry after drain"
            )
        return frid

    def _role(self, i: int) -> str:
        return self._roles.get(i, "unified")

    def _capacity_short(self, i: int, total_tokens: int) -> bool:
        """Would replica ``i``'s page pool head-of-line-block a request
        of this token footprint even after the next deferred flush?
        Contiguous replicas are never short (admission is slot-bounded
        there); prefix hits and LRU eviction could only help, so this
        is a conservative RANKING signal, not an admission gate."""
        kv = getattr(self._replicas[i], "_kv", None)
        if kv is None:
            return False
        return kv.pages_needed(total_tokens) > kv.pages_free_after_flush()

    def _place_order(
        self, req: _FleetRequest, *, exclude: frozenset = frozenset()
    ) -> list[int]:
        """Placement candidates, best first: role pool (a prefill-stage
        request prefers prefill replicas, a continuation prefers
        decode, unified serves either; the off-role pools stay as
        fallbacks — availability beats role purity), then KV capacity
        (a paged replica whose pool cannot map the request ranks behind
        one with headroom instead of accepting a head-of-line wait),
        then least-loaded."""
        if req.stage == "prefill":
            pools = ("prefill", "unified", "decode")
            remaining = 1
        else:
            pools = ("decode", "unified", "prefill")
            remaining = max(req.max_new_tokens - len(req.prefix), 1)
        total = len(req.prompt) + len(req.prefix) + remaining - 1
        return sorted(
            (i for i in self._live if i not in exclude),
            key=lambda i: (
                pools.index(self._role(i)),
                self._capacity_short(i, total),
                self._replicas[i].active,
                i,
            ),
        )

    def _try_place(
        self, frid: int, *, exclude: frozenset = frozenset(),
        prefer: int | None = None,
    ) -> bool:
        from d9d_tpu.loop.serve import QueueFullError

        req = self._reqs[frid]
        remaining = req.max_new_tokens - len(req.prefix)
        if remaining <= 0:
            return True  # fully emitted before its last replica died
        if req.stage == "prefill":
            # the prefill leg fills the prompt's pages and emits the
            # FIRST token (TTFT happens here); the remaining budget
            # runs on the decode side after the handoff
            remaining = 1
        deadline_s = None
        if req.deadline_t is not None:
            # preserve the ABSOLUTE deadline across migrations: the
            # survivor gets only the time still left on the contract
            deadline_s = req.deadline_t - time.perf_counter()
            if deadline_s <= 0:
                self.failed[frid] = "deadline"
                self._tele.counter("serve/expired").add(1)
                self._trace(
                    req.trace_id, "expired", reason="deadline",
                    at="fleet_place", tokens=len(req.prefix),
                )
                req.replica = req.local_rid = None
                return True  # retired: partial prefix kept, like PR 5
        order = self._place_order(req, exclude=exclude)
        if prefer is not None and prefer in order:
            order.remove(prefer)
            order.insert(0, prefer)
        prompt = req.prompt + req.prefix
        shipped = False
        for i in order:
            if not shipped:
                # fleet prefix directory: before the first (best)
                # candidate prefills a prompt another replica already
                # holds, ship those pages over instead of recomputing —
                # a shared prompt prefills once per FLEET. One attempt
                # per placement; failures just mean a local prefill.
                shipped = True
                self._maybe_ship_prefix(prompt, i)
            try:
                rid = self._replicas[i].submit(
                    prompt,
                    max_new_tokens=remaining,
                    deadline_s=deadline_s,
                    trace_id=req.trace_id,
                    priority=req.priority,
                )
            except QueueFullError:
                continue
            req.replica, req.local_rid = i, rid
            self._by_replica[(i, rid)] = frid
            return True
        req.replica = req.local_rid = None
        return False

    def _maybe_ship_prefix(self, prompt: list[int], target: int) -> None:
        """Local prefix miss + fleet-directory hit: ship the cached
        pages from their live owner into ``target`` before the prompt
        admits there. Every failure (stale directory entry, dead or
        mid-chunk owner, version skew, checksum, pool pressure) counts
        a miss and degrades to a local prefill — never an error."""
        tb = self._replicas[target]
        kv = getattr(tb, "_kv", None)
        if kv is None or not kv.prefix_cache_enabled or not self._prefix_dir:
            return
        ps = kv.page_size
        cap = (len(prompt) - 1) // ps  # admission's max hit run
        if cap <= 0:
            return
        tokens = prompt[: cap * ps]
        if len(kv.export_prefix(tokens)) >= cap:
            return  # full local hit: nothing a shipment could add
        keys = kv._chain_keys(tokens, cap)
        owner = None
        for d in range(cap - 1, -1, -1):  # deepest cached block wins
            cand = self._prefix_dir.get(keys[d])
            if cand is not None and cand in self._live and cand != target:
                owner = cand
                break
        if owner is None:
            self._tele.counter("serve/fleet_prefix_misses").add(1)
            return
        ship = self._replicas[owner].export_kv_pages(tokens)
        if ship is not None and tb.import_kv_pages(ship):
            self._tele.counter("serve/fleet_prefix_hits").add(1)
        else:
            self._tele.counter("serve/fleet_prefix_misses").add(1)

    def shed_queued(self, n: int) -> list[int]:
        """Retire up to ``n`` QUEUED (never-admitted) fleet requests as
        explicit ``failed[frid] == "shed"`` — lowest priority first,
        longest remaining deadline first within a tier (a deadline-less
        request is infinitely patient: it sheds before anything with a
        contract), newest first as the final tiebreak. Running rows are
        never shed (their committed tokens are real work); shedding
        only empties queue positions, which is exactly what relieves a
        burning latency SLO and what frees bounded-queue capacity so
        high-priority traffic stops seeing ``QueueFullError`` at the
        front door. Returns the shed fleet request ids.

        This is the autopilot's actuator (burn-driven admission
        tiering, docs/design/elasticity.md "SLO autopilot"); callers
        may also invoke it directly as a manual load-shed."""
        if n <= 0:
            return []
        queued_rids = {
            (i, q.rid)
            for i in self._live
            for q in self._replicas[i]._queue
        }
        overflow = set(self._overflow)
        candidates = []
        for frid, req in self._reqs.items():
            if frid in self.failed:
                continue
            if frid in overflow:
                where = "overflow"
            elif (
                req.replica is not None
                and (req.replica, req.local_rid) in queued_rids
            ):
                where = "replica"
            else:
                continue  # running (or already finishing): never shed
            candidates.append((frid, req, where))
        candidates.sort(key=lambda item: (
            item[1].priority,
            -(item[1].deadline_t if item[1].deadline_t is not None
              else math.inf),
            -item[0],
        ))
        shed: list[int] = []
        for frid, req, where in candidates[:n]:
            if where == "overflow":
                self._overflow.remove(frid)
                self._tele.counter("serve/shed").add(1)
                self._trace(
                    req.trace_id, "failed", reason="shed",
                    at="fleet_overflow", priority=req.priority,
                )
            else:
                b = self._replicas[req.replica]
                if not b.cancel_queued(req.local_rid, "shed"):
                    continue  # admitted since the scan: let it run
                self._by_replica.pop((req.replica, req.local_rid), None)
            self.failed[frid] = "shed"
            shed.append(frid)
        return shed

    # -- progress -------------------------------------------------------

    # finished-request output snapshots retained for the host API
    _MAX_FINISHED = 50_000

    def finished(self, frid: int) -> bool:
        if frid in self._finished_outputs or frid in self.failed:
            return True
        req = self._reqs.get(frid)
        if req is None:
            if 0 <= frid < self._next_frid:
                return True  # retired beyond the retention horizon
            raise KeyError(f"unknown fleet request id {frid}")
        if req.replica is None:
            return len(req.prefix) >= req.max_new_tokens
        b = self._replicas[req.replica]
        if req.local_rid not in b.done:
            return False
        if req.stage == "prefill" and req.local_rid not in b.failed:
            # the prefill LEG is done but the request is not: the
            # handoff (step()._poll_handoffs) still owes the decode
            # placement — unless the first token already exhausted the
            # budget, or EOS landed on it
            emitted = len(req.prefix) + len(b.outputs.get(req.local_rid, []))
            if emitted >= req.max_new_tokens:
                return True
            eos = getattr(b, "_eos", None)
            out = b.outputs.get(req.local_rid, [])
            return bool(out) and eos is not None and out[-1] == eos
        return True

    def outputs(self, frid: int) -> list[int]:
        """Emitted tokens for a fleet request: dead-replica prefix plus
        whatever its current replica has harvested (a retired request
        returns its snapshot, within the bounded retention horizon —
        like the batcher's ``_MAX_FINISHED_STATS`` contract, read
        results within it; past it this raises with an explanation)."""
        if frid in self._finished_outputs:
            return list(self._finished_outputs[frid])
        req = self._reqs.get(frid)
        if req is None:
            if 0 <= frid < self._next_frid:
                raise KeyError(
                    f"fleet request {frid} finished and was evicted from "
                    f"the bounded retention horizon "
                    f"({self._MAX_FINISHED} snapshots)"
                )
            raise KeyError(f"unknown fleet request id {frid}")
        toks = list(req.prefix)
        if req.replica is not None:
            toks += list(
                self._replicas[req.replica].outputs.get(req.local_rid, [])
            )
        return toks[: req.max_new_tokens]

    def _retire_finished(self) -> None:
        """Snapshot finished requests' outputs and drop their live
        records (bounded FIFO) — called at the end of every drain so
        neither ``_reqs`` nor ``_by_replica`` grows with lifetime
        traffic, and a finished request's result stays readable even
        after its replica's own done-FIFO rotates."""
        for frid in [f for f in self._reqs if self.finished(f)]:
            self._finished_outputs[frid] = self.outputs(frid)
            req = self._reqs.pop(frid)
            if req.replica is not None:
                # surface replica-level retirements (deadline expiry on
                # the replica) at the fleet: "finished" must not make a
                # failed request read as a successful short completion
                reason = self._replicas[req.replica].failed.get(
                    req.local_rid
                )
                if reason is not None:
                    self.failed.setdefault(frid, reason)
                self._by_replica.pop((req.replica, req.local_rid), None)
            self._finished_fifo.append(frid)
        while len(self._finished_fifo) > self._MAX_FINISHED:
            old = self._finished_fifo.popleft()
            self._finished_outputs.pop(old, None)
            self.failed.pop(old, None)

    def step(self) -> None:
        """One scheduling round: poll the bound autopilot (its control
        actions happen HERE, at the clean boundary before any chunk
        dispatches — never on an evaluation thread), consume the
        preemption/chaos triggers, retry overflow placements, advance
        every live replica a chunk."""
        self._rounds += 1
        if self._autopilot is not None:
            self._autopilot.poll()
        if self._preemption is not None:
            guard, idx = self._preemption
            if guard.triggered and idx in self._live:
                self._preemption = None
                self._tele.counter("resilience/preempt_shrinks").add(1)
                self.shrink(idx)
        if (
            self._chaos_shrink is not None
            and self._rounds >= self._chaos_shrink[1]
            and self._chaos_shrink[0] in self._live
        ):
            idx = self._chaos_shrink[0]
            self._chaos_shrink = None
            self.shrink(idx)
        self._sync_prefix_dir()
        self._poll_handoffs()
        for frid in [self._overflow.popleft() for _ in range(len(self._overflow))]:
            if not self._try_place(frid):
                self._overflow.append(frid)
        for i in sorted(self._live):
            self._replicas[i].step_chunk()

    # -- disaggregated serving: prefix directory + handoff -------------

    def _sync_prefix_dir(self) -> None:
        """Rebuild the fleet prefix directory from the live paged
        replicas' READY entries (dead/retired owners drop out here).
        A weight publish moves the generation: the directory clears
        fleet-wide and repopulates NEXT round, once the replicas have
        applied the publish at their own boundaries — and the shipment
        weights-version pin keeps even the in-between window safe."""
        if self._publisher is not None:
            v = self._publisher.version
            if v != self._dir_seen_version:
                self._dir_seen_version = v
                if self._prefix_dir:
                    self._prefix_dir = {}
                    self._tele.counter(
                        "serve/fleet_prefix_invalidations"
                    ).add(1)
                return
        dir_: dict[bytes, int] = {}
        for i in sorted(self._live):
            kv = getattr(self._replicas[i], "_kv", None)
            if kv is None or not kv.prefix_cache_enabled:
                continue
            for key, e in kv._entries.items():
                if e.ready and key not in dir_:
                    dir_[key] = i
        self._prefix_dir = dir_

    def _poll_handoffs(self) -> None:
        """Advance prefill-stage requests whose first-token leg is done:
        harvest the leg's tokens into the continuation prefix, flip the
        stage to decode, and hand off (page shipment + placement). A
        leg that already exhausted its budget or hit EOS is complete —
        it retires through the normal finished() path untouched."""
        for frid, req in list(self._reqs.items()):
            if (
                req.stage != "prefill" or req.replica is None
                or frid in self.failed
            ):
                continue
            src = req.replica
            b = self._replicas[src]
            if req.local_rid not in b.done or req.local_rid in b.failed:
                continue
            out = list(b.outputs.get(req.local_rid, []))
            eos = getattr(b, "_eos", None)
            if len(req.prefix) + len(out) >= req.max_new_tokens or (
                out and eos is not None and out[-1] == eos
            ):
                continue  # complete at the prefill leg: nothing to hand off
            self._by_replica.pop((src, req.local_rid), None)
            req.prefix = req.prefix + out
            req.replica = req.local_rid = None
            req.stage = "decode"
            self._handoff(frid, req, src)

    def _handoff(self, frid: int, req: _FleetRequest, src: int) -> None:
        """One prefill→decode handoff: export the prompt's READY prefix
        pages from the prefill replica, import them into the chosen
        decode target, place the continuation there. The original trace
        id, absolute deadline, priority tier and weights-version pin
        all ride along. EVERY failure — dead source, dirty boundary,
        version skew, corrupt shipment, pool pressure — degrades to the
        placement below, which re-prefills from prompt + harvested
        tokens token-identically (the PR 8/10 kill-recovery contract):
        fallback, not failure, is the contract."""
        prompt = req.prompt + req.prefix
        order = self._place_order(req)
        targets = [i for i in order if i != src] or order
        target = targets[0] if targets else None
        ship = None
        src_b = self._replicas.get(src)
        if target is not None and src in self._live and src_b is not None:
            tkv = getattr(self._replicas[target], "_kv", None)
            if tkv is not None and getattr(src_b, "_kv", None) is not None:
                cap = (len(prompt) - 1) // tkv.page_size
                if cap > 0:
                    ship = src_b.export_kv_pages(
                        prompt[: cap * tkv.page_size]
                    )
        if self._chaos_kill_handoff == src:
            # chaos: the prefill replica dies with exported-but-
            # unimported pages in flight — the shipment is lost with
            # it; its other in-flight requests recover via continuation
            self._chaos_kill_handoff = None
            ship = None
            self._live.discard(src)
            self._tele.gauge("serve/fleet_replicas").set(len(self._live))
            self._recover_killed(src)
        if ship is not None and self._chaos_corrupt_handoff:
            # chaos: flip one payload byte — the per-page checksum must
            # catch it BEFORE the importer mutates anything
            self._chaos_corrupt_handoff = False
            name = sorted(ship.payload)[0]
            raw = ship.payload[name].copy()
            raw.view(np.uint8).flat[0] ^= 0xFF
            ship.payload[name] = raw
        imported = False
        if ship is not None and target is not None:
            imported = self._replicas[target].import_kv_pages(ship)
        if imported:
            self._tele.counter("serve/fleet_handoffs").add(1)
        else:
            self._tele.counter("serve/fleet_handoff_fallbacks").add(1)
        self._trace(
            req.trace_id, "handoff",
            from_replica=src, to_replica=target,
            pages=ship.n_pages if (ship is not None and imported) else 0,
            fallback=not imported, prefix_tokens=len(req.prefix),
        )
        if not self._try_place(frid, prefer=target):
            self._overflow.append(frid)

    def drain(self, max_rounds: int = 10_000) -> dict[int, list[int]]:
        """Run scheduling rounds until every live fleet request
        finishes; returns ``{fleet_rid: tokens}`` for them, then
        retires their records into the bounded snapshot store."""
        rounds = 0
        while not all(self.finished(frid) for frid in self._reqs):
            self.step()
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError("fleet drain exceeded max_rounds")
        out = {frid: self.outputs(frid) for frid in self._reqs}
        self._retire_finished()
        return out

    # -- shrink / recovery ---------------------------------------------

    def shrink(self, idx: int) -> None:
        """Retire replica ``idx``: stop routing to it, migrate its
        queued (never-admitted) requests into survivors under the
        backpressure contract, and drain its running rows to completion
        inside the preemption grace window. A replica that dies during
        this drain is recovered by :meth:`_recover_killed`."""
        b = self._replicas[idx]
        self._live.discard(idx)
        self._tele.counter("serve/fleet_shrinks").add(1)
        self._tele.gauge("serve/fleet_replicas").set(len(self._live))
        for rid, _prompt, _mnt, _dl in b.eject_queued():
            frid = self._by_replica.pop((idx, rid), None)
            if frid is None:
                # submitted directly to the batcher, not through the
                # fleet: it can't be migrated (the caller holds THIS
                # replica's rid), so retire it as an explicit failure
                # instead of silently destroying it
                b.fail_request(rid, "shrunk")
                continue
            # migrated: the receiving replica re-admits under a new
            # local rid; drop the dying replica's now-dead records
            b.outputs.pop(rid, None)
            b.request_stats.pop(rid, None)
            req = self._reqs[frid]
            req.replica = req.local_rid = None
            req.migrations += 1
            self._tele.counter("serve/fleet_migrated").add(1)
            self._trace(
                req.trace_id, "migrate", reason="shrink",
                from_replica=idx, migrations=req.migrations,
            )
            if not self._try_place(frid, exclude=frozenset({idx})):
                self._overflow.append(frid)
        chunks = 0
        while b._busy() or b._pending:
            if (
                self._chaos_kill is not None
                and self._chaos_kill[0] == idx
                and chunks >= self._chaos_kill[1]
            ):
                self._chaos_kill = None
                self._recover_killed(idx)
                return
            b.step_chunk()
            chunks += 1
            # the grace drain must not stall the rest of the fleet: the
            # survivors — now carrying the migrated queue — keep
            # dispatching while the dying replica finishes its rows
            # (their own deadlines are absolute; a synchronous-only
            # drain would expire them spuriously)
            for i in sorted(self._live):
                self._replicas[i].step_chunk()
        self.retired.add(idx)

    def _recover_killed(self, idx: int) -> None:
        """The dying replica is gone mid-drain: resubmit its unfinished
        requests to survivors as continuation prompts (original prompt +
        tokens already harvested), so completed work is kept and greedy
        decoding resumes token-identically."""
        b = self._replicas[idx]
        self.dead.add(idx)
        self._tele.counter("serve/fleet_replica_deaths").add(1)
        # the dead replica's prefix pages die with it: drop its directory
        # entries NOW so no waiter wedges on a dead owner — shipping falls
        # back to local prefill until the next directory rebuild
        self._prefix_dir = {
            k: i for k, i in self._prefix_dir.items() if i != idx
        }
        recovered = 0
        for frid, req in self._reqs.items():
            if req.replica != idx or req.local_rid in b.done:
                continue
            # the dead replica's mapping is gone with it — drop it so
            # the index doesn't accumulate stale (dead-replica, rid)
            # entries across migrations
            self._by_replica.pop((idx, req.local_rid), None)
            req.prefix = req.prefix + list(b.outputs.get(req.local_rid, []))
            req.replica = req.local_rid = None
            req.migrations += 1
            recovered += 1
            self._tele.counter("serve/fleet_migrated").add(1)
            # the continuation keeps the ORIGINAL trace id: the harvested
            # prefix + the survivor's teacher-forced replay stay one track
            self._trace(
                req.trace_id, "continuation", reason="replica_death",
                from_replica=idx, prefix_tokens=len(req.prefix),
                migrations=req.migrations,
            )
            if len(req.prefix) >= req.max_new_tokens:
                continue
            if not self._try_place(frid, exclude=frozenset({idx})):
                self._overflow.append(frid)
        # black-box dump at the moment of death (no-op unless a flight
        # recorder is configured on the hub): the last metric windows +
        # span tail are exactly the post-mortem a dead replica can no
        # longer answer for itself
        self._tele.dump_flight_record(
            "replica_death",
            extra={"replica": idx, "recovered_requests": recovered},
        )

    @property
    def live_replicas(self) -> tuple[int, ...]:
        return tuple(sorted(self._live))
