"""SLO autopilot: burn-rate-driven fleet control
(docs/design/elasticity.md "SLO autopilot", ROADMAP item 3).

PR 9 built the senses — burn-rate ``SloMonitor`` policies, per-replica
``serve/r{i}/*`` instruments, the flight recorder — and PR 8 built the
actuators — ``ServingFleet.grow/shrink``, eject/migrate, the
zero-recompile ``install_weights`` publish path. Nothing connected
them: a burning TTFT policy paged an operator who acted by hand. This
module is the controller in between. :class:`FleetAutopilot` subscribes
to ``SloMonitor`` evaluations (sense) and drives the fleet through
three policies (act), every action producing an auditable
``autopilot/*`` telemetry bump, a JSONL decision-log line, and — for
destructive actions — a flight-recorder dump:

- **Burn-driven autoscaling.** A scale policy burning continuously for
  ``grow_after_s`` grows a cold replica from the publisher's latest
  (known-good) weights; a fleet that is idle — queue depth AND slot
  utilization under their floors — for ``idle_after_s`` shrinks back
  toward ``min_replicas`` through the existing migration path.
  Hysteresis both directions: sustained-burn / sustained-idle windows
  plus a shared ``cooldown_s`` between scale actions, so an oscillating
  load cannot flap the fleet.
- **Admission tiering under burn.** While a scale policy burns, queued
  traffic beyond ``shed_queue_depth`` is shed lowest-priority /
  longest-deadline first (``ServingFleet.shed_queued`` →
  ``failed[frid] == "shed"``, ``serve/shed``) instead of failing
  uniformly with ``QueueFullError`` at the front door — the
  backpressure contract is unchanged, the autopilot just chooses WHO
  absorbs it.
- **Canaried weight publish.** ``WeightPublisher.publish_canary``
  installs a candidate generation on one replica; the autopilot scopes
  temporary per-replica SLO policies over that replica's
  ``serve/r{i}/*`` instruments (``SloMonitor.extend``) next to
  same-window rollup twins, and after ``canary_window_s`` compares the
  deltas: a canary observably worse than both the policy target and
  the fleet rollup (× ``canary_tolerance``) rolls back to the retained
  prior tree (flight-recorder dump); otherwise it promotes fleet-wide.

Control-loop discipline (the bench-gated contract): SLO evaluations may
run on scrape threads, so the subscriber only *records* the latest
statuses; all fleet mutation happens in :meth:`poll`, which
``ServingFleet.step`` calls once per scheduling round at the clean
boundary before any chunk dispatches. The autopilot is pure host work —
no jax imports, zero added per-token dispatches/readbacks
(``tools/bench_compare.py``'s autopilot leg pins the structural counts
byte-identical to the plain serving leg).

Every quantity the controller reasons about flows through the
injectable ``clock`` (default ``time.monotonic``), so hysteresis,
decision windows and the chaos acceptance leg run deterministically
without sleeping wall time.
"""

import dataclasses
import json
import logging
import math
import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional, Sequence

from d9d_tpu.telemetry import get_telemetry
from d9d_tpu.telemetry.slo import SloPolicy, SloStatus

logger = logging.getLogger("d9d_tpu.resilience")

__all__ = [
    "AutopilotConfig",
    "DecisionLog",
    "FleetAutopilot",
    "read_decisions",
]

# canary comparator twins must never page or bump slo/violations on
# their own — they exist to be READ at the decision point, so their
# burn threshold is unreachable (observed/target can't meaningfully hit
# 1e18x) and ``violating`` stays False however bad the canary is
_CANARY_BURN_RATE = 1e18


@dataclasses.dataclass(frozen=True)
class AutopilotConfig:
    """Control thresholds (all durations in clock seconds).

    ``scale_policies`` / ``canary_policies`` name which of the
    monitor's policies drive autoscaling+shedding / the canary verdict;
    ``None`` means every registered policy. ``shed_queue_depth=None``
    disables shedding. ``canary_min_samples=0`` makes the canary
    promote-unless-observably-bad (an unobserved canary promotes at the
    window end instead of waiting for traffic); with it positive, a
    canary still unobserved after ``canary_max_wait_s`` rolls back —
    never promote weights nobody has watched serve.
    """

    scale_policies: Optional[tuple[str, ...]] = None
    grow_after_s: float = 30.0
    cooldown_s: float = 60.0
    min_replicas: int = 1
    max_replicas: int = 4
    # disaggregated serving (docs/design/elasticity.md): policies named
    # here steer burn-driven grows into a role pool — a burning TTFT
    # policy should add PREFILL capacity and a burning TPOT policy
    # DECODE capacity (the two pools bottleneck on different resources).
    # Unlisted policies grow a unified replica, exactly as before roles
    # existed. The per-role minimums floor idle shrink per pool.
    prefill_policies: Optional[tuple[str, ...]] = None
    decode_policies: Optional[tuple[str, ...]] = None
    min_prefill_replicas: int = 0
    min_decode_replicas: int = 0
    idle_after_s: float = 120.0
    idle_queue_depth: float = 0.0
    idle_slot_utilization: float = 0.25
    shed_queue_depth: Optional[int] = None
    canary_policies: Optional[tuple[str, ...]] = None
    canary_window_s: float = 30.0
    canary_tolerance: float = 1.25
    canary_min_samples: int = 1
    canary_max_wait_s: float = 120.0
    # staleness bound on the cached statuses: poll() triggers its own
    # monitor evaluation when nothing (flush/scrape) evaluated recently
    eval_interval_s: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}, {self.max_replicas}"
            )
        for name in ("grow_after_s", "cooldown_s", "idle_after_s",
                     "canary_max_wait_s", "eval_interval_s",
                     "min_prefill_replicas", "min_decode_replicas"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if self.canary_window_s <= 0:
            # the canary twins are real SloPolicy windows, which must
            # be positive; use an epsilon window for an immediate
            # next-poll decision (deterministic tests, the bench leg)
            raise ValueError("canary_window_s must be > 0")
        if self.canary_tolerance < 1.0:
            raise ValueError(
                f"canary_tolerance must be >= 1, got {self.canary_tolerance}"
            )


class DecisionLog:
    """Append-only JSONL audit log of control decisions.

    One line per decision (schema below, validated by
    :func:`read_decisions`); each line is flushed as written —
    decisions are rare and the log must survive the crash it may be
    explaining::

        {"kind": "autopilot_decision", "schema": 1, "action": "grow",
         "unix_time": ..., "reason": "...", "detail": {...}}
    """

    SCHEMA = 1
    REQUIRED = ("kind", "schema", "action", "unix_time", "reason")

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fh = None

    def append(
        self, action: str, *, reason: str, detail: dict | None = None
    ) -> dict:
        rec: dict[str, Any] = {
            "kind": "autopilot_decision",
            "schema": self.SCHEMA,
            "action": action,
            "unix_time": time.time(),
            "reason": reason,
        }
        if detail:
            rec["detail"] = detail
        try:
            if self._fh is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
        except OSError:
            logger.exception("autopilot decision log write failed")
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_decisions(path: str | Path) -> list[dict]:
    """Parse + validate a decision log; raises ``ValueError`` on a
    malformed line (the round-trip contract tests pin)."""
    out = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            missing = [k for k in DecisionLog.REQUIRED if k not in rec]
            if missing:
                raise ValueError(
                    f"{path}:{i + 1}: decision missing fields {missing}"
                )
            if rec["kind"] != "autopilot_decision":
                raise ValueError(
                    f"{path}:{i + 1}: unexpected kind {rec['kind']!r}"
                )
            if not (
                isinstance(rec["schema"], int)
                and 1 <= rec["schema"] <= DecisionLog.SCHEMA
            ):
                raise ValueError(
                    f"{path}:{i + 1}: schema {rec['schema']!r} not in "
                    f"supported range [1, {DecisionLog.SCHEMA}]"
                )
            out.append(rec)
    return out


@dataclasses.dataclass
class _CanaryTrack:
    """Autopilot-side state for one pending canary decision."""

    publish: Any           # the publisher's _CanaryPublish identity
    label: str             # canary replica's serve/{label}/* namespace
    t0: float              # clock() at tracking start
    # (watched policy, canary twin name, baseline twin name)
    twins: list[tuple[SloPolicy, str, str]]


class FleetAutopilot:
    """Close the sense→act loop between an ``SloMonitor`` and a
    ``ServingFleet`` (module docstring for the control policies).

    ``replica_factory(params) -> batcher`` is what ``grow`` hands to
    ``ServingFleet.grow``; without it (or without a publisher holding
    published weights) grow decisions are skipped with a logged
    ``grow_blocked`` decision. ``decision_log`` (a path) enables the
    JSONL audit log. ``clock`` must be the same clock the monitor uses
    when determinism matters (the chaos tests share one fake clock).

    Call :meth:`attach` to wire in (idempotent to :meth:`detach`); the
    fleet then polls the autopilot once per scheduling round.
    """

    def __init__(
        self,
        fleet,
        monitor,
        *,
        publisher=None,
        replica_factory: Optional[Callable[[Any], Any]] = None,
        config: AutopilotConfig | None = None,
        decision_log: str | Path | None = None,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.fleet = fleet
        self.monitor = monitor
        self.publisher = (
            publisher if publisher is not None else fleet._publisher
        )
        self.replica_factory = replica_factory
        self.config = config if config is not None else AutopilotConfig()
        self.log = (
            DecisionLog(decision_log) if decision_log is not None else None
        )
        self._tele = telemetry if telemetry is not None else get_telemetry()
        self._clock = clock
        # the subscriber may run on scrape threads; poll() runs on the
        # fleet's scheduling thread — the cached statuses are the only
        # shared state, everything fleet-mutating stays in poll()
        self._lock = threading.Lock()
        self._statuses: dict[str, SloStatus] = {}
        self._last_eval_t: float | None = None
        self._burn_since: float | None = None
        self._burning_names: tuple[str, ...] = ()
        self._idle_since: float | None = None
        self._last_scale_t: float = -math.inf
        self._grow_blocked_logged = False
        self._canary: Optional[_CanaryTrack] = None
        self._last_decision: dict | None = None
        self._in_poll = False

    # -- wiring ---------------------------------------------------------

    def attach(self) -> "FleetAutopilot":
        if self._on_evaluation not in self.monitor.subscribers:
            self.monitor.subscribers.append(self._on_evaluation)
        self.fleet._autopilot = self
        return self

    def detach(self) -> None:
        if self._on_evaluation in self.monitor.subscribers:
            self.monitor.subscribers.remove(self._on_evaluation)
        if self.fleet._autopilot is self:
            self.fleet._autopilot = None
        if self._canary is not None:
            self.monitor.remove(
                [n for _, c, b in self._canary.twins for n in (c, b)]
            )
            self._canary = None
        if self.log is not None:
            self.log.close()

    def _on_evaluation(self, statuses: Sequence[SloStatus]) -> None:
        """Monitor subscriber: cache the freshest statuses. Bookkeeping
        only — may run on a scrape thread, must never touch the fleet.
        The cache is REPLACED, not upserted: every evaluation covers all
        current policies, so a policy retired via ``monitor.remove``
        must drop out here too — a stale violating status would keep
        shedding and growing forever with no live policy behind it."""
        with self._lock:
            self._statuses = {s.policy.name: s for s in statuses}
            self._last_eval_t = self._clock()

    # -- introspection (fleet /healthz autopilot block) -----------------

    def status(self) -> dict[str, Any]:
        # snapshot the racy fields into locals first: poll() (the
        # scheduling thread) rebinds them without the lock, and a
        # /healthz scrape must never crash between a None-check and the
        # deref because _finish_canary ran in the gap
        track = self._canary
        burn_since = self._burn_since
        idle_since = self._idle_since
        with self._lock:
            last_decision = self._last_decision
        now = self._clock()
        canary = None
        if track is not None:
            canary = {
                "label": track.label,
                "version": track.publish.version,
                "age_s": round(now - track.t0, 3),
            }
        return {
            "burning": list(self._burning_names),
            "burn_age_s": (
                round(now - burn_since, 3)
                if burn_since is not None else None
            ),
            "idle_age_s": (
                round(now - idle_since, 3)
                if idle_since is not None else None
            ),
            "canary": canary,
            "last_decision": last_decision,
        }

    # -- decision plumbing ---------------------------------------------

    def _decide(
        self, action: str, *, reason: str, detail: dict | None = None
    ) -> None:
        """One auditable decision: counter + log line + cached status."""
        self._tele.counter("autopilot/decisions").add(1)
        rec: dict[str, Any] = {"action": action, "reason": reason}
        if detail:
            rec["detail"] = detail
        if self.log is not None:
            rec = self.log.append(action, reason=reason, detail=detail)
        with self._lock:
            self._last_decision = rec
        logger.info("autopilot: %s (%s)", action, reason)

    # -- the control loop (fleet scheduling-round cadence) --------------

    def poll(self) -> None:
        """One control tick, called by ``ServingFleet.step`` at the
        round boundary. Refreshes stale SLO state, then runs the three
        policies: canary decision, burn actions (shed, grow), idle
        shrink. Re-entrant calls (a shrink's nested stepping) no-op."""
        if self._in_poll:
            return
        self._in_poll = True
        try:
            now = self._clock()
            with self._lock:
                last_eval = self._last_eval_t
            if (
                last_eval is None
                or now - last_eval >= self.config.eval_interval_s
            ):
                # nothing flushed/scraped recently: evaluate ourselves
                # (pure host work; the subscriber refreshes the cache)
                self.monitor.evaluate()
            self._poll_canary(now)
            self._poll_scaling(now)
        finally:
            self._in_poll = False

    def _watched(self, names: Optional[tuple[str, ...]]) -> list[SloStatus]:
        with self._lock:
            statuses = dict(self._statuses)
        if names is None:
            # every non-temporary policy (canary twins judge the canary,
            # they must not drive autoscaling of the whole fleet)
            temp = set()
            if self._canary is not None:
                for _, c, b in self._canary.twins:
                    temp.add(c)
                    temp.add(b)
            return [s for n, s in statuses.items() if n not in temp]
        return [statuses[n] for n in names if n in statuses]

    # -- policy (a): burn-driven autoscaling + (b): shedding ------------

    def _utilization(self) -> float:
        busy = total = 0
        for i in self.fleet._live:
            b = self.fleet._replicas[i]
            busy += sum(1 for s in b._slots if s.rid >= 0)
            total += len(b._slots)
        return busy / total if total else 0.0

    def _poll_scaling(self, now: float) -> None:
        cfg = self.config
        burning = [
            s for s in self._watched(cfg.scale_policies) if s.violating
        ]
        with self._lock:
            self._burning_names = tuple(
                sorted(s.policy.name for s in burning)
            )
        self._tele.gauge("autopilot/burning_policies").set(
            float(len(burning))
        )
        live = len(self.fleet._live)
        if burning:
            self._idle_since = None
            if self._burn_since is None:
                self._burn_since = now
            self._shed(now, burning)
            if (
                now - self._burn_since >= cfg.grow_after_s
                and now - self._last_scale_t >= cfg.cooldown_s
                and live < cfg.max_replicas
            ):
                self._grow(now, burning)
            return
        self._burn_since = None
        self._grow_blocked_logged = False
        # idle shrink: queue AND utilization under their floors
        depth = self.fleet._queue_depth()
        util = self._utilization()
        idle = (
            live > cfg.min_replicas
            and depth <= cfg.idle_queue_depth
            and util <= cfg.idle_slot_utilization
        )
        if not idle:
            self._idle_since = None
            return
        if self._idle_since is None:
            self._idle_since = now
            return
        if (
            now - self._idle_since >= cfg.idle_after_s
            and now - self._last_scale_t >= cfg.cooldown_s
        ):
            # never shrink the pending canary replica out from under
            # its own decision window: a retired batcher stays strongly
            # referenced by the fleet, so the comparator would just see
            # an eternally-unobserved canary and roll back good weights
            canary_b = (
                self.publisher.canary.target()
                if self.publisher is not None
                and self.publisher.canary is not None else None
            )
            # idle shrink is per POOL when roles are in play: a role's
            # last replicas above its floor are fair game, the floor
            # itself is not — min_prefill/min_decode keep each side of
            # a disaggregated fleet from shrinking to nothing while the
            # other side's idleness drives the decision
            role_counts: dict[str, int] = {}
            for i in self.fleet._live:
                r = self.fleet._role(i)
                role_counts[r] = role_counts.get(r, 0) + 1
            floors = {"prefill": cfg.min_prefill_replicas,
                      "decode": cfg.min_decode_replicas}
            candidates = [
                i for i in sorted(self.fleet._live, reverse=True)
                if self.fleet._replicas[i] is not canary_b
                and role_counts[self.fleet._role(i)]
                > floors.get(self.fleet._role(i), 0)
            ]
            if not candidates:
                return  # only the canary / role floors are left
            idx = candidates[0]
            role = self.fleet._role(idx)
            action = {
                "prefill": "shrink_prefill", "decode": "shrink_decode",
            }.get(role, "shrink")
            # dump BEFORE the drain so the black box shows the fleet
            # the decision was made against
            self._tele.dump_flight_record(
                "autopilot_shrink",
                extra={"replica": idx, "queue_depth": depth,
                       "slot_utilization": util},
            )
            self.fleet.shrink(idx)
            self._last_scale_t = now
            self._idle_since = None
            self._tele.counter("autopilot/shrinks").add(1)
            self._decide(
                action,
                reason=(
                    f"idle {self.config.idle_after_s:g}s: queue_depth "
                    f"{depth:g} <= {cfg.idle_queue_depth:g}, utilization "
                    f"{util:.3f} <= {cfg.idle_slot_utilization:g}"
                ),
                detail={"replica": idx, "role": role,
                        "live_replicas": live - 1},
            )

    def _grow(self, now: float, burning: list[SloStatus]) -> None:
        # the guard checks the FLEET's publisher, not self.publisher:
        # fleet.grow() cold-starts the new replica from fleet._publisher
        # and raises without one — a divergent publisher= kwarg must
        # produce a logged grow_blocked, never crash the scheduling loop
        fleet_pub = self.fleet._publisher
        if (
            self.replica_factory is None
            or fleet_pub is None
            or fleet_pub.latest_params is None
        ):
            if not self._grow_blocked_logged:
                self._grow_blocked_logged = True
                self._decide(
                    "grow_blocked",
                    reason="no replica_factory or no weights published "
                           "on the fleet's publisher to cold-start from",
                    detail={"burning": [s.policy.name for s in burning]},
                )
            return
        cfg = self.config
        worst = max(burning, key=lambda s: s.burn)
        # role-aware capacity (disaggregated serving): the WORST burning
        # policy picks the pool — a TTFT burn means prefill is the
        # bottleneck, a TPOT burn means decode is; distinct decision
        # kinds keep the audit log attributable per pool
        role, action = "unified", "grow"
        if cfg.prefill_policies and worst.policy.name in cfg.prefill_policies:
            role, action = "prefill", "grow_prefill"
        elif cfg.decode_policies and worst.policy.name in cfg.decode_policies:
            role, action = "decode", "grow_decode"
        idx = self.fleet.grow(self.replica_factory, role=role)
        self._last_scale_t = now
        self._tele.counter("autopilot/grows").add(1)
        self._decide(
            action,
            reason=(
                f"{worst.policy.name} burning {worst.burn:.2f}x for >= "
                f"{self.config.grow_after_s:g}s"
            ),
            detail={
                "replica": idx,
                "role": role,
                "live_replicas": len(self.fleet._live),
                "weights_version": fleet_pub.latest_version,
                "burning": {
                    s.policy.name: round(s.burn, 4) for s in burning
                },
            },
        )

    def _shed(self, now: float, burning: list[SloStatus]) -> None:
        cfg = self.config
        if cfg.shed_queue_depth is None:
            return
        depth = self.fleet._queue_depth()
        excess = int(depth - cfg.shed_queue_depth)
        if excess <= 0:
            return
        shed = self.fleet.shed_queued(excess)
        if not shed:
            return
        self._tele.counter("autopilot/shed_requests").add(len(shed))
        self._tele.dump_flight_record(
            "autopilot_shed",
            extra={"shed": len(shed), "queue_depth": depth,
                   "burning": [s.policy.name for s in burning]},
        )
        self._decide(
            "shed",
            reason=(
                f"queue depth {depth:g} > {cfg.shed_queue_depth} while "
                f"{', '.join(s.policy.name for s in burning)} burning"
            ),
            detail={"shed_frids": shed, "queue_depth_after":
                    self.fleet._queue_depth()},
        )

    # -- policy (c): canaried weight publish ----------------------------

    def publish_canary(self, params, *, replica: Optional[int] = None) -> int:
        """Stage a canary generation on one live fleet replica (default:
        the highest-index one — usually the most recently grown) and
        start the decision clock; returns the canary generation stamp.
        Thin orchestration over ``WeightPublisher.publish_canary`` so
        callers never have to pick a batcher by hand."""
        if self.publisher is None:
            raise RuntimeError("publish_canary needs a WeightPublisher")
        if not self.fleet._live:
            raise RuntimeError("publish_canary needs a live replica")
        idx = replica if replica is not None else max(self.fleet._live)
        if idx not in self.fleet._live:
            raise ValueError(f"replica {idx} is not live")
        return self.publisher.publish_canary(
            params, batcher=self.fleet._replicas[idx]
        )

    def _replica_scoped(self, name: str, label: str) -> str:
        return (
            f"serve/{label}/{name[6:]}" if name.startswith("serve/")
            else name
        )

    @staticmethod
    def _already_replica_scoped(p: SloPolicy) -> bool:
        """Does the policy read a replica-labeled instrument already?
        Base serve instruments are ``serve/{name}`` (one segment);
        labeled ones are ``serve/{label}/{name}``. An already-scoped
        policy is a per-replica objective — rewriting it for the canary
        would fabricate ``serve/{canary}/{label}/...`` names nothing
        records, and comparing one replica against another replica's
        objective is not a canary-vs-fleet comparison at all."""
        return any(
            n.startswith("serve/") and n.count("/") >= 2
            for n in (p.metric, p.bad, *p.good)
        )

    def _canary_twins(
        self, label: str
    ) -> list[tuple[SloPolicy, str, str]]:
        """Temporary policy pairs for one canary decision: a
        replica-scoped twin of each watched policy plus a same-window
        rollup baseline twin — same horizon, so the comparison is
        apples to apples. Neither can page (``_CANARY_BURN_RATE``)."""
        cfg = self.config
        twins = []
        for p in self._canary_watched():
            cname = f"canary_{label}_{p.name}"
            bname = f"canary_base_{p.name}"
            common = dict(
                target=p.target, window_s=cfg.canary_window_s,
                burn_rate=_CANARY_BURN_RATE, kind=p.kind,
                quantile=p.quantile,
            )
            canary_p = SloPolicy(
                name=cname,
                metric=self._replica_scoped(p.metric, label),
                bad=self._replica_scoped(p.bad, label),
                good=tuple(
                    self._replica_scoped(g, label) for g in p.good
                ),
                min_samples=max(cfg.canary_min_samples, 1)
                if p.kind == "rate" else cfg.canary_min_samples,
                **common,
            )
            base_p = SloPolicy(
                name=bname, metric=p.metric, bad=p.bad, good=p.good,
                min_samples=1, **common,
            )
            twins.append((p, cname, bname))
            # isolate: the twins' decision window must start clean even
            # when (metric, window) collides with a standing policy
            self.monitor.extend([canary_p, base_p], isolate=True)
        return twins

    def _canary_watched(self) -> list[SloPolicy]:
        names = self.config.canary_policies
        out = []
        for p in self.monitor.policies:
            if p.name.startswith(("canary_",)):
                continue
            if self._already_replica_scoped(p):
                continue  # per-replica objectives are not fleet baselines
            if names is None or p.name in names:
                out.append(p)
        return out

    def _rollback_canary(self, *, reason: str, detail: dict) -> None:
        """The ONE rollback contract, however the decision was reached:
        publisher rollback (fresh stamp), tracking teardown, counter,
        flight-recorder black box (a rollback is destructive — the dump
        is promised for every one of them), decision-log entry."""
        version = self.publisher.rollback_canary()
        self._finish_canary()
        self._tele.counter("autopilot/canary_rollbacks").add(1)
        self._tele.dump_flight_record(
            "autopilot_rollback", extra={"reason": reason, **detail},
        )
        self._decide(
            "canary_rollback", reason=reason,
            detail={**detail, "rollback_version": version},
        )

    def _poll_canary(self, now: float) -> None:
        cfg = self.config
        pub = self.publisher
        pending = pub.canary if pub is not None else None
        if self._canary is None:
            if pending is None:
                self._tele.gauge("autopilot/canary_pending").set(0.0)
                return
            b = pending.target()
            label = getattr(b, "_replica_label", None) if b else None
            if label is None:
                # unlabeled / dead target: nothing to compare against —
                # roll straight back rather than promote blind
                self._rollback_canary(
                    reason="canary replica has no serve/{label}/* "
                           "namespace (dead or unlabeled): cannot be "
                           "observed, never promoted blind",
                    detail={"version": pending.version},
                )
                return
            self._canary = _CanaryTrack(
                publish=pending, label=label, t0=now,
                twins=self._canary_twins(label),
            )
            self._tele.gauge("autopilot/canary_pending").set(1.0)
            self._decide(
                "canary_start",
                reason=f"generation {pending.version} canaried on "
                       f"{label}; deciding in {cfg.canary_window_s:g}s",
                detail={"version": pending.version, "replica": label},
            )
            return
        track = self._canary
        if pending is not track.publish:
            # superseded (a plain publish landed) or externally resolved
            self._finish_canary()
            self._decide(
                "canary_superseded",
                reason="a fleet-wide publish (or external resolution) "
                       "replaced the pending canary before its decision",
                detail={"version": track.publish.version},
            )
            return
        if track.publish.target() is None:
            # the canary replica died mid-window (kill): its device
            # tree died with it — clear, don't promote
            self._rollback_canary(
                reason="canary replica died before the decision window "
                       "closed",
                detail={"version": track.publish.version,
                        "replica": track.label},
            )
            return
        if now - track.t0 < cfg.canary_window_s:
            return
        self._decide_canary(now, track)

    def _decide_canary(self, now: float, track: _CanaryTrack) -> None:
        cfg = self.config
        statuses = {s.policy.name: s for s in self.monitor.evaluate()}
        verdicts = {}
        unobserved = []
        bad = False
        # a 1-replica fleet has no independent baseline: the rollup IS
        # the canary's own traffic, so canary > rollup x tolerance is
        # unsatisfiable there and a bad canary would always promote —
        # fall back to the absolute policy target as the verdict line
        sole = len(self.fleet._live) <= 1
        for orig, cname, bname in track.twins:
            cs, bs = statuses.get(cname), statuses.get(bname)
            if cs is None:
                continue
            if cs.samples < max(cfg.canary_min_samples, 1):
                if cfg.canary_min_samples > 0:
                    unobserved.append(orig.name)
                continue
            base_obs = bs.observed if bs is not None else float("nan")
            worse_than_fleet = (
                sole
                or not math.isfinite(base_obs)
                or cs.observed > base_obs * cfg.canary_tolerance
            )
            this_bad = (
                math.isfinite(cs.observed)
                and cs.observed > orig.target
                and worse_than_fleet
            )
            bad = bad or this_bad
            verdicts[orig.name] = {
                "canary": round(cs.observed, 6)
                if math.isfinite(cs.observed) else None,
                "fleet": round(base_obs, 6)
                if math.isfinite(base_obs) else None,
                "target": orig.target,
                "samples": cs.samples,
                "bad": this_bad,
            }
        if unobserved and not bad:
            if now - track.t0 < cfg.canary_max_wait_s:
                return  # keep waiting for traffic to reach the canary
            self._rollback_canary(
                reason=(
                    f"canary on {track.label} saw no traffic on "
                    f"{', '.join(unobserved)} within "
                    f"{cfg.canary_max_wait_s:g}s: never promote weights "
                    "nobody watched serve"
                ),
                detail={"version": track.publish.version,
                        "replica": track.label, "verdicts": verdicts},
            )
            return
        if bad:
            self._rollback_canary(
                reason=(
                    f"canary on {track.label} over the policy target "
                    "with no independent fleet baseline (1-replica "
                    f"fleet) over {cfg.canary_window_s:g}s"
                    if sole else
                    f"canary on {track.label} worse than the fleet "
                    f"rollup beyond {cfg.canary_tolerance:g}x over "
                    f"{cfg.canary_window_s:g}s"
                ),
                detail={"version": track.publish.version,
                        "replica": track.label, "verdicts": verdicts},
            )
        else:
            version = self.publisher.promote_canary()
            self._finish_canary()
            self._tele.counter("autopilot/canary_promotes").add(1)
            self._decide(
                "canary_promote",
                reason=(
                    f"canary on {track.label} within the policy "
                    f"targets over {cfg.canary_window_s:g}s (1-replica "
                    "fleet: no independent baseline)"
                    if sole else
                    f"canary on {track.label} within {cfg.canary_tolerance:g}x "
                    f"of the fleet rollup over {cfg.canary_window_s:g}s"
                ),
                detail={"version": version, "verdicts": verdicts},
            )

    def _finish_canary(self) -> None:
        track = self._canary
        self._canary = None
        self._tele.gauge("autopilot/canary_pending").set(0.0)
        if track is not None:
            self.monitor.remove(
                [n for _, c, b in track.twins for n in (c, b)]
            )
