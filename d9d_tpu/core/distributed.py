"""Multi-host process bootstrap.

TPU-native equivalent of the reference's torchrun-env bootstrap
(reference: d9d/core/dist_context/configured.py:18,67-75 — RANK /
MASTER_ADDR / WORLD_SIZE → ``init_process_group``). Here the controller is
``jax.distributed.initialize``: every host in a pod slice starts the same
script, connects to the coordinator, and from then on ``jax.devices()``
spans the whole slice, so ``MeshParameters.build()`` produces a
process-spanning mesh with zero further changes.

Configuration resolution order (first hit wins):

1. explicit keyword arguments;
2. ``D9D_COORDINATOR`` / ``D9D_NUM_PROCESSES`` / ``D9D_PROCESS_ID`` env
   vars (this framework's own channel);
3. torchrun-style ``MASTER_ADDR`` / ``MASTER_PORT`` / ``WORLD_SIZE`` /
   ``RANK`` env vars (drop-in parity with the reference's launch story);
4. nothing → on Cloud TPU pod slices ``jax.distributed.initialize()``'s
   own auto-detection (TPU metadata); elsewhere a single-process no-op.

The call is idempotent and a no-op for single-process runs, so library
code and examples can call it unconditionally.

Pod launch story (documented for parity with the reference's torchrun
docs): start the identical script on every host of the slice —

    # Cloud TPU (GKE / queued resources): auto-detected, no env needed
    python pretrain.py --config config.json

    # explicit coordinator (e.g. on-prem, DCN-connected slices):
    D9D_COORDINATOR=host0:8476 D9D_NUM_PROCESSES=16 D9D_PROCESS_ID=$i \
        python pretrain.py --config config.json

after which ``init_distributed()`` + ``MeshParameters(...).build()`` give
every process the same global mesh and each host feeds its local shard of
the batch (the data loader shards by ``jax.process_index()``).
"""

import contextlib
import dataclasses
import logging
import os
from typing import Optional

import jax

logger = logging.getLogger("d9d_tpu.distributed")

_initialized = False
_owns_runtime = False


def _runtime_already_up() -> bool:
    """True when a distributed client already exists (launcher/test harness
    called ``jax.distributed.initialize`` before us).

    Deliberately avoids ``jax.process_count()``/``jax.devices()``: those
    initialize the XLA backend, after which ``jax.distributed.initialize``
    refuses to run — the exact multi-host path this module exists for.
    """
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # pragma: no cover - private-API drift fallback
        return False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Resolved bootstrap parameters (None = leave to jax auto-detection)."""

    coordinator_address: Optional[str]
    num_processes: Optional[int]
    process_id: Optional[int]

    @property
    def is_explicit(self) -> bool:
        return self.coordinator_address is not None

    @property
    def is_single_process(self) -> bool:
        return self.num_processes == 1


def resolve_distributed_config(
    env: Optional[dict] = None,
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> DistributedConfig:
    """Pure resolution of the bootstrap parameters (unit-testable)."""
    env = os.environ if env is None else env

    if coordinator_address is None:
        coordinator_address = env.get("D9D_COORDINATOR")
    if num_processes is None and "D9D_NUM_PROCESSES" in env:
        num_processes = int(env["D9D_NUM_PROCESSES"])
    if process_id is None and "D9D_PROCESS_ID" in env:
        process_id = int(env["D9D_PROCESS_ID"])

    # torchrun-style channel (reference configured.py:18: MASTER_ADDR/RANK)
    if coordinator_address is None and "MASTER_ADDR" in env:
        port = env.get("MASTER_PORT", "8476")
        coordinator_address = f"{env['MASTER_ADDR']}:{port}"
        if num_processes is None and "WORLD_SIZE" in env:
            num_processes = int(env["WORLD_SIZE"])
        if process_id is None and "RANK" in env:
            process_id = int(env["RANK"])

    return DistributedConfig(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def init_distributed(
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialization_timeout_s: int = 300,
) -> bool:
    """Initialize the multi-host runtime if the environment calls for it.

    Returns True when ``jax.distributed.initialize`` was invoked, False on
    the single-process / already-initialized no-op paths. Idempotent.

    Matches the reference's two-phase timeout intent
    (configured.py:126-144): the generous ``initialization_timeout_s``
    gates the coordinator handshake; per-step hang detection is the
    TimeoutManager's job (loop/components/timeout_manager.py).
    """
    global _initialized, _owns_runtime
    if _initialized:
        return False
    if _runtime_already_up():
        # someone else (launcher, test harness) already initialized
        _initialized = True
        return False

    cfg = resolve_distributed_config(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )

    if cfg.is_single_process:
        logger.info("init_distributed: single process, no-op")
        _initialized = True
        return False

    if not cfg.is_explicit:
        # No explicit coordinator. On Cloud TPU pods jax auto-detects from
        # the TPU metadata; elsewhere there is nothing to do. A single
        # entry in TPU_WORKER_HOSTNAMES means one host (some single-chip
        # containers set it to "localhost") — auto-init only for >1 worker,
        # where a coordinator actually exists to be detected.
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multi_worker = len([h for h in hostnames.split(",") if h.strip()]) > 1
        if multi_worker or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            jax.distributed.initialize(
                initialization_timeout=initialization_timeout_s
            )
            _initialized = True
            _owns_runtime = True
            logger.info(
                "init_distributed: TPU auto-detect, process %d/%d",
                jax.process_index(),
                jax.process_count(),
            )
            return True
        logger.info(
            "init_distributed: no coordinator configured, single-process"
        )
        _initialized = True
        return False

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
        initialization_timeout=initialization_timeout_s,
    )
    _initialized = True
    _owns_runtime = True
    logger.info(
        "init_distributed: coordinator %s, process %d/%d",
        cfg.coordinator_address,
        jax.process_index(),
        jax.process_count(),
    )
    return True


def shutdown_distributed() -> None:
    """Tear down the runtime — only if this module started it (an
    externally-initialized runtime belongs to the launcher)."""
    global _initialized, _owns_runtime
    if _owns_runtime:
        jax.distributed.shutdown()
    _initialized = False
    _owns_runtime = False


@contextlib.contextmanager
def main_process_first(tag: str = "main_process_first"):
    """Process 0 runs the body first; the rest wait, then run it.

    Parity: reference ``main_process_first``
    (d9d/core/dist_context/configured.py:162) — the rank-0-first pattern
    for downloads/dataset materialization where one process should
    populate a shared cache before the stampede. Single-process: plain
    passthrough.
    """
    if jax.process_count() == 1:
        yield
        return
    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        try:
            yield
        finally:
            multihost_utils.sync_global_devices(tag + ":main_done")
    else:
        multihost_utils.sync_global_devices(tag + ":main_done")
        yield
