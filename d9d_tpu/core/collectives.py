"""Host-level collective helpers for multi-process JAX.

TPU-native counterpart of the reference's buffer-allocating collective
wrappers (d9d/core/dist_ops/tensor.py:8-150, object.py:8-32). Inside jit,
collectives are ``lax.psum``/``all_gather`` chosen by shardings; these
helpers cover the *host-side* cases the reference used torch.distributed
for directly: metric sync, object gather, variadic-shape gather.

Single-process (tests, one host) degrades to identity/local ops with no
device traffic.
"""

from enum import Enum
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import PyTree


class ReduceOp(str, Enum):
    # 'avg' is deliberately absent: averaging is not associative across
    # uneven shards (same reasoning as reference accumulator.py:10).
    sum = "sum"
    max = "max"
    min = "min"


_NP_REDUCE = {
    ReduceOp.sum: np.sum,
    ReduceOp.max: np.max,
    ReduceOp.min: np.min,
}


def host_allreduce(
    value: np.ndarray | jnp.ndarray, op: ReduceOp = ReduceOp.sum
) -> np.ndarray:
    """All-reduce a host array across JAX processes.

    Every process must call this with the same-shaped array.
    """
    value = np.asarray(value)
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    gathered = np.asarray(multihost_utils.process_allgather(value))
    return _NP_REDUCE[op](gathered, axis=0)


def host_allreduce_tree(tree: PyTree, op: ReduceOp = ReduceOp.sum) -> PyTree:
    return jax.tree.map(lambda x: host_allreduce(x, op), tree)


def host_allgather_object(obj: Any) -> list[Any]:
    """Gather an arbitrary (pickleable) object from every process.

    Parity: reference all_gather_object (d9d/core/dist_ops/object.py:32).
    """
    if jax.process_count() == 1:
        return [obj]
    import pickle

    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # variadic-shape gather: exchange lengths, pad to max, gather, trim
    length = np.asarray([payload.size], np.int64)
    lengths = np.asarray(
        multihost_utils.process_allgather(length)
    ).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros((max_len,), np.uint8)
    padded[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return [
        pickle.loads(gathered[i, : int(lengths[i])].tobytes())
        for i in range(gathered.shape[0])
    ]


def host_broadcast_object(obj: Any, root: int = 0) -> Any:
    """Broadcast a pickleable object from ``root`` process to all.

    O(|obj|) on the wire: only the root's payload ships; other processes may
    pass ``None``.
    """
    if jax.process_count() == 1:
        return obj
    import pickle

    from jax.experimental import multihost_utils

    if jax.process_index() == root:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        length = np.asarray([payload.size], np.int64)
    else:
        payload = np.zeros((0,), np.uint8)
        length = np.zeros((1,), np.int64)
    length = np.asarray(
        multihost_utils.broadcast_one_to_all(length, is_source=jax.process_index() == root)
    )
    buf = np.zeros((int(length[0]),), np.uint8)
    buf[: payload.size] = payload[: buf.size]
    buf = np.asarray(
        multihost_utils.broadcast_one_to_all(buf, is_source=jax.process_index() == root)
    )
    return pickle.loads(buf.tobytes())


def host_gather_variadic(
    arrays: Sequence[np.ndarray],
) -> list[np.ndarray]:
    """Variadic-COUNT gather: each process contributes any number of
    arrays; defers to allgather_object (pickle transport).

    Parity: reference gather_variadic_shape (dist_ops/tensor.py:113) which
    pre-exchanges shapes then isend/irecvs. On TPU hosts the payload runs
    over the DCN gRPC channel; shape exchange is folded into pickling.
    For ONE large tensor per process, :func:`allgather_variadic` keeps the
    payload on the device transport instead.
    """
    return [a for objs in host_allgather_object(list(arrays)) for a in objs]


def allgather_variadic(x: "np.ndarray | jnp.ndarray") -> list[np.ndarray]:
    """Tensor-level variadic-shape all-gather: every process contributes a
    ``[n_i, ...]`` array whose leading dim differs; returns the per-process
    arrays trimmed to their true lengths.

    Parity: reference all_gather_variadic_shape
    (d9d/core/dist_ops/tensor.py:85) — shape pre-exchange, pad to max,
    one gather, trim. The padded gather rides
    ``multihost_utils.process_allgather`` (a jitted device all_gather over
    ICI/DCN), so large ragged eval outputs avoid the pickle channel of
    :func:`host_allgather_object`. Trailing dims and dtype must agree
    across processes.
    """
    x = np.asarray(x)
    if jax.process_count() == 1:
        return [x]
    from jax.experimental import multihost_utils

    meta = host_allgather_object((x.shape, str(x.dtype)))
    shapes = [m[0] for m in meta]
    if any(s[1:] != x.shape[1:] or d != str(x.dtype) for s, d in meta):
        raise ValueError(
            f"allgather_variadic needs matching trailing dims and dtype; "
            f"got {meta}"
        )
    # ship BYTES: process_allgather canonicalizes 64-bit dtypes to 32-bit
    # under the default jax_enable_x64=False, which would silently truncate
    # int64/float64 payloads — a uint8 view is dtype-exact for everything
    payload = np.ascontiguousarray(x).view(np.uint8).reshape(-1)
    max_bytes = max(
        int(np.prod(s)) * x.dtype.itemsize for s in shapes
    ) if shapes else 0
    padded = np.zeros((max(max_bytes, 1),), np.uint8)
    padded[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    out = []
    for i, s in enumerate(shapes):
        n = int(np.prod(s)) * x.dtype.itemsize
        out.append(
            np.frombuffer(gathered[i, :n].tobytes(), dtype=x.dtype).reshape(s)
        )
    return out
