"""6D device-mesh topology for TPU SPMD training.

This is the TPU-native equivalent of the reference's DeviceMesh "domains"
(reference: d9d/core/dist_context/device_mesh_domains.py:39-180 and
d9d/core/dist_context/params.py:9-105). Where the reference builds five
separate torch ``DeviceMesh`` objects over one topology (regular / dense /
expert / batch / flat), on TPU a *single* ``jax.sharding.Mesh`` with named
axes is enough: "fused" dims are expressed as tuples of axis names inside a
``PartitionSpec`` (e.g. the reference's ``dp_cp_shard`` fused dim is simply
``P(('dp_s', 'cp_s'))``), and the expert-parallel overlay is a suffix of the
flattened non-pp axes (validated here, like the reference validates
``dp*cp*tp % ep == 0`` at params.py:81-97).

Axis order is ``(pp, dp_r, dp_s, cp_s, cp_r, tp)`` — row-major, so ``tp``
varies fastest across physically-adjacent devices (ICI neighbours), which is
what you want: TP collectives are the most latency-sensitive, EP all-to-alls
ride the fast suffix, and PP crosses the slowest (possibly DCN) dimension.
"""

import dataclasses
import functools
import math
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.core import compat

# Canonical axis names, slowest-varying first.
AXIS_PP = "pp"
AXIS_DP_REPLICATE = "dp_r"
AXIS_DP_SHARD = "dp_s"
AXIS_CP_SHARD = "cp_s"
AXIS_CP_REPLICATE = "cp_r"
AXIS_TP = "tp"

MESH_AXIS_NAMES: tuple[str, ...] = (
    AXIS_PP,
    AXIS_DP_REPLICATE,
    AXIS_DP_SHARD,
    AXIS_CP_SHARD,
    AXIS_CP_REPLICATE,
    AXIS_TP,
)


def interleave_for_pp(devices, pp: int):
    """Order ``devices`` so every pipeline stage's submesh spans every
    process evenly.

    The mesh's leading axis is ``pp``; with jax's default device order a
    pp slice would be a contiguous block of one process's devices, making
    every stage jit un-runnable from the other processes (a submesh some
    process cannot address at all) and every stage boundary a cross-host
    copy. Interleaving gives each process ``local/pp`` devices in every
    stage: stage programs are ordinary SPMD over all hosts and boundary
    transfers stay process-local (see pipelining/runtime/transfer.py).
    No-op for a single process.
    """
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    if len(by_proc) <= 1:
        return list(devices)
    per = {p: len(ds) for p, ds in by_proc.items()}
    bad = {p: n for p, n in per.items() if n % pp != 0}
    if bad:
        raise ValueError(
            f"interleave_for_pp: per-process device counts {per} must be "
            f"divisible by pp={pp}"
        )
    out = []
    for s in range(pp):
        for p in sorted(by_proc):
            ds = by_proc[p]
            n = len(ds) // pp
            out.extend(ds[s * n:(s + 1) * n])
    return out


def resolve_ambient_mesh(required_axes=(), *, fallback=None, what="this op"):
    """The mesh a mesh-aware op should shard_map over, resolved at TRACE
    time: the ambient abstract mesh when one is set (under the pipeline
    engine each stage jits against its own pp-less submesh — a baked
    build-time mesh would disagree with the context there), else
    ``fallback``. Raises if neither exists or ``required_axes`` are
    missing. One helper so the resolution rule can't diverge between the
    ring SDPA, the MoE EP path, and the SDPA factory.
    """
    mesh = compat.get_abstract_mesh()
    if mesh is None or not mesh.shape:
        mesh = fallback
    if mesh is None or not mesh.shape:
        raise RuntimeError(
            f"{what} needs an ambient mesh; build it via "
            "MeshParameters.build() (which calls jax.set_mesh)"
        )
    missing = [a for a in required_axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            f"{what}: axes {missing} not in the context mesh "
            f"{dict(mesh.shape)} — was a different mesh built after "
            "this module was configured?"
        )
    return mesh


def _suffix_axes_covering(
    size: int, axes: Sequence[tuple[str, int]]
) -> tuple[str, ...]:
    """Find the fastest-varying (suffix) axes whose sizes multiply to ``size``.

    Raises if ``size`` does not align with whole-axis boundaries: the expert
    axis must factor exactly into mesh axes so that expert-parallel
    collectives can name real mesh axes.
    """
    if size == 1:
        return ()
    prod = 1
    chosen: list[str] = []
    for name, s in reversed(list(axes)):
        if prod >= size:
            break
        if s == 1:
            continue
        prod *= s
        chosen.append(name)
    if prod != size:
        raise ValueError(
            f"expert-shard size {size} does not factor into a suffix of mesh "
            f"axes {list(axes)}; got partial product {prod}"
        )
    return tuple(reversed(chosen))


@dataclasses.dataclass(frozen=True)
class MeshParameters:
    """Sizes of every parallelism dimension.

    Parity: reference ``DeviceMeshParameters`` (core/dist_context/params.py:9).
    ``ep_shard`` overlays the ``dp_r*dp_s*cp_s*cp_r*tp`` product exactly like the
    reference's ExpertDomain (device_mesh_domains.py:69-93); divisibility is
    validated in ``__post_init__`` (reference params.py:81-97).
    """

    pp: int = 1
    dp_replicate: int = 1
    dp_shard: int = 1
    cp_shard: int = 1
    cp_replicate: int = 1
    tp: int = 1
    ep_shard: int = 1

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{f.name} must be a positive int, got {v!r}")
        non_pp = (
            self.dp_replicate
            * self.dp_shard
            * self.cp_shard
            * self.cp_replicate
            * self.tp
        )
        if non_pp % self.ep_shard != 0:
            raise ValueError(
                f"ep_shard={self.ep_shard} must divide "
                f"dp_replicate*dp_shard*cp_shard*cp_replicate*tp={non_pp}"
            )

    @property
    def world_size(self) -> int:
        return (
            self.pp
            * self.dp_replicate
            * self.dp_shard
            * self.cp_shard
            * self.cp_replicate
            * self.tp
        )

    # -- serialization (checkpoint manifest v2 "mesh" block) -----------

    def as_dict(self) -> dict:
        """JSON-serializable axis sizes — what a checkpoint records
        about the topology that saved it (resilience/elastic.py)."""
        return {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}

    @classmethod
    def from_dict(cls, d: dict) -> "MeshParameters":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: int(v) for k, v in d.items() if k in names})

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return (
            self.pp,
            self.dp_replicate,
            self.dp_shard,
            self.cp_shard,
            self.cp_replicate,
            self.tp,
        )

    def build(self, devices: Sequence[jax.Device] | None = None) -> "MeshContext":
        """Build the mesh over ``devices`` (default: all visible devices).

        With no explicit device list, ``jax.make_mesh`` computes a
        topology-aware device assignment so the fastest-varying axes (tp, ep
        suffix) land on ICI neighbours and pp crosses the slowest links.
        An explicit list (tests, custom layouts) is used in the given order.
        """
        if devices is None:
            if len(jax.devices()) != self.world_size:
                raise ValueError(
                    f"mesh needs {self.world_size} devices "
                    f"({dict(zip(MESH_AXIS_NAMES, self.axis_sizes))}), "
                    f"got {len(jax.devices())}"
                )
            # axis_types must be Auto: jax 0.9's make_mesh defaults to
            # Explicit (sharding-in-types), which rejects plain jit use.
            # (core.compat: older runtimes take no axis_types at all.)
            mesh = jax.make_mesh(
                self.axis_sizes,
                MESH_AXIS_NAMES,
                **compat.mesh_axis_types_kwargs(len(MESH_AXIS_NAMES)),
            )
        else:
            if len(devices) != self.world_size:
                raise ValueError(
                    f"mesh needs {self.world_size} devices "
                    f"({dict(zip(MESH_AXIS_NAMES, self.axis_sizes))}), "
                    f"got {len(devices)}"
                )
            dev_array = np.asarray(devices).reshape(self.axis_sizes)
            mesh = Mesh(
                dev_array,
                MESH_AXIS_NAMES,
                **compat.mesh_axis_types_kwargs(len(MESH_AXIS_NAMES)),
            )
        # Make the mesh ambient: shard_map/get_abstract_mesh inside modules
        # (e.g. the MoE EP path) resolve it without explicit plumbing.
        # NOTE: the most recently built mesh wins process-wide — a model
        # bound to an earlier mesh must not be applied after a second
        # build() with different axis sizes (the EP path validates axis
        # sizes and fails loudly on mismatch).
        compat.set_mesh(mesh)
        return MeshContext(params=self, mesh=mesh)


@dataclasses.dataclass(frozen=True)
class MeshContext:
    """A built mesh plus the axis-group vocabulary of the framework.

    The reference's five mesh *domains* (device_mesh_domains.py:174-180)
    become properties returning axis-name tuples, usable directly inside
    ``PartitionSpec``s and as ``axis_name`` arguments to collectives.
    """

    params: MeshParameters
    mesh: Mesh

    # --- axis groups (the "domains") -------------------------------------

    @property
    def dp_axes(self) -> tuple[str, ...]:
        """All data-parallel axes (batch 'dp' dim of the reference's batch domain)."""
        return (AXIS_DP_REPLICATE, AXIS_DP_SHARD)

    @property
    def cp_axes(self) -> tuple[str, ...]:
        """All context-parallel axes (batch 'cp' dim)."""
        return (AXIS_CP_SHARD, AXIS_CP_REPLICATE)

    @property
    def batch_axes(self) -> tuple[str, ...]:
        """Axes over which the global batch dim is split (dp, incl. fsdp)."""
        return self.dp_axes

    @property
    def sequence_axes(self) -> tuple[str, ...]:
        """Axes over which the sequence dim is split (context parallel)."""
        return (AXIS_CP_SHARD,)

    @property
    def fsdp_axes(self) -> tuple[str, ...]:
        """Parameter-shard axes — the reference's fused ``dp_cp_shard`` dense dim
        (device_mesh_domains.py:99-121)."""
        return (AXIS_DP_SHARD, AXIS_CP_SHARD)

    @property
    def grad_reduce_axes(self) -> tuple[str, ...]:
        """Axes across which gradients of replicated params must be summed."""
        return (
            AXIS_DP_REPLICATE,
            AXIS_DP_SHARD,
            AXIS_CP_SHARD,
            AXIS_CP_REPLICATE,
        )

    @functools.cached_property
    def ep_shard_axes(self) -> tuple[str, ...]:
        """Mesh axes forming the expert-shard dim (fastest-varying suffix)."""
        non_pp = list(zip(MESH_AXIS_NAMES[1:], self.params.axis_sizes[1:]))
        return _suffix_axes_covering(self.params.ep_shard, non_pp)

    @functools.cached_property
    def ep_replicate_axes(self) -> tuple[str, ...]:
        """Non-pp axes not part of the expert shard (the ep_replicate dim)."""
        shard = set(self.ep_shard_axes)
        return tuple(
            n
            for n, s in zip(MESH_AXIS_NAMES[1:], self.params.axis_sizes[1:])
            if n not in shard
        )

    # --- sizes -----------------------------------------------------------

    def axis_size(self, *axes: str) -> int:
        return math.prod(self.mesh.shape[a] for a in axes)

    @property
    def world_size(self) -> int:
        return self.params.world_size

    @property
    def pp_size(self) -> int:
        return self.params.pp

    # --- pipeline submeshes ----------------------------------------------

    def stage_mesh(self, pp_rank: int) -> Mesh:
        """The non-pp submesh owned by pipeline rank ``pp_rank``.

        Pipeline stages are SPMD programs over their own device group
        (reference: per-rank NCCL process slice); here each pp coordinate's
        devices form a mesh with the same non-pp axis vocabulary, so one
        parallel plan (fsdp/tp/ep rules) applies unchanged per stage.
        """
        if not 0 <= pp_rank < self.params.pp:
            raise ValueError(
                f"pp_rank {pp_rank} out of range for pp={self.params.pp}"
            )
        # per-instance memo (direct __dict__ write: dataclass is frozen);
        # an lru_cache on the method would pin every MeshContext forever
        cache = self.__dict__.setdefault("_stage_meshes", {})
        if pp_rank not in cache:
            cache[pp_rank] = Mesh(
                self.mesh.devices[pp_rank], MESH_AXIS_NAMES[1:]
            )
        return cache[pp_rank]

    # --- sharding helpers ------------------------------------------------

    def spec(self, *dims: str | tuple[str, ...] | None) -> P:
        return P(*dims)

    def sharding(self, *dims: str | tuple[str, ...] | None) -> NamedSharding:
        return NamedSharding(self.mesh, P(*dims))

    def batch_sharding(self, extra: P | None = None) -> NamedSharding:
        """Sharding for a [batch, seq, ...] array: batch over dp, seq over cp."""
        dims: list = [self.batch_axes, self.sequence_axes]
        if extra is not None:
            dims.extend(extra)
        return NamedSharding(self.mesh, P(*dims))

    # --- process info ----------------------------------------------------

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def process_count(self) -> int:
        return jax.process_count()

    @property
    def is_main_process(self) -> bool:
        return jax.process_index() == 0
