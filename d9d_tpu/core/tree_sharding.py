"""Declarative split/merge of arbitrary pytrees into N shards.

Used for microbatch splitting and pipeline transient state. Parity with the
reference's pytree sharding spec system (d9d/core/sharding/spec.py:6-25,
shard.py:99, unshard.py:60, auto_spec.py:26,49), re-typed for JAX arrays.

A *spec tree* mirrors the data tree's structure (or is a single spec applied
to every array leaf). Each leaf spec is either ``SpecShard(dim)`` — split
that leaf along ``dim`` into N equal chunks — or ``SpecReplicate()`` — every
shard sees the same leaf.

The spec tree's structure drives flattening: wherever the spec has a leaf,
the corresponding data subtree is treated as one shardable unit. This lets a
``SpecShard(0)`` apply to a plain python list (e.g. a list of strings in a
batch), which is sliced as a sequence — matching the reference's list-leaf
handling (auto_spec.py / unshard.py list paths).
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import PyTree


@dataclasses.dataclass(frozen=True)
class SpecShard:
    dim: int = 0


@dataclasses.dataclass(frozen=True)
class SpecReplicate:
    pass


ShardingSpec = SpecShard | SpecReplicate


def _is_spec(x: Any) -> bool:
    return isinstance(x, (SpecShard, SpecReplicate))


def _broadcast_spec(tree: PyTree, spec: PyTree) -> PyTree:
    """If ``spec`` is a single leaf spec, broadcast it over ``tree``'s leaves."""
    if _is_spec(spec):
        return jax.tree.map(lambda _: spec, tree)
    return spec


def _shardable_on(leaf: Any, dim: int) -> bool:
    ndim = getattr(leaf, "ndim", None)
    if ndim is None or ndim == 0:
        return False
    return (-ndim <= dim < ndim) if dim < 0 else dim < ndim


def shard_spec_on_dim(tree: PyTree, dim: int = 0) -> PyTree:
    """Auto-spec: shard array leaves on ``dim``; python lists become sequence
    leaves sharded on dim 0; everything else replicates.

    Parity: reference ``shard_spec_on_dim`` (core/sharding/auto_spec.py:26).
    """

    def leaf_spec(leaf: Any) -> ShardingSpec:
        if isinstance(leaf, list):
            return SpecShard(0)
        if _shardable_on(leaf, dim):
            return SpecShard(dim)
        return SpecReplicate()

    return jax.tree.map(leaf_spec, tree, is_leaf=lambda x: isinstance(x, list))


def _split_leaf(leaf: Any, s: SpecShard, num_shards: int) -> list[Any]:
    if isinstance(leaf, (list, tuple)):
        if s.dim != 0:
            raise ValueError(f"sequence leaves can only shard on dim 0, got {s.dim}")
        if len(leaf) % num_shards != 0:
            raise ValueError(
                f"cannot shard sequence of length {len(leaf)} into {num_shards} chunks"
            )
        step = len(leaf) // num_shards
        return [leaf[i * step : (i + 1) * step] for i in range(num_shards)]
    if not _shardable_on(leaf, s.dim):
        raise ValueError(f"cannot shard leaf {type(leaf).__name__} on dim {s.dim}")
    if leaf.shape[s.dim] % num_shards != 0:
        raise ValueError(
            f"cannot shard leaf of shape {leaf.shape} on dim {s.dim} "
            f"into {num_shards} equal chunks"
        )
    if isinstance(leaf, jax.Array):
        return list(jnp.split(leaf, num_shards, axis=s.dim))
    return list(np.split(np.asarray(leaf), num_shards, axis=s.dim))


def _merge_leaf(parts: list[Any], s: SpecShard) -> Any:
    first = parts[0]
    if isinstance(first, list):
        return [item for part in parts for item in part]
    if isinstance(first, tuple):
        return tuple(item for part in parts for item in part)
    if isinstance(first, jax.Array):
        return jnp.concatenate(parts, axis=s.dim)
    return np.concatenate([np.asarray(p) for p in parts], axis=s.dim)


def shard_tree(tree: PyTree, spec: PyTree, num_shards: int) -> list[PyTree]:
    """Split ``tree`` into ``num_shards`` trees according to ``spec``.

    Parity: reference ``shard_tree`` (core/sharding/shard.py:99).
    """
    spec = _broadcast_spec(tree, spec)
    spec_leaves, spec_treedef = jax.tree.flatten(spec, is_leaf=_is_spec)
    data_units = spec_treedef.flatten_up_to(tree)

    shards_per_unit: list[list[Any]] = []
    for unit, s in zip(data_units, spec_leaves):
        if isinstance(s, SpecReplicate):
            shards_per_unit.append([unit] * num_shards)
        elif isinstance(s, SpecShard):
            shards_per_unit.append(_split_leaf(unit, s, num_shards))
        else:
            raise TypeError(f"unknown sharding spec leaf: {s!r}")

    return [
        jax.tree.unflatten(spec_treedef, [per[i] for per in shards_per_unit])
        for i in range(num_shards)
    ]


def unshard_tree(shards: list[PyTree], spec: PyTree) -> PyTree:
    """Merge shards back into one tree (inverse of :func:`shard_tree`).

    Parity: reference ``unshard_tree`` (core/sharding/unshard.py:60).
    Sharded leaves are concatenated along their dim (numpy leaves stay
    numpy); replicated leaves take the first shard's value.
    """
    if not shards:
        raise ValueError("need at least one shard")
    spec = _broadcast_spec(shards[0], spec)
    spec_leaves, spec_treedef = jax.tree.flatten(spec, is_leaf=_is_spec)
    all_units = [spec_treedef.flatten_up_to(s) for s in shards]

    merged: list[Any] = []
    for i, s in enumerate(spec_leaves):
        if isinstance(s, SpecReplicate):
            merged.append(all_units[0][i])
        elif isinstance(s, SpecShard):
            merged.append(_merge_leaf([units[i] for units in all_units], s))
        else:
            raise TypeError(f"unknown sharding spec leaf: {s!r}")
    return jax.tree.unflatten(spec_treedef, merged)


def replicate_uncommitted(tree: PyTree, mesh) -> PyTree:
    """Pin every *uncommitted* (single-default-device) array leaf to a
    mesh-replicated NamedSharding; committed/sharded leaves pass through.

    A ``jax.jit`` output that no input sharding constrains (e.g. a fresh
    optimizer step counter) comes back uncommitted on the default
    device. The live step tolerates that — jit relocates uncommitted
    operands freely — but the placement round-trips through a checkpoint
    as a *committed* single-device array, which then conflicts with the
    mesh-placed parameters at the first post-restore step. Normalizing
    at init keeps the job state's placement stable across
    save/restore (docs/design/resilience.md, checkpoint fallback).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())

    def fix(x):
        if isinstance(x, jax.Array) and not x.committed:
            return jax.device_put(x, replicated)
        return x

    return jax.tree.map(fix, tree)
