"""Version-compat shims for the ambient-mesh JAX API.

The framework targets the current JAX surface — ``jax.set_mesh``,
``jax.sharding.get_abstract_mesh``, ``jax.sharding.AxisType`` — which
older runtimes (0.4.x, e.g. a CPU rig whose JAX is pinned by an
accelerator plugin) predate. On a current JAX every helper here
degenerates to the native call; on an old one they fall back to a
process-local current-mesh slot with the same resolution semantics
("most recently set mesh wins; a scoped set restores the previous one
on exit"), so the mesh-dependent stack stays importable and testable
everywhere.

Only the AMBIENT-MESH bookkeeping is emulated — collectives, shard_map
and NamedSharding go through the public API on both sides.
"""

import jax

__all__ = [
    "HAS_MODERN_JAX",
    "get_abstract_mesh",
    "mesh_axis_types_kwargs",
    "set_mesh",
    "shard_map",
]

# True on a runtime with the native ambient-mesh API. The SPMD
# training/e2e test tier keys off this: the fallbacks below keep
# single-process serving/decode/bench paths working on old runtimes,
# but full mesh-training e2e there is uncertified (tests skip it).
HAS_MODERN_JAX = hasattr(jax, "set_mesh")

# fallback ambient mesh (single slot, matching jax.set_mesh semantics:
# a statement-form set replaces the current mesh; a scoped set restores
# the previous one on exit). Single-controller: the executor and all
# mesh builds run on the main thread; prefetch threads never set meshes.
_AMBIENT: list = [None]


def mesh_axis_types_kwargs(n_axes: int) -> dict:
    """``axis_types=(Auto,) * n`` where the runtime understands it."""
    if hasattr(jax.sharding, "AxisType"):
        return {
            "axis_types": (jax.sharding.AxisType.Auto,) * n_axes
        }
    return {}


class _FallbackSetMesh:
    """Matches ``jax.set_mesh``'s dual use: called as a statement the
    mesh stays ambient process-wide; used as a context manager the
    previously ambient mesh is restored at block exit."""

    def __init__(self, mesh):
        self._prev = _AMBIENT[0]
        _AMBIENT[0] = mesh

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _AMBIENT[0] = self._prev
        return False


def set_mesh(mesh):
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return _FallbackSetMesh(mesh)


def get_abstract_mesh():
    """The ambient mesh, or None. The fallback returns the concrete
    ``Mesh`` most recently set — its ``.shape`` mapping is what every
    caller consumes, so the two paths are interchangeable."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    return _AMBIENT[0]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """``jax.shard_map`` with the keyword surface this repo uses.

    The fallback maps onto ``jax.experimental.shard_map.shard_map``:
    ``check_vma`` → ``check_rep`` (the older name for the same
    replication-inference toggle) and ``axis_names`` (the subset of mesh
    axes that go manual) → ``auto`` (its complement).
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _sm

    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
