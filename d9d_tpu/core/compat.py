"""Version-compat shims for the ambient-mesh JAX API.

The framework targets the current JAX surface — ``jax.set_mesh``,
``jax.sharding.get_abstract_mesh``, ``jax.sharding.AxisType`` — which
older runtimes (0.4.x, e.g. a CPU rig whose JAX is pinned by an
accelerator plugin) predate. On a current JAX every helper here
degenerates to the native call; on an old one they fall back to a
process-local current-mesh slot with the same resolution semantics
("most recently set mesh wins; a scoped set restores the previous one
on exit"), so the mesh-dependent stack stays importable and testable
everywhere.

Only the AMBIENT-MESH bookkeeping is emulated — collectives, shard_map
and NamedSharding go through the public API on both sides.
"""

import jax

__all__ = [
    "HAS_MODERN_JAX",
    "compiled_cost_analysis",
    "compiled_memory_analysis",
    "device_hbm_capacity",
    "get_abstract_mesh",
    "mesh_axis_types_kwargs",
    "set_mesh",
    "shard_map",
]

# True on a runtime with the native ambient-mesh API. The SPMD
# training/e2e test tier keys off this: the fallbacks below keep
# single-process serving/decode/bench paths working on old runtimes,
# but full mesh-training e2e there is uncertified (tests skip it).
HAS_MODERN_JAX = hasattr(jax, "set_mesh")

# fallback ambient mesh (single slot, matching jax.set_mesh semantics:
# a statement-form set replaces the current mesh; a scoped set restores
# the previous one on exit). Single-controller: the executor and all
# mesh builds run on the main thread; prefetch threads never set meshes.
_AMBIENT: list = [None]


def mesh_axis_types_kwargs(n_axes: int) -> dict:
    """``axis_types=(Auto,) * n`` where the runtime understands it."""
    if hasattr(jax.sharding, "AxisType"):
        return {
            "axis_types": (jax.sharding.AxisType.Auto,) * n_axes
        }
    return {}


class _FallbackSetMesh:
    """Matches ``jax.set_mesh``'s dual use: called as a statement the
    mesh stays ambient process-wide; used as a context manager the
    previously ambient mesh is restored at block exit."""

    def __init__(self, mesh):
        self._prev = _AMBIENT[0]
        _AMBIENT[0] = mesh

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _AMBIENT[0] = self._prev
        return False


def set_mesh(mesh):
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return _FallbackSetMesh(mesh)


def get_abstract_mesh():
    """The ambient mesh, or None. The fallback returns the concrete
    ``Mesh`` most recently set — its ``.shape`` mapping is what every
    caller consumes, so the two paths are interchangeable."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    return _AMBIENT[0]


# -- compiled-executable introspection (telemetry/introspect.py) --------
#
# The AOT surface is stable (`Lowered.compile()` → `Compiled`), but what
# the *backend* returns from cost/memory analysis varies: lists vs dicts
# across jax versions, None on backends without the C++ implementation,
# and attribute-less stubs on some plugins. Normalize here so the
# introspection layer never has to version-switch.

_MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "generated_code_size_in_bytes",
    "alias_size_in_bytes",
)


def compiled_cost_analysis(compiled) -> dict | None:
    """``Compiled.cost_analysis()`` normalized to one flat dict (or None
    when the backend declines). Older runtimes return a one-element list
    of dicts; newer ones return the dict directly."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend without the analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not ca:
        return None
    try:
        return {str(k): float(v) for k, v in dict(ca).items()}
    except Exception:  # noqa: BLE001 — unexpected shape: treat as absent
        return None


def compiled_memory_analysis(compiled) -> dict | None:
    """``Compiled.memory_analysis()`` as ``{field: int bytes}`` over the
    standard CompiledMemoryStats size fields, or None when the backend
    returns nothing useful (all-absent attrs count as nothing)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend without the analysis
        return None
    if ma is None:
        return None
    out = {}
    for field in _MEMORY_FIELDS:
        v = getattr(ma, field, None)
        if v is not None:
            try:
                out[field] = int(v)
            except (TypeError, ValueError):
                continue
    return out or None


def device_hbm_capacity() -> int | None:
    """Per-chip accelerator memory capacity in bytes (``bytes_limit``
    from the device's memory stats), or None where the backend exposes
    none (CPU rigs) — callers skip the budget gauge then."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend not initialized/available
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """``jax.shard_map`` with the keyword surface this repo uses.

    The fallback maps onto ``jax.experimental.shard_map.shard_map``:
    ``check_vma`` → ``check_rep`` (the older name for the same
    replication-inference toggle) and ``axis_names`` (the subset of mesh
    axes that go manual) → ``auto`` (its complement).
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _sm

    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
