"""Common type aliases used across the framework.

Parity: mirrors the role of ``d9d/core/types`` (reference: core/types/pytree.py:7,
core/types/data.py:8) — but typed against JAX arrays instead of torch tensors.
"""

from collections.abc import Callable
from typing import Any, TypeAlias

import jax

# An arbitrary JAX pytree (nested dict/list/tuple of leaves).
PyTree: TypeAlias = Any

# A pytree whose leaves are jax.Array.
ArrayTree: TypeAlias = Any

# A pytree whose leaves are python scalars / 0-d arrays.
ScalarTree: TypeAlias = Any

Array: TypeAlias = jax.Array

# Collate function: list of per-sample pytrees -> one batched pytree.
CollateFn: TypeAlias = Callable[[list[PyTree]], PyTree]
