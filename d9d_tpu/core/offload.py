"""Host offload primitives (wake/sleep support).

Reference: d9d/core/offload/{tensor.py:26,49, api.py:8-79} — in-place swap
of tensor storage to pinned host memory and back, identity preserved, used
for colocated-RL sleep/wake. JAX arrays are immutable, so the TPU design
swaps *trees*: ``offload_tree`` returns a host-resident copy plus the
device shardings needed to restore; ``onload_tree`` puts it back. On TPU
the transfer uses the ``pinned_host`` memory kind (stays addressable by
the runtime, fast DMA back); elsewhere it falls back to host numpy.

``SleepTag`` mirrors the reference granularity: callers pick which groups
(model / optimizer) to offload.
"""

import enum
import logging

import jax

from d9d_tpu.core.types import PyTree

logger = logging.getLogger("d9d_tpu.offload")


class SleepTag(enum.Enum):
    MODEL = "model"
    OPTIMIZER = "optimizer"


def offload_tree(tree: PyTree) -> tuple[PyTree, PyTree]:
    """→ (host_tree, device_shardings). Device buffers are released once
    the transfer completes and no other reference holds them."""
    shardings = jax.tree.map(lambda x: x.sharding, tree)
    try:
        host_shardings = jax.tree.map(
            lambda s: s.with_memory_kind("pinned_host"), shardings
        )
        host = jax.device_put(tree, host_shardings)
        jax.block_until_ready(host)
        return host, shardings
    except (ValueError, TypeError, RuntimeError) as e:
        logger.debug("pinned_host offload unavailable (%s); using numpy", e)
        return jax.device_get(tree), shardings


def onload_tree(host_tree: PyTree, shardings: PyTree) -> PyTree:
    """Restore an offloaded tree onto devices with its original shardings."""
    out = jax.device_put(host_tree, shardings)
    jax.block_until_ready(out)
    return out
