"""Determinism helpers.

Reference: d9d/internals/determinism/seed.py:18 (``set_seeds`` — seed
torch/python/numpy/hash shifted by PP rank so pipeline stages draw
different init noise) and d9d/internals/state/main_process.py:8
(main-process-only statefuls). On TPU the model RNG is an explicit
``jax.random`` key threaded by the trainer, so this module covers the
*host-side* RNGs (python/numpy used by dataloaders and augmentation) and
derives the jax root key with the same stage shift.
"""

import random
from typing import Any

import jax
import numpy as np


def set_seeds(seed: int, *, pp_rank: int = 0) -> jax.Array:
    """Seed python/numpy (shifted by pipeline stage) and return the jax
    root key for that stage."""
    shifted = seed + pp_rank
    random.seed(shifted)
    np.random.seed(shifted % (2**32))
    return jax.random.fold_in(jax.random.PRNGKey(seed), pp_rank)


class MainProcessOnlyState:
    """Wraps a stateful object so only process 0 saves/loads its state
    (reference internals/state/main_process.py:8,29)."""

    def __init__(self, inner: Any):
        self.inner = inner

    def state_dict(self) -> dict:
        if jax.process_index() == 0 and hasattr(self.inner, "state_dict"):
            return {"state": self.inner.state_dict()}
        return {}

    def load_state_dict(self, state: dict) -> None:
        if (
            jax.process_index() == 0
            and "state" in state
            and hasattr(self.inner, "load_state_dict")
        ):
            self.inner.load_state_dict(state["state"])
