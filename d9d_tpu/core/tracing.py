"""Hot-path trace attribution (VERDICT r3 item 3).

The reference wraps every pipeline action and grad region in
``torch.profiler.record_function`` (d9d/pipelining/runtime/executor.py:96,
internals/grad_sync/bucket.py:194, internals/grad_norm/norm.py:125) so a
captured trace attributes time to schedule slots. The TPU equivalents:

- **Host side** — :func:`annotate` emits a ``jax.profiler.TraceAnnotation``
  (TraceMe) around dispatch regions (pipeline actions, optimizer phases,
  batch staging). Annotations are gated behind a process-wide flag so the
  steady-state step path pays one attribute read per region when profiling
  is off; ``JobProfiler`` flips the flag for the duration of each capture
  window (and tools that profile do the same).
- **Device side** — jitted stage/step functions wrap their bodies in
  ``jax.named_scope`` (zero runtime cost: names attach to HLO ops at trace
  time), so XLA ops in the captured trace carry ``pp_stage*/fwd`` -style
  prefixes that ``tools/trace_summary.py`` groups by.
"""

import contextlib

import jax

__all__ = ["annotate", "annotations_enabled", "set_trace_annotations"]

_enabled = False

_NULL = contextlib.nullcontext()


def set_trace_annotations(on: bool) -> None:
    """Globally enable/disable host-side trace annotations (cheap toggle;
    called by the profiler around capture windows)."""
    global _enabled
    _enabled = bool(on)


def annotations_enabled() -> bool:
    return _enabled


def annotate(label: str):
    """Context manager: a named host-trace region when annotations are on,
    a shared null context otherwise."""
    if _enabled:
        return jax.profiler.TraceAnnotation(label)
    return _NULL
