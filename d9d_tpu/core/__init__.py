from d9d_tpu.core.distributed import (
    DistributedConfig,
    init_distributed,
    resolve_distributed_config,
    shutdown_distributed,
)
from d9d_tpu.core.mesh import (
    AXIS_CP_REPLICATE,
    AXIS_CP_SHARD,
    AXIS_DP_REPLICATE,
    AXIS_DP_SHARD,
    AXIS_PP,
    AXIS_TP,
    MESH_AXIS_NAMES,
    MeshContext,
    MeshParameters,
    resolve_ambient_mesh,
)
from d9d_tpu.core.tree_sharding import (
    SpecReplicate,
    SpecShard,
    shard_spec_on_dim,
    shard_tree,
    unshard_tree,
)
from d9d_tpu.core.types import Array, ArrayTree, CollateFn, PyTree, ScalarTree

__all__ = [
    "DistributedConfig",
    "init_distributed",
    "resolve_distributed_config",
    "shutdown_distributed",
    "AXIS_CP_REPLICATE",
    "AXIS_CP_SHARD",
    "AXIS_DP_REPLICATE",
    "AXIS_DP_SHARD",
    "AXIS_PP",
    "AXIS_TP",
    "MESH_AXIS_NAMES",
    "MeshContext",
    "MeshParameters",
    "resolve_ambient_mesh",
    "SpecReplicate",
    "SpecShard",
    "shard_spec_on_dim",
    "shard_tree",
    "unshard_tree",
    "Array",
    "ArrayTree",
    "CollateFn",
    "PyTree",
    "ScalarTree",
]
