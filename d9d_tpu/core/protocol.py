"""Training protocols.

Reference: d9d/core/protocol/training.py:5,34 (OptimizerProtocol /
LRSchedulerProtocol). The TPU engine accepts any plain optax
``GradientTransformation`` AND optimizers implementing this richer
protocol, which adds two optional capabilities the train step honors:

- ``accepts_fp32_grads = True`` — the step passes accumulated fp32 grads
  through without down-casting them to the param dtype first (needed by
  optimizers that do their own precision management, e.g. StochasticAdamW).
- ``apply_updates(params, updates)`` — the optimizer owns the parameter
  write instead of ``optax.apply_updates`` (needed when the write itself
  carries semantics, e.g. stochastic rounding into bf16).
"""

from typing import Any, Protocol, runtime_checkable

from d9d_tpu.core.types import PyTree


@runtime_checkable
class OptimizerProtocol(Protocol):
    """Structural type for engine-compatible optimizers."""

    def init(self, params: PyTree) -> Any: ...

    def update(
        self, grads: PyTree, state: Any, params: PyTree
    ) -> tuple[PyTree, Any]: ...


@runtime_checkable
class OptimizerOwnsApply(OptimizerProtocol, Protocol):
    """Optimizers that additionally own the parameter write."""

    accepts_fp32_grads: bool

    def apply_updates(self, params: PyTree, updates: PyTree) -> PyTree: ...
