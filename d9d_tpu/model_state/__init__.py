from d9d_tpu.model_state.io import *  # noqa: F401,F403
from d9d_tpu.model_state.mapper import *  # noqa: F401,F403
