"""Compose mappers: parallel union, sequential pipeline, prefix scoping,
group sharding.

Parity: reference d9d/model_state/mapper/compose/{parallel,sequential,
prefix_scope,shard,helper}.py. Sequential keeps the reference's two key
behaviors: gap-filling (identity pass-through injection between stages) and
net dependency-group computation with transitive merging, so a chain
A:{x}->{y}, B:{y}->{z} reports a single group {x}->{z}.
"""

from collections.abc import Sequence

from d9d_tpu.model_state.mapper.abc import (
    ModelStateMapper,
    StateDict,
    StateGroup,
)
from d9d_tpu.model_state.mapper.leaf import ModelStateMapperIdentity


def filter_empty_mappers(
    mappers: Sequence[ModelStateMapper],
) -> list[ModelStateMapper]:
    """Drop mappers with no non-empty dependency group."""
    result = []
    for mapper in mappers:
        for group in mapper.state_dependency_groups():
            if len(group.inputs) > 0 or len(group.outputs) > 0:
                result.append(mapper)
                break
    return result


class ModelStateMapperParallel(ModelStateMapper):
    """Disjoint union of mappers; input/output key collisions are errors."""

    def __init__(self, mappers: Sequence[ModelStateMapper]):
        mappers_lst = filter_empty_mappers(mappers)

        all_groups: set[StateGroup] = set()
        inputs_to_mapper: dict[frozenset[str], ModelStateMapper] = {}
        seen_inputs: set[str] = set()
        seen_outputs: set[str] = set()
        for mapper in mappers_lst:
            for sub_group in mapper.state_dependency_groups():
                if not seen_inputs.isdisjoint(sub_group.inputs):
                    raise ValueError(
                        f"Found a colliding input group: {sub_group.inputs}"
                    )
                seen_inputs.update(sub_group.inputs)
                if not seen_outputs.isdisjoint(sub_group.outputs):
                    raise ValueError(
                        f"Found colliding output keys: {sub_group.outputs}"
                    )
                seen_outputs.update(sub_group.outputs)
                all_groups.add(sub_group)
                inputs_to_mapper[sub_group.inputs] = mapper

        self._all_groups = frozenset(all_groups)
        self._inputs_to_mapper = inputs_to_mapper

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._all_groups

    def apply(self, group: StateDict) -> StateDict:
        group_keys = frozenset(group.keys())
        if group_keys not in self._inputs_to_mapper:
            raise ValueError(
                "Tried to run a parallel mapper with undefined group. "
                "Perhaps you sent groups that are not isolated?"
            )
        return self._inputs_to_mapper[group_keys].apply(group)


class ModelStateMapperSequential(ModelStateMapper):
    """Pipeline of mappers with automatic gap filling and group merging."""

    def __init__(self, mappers: list[ModelStateMapper]):
        mappers = filter_empty_mappers(mappers)
        if not mappers:
            raise ValueError("Mappers list cannot be empty.")
        mappers = self._fill_gaps(mappers)
        self._groups = self._compute_pipeline_groups(mappers)
        self._mappers = mappers

    @staticmethod
    def _fill_gaps(
        mappers: list[ModelStateMapper],
    ) -> list[ModelStateMapper]:
        mappers = mappers.copy()
        # inputs needed downstream but not produced upstream pass through
        for stage_i in reversed(range(1, len(mappers))):
            current_requires = frozenset().union(
                *(
                    g.inputs
                    for g in mappers[stage_i].state_dependency_groups()
                )
            )
            prev_produces = frozenset().union(
                *(
                    g.outputs
                    for g in mappers[stage_i - 1].state_dependency_groups()
                )
            )
            pass_through = current_requires - prev_produces
            mappers[stage_i - 1] = ModelStateMapperParallel(
                [mappers[stage_i - 1]]
                + [ModelStateMapperIdentity(x) for x in pass_through]
            )
        # outputs produced upstream but not consumed downstream also pass
        for stage_i in range(0, len(mappers) - 1):
            current_produces = frozenset().union(
                *(
                    g.outputs
                    for g in mappers[stage_i].state_dependency_groups()
                )
            )
            next_requires = frozenset().union(
                *(
                    g.inputs
                    for g in mappers[stage_i + 1].state_dependency_groups()
                )
            )
            pass_through = current_produces - next_requires
            mappers[stage_i + 1] = ModelStateMapperParallel(
                [mappers[stage_i + 1]]
                + [ModelStateMapperIdentity(x) for x in pass_through]
            )
        return mappers

    @staticmethod
    def _compute_pipeline_groups(
        mappers: list[ModelStateMapper],
    ) -> frozenset[StateGroup]:
        outputs_depend_on_inputs = {}
        for last_group in mappers[-1].state_dependency_groups():
            required_inputs = last_group.inputs
            for mapper_i in reversed(range(0, len(mappers) - 1)):
                hit_groups = [
                    g
                    for g in mappers[mapper_i].state_dependency_groups()
                    if not g.outputs.isdisjoint(required_inputs)
                ]
                required_inputs = frozenset().union(
                    *(g.inputs for g in hit_groups)
                )
            outputs_depend_on_inputs[last_group.outputs] = required_inputs
        return ModelStateMapperSequential._merge_groups(
            list(outputs_depend_on_inputs.items())
        )

    @staticmethod
    def _merge_groups(groups) -> frozenset[StateGroup]:
        # Transitively union groups sharing any input or output key
        # (union-find; a group is (outputs, inputs) as produced by
        # _compute_pipeline_groups).
        items = [(set(outs), set(ins)) for outs, ins in groups]
        parent = list(range(len(items)))

        def find(i: int) -> int:
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i

        key_owner: dict[tuple[str, str], int] = {}
        for i, (outs, ins) in enumerate(items):
            for kind, keys in (("in", ins), ("out", outs)):
                for key in keys:
                    owner = key_owner.setdefault((kind, key), i)
                    if owner != i:
                        parent[find(i)] = find(owner)

        merged: dict[int, tuple[set[str], set[str]]] = {}
        for i, (outs, ins) in enumerate(items):
            root = find(i)
            acc = merged.setdefault(root, (set(), set()))
            acc[0].update(outs)
            acc[1].update(ins)
        return frozenset(
            StateGroup(inputs=frozenset(ins), outputs=frozenset(outs))
            for outs, ins in merged.values()
        )

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._groups

    def apply(self, group: StateDict) -> StateDict:
        current_state = group
        next_state: StateDict = {}
        for mapper in self._mappers:
            for deps in mapper.state_dependency_groups():
                if not deps.inputs <= current_state.keys():
                    continue
                next_state.update(
                    mapper.apply(
                        {
                            k: v
                            for k, v in current_state.items()
                            if k in deps.inputs
                        }
                    )
                )
            current_state = next_state
            next_state = {}
        return current_state


class ModelStateMapperPrefixScope(ModelStateMapper):
    """Scope a child mapper under source/target key prefixes."""

    def __init__(
        self,
        mapper: ModelStateMapper,
        source_prefix: str = "",
        target_prefix: str = "",
    ):
        self._mapper = mapper
        self._source_prefix = source_prefix
        self._target_prefix = target_prefix
        self._groups = frozenset(
            StateGroup(
                inputs=frozenset(f"{source_prefix}{k}" for k in g.inputs),
                outputs=frozenset(f"{target_prefix}{k}" for k in g.outputs),
            )
            for g in mapper.state_dependency_groups()
        )

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._groups

    def apply(self, group: StateDict) -> StateDict:
        scoped = {
            k.removeprefix(self._source_prefix): v for k, v in group.items()
        }
        result = self._mapper.apply(scoped)
        return {f"{self._target_prefix}{k}": v for k, v in result.items()}


class ModelStateMapperShard(ModelStateMapper):
    """Restrict a mapper to every ``total_shards``-th dependency group —
    splits checkpoint loading work across processes."""

    def __init__(
        self,
        sub_mapper: ModelStateMapper,
        total_shards: int,
        current_shard: int,
    ):
        groups_sorted = sorted(
            sub_mapper.state_dependency_groups(),
            key=lambda g: sorted(g.inputs),
        )
        self._groups = frozenset(
            g
            for i, g in enumerate(groups_sorted)
            if i % total_shards == current_shard
        )
        self._sub_mapper = sub_mapper

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._groups

    def apply(self, group: StateDict) -> StateDict:
        return self._sub_mapper.apply(group)
