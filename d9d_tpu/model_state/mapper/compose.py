"""Compose mappers: parallel union, sequential pipeline, prefix scoping,
group sharding.

Parity targets: reference d9d/model_state/mapper/compose/{parallel,
sequential,prefix_scope,shard}.py — same behavioral contract, different
machinery. The sequential composition here is built around a *static carry
plan* instead of rewriting the mapper list with injected identity mappers:
at construction we compute, per stage boundary, which keys must flow past
the stage untouched (needed downstream but not produced in between, or
produced earlier and never consumed again), and ``apply`` consults that
plan at runtime. Net dependency groups come from a union-find over the
key graph, which gives the same transitive merging (stage A ``{x}→{y}``
then stage B ``{y}→{z}`` reports one net group ``{x}→{z}``).
"""

from collections.abc import Iterable, Sequence

from d9d_tpu.model_state.mapper.abc import (
    ModelStateMapper,
    StateDict,
    StateGroup,
)


def _union(sets: Iterable[frozenset[str]]) -> frozenset[str]:
    out: set[str] = set()
    for s in sets:
        out |= s
    return frozenset(out)


def _stage_io(mapper: ModelStateMapper) -> tuple[frozenset[str], frozenset[str]]:
    groups = mapper.state_dependency_groups()
    return _union(g.inputs for g in groups), _union(g.outputs for g in groups)


def filter_empty_mappers(
    mappers: Sequence[ModelStateMapper],
) -> list[ModelStateMapper]:
    """Drop mappers whose every dependency group is empty."""
    return [
        m
        for m in mappers
        if any(g.inputs or g.outputs for g in m.state_dependency_groups())
    ]


class _KeyComponents:
    """Union-find over state keys; one component per connected transform."""

    def __init__(self) -> None:
        self._parent: dict[str, str] = {}

    def _root(self, key: str) -> str:
        parent = self._parent.setdefault(key, key)
        if parent == key:
            return key
        root = self._root(parent)
        self._parent[key] = root
        return root

    def connect(self, keys: Iterable[str]) -> None:
        it = iter(keys)
        first = next(it, None)
        if first is None:
            return
        anchor = self._root(first)
        for key in it:
            self._parent[self._root(key)] = anchor

    def components(self) -> dict[str, set[str]]:
        out: dict[str, set[str]] = {}
        for key in list(self._parent):
            out.setdefault(self._root(key), set()).add(key)
        return out


class ModelStateMapperParallel(ModelStateMapper):
    """Side-by-side union of independent mappers.

    Every sub-mapper keeps exclusive ownership of its input and output
    keys; overlap is a construction-time error. ``apply`` dispatches a
    complete input group to whichever sub-mapper declared it.
    """

    def __init__(self, mappers: Sequence[ModelStateMapper]):
        members = filter_empty_mappers(mappers)
        self._route: dict[frozenset[str], ModelStateMapper] = {}
        claimed_in: set[str] = set()
        claimed_out: set[str] = set()
        for member in members:
            for g in member.state_dependency_groups():
                overlap_in = claimed_in & g.inputs
                if overlap_in:
                    raise ValueError(
                        f"parallel mapper: input keys {sorted(overlap_in)} "
                        "claimed by more than one sub-mapper"
                    )
                overlap_out = claimed_out & g.outputs
                if overlap_out:
                    raise ValueError(
                        f"parallel mapper: output keys {sorted(overlap_out)} "
                        "produced by more than one sub-mapper"
                    )
                claimed_in |= g.inputs
                claimed_out |= g.outputs
                self._route[g.inputs] = member
        self._groups = frozenset(
            g for m in members for g in m.state_dependency_groups()
        )

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._groups

    def apply(self, group: StateDict) -> StateDict:
        member = self._route.get(frozenset(group))
        if member is None:
            raise ValueError(
                f"parallel mapper: keys {sorted(group)} do not form a "
                "declared dependency group (groups must be applied whole)"
            )
        return member.apply(group)


class ModelStateMapperSequential(ModelStateMapper):
    """Pipeline of mappers with automatic key pass-through.

    Keys a later stage needs that an earlier stage does not produce flow
    through untouched; keys produced mid-pipeline and never consumed again
    flow to the output. Net dependency groups are transitively merged
    across stages.
    """

    def __init__(self, mappers: list[ModelStateMapper]):
        stages = filter_empty_mappers(mappers)
        if not stages:
            raise ValueError(
                "sequential mapper needs at least one stage with a "
                "non-empty dependency group"
            )
        self._stages = stages
        io = [_stage_io(m) for m in stages]

        # needed[i] = keys stages i..end must see entering stage i
        needed: list[frozenset[str]] = [frozenset()] * (len(stages) + 1)
        for i in reversed(range(len(stages))):
            ins, outs = io[i]
            needed[i] = ins | (needed[i + 1] - outs)

        # keys that must pass over stage i untouched; consuming one of them
        # at stage i would leave downstream starved — reject at build time
        self._carry: list[frozenset[str]] = []
        for i, (ins, outs) in enumerate(io):
            over = needed[i + 1] - outs
            stuck = over & ins
            if stuck:
                raise ValueError(
                    f"sequential mapper: keys {sorted(stuck)} are consumed "
                    f"by stage {i} but later stages still need them and no "
                    "stage in between re-produces them"
                )
            self._carry.append(over)

        self._net_inputs = needed[0]
        self._groups = self._compute_net_groups(io)

    def _compute_net_groups(self, io) -> frozenset[StateGroup]:
        # simulate key flow to find the final key set
        live = set(self._net_inputs)
        made: set[str] = set()
        for i, stage in enumerate(self._stages):
            nxt: set[str] = set()
            used: set[str] = set()
            for g in stage.state_dependency_groups():
                if g.inputs <= live:
                    nxt |= g.outputs
                    made |= g.outputs
                    used |= g.inputs
            for key in live - used:
                if key in self._carry[i] or key in made:
                    nxt.add(key)
            live = nxt
        net_outputs = frozenset(live)

        comps = _KeyComponents()
        for stage in self._stages:
            for g in stage.state_dependency_groups():
                comps.connect(g.inputs | g.outputs)
        groups = []
        for keys in comps.components().values():
            ins = frozenset(keys) & self._net_inputs
            outs = frozenset(keys) & net_outputs
            if ins or outs:
                groups.append(StateGroup(inputs=ins, outputs=outs))
        return frozenset(groups)

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._groups

    def apply(self, group: StateDict) -> StateDict:
        state = dict(group)
        made: set[str] = set()
        for i, stage in enumerate(self._stages):
            nxt: StateDict = {}
            used: set[str] = set()
            for g in stage.state_dependency_groups():
                if g.inputs <= state.keys():
                    nxt.update(
                        stage.apply({k: state[k] for k in g.inputs})
                    )
                    made.update(g.outputs)
                    used |= g.inputs
            for key, value in state.items():
                if key not in used and (key in self._carry[i] or key in made):
                    nxt.setdefault(key, value)
            state = nxt
        return state


class ModelStateMapperPrefixScope(ModelStateMapper):
    """Run a child mapper under source/target key-name prefixes."""

    def __init__(
        self,
        mapper: ModelStateMapper,
        source_prefix: str = "",
        target_prefix: str = "",
    ):
        self._child = mapper
        self._src = source_prefix
        self._dst = target_prefix

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            StateGroup(
                inputs=frozenset(self._src + k for k in g.inputs),
                outputs=frozenset(self._dst + k for k in g.outputs),
            )
            for g in self._child.state_dependency_groups()
        )

    def apply(self, group: StateDict) -> StateDict:
        inner = self._child.apply(
            {k.removeprefix(self._src): v for k, v in group.items()}
        )
        return {self._dst + k: v for k, v in inner.items()}


class ModelStateMapperShard(ModelStateMapper):
    """Round-robin a mapper's dependency groups across ``total_shards``
    workers — splits checkpoint transformation work across processes."""

    def __init__(
        self,
        sub_mapper: ModelStateMapper,
        total_shards: int,
        current_shard: int,
    ):
        ordered = sorted(
            sub_mapper.state_dependency_groups(),
            key=lambda g: sorted(g.inputs),
        )
        self._mine = frozenset(
            ordered[i] for i in range(current_shard, len(ordered), total_shards)
        )
        self._child = sub_mapper

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return self._mine

    def apply(self, group: StateDict) -> StateDict:
        return self._child.apply(group)
