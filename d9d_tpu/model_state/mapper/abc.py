"""Model-state transformation graph: the mapper abstraction.

Parity: reference d9d/model_state/mapper/abc.py:7,23 (StateGroup +
ModelStateMapper). The declarative/imperative split is kept exactly:

1. Declarative — ``state_dependency_groups()`` announces *what* will be
   consumed/produced, letting the IO layer build streaming plans, validate
   chains and shard work before touching tensor data.
2. Imperative — ``apply()`` transforms one complete input group.

Tensors are host ``numpy`` arrays: checkpoint transformation happens on
host, then the module layer device_puts with target shardings (the jax
replacement for DTensor distribution).
"""

import abc
import dataclasses

import numpy as np

StateDict = dict[str, np.ndarray]


@dataclasses.dataclass(frozen=True)
class StateGroup:
    """Atomic dependency unit: ``inputs`` are all keys required, ``outputs``
    all keys produced by one independent transformation."""

    inputs: frozenset[str]
    outputs: frozenset[str]


class ModelStateMapper(abc.ABC):
    @abc.abstractmethod
    def state_dependency_groups(self) -> frozenset[StateGroup]:
        """Disjoint dependency groups this mapper handles."""
        ...

    @abc.abstractmethod
    def apply(self, group: StateDict) -> StateDict:
        """Transform one group; ``group`` contains exactly the keys of a
        single StateGroup's inputs, the result exactly its outputs."""
        ...
