"""Leaf mappers: single-tensor and multi-tensor primitives.

Parity: reference d9d/model_state/mapper/leaf/{single_tensor,rename,stack,
select_child}.py. The DTensor pair (Distribute / GatherFullTensor,
leaf/dtensor.py) has no leaf equivalent here: under jax, distribution is a
``device_put`` with a NamedSharding and gathering is ``np.asarray`` on the
global array — both live in the module IO layer
(d9d_tpu/model_state/io/module.py), not in the mapper graph.
"""

import numpy as np

from d9d_tpu.model_state.mapper.abc import (
    ModelStateMapper,
    StateDict,
    StateGroup,
)


def _single(name_in: str, name_out: str) -> frozenset[StateGroup]:
    return frozenset(
        [StateGroup(inputs=frozenset([name_in]), outputs=frozenset([name_out]))]
    )


class ModelStateMapperIdentity(ModelStateMapper):
    """Pass one tensor through unchanged."""

    def __init__(self, name: str):
        self._name = name

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name, self._name)

    def apply(self, group: StateDict) -> StateDict:
        return group


class ModelStateMapperRename(ModelStateMapper):
    def __init__(self, name_from: str, name_to: str):
        self._name_from = name_from
        self._name_to = name_to

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name_from, self._name_to)

    def apply(self, group: StateDict) -> StateDict:
        return {self._name_to: group[self._name_from]}


class ModelStateMapperTranspose(ModelStateMapper):
    def __init__(self, name: str, dims: tuple[int, int]):
        self._name = name
        self._dims = dims

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name, self._name)

    def apply(self, group: StateDict) -> StateDict:
        return {self._name: np.swapaxes(group[self._name], *self._dims)}


class ModelStateMapperSqueeze(ModelStateMapper):
    def __init__(self, name: str, dim: int | None = None):
        self._name = name
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name, self._name)

    def apply(self, group: StateDict) -> StateDict:
        return {self._name: np.squeeze(group[self._name], axis=self._dim)}


class ModelStateMapperUnsqueeze(ModelStateMapper):
    def __init__(self, name: str, dim: int):
        self._name = name
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name, self._name)

    def apply(self, group: StateDict) -> StateDict:
        return {self._name: np.expand_dims(group[self._name], axis=self._dim)}


class ModelStateMapperCast(ModelStateMapper):
    """Cast one tensor to a target dtype (jax extension; the torch reference
    leaves dtype conversion to load_state_dict)."""

    def __init__(self, name: str, dtype):
        self._name = name
        self._dtype = dtype

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return _single(self._name, self._name)

    def apply(self, group: StateDict) -> StateDict:
        return {self._name: np.asarray(group[self._name]).astype(self._dtype)}


class ModelStateMapperStackTensors(ModelStateMapper):
    """Stack inputs into one output along a new dim."""

    def __init__(self, source_names: list[str], target_name: str, dim: int):
        self._source_names = list(source_names)
        self._target_name = target_name
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset(self._source_names),
                    outputs=frozenset([self._target_name]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        return {
            self._target_name: np.stack(
                [group[n] for n in self._source_names], axis=self._dim
            )
        }


class ModelStateMapperUnstackTensors(ModelStateMapper):
    def __init__(self, source_name: str, target_names: list[str], dim: int):
        self._source_name = source_name
        self._target_names = list(target_names)
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source_name]),
                    outputs=frozenset(self._target_names),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        tensor = np.asarray(group[self._source_name])
        if tensor.shape[self._dim] != len(self._target_names):
            raise ValueError(
                f"cannot unstack dim of size {tensor.shape[self._dim]} into "
                f"{len(self._target_names)} tensors"
            )
        parts = np.moveaxis(tensor, self._dim, 0)
        return {
            name: np.ascontiguousarray(parts[i])
            for i, name in enumerate(self._target_names)
        }


class ModelStateMapperChunkTensors(ModelStateMapper):
    def __init__(self, source_name: str, target_names: list[str], dim: int):
        self._source_name = source_name
        self._target_names = list(target_names)
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source_name]),
                    outputs=frozenset(self._target_names),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        chunks = np.array_split(
            np.asarray(group[self._source_name]),
            len(self._target_names),
            axis=self._dim,
        )
        return {
            name: np.ascontiguousarray(chunk)
            for name, chunk in zip(self._target_names, chunks, strict=True)
        }


class ModelStateMapperConcatenateTensors(ModelStateMapper):
    def __init__(self, source_names: list[str], target_name: str, dim: int):
        self._source_names = list(source_names)
        self._target_name = target_name
        self._dim = dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset(self._source_names),
                    outputs=frozenset([self._target_name]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        return {
            self._target_name: np.concatenate(
                [group[n] for n in self._source_names], axis=self._dim
            )
        }


class ModelStateMapperSelectChildModules(ModelStateMapper):
    """Hoist keys out of a parent scope: ``parent.x -> x`` batch rename."""

    def __init__(self, base_names: list[str], parent_name: str):
        self._base_names = list(base_names)
        self._parent_prefix = f"{parent_name}."

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._parent_prefix + name]),
                    outputs=frozenset([name]),
                )
                for name in self._base_names
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        name, value = next(iter(group.items()))
        if name.startswith(self._parent_prefix):
            return {name[len(self._parent_prefix) :]: value}
        return {}
