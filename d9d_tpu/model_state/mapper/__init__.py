from d9d_tpu.model_state.mapper.abc import (
    ModelStateMapper,
    StateDict,
    StateGroup,
)
from d9d_tpu.model_state.mapper.compose import (
    ModelStateMapperParallel,
    ModelStateMapperPrefixScope,
    ModelStateMapperSequential,
    ModelStateMapperShard,
    filter_empty_mappers,
)
from d9d_tpu.model_state.mapper.leaf import (
    ModelStateMapperCast,
    ModelStateMapperChunkTensors,
    ModelStateMapperConcatenateTensors,
    ModelStateMapperIdentity,
    ModelStateMapperRename,
    ModelStateMapperSelectChildModules,
    ModelStateMapperSqueeze,
    ModelStateMapperStackTensors,
    ModelStateMapperTranspose,
    ModelStateMapperUnsqueeze,
    ModelStateMapperUnstackTensors,
)

__all__ = [
    "ModelStateMapper",
    "ModelStateMapperCast",
    "ModelStateMapperChunkTensors",
    "ModelStateMapperConcatenateTensors",
    "ModelStateMapperIdentity",
    "ModelStateMapperParallel",
    "ModelStateMapperPrefixScope",
    "ModelStateMapperRename",
    "ModelStateMapperSelectChildModules",
    "ModelStateMapperSequential",
    "ModelStateMapperShard",
    "ModelStateMapperSqueeze",
    "ModelStateMapperStackTensors",
    "ModelStateMapperTranspose",
    "ModelStateMapperUnsqueeze",
    "ModelStateMapperUnstackTensors",
    "StateDict",
    "StateGroup",
    "filter_empty_mappers",
]
