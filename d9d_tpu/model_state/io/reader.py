"""Streaming checkpoint reader.

Parity: reference d9d/model_state/io/reader.py:92 (read_model_state): build
a file→keys loading plan from the mapper's dependency groups, load each
safetensors file once, fire mapper groups as their inputs complete, evict
consumed inputs immediately. Memory high-water is one group + one open
file, never the whole checkpoint.
"""

from collections import defaultdict
from collections.abc import Generator, Iterable
from pathlib import Path

import numpy as np
from safetensors import safe_open

from d9d_tpu.model_state.io.dto import (
    MODEL_STATE_INDEX_FILE_NAME,
    ModelStateIndex,
)
from d9d_tpu.model_state.mapper.abc import ModelStateMapper


class _StateLoadingFlow:
    def __init__(self, src_dir: Path, mapper: ModelStateMapper):
        self._src_dir = Path(src_dir)
        self._mapper = mapper
        self._index = self._load_index()
        self._groups_to_process = set(mapper.state_dependency_groups())
        self._stored_states: dict[str, np.ndarray] = {}
        self._check_index()

    def _load_index(self) -> ModelStateIndex:
        index_file = self._src_dir / MODEL_STATE_INDEX_FILE_NAME
        if not index_file.exists():
            # single-file checkpoints (bare model.safetensors) get a
            # synthesized index
            single = self._src_dir / "model.safetensors"
            if single.exists():
                with safe_open(str(single), framework="np") as st:
                    keys = list(st.keys())
                return ModelStateIndex(
                    metadata={"total_size": 0},
                    weight_map={k: "model.safetensors" for k in keys},
                )
            raise FileNotFoundError(index_file)
        return ModelStateIndex.model_validate_json(
            index_file.read_text(encoding="utf-8")
        )

    def _check_index(self) -> None:
        required: set[str] = set()
        for group in self._groups_to_process:
            required.update(group.inputs)
        missing = required.difference(self._index.weight_map.keys())
        if missing:
            raise ValueError(
                f"Cannot run state loading: states {sorted(missing)} are missing!"
            )

    def _process_available_groups(
        self,
    ) -> Generator[tuple[str, np.ndarray], None, None]:
        for group in self._groups_to_process.copy():
            if not group.inputs.issubset(self._stored_states.keys()):
                continue
            self._groups_to_process.remove(group)
            outputs = self._mapper.apply(
                {
                    k: v
                    for k, v in self._stored_states.items()
                    if k in group.inputs
                }
            )
            yield from outputs.items()
            for input_name in group.inputs:
                del self._stored_states[input_name]

    def _build_file_loading_plan(self) -> dict[str, set[str]]:
        plan: dict[str, set[str]] = defaultdict(set)
        for group in self._mapper.state_dependency_groups():
            for key in group.inputs:
                plan[self._index.weight_map[key]].add(key)
        return plan

    def load(self) -> Iterable[tuple[str, np.ndarray]]:
        for file_name, keys in self._build_file_loading_plan().items():
            with safe_open(
                str(self._src_dir / file_name), framework="np"
            ) as st:
                for key in keys:
                    self._stored_states[key] = st.get_tensor(key)
            yield from self._process_available_groups()
        if self._groups_to_process:
            missing = {g.inputs for g in self._groups_to_process}
            raise ValueError(
                f"Reading finished with unsatisfied groups: {missing}"
            )


def read_model_state(
    src_dir: Path, mapper: ModelStateMapper
) -> Iterable[tuple[str, np.ndarray]]:
    """Stream (name, array) pairs from a checkpoint, transformed by ``mapper``."""
    yield from _StateLoadingFlow(src_dir=src_dir, mapper=mapper).load()
