"""Streaming checkpoint writer.

Parity target: reference d9d/model_state/io/writer.py:175,210,252 — consume
a (name, array) generator, fire mapper groups as their inputs complete,
spill ≤shard_size_gb safetensors files, publish a global index. Three
modes: local (single process), distributed (replicated state, one writing
process), and pipeline-parallel (each stage group writes its own slice;
indices merged via host object gather, the reference's all_gather_object
pattern at writer.py:285-309).

Structure here: a ``_ShardSpool`` owns size-capped spilling to
process-unique temp files, a ``_GroupStream`` owns reactive group firing
(arriving keys are matched through a key→groups index rather than
rescanning every group per tensor), and ``_publish`` renames spooled files
into the final ``model-XXXXX-of-YYYYY.safetensors`` numbering with one
HF-compatible index JSON.
"""

import warnings
from collections.abc import Callable, Iterable
from pathlib import Path

import numpy as np
from safetensors.numpy import save_file

from d9d_tpu.core.collectives import host_allgather_object
from d9d_tpu.model_state.io.dto import (
    MODEL_STATE_INDEX_FILE_NAME,
    ModelStateIndex,
    ModelStateIndexMeta,
)
from d9d_tpu.model_state.mapper.abc import ModelStateMapper


class _ShardSpool:
    """Size-capped safetensors spooler writing ``.spool-{tag}-N`` files."""

    def __init__(self, dest_dir: Path, cap_bytes: int, tag: str):
        self._dir = Path(dest_dir)
        self._cap = cap_bytes
        self._tag = tag
        self._buffer: dict[str, np.ndarray] = {}
        self._buffered = 0
        self._spilled_files: list[str] = []
        self._locations: dict[str, str] = {}  # weight name → temp file name
        self._bytes_total = 0

    def add(self, name: str, arr: np.ndarray) -> None:
        if arr.nbytes > self._cap:
            raise ValueError(
                f"tensor {name!r} is {arr.nbytes} bytes — larger than the "
                f"shard size cap ({self._cap}); raise shard_size_gb"
            )
        if self._buffered + arr.nbytes > self._cap:
            self._spill()
        self._buffer[name] = arr
        self._buffered += arr.nbytes

    def _spill(self) -> None:
        if not self._buffer:
            return
        fname = f".spool-{self._tag}-{len(self._spilled_files)}.safetensors"
        save_file(
            {k: np.ascontiguousarray(v) for k, v in self._buffer.items()},
            str(self._dir / fname),
        )
        self._spilled_files.append(fname)
        self._locations.update({k: fname for k in self._buffer})
        self._bytes_total += self._buffered
        self._buffer.clear()
        self._buffered = 0

    def finish(self) -> ModelStateIndex:
        self._spill()
        return ModelStateIndex(
            metadata=ModelStateIndexMeta(total_size=self._bytes_total),
            weight_map=dict(self._locations),
        )


class _GroupStream:
    """Reactive mapper-group execution over a stream of named tensors."""

    def __init__(
        self,
        mapper: ModelStateMapper,
        emit: Callable[[str, np.ndarray], None],
    ):
        self._mapper = mapper
        self._emit = emit
        self._inbox: dict[str, np.ndarray] = {}
        self._open: set = set(mapper.state_dependency_groups())
        self._by_key: dict[str, list] = {}
        for group in self._open:
            for key in group.inputs:
                self._by_key.setdefault(key, []).append(group)

    def push(self, name: str, arr: np.ndarray) -> None:
        self._inbox[name] = np.asarray(arr)
        for group in self._by_key.get(name, ()):
            if group not in self._open:
                continue
            if not group.inputs <= self._inbox.keys():
                continue
            self._open.discard(group)
            produced = self._mapper.apply(
                {k: self._inbox[k] for k in group.inputs}
            )
            for key in group.inputs:
                # a key may feed exactly one group (mapper contract), so it
                # is dead once that group fired
                del self._inbox[key]
            for out_name, out_arr in produced.items():
                self._emit(out_name, np.asarray(out_arr))

    def finish(self) -> None:
        if self._open:
            unfired = sorted(
                tuple(sorted(g.inputs)) for g in self._open
            )
            raise ValueError(
                "state stream ended with dependency groups still waiting "
                f"for inputs: {unfired}"
            )
        if self._inbox:
            warnings.warn(
                "state stream carried tensors no mapper group consumes: "
                f"{sorted(self._inbox)}",
                stacklevel=2,
            )


def _run_stream(
    dest_dir: Path,
    mapper: ModelStateMapper,
    states: Iterable[tuple[str, np.ndarray]],
    shard_size_gb: float,
    tag: str,
    writes: bool,
) -> ModelStateIndex | None:
    """Drive the stream; spool to disk only when ``writes`` is set (other
    processes still validate group completeness)."""
    dest_dir = Path(dest_dir)
    dest_dir.mkdir(parents=True, exist_ok=True)
    spool = (
        _ShardSpool(dest_dir, int(shard_size_gb * (1024**3)), tag)
        if writes
        else None
    )
    sink = spool.add if spool is not None else (lambda name, arr: None)
    stream = _GroupStream(mapper, sink)
    for name, arr in states:
        stream.push(name, arr)
    stream.finish()
    return spool.finish() if spool is not None else None


def _publish(dest_dir: Path, spooled: list[ModelStateIndex]) -> None:
    """Rename spool files into the global shard numbering + write the index."""
    dest_dir = Path(dest_dir)
    temp_files: list[str] = []
    for index in spooled:
        for fname in index.weight_map.values():
            if fname not in temp_files:
                temp_files.append(fname)
    renamed = {
        old: f"model-{i + 1:05d}-of-{len(temp_files):05d}.safetensors"
        for i, old in enumerate(temp_files)
    }
    for old, new in renamed.items():
        (dest_dir / old).rename(dest_dir / new)
    merged = ModelStateIndex(
        metadata=ModelStateIndexMeta(
            total_size=sum(ix.metadata.total_size for ix in spooled)
        ),
        weight_map={
            name: renamed[fname]
            for ix in spooled
            for name, fname in ix.weight_map.items()
        },
    )
    (dest_dir / MODEL_STATE_INDEX_FILE_NAME).write_text(
        merged.model_dump_json(indent=4), encoding="utf-8"
    )


def write_model_state_local(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    shard_size_gb: float = 4.0,
) -> None:
    """Single-process save."""
    index = _run_stream(
        dest_dir, mapper, state_generator, shard_size_gb, tag="0", writes=True
    )
    _publish(dest_dir, [index])


def write_model_state_distributed(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    shard_size_gb: float = 4.0,
) -> None:
    """Every process streams the same (replicated) state; process 0 writes."""
    import jax

    is_master = jax.process_index() == 0
    index = _run_stream(
        dest_dir, mapper, state_generator, shard_size_gb,
        tag="0", writes=is_master,
    )
    if is_master:
        _publish(dest_dir, [index])
    # barrier: no process may observe the directory before the master
    # finished renaming shards + writing the index
    host_allgather_object(None)


def write_model_state_pipeline_parallel(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    writer_rank: int,
    is_local_writer: bool,
    shard_size_gb: float = 4.0,
) -> None:
    """Each pipeline stage group writes its own states; indices are merged.

    ``is_local_writer`` selects one process per stage group (the reference's
    coordinate-sum-0 rule, writer.py:285-309); ``writer_rank`` must be
    unique among writers (e.g. the pp rank) so temp spool names don't
    collide.
    """
    import jax

    index = _run_stream(
        dest_dir, mapper, state_generator, shard_size_gb,
        tag=str(writer_rank), writes=is_local_writer,
    )
    spooled = [ix for ix in host_allgather_object(index) if ix is not None]
    if jax.process_index() == 0:
        _publish(dest_dir, spooled)
