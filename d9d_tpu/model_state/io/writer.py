"""Streaming checkpoint writer.

Parity: reference d9d/model_state/io/writer.py:175,210,252: consume a
(name, array) generator, fire mapper groups as inputs complete, spill
≤shard_size_gb safetensors shards under temp names, then a master pass
renames shards to ``model-XXXXX-of-YYYYY.safetensors`` and writes one
global index. Three modes: local (single process), distributed (every
process holds the full state; only master writes), and pipeline-parallel
(each process writes only its stages' states; indices merged via
host object gather — the reference's all_gather_object at writer.py:285-309).
"""

import warnings
from collections.abc import Iterable
from pathlib import Path

import numpy as np
from safetensors.numpy import save_file

from d9d_tpu.core.collectives import host_allgather_object
from d9d_tpu.model_state.io.dto import (
    MODEL_STATE_INDEX_FILE_NAME,
    ModelStateIndex,
    ModelStateIndexMeta,
)
from d9d_tpu.model_state.mapper.abc import ModelStateMapper


class _StateWritingFlowLocal:
    def __init__(
        self,
        dest_dir: Path,
        mapper: ModelStateMapper,
        shard_size_gb: float,
        sharding_rank: int,
        is_current_process_rank_master: bool,
    ):
        self._dest_dir = Path(dest_dir)
        self._mapper = mapper
        self._shard_size_bytes = int(shard_size_gb * (1024**3))
        self._groups_to_process = set(mapper.state_dependency_groups())
        self._available_source_states: dict[str, np.ndarray] = {}
        self._total_size = 0
        self._pending_write_tensors: dict[str, np.ndarray] = {}
        self._current_shard_size = 0
        self._sharding_rank = sharding_rank
        self._weight_name_to_local_shard_idx: dict[str, int] = {}
        self._local_shard_idx_to_tmp_path: dict[int, Path] = {}
        self._is_master = is_current_process_rank_master

    def _flush_shard(self) -> None:
        if not self._pending_write_tensors:
            return
        local_shard_num = len(self._local_shard_idx_to_tmp_path) + 1
        shard_tmp_path = (
            self._dest_dir
            / f".tmp-rank{self._sharding_rank}-shard-{local_shard_num}.safetensors"
        )
        self._local_shard_idx_to_tmp_path[local_shard_num] = shard_tmp_path
        save_file(
            {
                k: np.ascontiguousarray(v)
                for k, v in self._pending_write_tensors.items()
            },
            str(shard_tmp_path),
        )
        for state_name in self._pending_write_tensors:
            self._weight_name_to_local_shard_idx[state_name] = local_shard_num
        self._total_size += self._current_shard_size
        self._pending_write_tensors.clear()
        self._current_shard_size = 0

    def _process_available_groups(self) -> None:
        for group in self._groups_to_process.copy():
            if not group.inputs.issubset(self._available_source_states.keys()):
                continue
            self._groups_to_process.remove(group)
            states_to_save = self._mapper.apply(
                {
                    k: self._available_source_states[k]
                    for k in group.inputs
                }
            )
            for input_name in group.inputs:
                del self._available_source_states[input_name]
            if not self._is_master:
                continue
            for name, tensor in states_to_save.items():
                tensor = np.asarray(tensor)
                update_size = tensor.nbytes
                if update_size > self._shard_size_bytes:
                    raise ValueError(
                        f"Cannot save state {name} larger than shard size"
                    )
                if (
                    self._current_shard_size + update_size
                    > self._shard_size_bytes
                ):
                    self._flush_shard()
                self._pending_write_tensors[name] = tensor
                self._current_shard_size += update_size

    def _finalize_locally(self) -> ModelStateIndex:
        self._flush_shard()
        if self._groups_to_process:
            missing = {g.inputs for g in self._groups_to_process}
            raise ValueError(
                f"Writing failed: not all source tensors were provided. "
                f"Missing inputs for groups: {missing}"
            )
        if self._available_source_states:
            warnings.warn(
                f"State Writing: unconsumed source tensors ignored: "
                f"{sorted(self._available_source_states.keys())}",
                stacklevel=2,
            )
        weight_map_local = {
            name: self._local_shard_idx_to_tmp_path[shard_idx].name
            for name, shard_idx in self._weight_name_to_local_shard_idx.items()
        }
        return ModelStateIndex(
            metadata=ModelStateIndexMeta(total_size=self._total_size),
            weight_map=weight_map_local,
        )

    def write(
        self, state_generator: Iterable[tuple[str, np.ndarray]]
    ) -> ModelStateIndex | None:
        self._dest_dir.mkdir(parents=True, exist_ok=True)
        for name, tensor in state_generator:
            self._available_source_states[name] = np.asarray(tensor)
            self._process_available_groups()
        if self._is_master:
            return self._finalize_locally()
        # non-masters still validate that every group fired
        self._finalize_locally()
        return None


def _finalize_master(dest_dir: Path, indices: list[ModelStateIndex]) -> None:
    """Rename temp shards into the global numbering and write one index."""
    dest_dir = Path(dest_dir)
    total_size = sum(index.metadata.total_size for index in indices)
    total_weight_map_local = {
        name: file
        for index in indices
        for name, file in index.weight_map.items()
    }
    shard_count = len(
        {file for index in indices for file in index.weight_map.values()}
    )
    total_weight_map: dict[str, str] = {}
    local_to_global: dict[str, str] = {}
    used = 0
    for weight_name, old_file in total_weight_map_local.items():
        if old_file not in local_to_global:
            used += 1
            new_file = f"model-{used:05d}-of-{shard_count:05d}.safetensors"
            (dest_dir / old_file).rename(dest_dir / new_file)
            local_to_global[old_file] = new_file
        total_weight_map[weight_name] = local_to_global[old_file]
    (dest_dir / MODEL_STATE_INDEX_FILE_NAME).write_text(
        ModelStateIndex(
            metadata=ModelStateIndexMeta(total_size=total_size),
            weight_map=total_weight_map,
        ).model_dump_json(indent=4),
        encoding="utf-8",
    )


def write_model_state_local(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    shard_size_gb: float = 4.0,
) -> None:
    """Single-process save."""
    index = _StateWritingFlowLocal(
        dest_dir=dest_dir,
        mapper=mapper,
        shard_size_gb=shard_size_gb,
        sharding_rank=0,
        is_current_process_rank_master=True,
    ).write(state_generator)
    assert index is not None
    _finalize_master(dest_dir, [index])


def write_model_state_distributed(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    shard_size_gb: float = 4.0,
) -> None:
    """Every process streams the same (replicated) state; process 0 writes."""
    import jax

    is_master = jax.process_index() == 0
    index = _StateWritingFlowLocal(
        dest_dir=dest_dir,
        mapper=mapper,
        shard_size_gb=shard_size_gb,
        sharding_rank=0,
        is_current_process_rank_master=is_master,
    ).write(state_generator)
    if is_master:
        assert index is not None
        _finalize_master(dest_dir, [index])
    # barrier: no process may observe the directory before the master
    # finished renaming shards + writing the index
    host_allgather_object(None)


def write_model_state_pipeline_parallel(
    dest_dir: Path,
    mapper: ModelStateMapper,
    state_generator: Iterable[tuple[str, np.ndarray]],
    writer_rank: int,
    is_local_writer: bool,
    shard_size_gb: float = 4.0,
) -> None:
    """Each pipeline stage group writes its own states; indices are merged.

    ``is_local_writer`` selects one process per stage group (the reference's
    coordinate-sum-0 rule, writer.py:285-309); ``writer_rank`` must be
    unique among writers (e.g. the pp rank) so temp shard names don't
    collide.
    """
    import jax

    index = _StateWritingFlowLocal(
        dest_dir=dest_dir,
        mapper=mapper,
        shard_size_gb=shard_size_gb,
        sharding_rank=writer_rank,
        is_current_process_rank_master=is_local_writer,
    ).write(state_generator)
    indices = [i for i in host_allgather_object(index) if i is not None]
    if jax.process_index() == 0:
        _finalize_master(dest_dir, indices)
