"""Param-tree ⇄ checkpoint integration.

Parity: reference d9d/model_state/io/{module_reader.py:41,
module_writer.py:54,79}. The reference augments user mappers with DTensor
Distribute / GatherFullTensor leaves; under jax the equivalents are plain
array movement, applied here at the tree boundary:

- save: every param is brought to host (``np.asarray`` — for a sharded
  ``jax.Array`` XLA gathers the full value; for non-fully-addressable
  multi-host arrays an explicit process gather runs first), then streamed
  through the mapper into safetensors shards.
- load: every streamed output is ``device_put`` with the target leaf's
  sharding, so parameters land distributed exactly as the parallel plan
  demands — no full-model host materialization on any single step.
"""

from collections.abc import Iterable
from pathlib import Path
from typing import Any

import jax
import numpy as np
from flax import traverse_util

from d9d_tpu.core.types import PyTree
from d9d_tpu.model_state.io.reader import read_model_state
from d9d_tpu.model_state.io.writer import write_model_state_local
from d9d_tpu.model_state.mapper.abc import ModelStateMapper
from d9d_tpu.model_state.mapper.compose import ModelStateMapperParallel
from d9d_tpu.model_state.mapper.leaf import ModelStateMapperIdentity

SEP = "."


def flatten_params(params: PyTree) -> dict[str, Any]:
    """Flax param tree → flat {'a.b.c': leaf} dict."""
    flat = traverse_util.flatten_dict(params, sep=SEP)
    return dict(flat)


def unflatten_params(flat: dict[str, Any]) -> PyTree:
    return traverse_util.unflatten_dict(flat, sep=SEP)


def identity_mapper_from_names(names: Iterable[str]) -> ModelStateMapper:
    """Mapper that passes every named state through unchanged.

    Parity: reference adapters/module.py:8 (identity_mapper_from_module).
    """
    return ModelStateMapperParallel(
        [ModelStateMapperIdentity(n) for n in names]
    )


def identity_mapper_from_params(params: PyTree) -> ModelStateMapper:
    return identity_mapper_from_names(flatten_params(params).keys())


def _to_host(x) -> np.ndarray:
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def param_state_generator(
    params: PyTree,
) -> Iterable[tuple[str, np.ndarray]]:
    """Stream (dotted-name, host array) pairs; one param on host at a time."""
    for name, leaf in flatten_params(params).items():
        yield name, _to_host(leaf)


def save_params(
    dest_dir: Path,
    params: PyTree,
    mapper: ModelStateMapper | None = None,
    shard_size_gb: float = 4.0,
) -> None:
    """Save a param tree through ``mapper`` into sharded safetensors."""
    if mapper is None:
        mapper = identity_mapper_from_params(params)
    write_model_state_local(
        dest_dir=dest_dir,
        mapper=mapper,
        state_generator=param_state_generator(params),
        shard_size_gb=shard_size_gb,
    )


def load_params(
    src_dir: Path,
    template: PyTree,
    mapper: ModelStateMapper | None = None,
    shardings: PyTree | None = None,
) -> PyTree:
    """Load a checkpoint into the structure of ``template``.

    ``template`` leaves may be concrete arrays or ``jax.ShapeDtypeStruct``;
    ``shardings`` (same structure) provides per-leaf ``NamedSharding``s —
    streamed outputs are placed directly with them.
    """
    flat_template = flatten_params(template)
    if mapper is None:
        mapper = identity_mapper_from_names(flat_template.keys())
    flat_shardings = (
        flatten_params(shardings) if shardings is not None else {}
    )

    loaded: dict[str, Any] = {}
    for name, value in read_model_state(src_dir, mapper):
        if name not in flat_template:
            raise KeyError(
                f"checkpoint produced unknown param {name!r}; template has "
                f"{len(flat_template)} params"
            )
        want = flat_template[name]
        if tuple(value.shape) != tuple(want.shape):
            raise ValueError(
                f"shape mismatch for {name}: checkpoint {tuple(value.shape)} "
                f"vs template {tuple(want.shape)}"
            )
        value = value.astype(want.dtype)
        sharding = flat_shardings.get(name)
        if sharding is not None:
            loaded[name] = jax.device_put(value, sharding)
        else:
            loaded[name] = jax.numpy.asarray(value)

    missing = set(flat_template) - set(loaded)
    if missing:
        raise ValueError(f"checkpoint missing params: {sorted(missing)[:10]}"
                         f"{'...' if len(missing) > 10 else ''}")
    return unflatten_params(loaded)
