from d9d_tpu.model_state.io.dto import (
    MODEL_STATE_INDEX_FILE_NAME,
    ModelStateIndex,
    ModelStateIndexMeta,
)
from d9d_tpu.model_state.io.module import (
    flatten_params,
    identity_mapper_from_names,
    identity_mapper_from_params,
    load_params,
    param_state_generator,
    save_params,
    unflatten_params,
)
from d9d_tpu.model_state.io.reader import read_model_state
from d9d_tpu.model_state.io.writer import (
    write_model_state_distributed,
    write_model_state_local,
    write_model_state_pipeline_parallel,
)

__all__ = [
    "MODEL_STATE_INDEX_FILE_NAME",
    "ModelStateIndex",
    "ModelStateIndexMeta",
    "flatten_params",
    "identity_mapper_from_names",
    "identity_mapper_from_params",
    "load_params",
    "param_state_generator",
    "read_model_state",
    "save_params",
    "unflatten_params",
    "write_model_state_distributed",
    "write_model_state_local",
    "write_model_state_pipeline_parallel",
]
