"""Checkpoint index schema.

Parity: reference d9d/model_state/io/dto.py — the standard HF-compatible
``model.safetensors.index.json`` with a weight→file map.
"""

from pydantic import BaseModel

MODEL_STATE_INDEX_FILE_NAME = "model.safetensors.index.json"


class ModelStateIndexMeta(BaseModel):
    total_size: int


class ModelStateIndex(BaseModel):
    metadata: ModelStateIndexMeta
    weight_map: dict[str, str]
