"""ZeRO-style optimizer-state sharding over the ``dp_r`` (dp_replicate)
mesh axis (PAPERS.md, arxiv 2004.13336 — ZeRO stage 1/2).

The data-parallel replicate axis keeps a full copy of the fp32 masters
and Adam moments on every chip, and the optimizer step streams all of it
through HBM: BASELINE.md's roofline attributes a large slice of the MoE
north-star's HBM-bound step to exactly this traffic (the fp32
master/optimizer stream plus the 66 ms/step fp32 grad accumulator).
ZeRO's observation is that the *update* is elementwise, so each replica
only needs 1/N of the state:

- gradients are **reduce-scattered** into the local shard (the scan-carry
  grad accumulator is annotated with the sharded spec, so XLA turns the
  backward's dp_r all-reduce into a reduce-scatter and the fp32
  accumulator itself shrinks to 1/N per chip);
- the optimizer **update runs on 1/N** of the masters/moments (the
  moments live sharded in HBM between steps — the durable 1/N);
- the new parameters are **all-gathered** back to the replicated layout
  the forward pass needs.

Everything is expressed as ``with_sharding_constraint`` annotations
around the existing ``optimizer.update`` / ``apply_updates`` seam
(loop/train_step.py, pipelining/training.py) — XLA SPMD inserts the
reduce-scatter/all-gather pair and fuses it with the update, so the
update math is untouched and CPU-exactness-testable against the
replicated path (tests/parallel/test_zero.py).

Composition: the transform *extends* each leaf's existing sharding (the
plan's fsdp/ep axes stay), adding ``dp_r`` to the largest still-divisible
dim. Leaves with no eligible dim (scalars, the StochasticAdamW RNG key,
odd shapes) stay as they are — the transform degrades per-leaf, never
per-tree. With ``dp_replicate == 1`` every constraint is an identity, so
the wrapped path is bit-identical to the unwrapped one by construction.

Checkpoint interplay: sharded state keeps its **global** shapes — only
the placement changes — so orbax saves/restores round-trip unchanged,
and restoring a sharded save onto a replicated mesh layout (or vice
versa) is just a resharding device_put on load (gather-on-load), driven
by the live state the trainer passes as the restore target
(tests/loop/test_zero_checkpoint.py). The same contract carries across
*chip counts*: the trainer builds these tables from the live state on
whatever mesh it initialized with, so an N-chip ``dp_replicate`` save
restores onto M chips as the M-chip 1/M layout with no table
translation — the elastic-restore path (docs/design/elasticity.md,
tests/resilience/test_elastic_restore.py) only adds mismatch detection
and HBM-bounded staging on top.
"""

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import AXIS_DP_REPLICATE
from d9d_tpu.core.types import PyTree

__all__ = [
    "ZeroSharding",
    "ZeroShardedOptimizer",
    "build_zero_sharding",
    "constrain_tree",
    "place_tree",
]


def _axis_names(entry) -> tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, tuple):
        return tuple(entry)
    return (entry,)


def _extend_spec(
    spec: P, shape: tuple[int, ...], mesh: Mesh, axis: str
) -> P | None:
    """Insert ``axis`` into ``spec`` on the best eligible dim of ``shape``.

    Eligible: the dim's per-shard size (after any axes already in its
    entry) divides evenly by the new axis size and the entry doesn't
    already name ``axis``. Among eligible dims the one with the largest
    per-shard size wins (maximum bytes moved off-replica). Returns None
    when no dim is eligible (or ``axis`` already shards the leaf) — the
    caller leaves such leaves untouched.
    """
    n = mesh.shape[axis]
    if n <= 1:
        return None
    entries = list(spec) + [None] * (len(shape) - len(spec))
    best: tuple[int, int] | None = None  # (per_shard_size, dim)
    for d, size in enumerate(shape):
        names = _axis_names(entries[d])
        if axis in names:
            return None
        factor = math.prod(mesh.shape[a] for a in names) if names else 1
        if size % (factor * n) != 0:
            continue
        per = size // factor
        if best is None or per > best[0]:
            best = (per, d)
    if best is None:
        return None
    d = best[1]
    names = _axis_names(entries[d])
    entries[d] = names + (axis,) if names else axis
    return P(*entries)


def _spec_of(leaf: jax.Array, mesh: Mesh, candidates: list[P]) -> P | None:
    """Recover the PartitionSpec of ``leaf``'s current placement.

    jit outputs on this rig carry GSPMD shardings (no spec attribute), so
    non-Named shardings are matched by *equivalence* against the small
    candidate set a job actually uses: replicated plus the distinct specs
    of the parameter tree. Unmatched placements return None and the leaf
    is left alone — never guess a spec and silently reshard.
    """
    sh = leaf.sharding
    if isinstance(sh, NamedSharding):
        return sh.spec
    for spec in candidates:
        try:
            if sh.is_equivalent_to(NamedSharding(mesh, spec), leaf.ndim):
                return spec
        except Exception:  # noqa: BLE001 — exotic sharding: skip the leaf
            return None
    return None


def _shardable(leaf: Any, axis_size: int) -> bool:
    """Only float leaves big enough to split carry optimizer state worth
    sharding; integer riders (step counters, the StochasticAdamW RNG
    key) stay replicated so their semantics can't be touched."""
    return (
        isinstance(leaf, jax.Array)
        and jnp.issubdtype(leaf.dtype, jnp.floating)
        and leaf.ndim >= 1
        and leaf.size >= axis_size
    )


@dataclasses.dataclass(frozen=True)
class ZeroSharding:
    """The computed sharding tables for one (params, opt_state) pair.

    ``grad_shardings``/``state_shardings`` leaves are ``NamedSharding``s
    where the leaf participates in the 1/N split and ``None`` where it is
    left untouched; ``param_shardings`` is the gather-back target (the
    parameters' original placement).
    """

    axis: str
    axis_size: int
    mesh: Mesh
    param_shardings: PyTree
    grad_shardings: PyTree
    state_shardings: PyTree
    # per-microbatch gradients are pinned to this (the parameters' own,
    # axis-replicated layout) BEFORE being accumulated into the sharded
    # carry: the backward pass then partitions exactly as the unsharded
    # baseline — XLA's bidirectional sharding propagation would otherwise
    # re-partition the backward matmuls off the carry constraint and
    # perturb gradient values at the ulp level. The accumulate is then a
    # shard-local elementwise add (carry[i] += g[i]), so accumulated
    # grads, moments and parameters stay BITWISE identical to the
    # replicated path; only the grad-norm scalar (reduced shard-wise +
    # psum instead of whole-array) can differ in summation order.
    grad_pin_shardings: PyTree = None

    @property
    def active(self) -> bool:
        return self.axis_size > 1


def build_zero_sharding(
    *,
    params: PyTree,
    opt_state: PyTree,
    mesh: Mesh,
    axis: str = AXIS_DP_REPLICATE,
) -> ZeroSharding:
    """Compute the ZeRO sharding tables from live (concrete) trees.

    Must run on the *initialized* state — shardings are read off the
    arrays themselves, so the plan's fsdp/ep placement composes without
    re-deriving it here.
    """
    if axis not in mesh.shape:
        raise ValueError(
            f"zero sharding axis {axis!r} not in mesh axes "
            f"{tuple(mesh.shape)}"
        )
    n = mesh.shape[axis]

    param_sh = jax.tree.map(
        lambda p: p.sharding if isinstance(p, jax.Array) else None, params
    )
    candidates: list[P] = [P()]
    for sh in jax.tree.leaves(param_sh):
        if isinstance(sh, NamedSharding) and sh.spec not in candidates:
            candidates.append(sh.spec)

    def extend(leaf):
        if not _shardable(leaf, n):
            return None
        spec = _spec_of(leaf, mesh, candidates)
        if spec is None:
            return None
        new_spec = _extend_spec(spec, leaf.shape, mesh, axis)
        if new_spec is None:
            return None
        return NamedSharding(mesh, new_spec)

    grad_sh = jax.tree.map(extend, params)
    # pin targets: only leaves that actually reshard need the baseline
    # anchor (see the field comment); leave the rest unconstrained
    grad_pin = jax.tree.map(
        lambda g_sh, p_sh: p_sh if g_sh is not None else None,
        grad_sh,
        param_sh,
        is_leaf=_none_leaf,
    )
    return ZeroSharding(
        axis=axis,
        axis_size=n,
        mesh=mesh,
        param_shardings=param_sh,
        grad_shardings=grad_sh,
        state_shardings=jax.tree.map(extend, opt_state),
        grad_pin_shardings=grad_pin,
    )


def _none_leaf(x: Any) -> bool:
    # sharding tables carry None where a leaf opted out; None is normally
    # an EMPTY pytree, so the table must lead the map with None-as-leaf
    # for the structures to stay zippable
    return x is None


def constrain_tree(tree: PyTree, shardings: PyTree) -> PyTree:
    """``with_sharding_constraint`` each leaf whose sharding entry is not
    None (trace-time annotation; XLA inserts the collectives)."""
    return jax.tree.map(
        lambda s, x: x if s is None else lax.with_sharding_constraint(x, s),
        shardings,
        tree,
        is_leaf=_none_leaf,
    )


def place_tree(tree: PyTree, shardings: PyTree) -> PyTree:
    """Eagerly reshard ``tree`` onto ``shardings`` (None = leave leaf).

    Used once at init (and after a gather-on-load restore) to move the
    live optimizer state onto its 1/N layout.
    """
    return jax.tree.map(
        lambda s, x: x if s is None else jax.device_put(x, s),
        shardings,
        tree,
        is_leaf=_none_leaf,
    )


def tree_bytes_per_device(tree: PyTree) -> int:
    """Per-chip bytes of a (possibly sharded) pytree — the
    ``opt/state_bytes_per_chip`` gauge and bench column. Host leaves
    count their full size (they are replicated by definition)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        if isinstance(leaf, jax.Array):
            try:
                shape = leaf.sharding.shard_shape(leaf.shape)
            except Exception:  # noqa: BLE001 — unplaced/abstract: full size
                shape = leaf.shape
        total += math.prod(shape) * jnp.dtype(dtype).itemsize
    return total


class ZeroShardedOptimizer:
    """Wrap any engine-compatible optimizer with the ZeRO collectives.

    ``update`` constrains the incoming grads to the 1/N layout
    (reduce-scatter — a no-op slice when the accumulator upstream already
    carries the sharded spec), runs the inner update (which XLA then
    partitions to the shard), and pins the new state back onto the 1/N
    layout; ``apply_updates`` constrains the written parameters back to
    their original (replicated-over-``axis``) placement — the all-gather.

    The wrapper preserves the OptimizerOwnsApply capabilities of the
    inner optimizer (``accepts_fp32_grads`` passthrough; StochasticAdamW
    keeps owning its stochastic-rounding write).
    """

    def __init__(self, inner, zero: ZeroSharding):
        self.inner = inner
        self.zero = zero

    @property
    def accepts_fp32_grads(self) -> bool:
        return getattr(self.inner, "accepts_fp32_grads", False)

    def init(self, params: PyTree):
        # plain inner init: the sharded placement is applied eagerly by
        # the caller via place_tree (build_zero_sharding needs the
        # concrete state first, so init-time constraint would be circular)
        return self.inner.init(params)

    def update(
        self, grads: PyTree, state: PyTree, params: PyTree
    ) -> tuple[PyTree, PyTree]:
        grads = constrain_tree(grads, self.zero.grad_shardings)
        updates, new_state = self.inner.update(grads, state, params)
        new_state = constrain_tree(new_state, self.zero.state_shardings)
        return updates, new_state

    def apply_updates(self, params: PyTree, updates: PyTree) -> PyTree:
        apply = getattr(self.inner, "apply_updates", optax.apply_updates)
        new_params = apply(params, updates)
        return constrain_tree(new_params, self.zero.param_shardings)
