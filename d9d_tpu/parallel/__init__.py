from d9d_tpu.parallel.plan import (
    LogicalRules,
    ParallelPlan,
    fsdp_ep_plan,
    fsdp_plan,
    hsdp_plan,
    logical_to_mesh_sharding,
    replicate_plan,
    tp_plan,
)

__all__ = [
    "LogicalRules",
    "ParallelPlan",
    "fsdp_ep_plan",
    "fsdp_plan",
    "hsdp_plan",
    "logical_to_mesh_sharding",
    "replicate_plan",
    "tp_plan",
]
