from d9d_tpu.parallel.plan import (
    LogicalRules,
    ParallelPlan,
    fsdp_ep_plan,
    fsdp_plan,
    hsdp_plan,
    logical_to_mesh_sharding,
    replicate_plan,
    tp_plan,
)
from d9d_tpu.parallel.zero import (
    ZeroSharding,
    ZeroShardedOptimizer,
    build_zero_sharding,
    tree_bytes_per_device,
)

__all__ = [
    "LogicalRules",
    "ParallelPlan",
    "ZeroSharding",
    "ZeroShardedOptimizer",
    "build_zero_sharding",
    "fsdp_ep_plan",
    "fsdp_plan",
    "hsdp_plan",
    "logical_to_mesh_sharding",
    "replicate_plan",
    "tp_plan",
    "tree_bytes_per_device",
]
