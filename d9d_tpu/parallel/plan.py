"""Parallelism plans: logical-axis → mesh-axis rule tables.

The TPU-native replacement for the reference's parallelize API
(d9d/module/parallelism/api/{replicate_parallel,fully_sharded,
hybrid_sharded,expert_parallel}.py). A *plan* is a table mapping the logical
axis names parameters were annotated with (d9d_tpu/nn/logical_axes.py) to
mesh axes; applying a plan turns the abstract param tree into
``NamedSharding``s, and XLA SPMD inserts the all-gathers/reduce-scatters the
reference implements imperatively (DTensor styles + bucketed allreduce).

- replicate  → DDP: params replicated over every data axis; gradient psum
  happens inside the jitted step (reference api/replicate_parallel.py:9).
- fsdp       → ZeRO-3: every weight sharded on its ``embed`` dim over the
  fused dp_s×cp_s axes (reference api/fully_sharded.py:14); XLA gathers
  params at use and reduce-scatters grads.
- hsdp       → same sharding; dp_r replicates implicitly because the rule
  table never mentions it (reference api/hybrid_sharded.py:10).
- tp         → Megatron-style: heads/mlp/vocab dims over the tp axis —
  a capability the reference reserves mesh dims for but never implements
  (SURVEY §2.9); on TPU it is just more rules in the table.
"""

import dataclasses

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import AXIS_TP, MeshContext
from d9d_tpu.core.types import PyTree
from d9d_tpu.nn import logical_axes as la

LogicalRules = tuple[tuple[str, str | tuple[str, ...] | None], ...]


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """A named logical→mesh rule table."""

    name: str
    rules: LogicalRules

    def param_shardings(self, ctx: MeshContext, abstract_params: PyTree) -> PyTree:
        """Map an abstract (eval_shape) param tree with flax Partitioned
        metadata to a tree of NamedShardings."""
        logical = nn.get_partition_spec(abstract_params)
        return logical_to_mesh_sharding(logical, ctx.mesh, self.rules)


def logical_to_mesh_sharding(
    logical_spec_tree: PyTree, mesh: Mesh, rules: LogicalRules
) -> PyTree:
    table = dict(rules)

    def convert(spec) -> NamedSharding:
        if not isinstance(spec, P):
            return NamedSharding(mesh, P())
        dims = []
        for axis in spec:
            mapped = table.get(axis) if axis is not None else None
            dims.append(mapped)
        return NamedSharding(mesh, P(*dims))

    return jax.tree.map(
        convert, logical_spec_tree, is_leaf=lambda x: isinstance(x, P)
    )


def replicate_plan(ctx: MeshContext) -> ParallelPlan:
    return ParallelPlan(name="replicate", rules=())


def fsdp_plan(ctx: MeshContext, *, with_tp: bool = False) -> ParallelPlan:
    """Shard every parameter's embed dim over the fused dp_s×cp_s axes."""
    rules: list[tuple[str, str | tuple[str, ...] | None]] = [
        (la.EMBED, ctx.fsdp_axes),
    ]
    if with_tp:
        rules += _tp_rules()
    rules += _vocab_table_rules(ctx, with_tp=with_tp)
    return ParallelPlan(name="fsdp", rules=tuple(rules))


def _vocab_table_rules(
    ctx: MeshContext, *, with_tp: bool
) -> list[tuple[str, str | tuple[str, ...] | None]]:
    """Vocab-range tables (embedding + LM head) ZeRO-3-shard on their vocab
    dim (fused with tp when active); the feature dim stays unsharded so the
    lookup output lands directly in the sequence-parallel activation layout
    instead of fighting it (e@cp vs t@cp forced replicate-reshards)."""
    vocab_axes = (AXIS_TP,) + ctx.fsdp_axes if with_tp else ctx.fsdp_axes
    return [(la.VOCAB, vocab_axes), (la.VOCAB_FEATURES, None)]


def hsdp_plan(ctx: MeshContext, *, with_tp: bool = False) -> ParallelPlan:
    # dp_r is simply absent from the table → replicated across it.
    return dataclasses.replace(fsdp_plan(ctx, with_tp=with_tp), name="hsdp")


def _tp_rules() -> list[tuple[str, str | tuple[str, ...] | None]]:
    return [
        (la.HEADS, AXIS_TP),
        (la.KV_HEADS, AXIS_TP),
        (la.MLP, AXIS_TP),
        (la.VOCAB, AXIS_TP),
    ]


def tp_plan(ctx: MeshContext) -> ParallelPlan:
    return ParallelPlan(name="tp", rules=tuple(_tp_rules()))


def fsdp_ep_plan(ctx: MeshContext, *, with_tp: bool = False) -> ParallelPlan:
    """FSDP/HSDP for dense params + expert parallelism for MoE weights.

    Experts are Shard(0) over the expert mesh axes and replicated on
    ep_replicate — reference api/expert_parallel.py:9
    (ShardMoESparseExpertsParallel). Grouped-weight feature dims stay
    unsharded (they ride the ragged grouped GEMM whole).
    """
    rules: list[tuple[str, str | tuple[str, ...] | None]] = [
        (la.EMBED, ctx.fsdp_axes),
        (la.EXPERT, ctx.ep_shard_axes),
        (la.EXPERT_EMBED, None),
        (la.EXPERT_MLP, None),
    ]
    if with_tp:
        rules += _tp_rules()
    rules += _vocab_table_rules(ctx, with_tp=with_tp)
    return ParallelPlan(name="fsdp_ep", rules=tuple(rules))
