"""Degraded-mode serving: bounded-queue backpressure, per-request
deadlines (queued and running), and the drain stall watchdog."""

import time

import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import toy_expected

from d9d_tpu.loop.serve import QueueFullError, ServeStalledError
from d9d_tpu.resilience.chaos import wedge_batcher


def test_bounded_queue_rejects_with_backpressure(toy_batcher_factory):
    b = toy_batcher_factory(max_queue=2)
    r1 = b.submit([3, 4], max_new_tokens=4)
    r2 = b.submit([7], max_new_tokens=3)
    b.step_chunk()  # admit r1/r2 into the two slots
    r3 = b.submit([1], max_new_tokens=2)
    b.submit([2], max_new_tokens=2)  # queue now at max_queue
    with pytest.raises(QueueFullError):
        b.submit([5], max_new_tokens=2)
    assert b.stats.rejected == 1
    # the overload shed cleanly: everything admitted still decodes right
    out = b.drain()
    assert out[r1] == toy_expected([3, 4], 4)
    assert out[r2] == toy_expected([7], 3)
    assert out[r3] == toy_expected([1], 2)
    assert not b.failed


def test_full_queue_expires_running_rows_before_rejecting(
    toy_batcher_factory,
):
    """ISSUE 11 satellite fix (running-side mirror of the PR 5
    queued-side fix): with the queue at max_queue, a deadline-expired
    RUNNING row frees a slot this boundary — the queue head will admit
    into it, so the submit must be accepted, not rejected."""
    b = toy_batcher_factory(max_queue=1, batch_size=1)
    doomed = b.submit([3], max_new_tokens=30, deadline_s=0.05)
    b.step_chunk()  # admitted into the only slot
    queued = b.submit([5], max_new_tokens=3)  # queue now at max_queue
    time.sleep(0.1)
    late = b.submit([9], max_new_tokens=3)  # pre-fix: QueueFullError
    assert b.failed[doomed] == "deadline"
    assert b.stats.rejected == 0
    out = b.drain()
    assert out[queued] == toy_expected([5], 3)
    assert out[late] == toy_expected([9], 3)
    # with nothing expirable the bounded-queue contract is unchanged
    r = b.submit([4], max_new_tokens=30)
    b.step_chunk()
    b.submit([6], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        b.submit([8], max_new_tokens=2)
    assert b.stats.rejected == 1
    del r


def test_full_queue_expiry_credit_is_page_bounded_when_paged(
    toy_batcher_factory,
):
    """Paged admission is bounded by pages, not slots: a freed slot
    only counts as capacity for the full-queue check if the queue head
    can actually map onto free pages — otherwise the bounded-queue
    contract would be violated with the head still blocked."""
    b = toy_batcher_factory(
        max_queue=1, batch_size=2, page_size=4, num_pages=7,
    )
    # a long-lived row pinning 3 pages + a doomed row holding 3 more
    alive = b.submit([3], max_new_tokens=12)
    b.step_chunk()
    doomed = b.submit([4], max_new_tokens=12, deadline_s=0.05)
    b.step_chunk()
    # head of queue needs 4 pages; the expiry can only ever free 3
    head = b.submit([5], max_new_tokens=16)
    time.sleep(0.1)
    with pytest.raises(QueueFullError):
        b.submit([9], max_new_tokens=2)
    assert b.failed[doomed] == "deadline"  # the expiry itself happened
    out = b.drain()
    assert out[alive] == toy_expected([3], 12)
    assert out[head] == toy_expected([5], 16)
    b._kv.check_invariants()


def test_queued_request_past_deadline_expires_cleanly(toy_batcher_factory):
    b = toy_batcher_factory()
    ra = b.submit([3], max_new_tokens=30)
    rb = b.submit([4], max_new_tokens=30)
    rc = b.submit([5], max_new_tokens=4, deadline_s=0.01)  # will queue
    time.sleep(0.05)
    out = b.drain()
    assert b.failed[rc] == "deadline"
    assert rc in b.done and out[rc] == []
    assert b.stats.expired == 1
    # the live requests were untouched by the expiry
    assert out[ra] == toy_expected([3], 30)
    assert out[rb] == toy_expected([4], 30)


def test_running_request_past_deadline_evicted_at_boundary(
    toy_batcher_factory,
):
    b = toy_batcher_factory()
    rid = b.submit([3], max_new_tokens=30, deadline_s=0.05)
    b.step_chunk()  # admitted + decoding
    time.sleep(0.1)
    out = b.drain()
    assert b.failed[rid] == "deadline"
    # partial output up to the boundary is preserved, the row was freed
    assert 0 < len(out[rid]) < 30
    assert out[rid] == toy_expected([3], len(out[rid]))
    assert all(s.rid < 0 for s in b._slots)


def test_freed_slot_is_reusable_after_expiry(toy_batcher_factory):
    b = toy_batcher_factory(batch_size=1)
    r1 = b.submit([3], max_new_tokens=30, deadline_s=0.05)
    b.step_chunk()
    time.sleep(0.1)
    b.step_chunk()  # boundary: expire r1, free the only slot
    assert b.failed[r1] == "deadline"
    r2 = b.submit([9], max_new_tokens=3)
    out = b.drain()
    # the reused row was reset on admission: r2 decodes exactly
    assert out[r2] == toy_expected([9], 3)


def test_drain_stall_watchdog_converts_hang_to_error(toy_batcher_factory):
    b = toy_batcher_factory(stall_timeout_s=0.3)
    b.submit([3], max_new_tokens=30)
    # warm up one real chunk: the watchdog deliberately holds fire until
    # a readback has ever completed (first-call XLA compile can
    # legitimately exceed any reasonable stall timeout)
    b.step_chunk()
    wedge_batcher(b, seconds=60.0)
    t0 = time.monotonic()
    with pytest.raises(ServeStalledError):
        b.drain()
    assert time.monotonic() - t0 < 10.0  # error, not a 60 s hang
    assert b._tele.registry.counter("serve/stalls").value >= 1


def test_legacy_per_token_path_honors_deadlines(toy_batcher_factory):
    b = toy_batcher_factory(chunk_size=None)
    rid = b.submit([3], max_new_tokens=20, deadline_s=0.05)
    for _ in range(3):
        b.step()
    time.sleep(0.1)
    b.step()  # boundary: expiry
    assert b.failed[rid] == "deadline"
    assert b.active == 0
