"""Fast unit coverage of the resilience surfaces: manifest validation
modes, guard/config validation, serve degraded-mode knobs, data-retry
contracts, exit-code plumbing, and the curves rename compat aliases.
All host-only (no model compiles) — sub-second each."""

import json
import signal
import threading
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from d9d_tpu.loop import TrainerConfig
from d9d_tpu.loop.components.data_loader import (
    DataFetchError,
    StatefulDataLoader,
)
from d9d_tpu.loop.components.timeout_manager import TimeoutManager
from d9d_tpu.resilience import (
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
    HostAnomalyGuard,
    PreemptionGuard,
    TrainingPreempted,
)
from d9d_tpu.resilience.chaos import FlakyDataset
from d9d_tpu.resilience.manifest import (
    MANIFEST_NAME,
    CheckpointIntegrityError,
    read_manifest,
    validate_checkpoint_dir,
    write_manifest,
)
from d9d_tpu.telemetry import Telemetry


# -- manifest -------------------------------------------------------------

def _fake_step_dir(tmp_path: Path) -> Path:
    d = tmp_path / "save_7"
    (d / "arrays").mkdir(parents=True)
    (d / "meta").mkdir()
    (d / "arrays" / "data0").write_bytes(b"\x01" * 1024)
    (d / "meta" / "metadata").write_text(json.dumps({"step": 7}))
    return d


def test_manifest_roundtrip_validates(tmp_path):
    d = _fake_step_dir(tmp_path)
    write_manifest(d, step=7)
    m = read_manifest(d)
    assert m["step"] == 7
    paths = {f["path"] for f in m["files"]}
    assert paths == {"arrays/data0", "meta/metadata"}
    # small files carry content checksums
    assert all("sha256" in f for f in m["files"])
    assert validate_checkpoint_dir(d) is True


def test_manifest_detects_truncation(tmp_path):
    d = _fake_step_dir(tmp_path)
    write_manifest(d, step=7)
    (d / "arrays" / "data0").write_bytes(b"\x01" * 100)
    with pytest.raises(CheckpointIntegrityError, match="size mismatch"):
        validate_checkpoint_dir(d)


def test_manifest_detects_missing_file(tmp_path):
    d = _fake_step_dir(tmp_path)
    write_manifest(d, step=7)
    (d / "arrays" / "data0").unlink()
    with pytest.raises(CheckpointIntegrityError, match="missing file"):
        validate_checkpoint_dir(d)


def test_manifest_detects_content_corruption(tmp_path):
    d = _fake_step_dir(tmp_path)
    write_manifest(d, step=7)
    # same size, different bytes: only the checksum can catch this
    (d / "meta" / "metadata").write_text(
        json.dumps({"step": 9})[: len(json.dumps({"step": 7}))].ljust(
            len(json.dumps({"step": 7})), " "
        )
    )
    with pytest.raises(CheckpointIntegrityError, match="checksum mismatch"):
        validate_checkpoint_dir(d)


def test_manifest_absent_is_unverified_not_invalid(tmp_path):
    d = _fake_step_dir(tmp_path)
    assert validate_checkpoint_dir(d) is False  # unverified, no raise


def test_manifest_missing_dir_raises(tmp_path):
    with pytest.raises(CheckpointIntegrityError, match="missing"):
        validate_checkpoint_dir(tmp_path / "save_404")


def test_manifest_excludes_itself_and_is_atomic(tmp_path):
    d = _fake_step_dir(tmp_path)
    write_manifest(d, step=7)
    write_manifest(d, step=7)  # rewrite over existing: atomic replace
    m = read_manifest(d)
    assert MANIFEST_NAME not in {f["path"] for f in m["files"]}
    assert not (d / (MANIFEST_NAME + ".tmp")).exists()


# -- host anomaly guard ---------------------------------------------------

def test_guard_rejects_unknown_policy():
    with pytest.raises(ValueError, match="policy"):
        HostAnomalyGuard(policy="explode")


def test_guard_rejects_bad_rollback_after():
    with pytest.raises(ValueError, match="rollback_after"):
        HostAnomalyGuard(policy="warn", rollback_after=0)


def test_guard_reset_clears_streaks():
    tele = Telemetry()
    g = HostAnomalyGuard(
        policy="rollback", rollback_after=1, spike_factor=2.0,
        spike_window=4, telemetry=tele,
    )
    for s in range(5):
        g.observe(s, {"loss": 1.0})
    assert g.observe(5, {"loss": 100.0}) == "rollback"
    g.reset()
    # post-reset: the window is empty, the old spike streak is gone
    assert g.observe(6, {"loss": 100.0}) == "ok"


def test_guard_spike_disabled_with_none_factor():
    g = HostAnomalyGuard(policy="warn", spike_factor=None,
                         telemetry=Telemetry())
    for s in range(8):
        assert g.observe(s, {"loss": 1.0}) == "ok"
    assert g.observe(9, {"loss": 1e9}) == "ok"


def test_device_streak_triggers_rollback_via_metrics():
    g = HostAnomalyGuard(policy="rollback", rollback_after=3,
                         telemetry=Telemetry())
    m = lambda streak: {  # noqa: E731
        "loss": float("nan"), "resilience/anomaly": 1.0,
        "resilience/anomaly_streak": float(streak),
        "resilience/anomaly_total": float(streak),
    }
    assert g.observe(1, m(1)) == "warn"
    assert g.observe(2, m(2)) == "warn"
    assert g.observe(3, m(3)) == "rollback"


# -- trainer config knobs -------------------------------------------------

def _cfg(**kw):
    base = dict(global_batch_size=8, microbatch_size=8, seq_len=8,
                total_steps=1)
    base.update(kw)
    return TrainerConfig(**base)


def test_config_accepts_policies_and_exit_codes():
    cfg = _cfg(anomaly_policy="rollback", preemption_exit_code=90,
               watchdog_exit_code=91)
    assert cfg.anomaly_policy == "rollback"
    assert cfg.preemption_exit_code == 90
    assert cfg.watchdog_exit_code == 91
    assert _cfg().anomaly_policy is None  # guard off by default


def test_config_rejects_unknown_policy():
    with pytest.raises(Exception):
        _cfg(anomaly_policy="nope")


def test_config_rejects_degenerate_spike_factor():
    with pytest.raises(Exception):
        _cfg(anomaly_spike_factor=1.0)


def test_build_train_step_rejects_unknown_policy():
    from d9d_tpu.loop.train_step import build_train_step

    with pytest.raises(ValueError, match="anomaly_policy"):
        build_train_step(module=None, task=None, optimizer=None,
                         num_microbatches=1, anomaly_policy="bogus")


# -- preemption / exit codes ----------------------------------------------

def test_exit_code_constants_documented():
    assert EXIT_PREEMPTED == 83
    assert EXIT_WATCHDOG == 42
    assert TimeoutManager().exit_code == EXIT_WATCHDOG
    assert TimeoutManager(exit_code=7).exit_code == 7


def test_training_preempted_is_system_exit_with_code():
    e = TrainingPreempted(83, step=12)
    assert isinstance(e, SystemExit)
    assert e.code == 83 and e.step == 12
    assert "83" in str(e) and "12" in str(e)


def test_preemption_guard_disabled_is_inert():
    g = PreemptionGuard(enabled=False, telemetry=Telemetry())
    with g:
        assert not g.triggered
    g.trip(signal.SIGTERM)
    assert g.triggered  # flag still works programmatically


def test_preemption_guard_degrades_off_main_thread():
    """Signal handlers need the main thread; elsewhere the guard must
    turn itself off with a warning instead of crashing the trainer."""
    g = PreemptionGuard(telemetry=Telemetry())
    seen = {}

    def enter():
        with g:
            seen["triggered"] = g.triggered

    t = threading.Thread(target=enter)
    t.start()
    t.join(5.0)
    assert seen == {"triggered": False}  # no crash, guard inert


# -- data retry -----------------------------------------------------------

def _loader(ds, **kw):
    kw.setdefault("shuffle", False)
    kw.setdefault("batch_size", 2)
    return StatefulDataLoader(ds, **kw)


def test_retry_survives_transient_failures():
    ds = FlakyDataset([{"x": np.ones(2)} for _ in range(8)],
                      fail_calls={1})
    loader = _loader(ds, retry_attempts=2, retry_backoff_s=0.0)
    batches = list(iter(loader))
    assert len(batches) == 4
    assert ds.failures == 1


def test_retry_exhaustion_names_position():
    ds = FlakyDataset([{"x": np.ones(2)} for _ in range(8)], dead_from=4)
    loader = _loader(ds, retry_attempts=1, retry_backoff_s=0.0)
    it = iter(loader)
    next(it)
    next(it)
    with pytest.raises(DataFetchError) as exc:
        next(it)
    assert exc.value.epoch == 0 and exc.value.batch_index == 2
    assert "epoch 0 batch 2" in str(exc.value)
    assert "2 attempt" in str(exc.value)  # initial try + 1 retry


def test_retry_default_off_wraps_immediately():
    ds = FlakyDataset([{"x": np.ones(2)} for _ in range(4)],
                      fail_calls={0})
    with pytest.raises(DataFetchError):
        next(iter(_loader(ds)))
    assert ds.calls == 1  # no retry by default


def test_loader_rejects_negative_retries():
    with pytest.raises(ValueError, match="retry_attempts"):
        _loader([1, 2], retry_attempts=-1)


def test_backoff_is_capped(monkeypatch):
    sleeps = []
    ds = FlakyDataset([{"x": np.ones(2)} for _ in range(4)],
                      fail_calls={0, 1, 2})
    loader = _loader(ds, retry_attempts=3, retry_backoff_s=0.1,
                     retry_max_backoff_s=0.15)
    import d9d_tpu.loop.components.data_loader as dl

    monkeypatch.setattr(dl.time, "sleep", lambda s: sleeps.append(s))
    next(iter(loader))
    assert sleeps == [0.1, 0.15, 0.15]  # exponential, capped at max


# -- serve knob validation ------------------------------------------------

def test_serve_stats_reset_covers_degraded_counters():
    from d9d_tpu.loop.serve import ServeStats

    s = ServeStats()
    s.rejected = 3
    s.expired = 2
    s.reset()
    assert s.rejected == 0 and s.expired == 0


# -- curves rename (VERDICT Weak #6): aliases are the same classes --------

def test_curve_aliases_preserve_api():
    from d9d_tpu.lr_scheduler.curves import (
        CosineAnneal,
        CurveBase,
        CurveCosine,
        CurveExponential,
        CurveLinear,
        CurvePoly,
        LinearInterp,
        LogSpaceInterp,
        PowerInterp,
        ScheduleCurve,
    )

    assert CurveBase is ScheduleCurve
    assert CurveLinear is LinearInterp
    assert CurveCosine is CosineAnneal
    assert CurvePoly is PowerInterp
    assert CurveExponential is LogSpaceInterp
    # positional construction kept (CurvePoly(2.0) spelling)
    assert CurvePoly(3.0).power == 3.0
    # legacy compute() spelling still answers
    assert float(CurveLinear().compute(0.0, 2.0, 0.5)) == 1.0
    assert float(LinearInterp().blend(0.0, 2.0, 0.25)) == 0.5

    # a pre-rename subclass implementing only compute() still works,
    # through BOTH spellings
    class LegacyCurve(CurveBase):
        def compute(self, start, end, step_p):
            return end

    assert LegacyCurve().compute(0.0, 5.0, 0.1) == 5.0
    assert LegacyCurve().blend(0.0, 5.0, 0.1) == 5.0
    # and a curve implementing neither fails loudly at call time
    class EmptyCurve(ScheduleCurve):
        pass

    with pytest.raises(NotImplementedError):
        EmptyCurve().blend(0.0, 1.0, 0.5)
