"""Monitoring-plane acceptance demo (docs/design/observability.md):
a 2-replica fleet serves with the exporter up — the /metrics scrape
shows per-replica and fleet rollup values, a migrated request's trace
stays continuous under one trace id through shrink AND kill-mid-drain
continuation, an induced deadline burn trips ``slo/violations`` exactly
once per window, and an induced NaN / replica death produces a
flight-recorder dump — all at zero added device readbacks."""

import json
import urllib.error
import urllib.request

import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import ToyDecodeLM, toy_expected
from tests.telemetry.test_export import parse_prometheus

from d9d_tpu.loop.serve import ContinuousBatcher
from d9d_tpu.resilience import ServingFleet
from d9d_tpu.resilience.chaos import kill_replica_mid_drain, shrink_at_step
from d9d_tpu.telemetry import (
    JsonlSink,
    SloMonitor,
    SloPolicy,
    Telemetry,
    get_telemetry,
    set_telemetry,
)


@pytest.fixture(autouse=True)
def _fresh_hub():
    """Isolate each test's instruments from the process hub (fleet and
    batcher default to get_telemetry())."""
    old = get_telemetry()
    hub = set_telemetry(Telemetry())
    yield hub
    set_telemetry(old)


def _make_batcher(**kwargs):
    model = ToyDecodeLM()
    kwargs.setdefault("batch_size", 2)
    kwargs.setdefault("chunk_size", 4)
    return ContinuousBatcher(model, {}, **kwargs)


def _fleet(n=2, **fleet_kwargs):
    fleet = ServingFleet(**fleet_kwargs)
    for _ in range(n):
        fleet.add_replica(_make_batcher())
    return fleet


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_fleet_scrape_shows_per_replica_and_rollup_values():
    fleet = _fleet(2, metrics_port=0)
    try:
        url = fleet.metrics_server.url
        # before any readback: compiling must not read as serving
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(url("/readyz"))
        assert exc.value.code == 503
        prompts = [[3], [7, 8], [1], [5], [9], [2, 6]]
        frids = [fleet.submit(p, max_new_tokens=4) for p in prompts]
        out = fleet.drain()
        for frid, p in zip(frids, prompts):
            assert out[frid] == toy_expected(p, 4)
        _, text = _get(url("/metrics"))
        samples = parse_prometheus(text)  # asserts valid Prometheus text
        total = samples[("d9d_serve_tokens", "")]
        r0 = samples[("d9d_serve_tokens", 'replica="0"')]
        r1 = samples[("d9d_serve_tokens", 'replica="1"')]
        assert total == 6 * 4
        assert r0 > 0 and r1 > 0 and r0 + r1 == total
        # scrape matches the registry mid-run, not a stale copy
        snap = get_telemetry().registry.snapshot()
        assert snap["counters"]["serve/tokens"] == total
        assert samples[("d9d_serve_fleet_replicas", "")] == 2
        assert ("d9d_serve_fleet_queue_depth", "") in samples
        # per-replica health + fleet readiness
        code, body = _get(url("/healthz"))
        health = json.loads(body)
        assert code == 200
        assert health["replicas"]["0"]["ready"] is True
        assert health["replicas"]["1"]["live"] is True
        code, _ = _get(url("/readyz"))
        assert code == 200
        # the monitoring plane added ZERO device readbacks: one readback
        # per chunk, exactly the pre-exporter contract
        for i in (0, 1):
            b = fleet._replicas[i]
            assert b.stats.readbacks == b.stats.chunks
            assert b.stats.host_dispatches == b.stats.chunks
    finally:
        fleet.close()
    # close() tears the fleet rollup gauges down — a closed fleet must
    # not keep reporting stale depth/rate into later snapshots
    gauges = get_telemetry().registry.snapshot()["gauges"]
    assert "serve/fleet_queue_depth" not in gauges
    assert "serve/fleet_tokens_per_s" not in gauges


def test_trace_id_continuous_across_migration_and_kill(tmp_path):
    """One trace id follows a request through shrink migration AND
    kill-mid-drain continuation; the Perfetto export renders it as one
    contiguous track."""
    hub = get_telemetry()
    sink = hub.add_sink(JsonlSink(tmp_path, run_name="fleet"))
    fleet = _fleet(2)
    prompts = [[3], [7], [12], [1]]
    frids = [fleet.submit(p, max_new_tokens=10) for p in prompts]
    fleet.step()  # let chunks land so the dying replica holds progress
    shrink_at_step(fleet, 0, step=2)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)
    out = fleet.drain()
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 10)
    hub.flush(step=0)
    hub.remove_sink(sink)

    from d9d_tpu.telemetry import iter_events

    traces = {}
    for ev in iter_events(sink.path):  # schema-validates every event
        if ev["kind"] == "request_trace":
            traces.setdefault(ev["trace_id"], []).append(ev)
    assert len(traces) == len(prompts)
    continued = [
        tid for tid, evs in traces.items()
        if any(e["event"] == "continuation" for e in evs)
    ]
    assert continued, "the kill must have recovered at least one request"
    for tid, evs in traces.items():
        evs.sort(key=lambda e: e["t"])
        assert evs[0]["event"] == "submit"
        # every request finishes exactly once, under its original id
        assert [e["event"] for e in evs].count("finish") == 1
        assert evs[-1]["event"] == "finish"
    for tid in continued:
        evs = traces[tid]
        replicas = {
            e["replica"] for e in evs
            if e["event"] == "submit" and "replica" in e
        }
        assert len(replicas) >= 2, (
            "a continuation must re-submit on a DIFFERENT replica "
            f"under the same trace id (saw {replicas})"
        )

    # Perfetto: the migrated request is ONE track whose state spans
    # tile the submit→finish interval with no gaps
    from d9d_tpu.telemetry.trace_export import merge_to_chrome_trace

    trace = merge_to_chrome_trace([sink.path])
    tid0 = continued[0]
    lane_names = {
        e["tid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    req_lanes = {
        t for t, name in lane_names.items() if name == f"req/{tid0}"
    }
    assert len(req_lanes) == 1, "one request = one track"
    lane = req_lanes.pop()
    xs = sorted(
        (e for e in trace["traceEvents"]
         if e["ph"] == "X" and e["tid"] == lane),
        key=lambda e: e["ts"],
    )
    assert xs, "the request must have state spans"
    for a, b in zip(xs, xs[1:]):
        assert a["ts"] + a["dur"] == pytest.approx(b["ts"], abs=1.0), (
            "state spans must tile the request's lifetime contiguously"
        )
    pins = [
        e for e in trace["traceEvents"]
        if e["ph"] == "i" and e["tid"] == lane
    ]
    assert [p["name"] for p in pins] == ["finish"]


def test_rejected_trace_terminal_only_at_the_front_door(tmp_path):
    """A replica rejecting a FLEET placement attempt is not terminal (a
    survivor may accept); exactly one terminal 'rejected' appears — from
    the fleet when every replica rejects, or from a direct batcher
    submit (its own front door)."""
    from d9d_tpu.loop.serve import QueueFullError
    from d9d_tpu.telemetry import iter_events

    hub = get_telemetry()
    sink = hub.add_sink(JsonlSink(tmp_path, run_name="rej"))
    fleet = ServingFleet()
    for _ in range(2):
        fleet.add_replica(_make_batcher(max_queue=1, batch_size=1))
    placed = [fleet.submit([3], max_new_tokens=2) for _ in range(2)]
    with pytest.raises(QueueFullError):
        fleet.submit([4], max_new_tokens=2)  # every replica rejects
    # direct front-door rejection on a full replica mints its own id
    with pytest.raises(QueueFullError):
        fleet._replicas[0].submit([5], max_new_tokens=2)
    out = fleet.drain()
    for frid in placed:
        assert out[frid] == toy_expected([3], 2)
    hub.flush(step=0)
    hub.remove_sink(sink)
    rejected = []
    finished = set()
    for ev in iter_events(sink.path):
        if ev["kind"] != "request_trace":
            continue
        if ev["event"] == "rejected":
            rejected.append(ev)
        if ev["event"] == "finish":
            finished.add(ev["trace_id"])
    # exactly two terminal rejections: the fleet's all-replicas-full one
    # + the direct submit's — NO per-replica placement-attempt noise
    assert len(rejected) == 2
    assert not any(r["trace_id"] in finished for r in rejected), (
        "a trace that finished must never also carry a terminal reject"
    )
    fleet_rej = [r for r in rejected if "replica" not in r]
    direct_rej = [r for r in rejected if r.get("replica") == "r0"]
    assert len(fleet_rej) == 1 and len(direct_rej) == 1


def test_deadline_burn_trips_slo_violations_once_per_window():
    hub = get_telemetry()
    monitor = SloMonitor([
        SloPolicy(
            name="deadline_miss", kind="rate", bad="serve/expired",
            good=("serve/requests_finished",), target=0.01,
            window_s=60.0,
        ),
    ]).attach(hub)
    monitor.evaluate()  # baseline counter sample before the burn
    fleet = _fleet(1)
    import time

    doomed = [
        fleet.submit([3], max_new_tokens=4, deadline_s=0.001)
        for _ in range(3)
    ]
    ok = fleet.submit([9], max_new_tokens=4)
    time.sleep(0.02)  # all three deadlines expire while queued
    out = fleet.drain()
    assert out[ok] == toy_expected([9], 4)
    assert all(fleet.failed.get(d) == "deadline" for d in doomed)
    # 3 misses of 4 requests vs a 1% budget: a hard burn — but however
    # many flushes/scrapes evaluate it, ONE violation per window
    for _ in range(4):
        hub.flush(step=0)
    reg = hub.registry
    assert reg.counter("slo/violations").value == 1
    assert reg.counter("slo/deadline_miss/violations").value == 1
    snap = reg.snapshot()
    assert snap["gauges"]["slo/deadline_miss/violating"] == 1.0
    assert snap["gauges"]["slo/burning"] == 1.0
    monitor.detach()


def test_replica_death_dumps_flight_recorder(tmp_path):
    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path)
    fleet = _fleet(2)
    frids = [
        fleet.submit(p, max_new_tokens=10)
        for p in ([3], [7], [12], [1])
    ]
    fleet.step()
    shrink_at_step(fleet, 0, step=2)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)
    fleet.drain()
    assert 0 in fleet.dead, "the chaos kill must have fired"
    path = tmp_path / "flight_recorder_replica_death.json"
    assert path.exists()
    record = json.loads(path.read_text())
    assert record["event"] == "replica_death"
    assert record["extra"]["replica"] == 0
    assert record["extra"]["recovered_requests"] >= 1
    assert record["current"]["counters"]["serve/fleet_replica_deaths"] == 1
    for frid in frids:
        assert len(fleet.outputs(frid)) == 10


def test_trainer_nan_dumps_flight_recorder(tmp_path):
    """A deterministic ChaosScaleTask NaN must leave
    flight_recorder_anomaly.json next to the telemetry dir."""
    from tests.resilience.conftest import make_micro_trainer

    from d9d_tpu.loop import CausalLMTask
    from d9d_tpu.resilience.chaos import ChaosScaleTask

    tele_dir = tmp_path / "telemetry"
    task = ChaosScaleTask(CausalLMTask(), scale_at={2: float("nan")})
    trainer = make_micro_trainer(
        task, total_steps=5, anomaly_policy="warn",
        telemetry_dir=str(tele_dir),
    )
    trainer.train()
    path = tmp_path / "flight_recorder_anomaly.json"
    assert path.exists(), "the anomaly guard must dump the black box"
    record = json.loads(path.read_text())
    assert record["event"] == "anomaly"
    assert record["extra"]["policy"] == "warn"
    assert record["extra"]["step"] >= 1
    # the dump carries executable inventory at the moment of the anomaly
    assert any(
        e.get("name") == "train_step" for e in record["executables"]
    )


def test_trainer_metrics_endpoint_readiness(tmp_path):
    """TrainerConfig.metrics_port serves /metrics during train() and the
    endpoint is closed (port released) when train() returns."""
    from tests.resilience.conftest import make_micro_trainer

    from d9d_tpu.loop import CausalLMTask

    trainer = make_micro_trainer(
        CausalLMTask(), total_steps=4, metrics_port=0,
    )
    seen = {}

    def probe(**payload):
        if payload.get("step") == 3 and "text" not in seen:
            url = trainer.metrics_server.url
            seen["ready_code"] = _get(url("/readyz"))[0]
            seen["text"] = _get(url("/metrics"))[1]

    from d9d_tpu.loop import event as ev

    trainer.events.subscribe(ev.EVENT_STEP.pre, probe)
    trainer.train()
    assert seen["ready_code"] == 200  # past warmup (2 steps) at step 3
    samples = parse_prometheus(seen["text"])
    assert samples[("d9d_train_steps", "")] >= 2
    assert trainer.metrics_server is None  # closed in the finally block
