"""Live train→serve weight publish (docs/design/elasticity.md):
``install_weights`` swaps a published param tree into a running
``ContinuousBatcher`` at a chunk boundary — post-publish requests are
token-identical to a fresh batcher built with the new weights, the swap
causes ZERO steady-state recompiles (params are a traced argument with
an unchanged signature), and generation-stamped versioning records
which weights produced each request's tail."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from d9d_tpu.loop.serve import ContinuousBatcher
from d9d_tpu.resilience.elastic import WeightPublisher
from d9d_tpu.telemetry import introspect

VOCAB = 32


class ShiftDecodeLM(nn.Module):
    """Param-dependent deterministic decode model: next token =
    ``(tok + round(shift)) % vocab`` where ``shift`` is a trainable
    scalar — publishing a tree with a different shift visibly (and
    exactly predictably) changes every subsequent emission. Carries a
    real decode cache (``cache_index`` + a written memory leaf) so the
    serving loop's cache machinery runs for real."""

    vocab: int = VOCAB
    decode_max_length: int = 64

    @nn.compact
    def __call__(self, tokens, positions, labels=None, mask=None):
        b = tokens.shape[0]
        shift = self.param("shift", lambda _rng: jnp.float32(1.0))
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        mem = self.variable(
            "cache", "mem",
            lambda: jnp.zeros((b, self.decode_max_length), jnp.int32),
        )
        i = jnp.broadcast_to(idx.value, (b,))
        mem.value = mem.value.at[
            jnp.arange(b), jnp.clip(i, 0, self.decode_max_length - 1)
        ].set(tokens[:, 0])
        idx.value = idx.value + 1
        step = jnp.round(shift).astype(jnp.int32)
        return jax.nn.one_hot((tokens + step) % self.vocab, self.vocab) * 20.0

    def logits(self, tokens, positions, mask=None):
        return self(tokens, positions)


def _params(shift: float):
    return {"shift": jnp.float32(shift)}


def _expected(prompt, n, shift):
    toks = []
    last = prompt[-1]
    for _ in range(n):
        last = (last + shift) % VOCAB
        toks.append(last)
    return toks


def _batcher(params, **kwargs):
    kwargs.setdefault("batch_size", 2)
    kwargs.setdefault("chunk_size", 4)
    return ContinuousBatcher(ShiftDecodeLM(), params, **kwargs)


def test_post_publish_requests_token_identical_to_fresh_batcher():
    b = _batcher(_params(1.0))
    r1 = b.submit([3, 4], max_new_tokens=5)
    b.drain()
    assert b.outputs[r1] == _expected([3, 4], 5, 1)

    version = b.install_weights(_params(2.0))
    r2 = b.submit([3, 4], max_new_tokens=5)
    b.drain()
    # token-identical to a cold batcher built with the published tree
    fresh = _batcher(_params(2.0))
    rf = fresh.submit([3, 4], max_new_tokens=5)
    fresh.drain()
    assert b.outputs[r2] == fresh.outputs[rf] == _expected([3, 4], 5, 2)
    assert b.weights_version == version == 1
    assert b.request_stats[r2].weights_version == 1
    assert b.request_stats[r1].weights_version == 0


def test_publish_applies_at_chunk_boundary_not_mid_chunk():
    """Install mid-request: tokens already harvested (old chunks) keep
    the old step; emissions from chunks dispatched after the boundary
    switch to the new step — exactly the chunk-boundary contract."""
    b = _batcher(_params(1.0), chunk_size=4, overlap=False)
    rid = b.submit([5], max_new_tokens=8)
    first = b.step_chunk()  # one K=4 chunk, all on the old weights
    assert first[rid] == _expected([5], 4, 1)
    b.install_weights(_params(2.0))
    b.drain()
    tail = b.outputs[rid][4:]
    # the tail continues from the last OLD-weights token with step 2
    assert tail == _expected([b.outputs[rid][3]], 4, 2)
    assert b.request_stats[rid].weights_version == 1


def test_defer_to_idle_finishes_inflight_on_old_weights():
    b = _batcher(_params(1.0), chunk_size=2, overlap=False)
    rid = b.submit([7], max_new_tokens=6)
    b.step_chunk()  # request now mid-flight
    b.install_weights(_params(2.0), defer_to_idle=True)
    b.drain()
    # the in-flight request finished wholly on the old generation
    assert b.outputs[rid] == _expected([7], 6, 1)
    assert b.request_stats[rid].weights_version == 0
    # the deferred swap lands before the next request's first chunk
    r2 = b.submit([7], max_new_tokens=4)
    b.drain()
    assert b.outputs[r2] == _expected([7], 4, 2)
    assert b.request_stats[r2].weights_version == 1


def test_publish_causes_zero_steady_state_recompiles():
    b = _batcher(_params(1.0))
    b.submit([2], max_new_tokens=10)
    b.drain()  # warm: both fused variants compiled
    mark = len(introspect.inventory())
    b.install_weights(_params(3.0))
    r = b.submit([2], max_new_tokens=10)
    b.drain()
    assert b.outputs[r] == _expected([2], 10, 3)
    new_records = introspect.inventory()[mark:]
    assert not new_records, [r.name for r in new_records]


def test_legacy_per_token_path_publishes_too():
    b = _batcher(_params(1.0), chunk_size=None)
    r1 = b.submit([4], max_new_tokens=3)
    b.drain()
    b.install_weights(_params(2.0))
    r2 = b.submit([4], max_new_tokens=3)
    b.drain()
    assert b.outputs[r1] == _expected([4], 3, 1)
    assert b.outputs[r2] == _expected([4], 3, 2)
    assert b.request_stats[r2].weights_version == 1


def test_publisher_fans_out_and_records_telemetry():
    from d9d_tpu.telemetry import Telemetry

    tele = Telemetry()
    b1 = _batcher(_params(1.0), telemetry=tele)
    b2 = _batcher(_params(1.0), telemetry=tele)
    pub = WeightPublisher(telemetry=tele)
    pub.attach(b1)
    pub.attach(b2)
    version = pub.publish(_params(2.0))
    assert version == 1
    assert pub.latest_params is not None
    for b in (b1, b2):
        r = b.submit([6], max_new_tokens=4)
        b.drain()
        assert b.outputs[r] == _expected([6], 4, 2)
    # one applied install per batcher, with a publish-latency sample
    assert tele.counter("serve/weight_publish").value == 2
    assert tele.histogram("serve/weight_publish_s").count == 2
    assert tele.counter("serve/weight_publish_fanout").value == 2


def test_publisher_weakrefs_do_not_pin_batchers():
    pub = WeightPublisher()
    b = _batcher(_params(1.0))
    pub.attach(b)
    del b
    import gc

    gc.collect()
    # publishing into a dead target is a no-op, not an error
    assert pub.publish(_params(2.0)) == 1
    assert pub._targets == []


def test_publish_from_trainer_snapshot():
    """publish_from snapshots merged_params() — the step-boundary
    train→serve handoff surface."""

    class FakeTrainer:
        def merged_params(self):
            return _params(5.0)

    pub = WeightPublisher()
    b = _batcher(_params(1.0))
    pub.attach(b)
    pub.publish_from(FakeTrainer())
    r = b.submit([1], max_new_tokens=3)
    b.drain()
    assert b.outputs[r] == _expected([1], 3, 5)


def test_install_normalizes_uncommitted_leaves():
    """The satellite fix: a published tree whose committed leaves name a
    mesh gets its uncommitted scalar riders replicated onto it (the PR 5
    latent-placement class) before the first dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("x",))
    committed = jax.device_put(
        jnp.zeros((4,), jnp.float32), NamedSharding(mesh, P())
    )
    uncommitted = jnp.float32(2.0)
    assert not uncommitted.committed
    tree = {"shift": uncommitted, "anchor": committed}
    b = ContinuousBatcher(
        ShiftDecodeLM(), tree, batch_size=2, chunk_size=2
    )
    assert b._params["shift"].committed
    installed = b.install_weights({"shift": jnp.float32(3.0),
                                   "anchor": committed})
    assert installed == 1
    assert b._pending_weights[0]["shift"].committed
