"""SLO autopilot (resilience/autopilot.py, docs/design/elasticity.md
"SLO autopilot"): burn-driven autoscaling with hysteresis (no flapping
under an oscillating load), shed-by-priority ordering, canary promote
vs rollback pinned token- and weights_version-exact, decision-log JSONL
schema round-trip, and the end-to-end chaos acceptance leg — a scripted
load ramp + replica kill + bad-weight canary that ends with every SLO
policy non-burning, the bad generation rolled back, >=1 grow and >=1
shrink taken, and the decision log + flight recorder explaining every
action — fully deterministic (shared fake clock, scripted arrivals),
no human input."""

import json

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import ToyDecodeLM, toy_expected

from d9d_tpu.loop.serve import ContinuousBatcher, QueueFullError
from d9d_tpu.resilience import (
    AutopilotConfig,
    DecisionLog,
    FleetAutopilot,
    ServingFleet,
    WeightPublisher,
    read_decisions,
)
from d9d_tpu.resilience.chaos import (
    kill_replica_mid_drain,
    ramp_arrivals,
    shrink_at_step,
)
from d9d_tpu.telemetry import (
    SloMonitor,
    SloPolicy,
    Telemetry,
    get_telemetry,
    set_telemetry,
)


@pytest.fixture(autouse=True)
def _fresh_hub():
    old = get_telemetry()
    hub = set_telemetry(Telemetry())
    yield hub
    set_telemetry(old)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


SHIFT_VOCAB = 32
SHIFT_EOS = 6


class ToyShiftLM(nn.Module):
    """ToyDecodeLM whose next token DEPENDS ON THE WEIGHTS: ``(tok +
    shift) % vocab`` with ``shift`` a real param leaf — a canary
    publish of a different shift observably changes what the replica
    emits, which is what the promote/rollback legs need."""

    vocab: int = SHIFT_VOCAB
    decode_max_length: int = 32

    @nn.compact
    def __call__(self, tokens, positions, labels=None, mask=None):
        b = tokens.shape[0]
        shift = self.param("shift", lambda k: jnp.ones((), jnp.int32))
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        mem = self.variable(
            "cache", "mem",
            lambda: jnp.zeros((b, self.decode_max_length), jnp.int32),
        )
        i = jnp.broadcast_to(idx.value, (b,))
        mem.value = mem.value.at[
            jnp.arange(b), jnp.clip(i, 0, self.decode_max_length - 1)
        ].set(tokens[:, 0])
        idx.value = idx.value + 1
        return jax.nn.one_hot(
            (tokens + shift) % self.vocab, self.vocab
        ) * 20.0

    def logits(self, tokens, positions, mask=None):
        return self(tokens, positions)


GOOD = {"shift": jnp.array(1, jnp.int32)}
# shift 2 from an ODD token stays odd forever: it can never emit the
# even EOS, so every request runs to its full budget — the
# serve/request_tokens distribution jumps to the ceiling on the canary
BAD = {"shift": jnp.array(2, jnp.int32)}


def shift_expected(prompt, n, shift=1):
    toks = []
    t = prompt[-1]
    for _ in range(n):
        t = (t + shift) % SHIFT_VOCAB
        toks.append(t)
        if t == SHIFT_EOS:
            break
    return toks


def make_shift_batcher(params, **kw):
    kw.setdefault("batch_size", 2)
    kw.setdefault("chunk_size", 4)
    return ContinuousBatcher(ToyShiftLM(), params, eos_id=SHIFT_EOS, **kw)


def make_toy_batcher(params=None, **kw):
    kw.setdefault("batch_size", 2)
    kw.setdefault("chunk_size", 4)
    return ContinuousBatcher(ToyDecodeLM(), params or {}, **kw)


# ---------------------------------------------------------------------------
# burn-driven autoscaling: hysteresis both directions


def test_grow_on_burn_with_hysteresis_no_flapping():
    """An oscillating burn shorter than ``grow_after_s`` never grows;
    a sustained burn grows exactly once per cooldown window; sustained
    idle then shrinks back to ``min_replicas`` — no flapping."""
    hub = get_telemetry()
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish({})
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_toy_batcher())
    monitor = SloMonitor(
        [SloPolicy(name="err", kind="rate", bad="serve/rejected",
                   good=("serve/requests_finished",), target=0.05,
                   window_s=2.0)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_toy_batcher(params=p),
        config=AutopilotConfig(
            grow_after_s=4.0, cooldown_s=10.0, min_replicas=1,
            max_replicas=3, idle_after_s=6.0, idle_queue_depth=0,
            idle_slot_utilization=0.5, eval_interval_s=1.0,
        ),
        clock=clock,
    ).attach()

    def tick(burning: bool, rounds: int):
        for _ in range(rounds):
            if burning:
                hub.counter("serve/rejected").add(1)
            fleet.step()
            clock.advance(1.0)

    # oscillation: 1s burning bursts, 5s recovery — a burst keeps the
    # windowed rate violating for ~window_s after it passes, still well
    # under grow_after_s, so the fleet must not flap
    for _ in range(3):
        tick(True, 1)
        tick(False, 5)
    assert len(fleet.live_replicas) == 1

    # sustained burn: one grow at grow_after_s, the next only after
    # cooldown — never one per evaluation
    tick(True, 5)
    assert len(fleet.live_replicas) == 2
    tick(True, 4)  # still inside cooldown
    assert len(fleet.live_replicas) == 2
    tick(True, 7)  # cooldown passed, burn sustained
    assert len(fleet.live_replicas) == 3
    tick(True, 20)  # at max_replicas: never beyond
    assert len(fleet.live_replicas) == 3

    # recovery: the window ages out, then sustained idle shrinks back
    # to min_replicas one cooldown apart
    tick(False, 40)
    assert len(fleet.live_replicas) == 1
    snap = hub.registry.snapshot()
    assert snap["counters"]["autopilot/grows"] == 2
    assert snap["counters"]["autopilot/shrinks"] == 2
    assert snap["counters"]["serve/fleet_grows"] == 2


def test_grow_blocked_without_factory_is_one_logged_decision(tmp_path):
    hub = get_telemetry()
    clock = FakeClock()
    fleet = ServingFleet()
    fleet.add_replica(make_toy_batcher())
    monitor = SloMonitor(
        [SloPolicy(name="err", kind="rate", bad="serve/rejected",
                   target=0.05, window_s=5.0)],
        clock=clock,
    ).attach(hub)
    log = tmp_path / "decisions.jsonl"
    ap = FleetAutopilot(
        fleet, monitor, replica_factory=None,
        config=AutopilotConfig(grow_after_s=2.0, cooldown_s=1.0),
        decision_log=log, clock=clock,
    ).attach()
    for _ in range(8):
        hub.counter("serve/rejected").add(1)
        fleet.step()
        clock.advance(1.0)
    assert fleet.live_replicas == (0,)
    blocked = [
        d for d in read_decisions(log) if d["action"] == "grow_blocked"
    ]
    assert len(blocked) == 1  # logged once, not per evaluation


# ---------------------------------------------------------------------------
# admission tiering: shed lowest-priority / longest-deadline first


def test_shed_by_priority_and_deadline_ordering():
    """Burn + queue over the shed target: the autopilot sheds lowest
    priority first, then (within a tier) the deadline-less before the
    tight-deadline request — and the highest-priority request is the
    one left queued."""
    hub = get_telemetry()
    clock = FakeClock()
    fleet = ServingFleet()
    b = make_toy_batcher(batch_size=1)
    fleet.add_replica(b)
    monitor = SloMonitor(
        [SloPolicy(name="err", kind="rate", bad="serve/rejected",
                   target=0.05, window_s=5.0)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        config=AutopilotConfig(
            grow_after_s=1e9, shed_queue_depth=1, eval_interval_s=1.0,
        ),
        clock=clock,
    ).attach()
    running = fleet.submit([3], max_new_tokens=8, priority=0)
    b.step_chunk()  # admitted into the single slot
    fleet.step()  # baseline rate-policy sample (cold-start delta is 0)
    clock.advance(1.0)
    low = fleet.submit([4], max_new_tokens=4, priority=-1, deadline_s=5.0)
    patient = fleet.submit([5], max_new_tokens=4, priority=0)
    tight = fleet.submit([6], max_new_tokens=4, priority=0,
                         deadline_s=1e3)
    vip = fleet.submit([7], max_new_tokens=4, priority=3)
    assert fleet._queue_depth() == 4
    # burn: the next poll sheds down to shed_queue_depth=1
    hub.counter("serve/rejected").add(5)
    fleet.step()
    clock.advance(1.0)
    # victims: low (priority -1), then patient (no deadline sheds
    # before any contract), then tight; vip survives
    assert fleet.failed.get(low) == "shed"
    assert fleet.failed.get(patient) == "shed"
    assert fleet.failed.get(tight) == "shed"
    assert vip not in fleet.failed
    out = fleet.drain()
    assert out[running] == toy_expected([3], 8)
    assert out[vip] == toy_expected([7], 4)
    assert out[low] == []  # shed: observable, empty, never served
    snap = hub.registry.snapshot()
    assert snap["counters"]["serve/shed"] == 3
    assert snap["counters"]["autopilot/shed_requests"] == 3
    assert b.stats.shed == 3
    # shed is its own signal: not an expiry, not a generic failure
    assert "serve/expired" not in snap["counters"]
    assert "serve/failed" not in snap["counters"]


def test_running_requests_are_never_shed():
    hub = get_telemetry()
    clock = FakeClock()
    fleet = ServingFleet()
    b = make_toy_batcher(batch_size=2)
    fleet.add_replica(b)
    r1 = fleet.submit([3], max_new_tokens=6, priority=-5)
    r2 = fleet.submit([9], max_new_tokens=6, priority=-5)
    b.step_chunk()  # both admitted: nothing left to shed
    assert fleet.shed_queued(5) == []
    out = fleet.drain()
    assert out[r1] == toy_expected([3], 6)
    assert out[r2] == toy_expected([9], 6)


# ---------------------------------------------------------------------------
# canaried weight publish: promote vs rollback, token/version-exact


def _canary_rig(clock, *, tmp_path=None, n_replicas=2):
    hub = get_telemetry()
    pub = WeightPublisher()
    pub.publish(GOOD)  # generation 1, fleet-wide known-good tree
    fleet = ServingFleet(publisher=pub)
    for _ in range(n_replicas):
        fleet.add_replica(make_shift_batcher(GOOD))
    monitor = SloMonitor(
        [SloPolicy(name="gen_len_p50", metric="serve/request_tokens",
                   quantile=0.5, target=6.0, window_s=30.0,
                   burn_rate=1e18)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_shift_batcher(p),
        config=AutopilotConfig(
            scale_policies=(), canary_policies=("gen_len_p50",),
            canary_window_s=10.0, canary_tolerance=1.25,
            canary_min_samples=2, canary_max_wait_s=30.0,
            eval_interval_s=1.0,
        ),
        decision_log=(
            tmp_path / "decisions.jsonl" if tmp_path is not None else None
        ),
        clock=clock,
    ).attach()
    return hub, pub, fleet, monitor, ap


def _serve_rounds(fleet, clock, prompts, budget=10):
    frids = []
    for p in prompts:
        frids.append(fleet.submit(p, max_new_tokens=budget))
        fleet.step()
        clock.advance(1.0)
    fleet.drain()
    for _ in range(3):
        fleet.step()
        clock.advance(1.0)
    return frids


def test_canary_rollback_is_token_and_version_exact(tmp_path):
    """A bad canary generation (never emits EOS) is detected from the
    canary replica's per-replica serve/r{i}/* deltas vs the fleet
    rollup and rolled back: the canary generation got stamp 2, the
    rollback re-installs the RETAINED tree under stamp 3, and the
    replica serves good-generation tokens again — while requests that
    finished DURING the canary carry the bad stamp in their audit
    trail."""
    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path / "flight")
    clock = FakeClock()
    hub2, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    v = ap.publish_canary(BAD)
    assert v == 2 and pub.canary is not None
    assert pub.latest_version == 1  # the retained tree is still gen 1
    canary_b = fleet._replicas[max(fleet.live_replicas)]
    _serve_rounds(fleet, clock, [[3], [5], [1]] * 4)
    # decided: rolled back under a FRESH stamp (never reuse the bad one)
    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert [d["action"] for d in decs] == ["canary_start",
                                          "canary_rollback"]
    verdicts = decs[-1]["detail"]["verdicts"]["gen_len_p50"]
    assert verdicts["bad"] is True and verdicts["canary"] == 10.0
    assert pub.canary is None
    assert canary_b.weights_version == 3
    assert pub.latest_version == 1  # retained tree unchanged by rollback
    # requests the canary served are stamped with the bad generation
    bad_stamps = [
        rec.weights_version for rec in canary_b.request_stats.values()
        if rec.finish_t is not None and rec.weights_version == 2
    ]
    assert bad_stamps, "the canary must have served stamped traffic"
    # token-exact after rollback: the bad generation is gone everywhere
    f = fleet.submit([3], max_new_tokens=10)
    out = fleet.drain()
    assert out[f] == shift_expected([3], 10, shift=1) == [4, 5, 6]
    # destructive action → flight record
    assert (tmp_path / "flight"
            / "flight_recorder_autopilot_rollback.json").exists()
    # temp canary twins removed, their gauges cleared from snapshots
    assert all(
        not p.name.startswith("canary_") for p in monitor.policies
    )
    gauges = hub.registry.snapshot()["gauges"]
    assert not any(k.startswith("slo/canary_") for k in gauges)
    snap = hub.registry.snapshot()
    assert snap["counters"]["autopilot/canary_rollbacks"] == 1
    assert snap["counters"]["serve/weight_canary"] == 1


def test_canary_promote_is_token_and_version_exact(tmp_path):
    """A healthy canary promotes: every replica converges on the canary
    generation under the SAME stamp, and the publisher retains the
    canary tree for future grows."""
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    v = ap.publish_canary(GOOD)  # same behavior as the live tree
    assert v == 2
    _serve_rounds(fleet, clock, [[3], [5], [1]] * 4)
    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert [d["action"] for d in decs] == ["canary_start",
                                          "canary_promote"]
    assert pub.canary is None and pub.latest_version == 2
    assert all(
        fleet._replicas[i].weights_version == 2
        for i in fleet.live_replicas
    )
    f = fleet.submit([1], max_new_tokens=10)
    out = fleet.drain()
    assert out[f] == shift_expected([1], 10) == [2, 3, 4, 5, 6]
    snap = hub.registry.snapshot()
    assert snap["counters"]["autopilot/canary_promotes"] == 1
    assert "autopilot/canary_rollbacks" not in snap["counters"]


def test_unobserved_canary_rolls_back_never_promotes_blind(tmp_path):
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    ap.publish_canary(BAD)
    for _ in range(40):  # no traffic at all: past canary_max_wait_s
        fleet.step()
        clock.advance(1.0)
    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert decs[-1]["action"] == "canary_rollback"
    assert "no traffic" in decs[-1]["reason"]
    assert pub.canary is None
    f = fleet.submit([3], max_new_tokens=10)
    assert fleet.drain()[f] == [4, 5, 6]


def test_fleet_publish_supersedes_pending_canary(tmp_path):
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    ap.publish_canary(BAD)
    fleet.step()
    clock.advance(1.0)
    pub.publish(GOOD)  # a trainer publish lands mid-canary
    fleet.step()
    clock.advance(1.0)
    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert decs[-1]["action"] == "canary_superseded"
    assert all(
        not p.name.startswith("canary_") for p in monitor.policies
    )
    f = fleet.submit([3], max_new_tokens=10)
    assert fleet.drain()[f] == [4, 5, 6]


def test_second_canary_while_pending_raises(tmp_path):
    """Silently replacing a pending canary would strand the first
    canary replica on abandoned candidate weights with nothing left to
    roll it back — the publisher refuses instead."""
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    ap.publish_canary(BAD)
    with pytest.raises(RuntimeError, match="already[\\s\\S]*pending"):
        ap.publish_canary(GOOD)
    # a fleet-wide publish is the sanctioned supersede: it converges
    # EVERY replica (the canary one included) on the new tree
    pub.publish(GOOD)
    assert pub.canary is None
    ap.publish_canary(GOOD)  # resolvable again


class ToyQuantLM(nn.Module):
    """ToyShiftLM with a REAL 2-D matmul kernel so the serving weight
    quantizer (loop/quantize.py) has something to quantize: ``logits =
    one_hot(tok) @ kernel`` with ``kernel = 20 * shift-by-1
    permutation``. Per-column absmax quantization is EXACT on it (every
    column's single nonzero hits qvalue 127), so a healthy quantized
    publish is token-identical to full precision — and a broken
    quantizer (zeroed scales) flattens the logits to all-zero, greedy
    decode emits token 0 forever, EOS never lands, and the
    serve/request_tokens distribution jumps to the budget ceiling on
    the canary replica."""

    vocab: int = SHIFT_VOCAB
    decode_max_length: int = 32

    @nn.compact
    def __call__(self, tokens, positions, labels=None, mask=None):
        b = tokens.shape[0]
        kernel = self.param(
            "kernel",
            lambda k: 20.0 * jnp.eye(self.vocab, dtype=jnp.float32),
        )
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        mem = self.variable(
            "cache", "mem",
            lambda: jnp.zeros((b, self.decode_max_length), jnp.int32),
        )
        i = jnp.broadcast_to(idx.value, (b,))
        mem.value = mem.value.at[
            jnp.arange(b), jnp.clip(i, 0, self.decode_max_length - 1)
        ].set(tokens[:, 0])
        idx.value = idx.value + 1
        return jax.nn.one_hot(tokens, self.vocab) @ kernel

    def logits(self, tokens, positions, mask=None):
        return self(tokens, positions)


# kernel[t, (t+1) % vocab] = 20: greedy next token == (t + 1) % vocab,
# the same walk as ToyShiftLM's GOOD shift, so shift_expected() is the
# oracle for the quantized model too
GOOD_Q = {
    "kernel": 20.0 * jnp.eye(SHIFT_VOCAB, dtype=jnp.float32)[
        :, (jnp.arange(SHIFT_VOCAB) - 1) % SHIFT_VOCAB
    ]
}


def make_quant_batcher(params, **kw):
    kw.setdefault("batch_size", 2)
    kw.setdefault("chunk_size", 4)
    return ContinuousBatcher(ToyQuantLM(), params, eos_id=SHIFT_EOS, **kw)


def test_broken_quantizer_canary_rolls_back(tmp_path):
    """The low-precision deployment contract end to end
    (docs/design/generation.md "Low-precision serving"): the fleet
    serves a HEALTHY int8-quantized generation; a deliberately broken
    re-quantization (zeroed scales — the classic all-zero-logits
    quantizer bug) goes out as an autopilot canary, the canary
    replica's serve/request_tokens distribution hits the budget
    ceiling, and the autopilot rolls back to the retained quantized
    tree under a fresh stamp with a flight-recorder dump and a
    decision-log entry — no human input, no fleet-wide damage."""
    from d9d_tpu.loop.quantize import (
        is_quantized_tree,
        quantize_for_serving,
    )

    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path / "flight")
    clock = FakeClock()
    good_q = quantize_for_serving(GOOD_Q)
    assert is_quantized_tree(good_q)
    pub = WeightPublisher()
    pub.publish(good_q)  # generation 1: the healthy quantized tree
    fleet = ServingFleet(publisher=pub)
    for _ in range(2):
        fleet.add_replica(make_quant_batcher(good_q))
    monitor = SloMonitor(
        [SloPolicy(name="gen_len_p50", metric="serve/request_tokens",
                   quantile=0.5, target=6.0, window_s=30.0,
                   burn_rate=1e18)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_quant_batcher(p),
        config=AutopilotConfig(
            scale_policies=(), canary_policies=("gen_len_p50",),
            canary_window_s=10.0, canary_tolerance=1.25,
            canary_min_samples=2, canary_max_wait_s=30.0,
            eval_interval_s=1.0,
        ),
        decision_log=tmp_path / "decisions.jsonl",
        clock=clock,
    ).attach()

    # healthy quantized serving IS token-exact on this model (the
    # permutation kernel quantizes losslessly)
    f = fleet.submit([3], max_new_tokens=10)
    assert fleet.drain()[f] == shift_expected([3], 10) == [4, 5, 6]

    # the broken quantizer: same tree, every scale zeroed — dequant
    # yields all-zero kernels, greedy argmax pins to token 0, EOS never
    bad_q = jax.tree.map(jnp.zeros_like, good_q)
    v = ap.publish_canary(bad_q)
    assert v == 2 and pub.canary is not None
    canary_b = fleet._replicas[max(fleet.live_replicas)]
    _serve_rounds(fleet, clock, [[3], [5], [1]] * 4)

    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert [d["action"] for d in decs] == ["canary_start",
                                           "canary_rollback"]
    verdicts = decs[-1]["detail"]["verdicts"]["gen_len_p50"]
    assert verdicts["bad"] is True and verdicts["canary"] == 10.0
    assert pub.canary is None
    # rollback re-installs the RETAINED quantized tree, fresh stamp
    assert canary_b.weights_version == 3
    assert pub.latest_version == 1
    assert (tmp_path / "flight"
            / "flight_recorder_autopilot_rollback.json").exists()
    snap = hub.registry.snapshot()
    assert snap["counters"]["autopilot/canary_rollbacks"] == 1
    # token-exact again everywhere after the rollback
    f2 = fleet.submit([3], max_new_tokens=10)
    assert fleet.drain()[f2] == [4, 5, 6]


def test_removed_policy_stops_driving_decisions():
    """A policy retired via monitor.remove() while violating must drop
    out of the autopilot's cached statuses — a stale violating status
    would keep shedding/growing forever with nothing live behind it."""
    hub = get_telemetry()
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish({})
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_toy_batcher())
    monitor = SloMonitor(
        [SloPolicy(name="err", kind="rate", bad="serve/rejected",
                   target=0.05, window_s=60.0)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_toy_batcher(params=p),
        config=AutopilotConfig(grow_after_s=3.0, cooldown_s=1.0,
                               max_replicas=4, eval_interval_s=1.0),
        clock=clock,
    ).attach()
    fleet.step()
    clock.advance(1.0)
    hub.counter("serve/rejected").add(5)  # burn, sustained by window
    fleet.step()
    clock.advance(1.0)
    assert ap.status()["burning"] == ["err"]
    monitor.remove(["err"])
    for _ in range(10):
        fleet.step()
        clock.advance(1.0)
    assert ap.status()["burning"] == []
    assert fleet.live_replicas == (0,), "no grow without a live policy"


def test_canary_skips_already_replica_scoped_policies(tmp_path):
    """A per-replica objective (metric already serve/r{i}/...) is not a
    fleet baseline: the comparator must not rewrite it into fabricated
    serve/{canary}/{label}/... names that nothing records (which would
    read as an unobserved canary and roll back healthy weights)."""
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(clock, tmp_path=tmp_path)
    monitor.extend([
        SloPolicy(name="r0_miss", kind="rate", bad="serve/r0/expired",
                  good=("serve/r0/requests_finished",), target=0.1,
                  window_s=10.0),
        SloPolicy(name="r0_len", metric="serve/r0/request_tokens",
                  quantile=0.5, target=6.0, window_s=10.0,
                  burn_rate=1e18),
    ])
    ap2 = FleetAutopilot(
        fleet, monitor,
        config=AutopilotConfig(canary_window_s=1.0), clock=clock,
    )
    watched = {p.name for p in ap2._canary_watched()}
    assert "gen_len_p50" in watched
    assert "r0_miss" not in watched and "r0_len" not in watched


def test_bad_canary_on_sole_replica_still_rolls_back(tmp_path):
    """On a 1-replica fleet the rollup baseline IS the canary's own
    traffic, so canary > rollup x tolerance is unsatisfiable — the
    verdict must fall back to the absolute policy target, or a bad
    canary would always promote at exactly the fleet size idle shrink
    converges to."""
    clock = FakeClock()
    hub, pub, fleet, monitor, ap = _canary_rig(
        clock, tmp_path=tmp_path, n_replicas=1
    )
    v = ap.publish_canary(BAD)
    _serve_rounds(fleet, clock, [[3], [5], [1]] * 4)
    decs = read_decisions(tmp_path / "decisions.jsonl")
    assert decs[-1]["action"] == "canary_rollback"
    assert "no independent fleet baseline" in decs[-1]["reason"]
    assert pub.canary is None
    f = fleet.submit([3], max_new_tokens=10)
    assert fleet.drain()[f] == [4, 5, 6]
    assert fleet._replicas[0].weights_version != v


def test_canary_without_prior_publish_refuses():
    """A canary with no retained prior tree has no rollback target —
    the publisher refuses instead of silently making the 'canary'
    an undoable publish (a bad one would stay installed while the
    autopilot logged a rollback that re-installed nothing)."""
    pub = WeightPublisher()
    b = make_shift_batcher(GOOD)  # strong ref: attach() only weakrefs
    pub.attach(b)
    with pytest.raises(RuntimeError, match="prior fleet-wide publish"):
        pub.publish_canary(BAD)
    pub.publish(GOOD)
    assert pub.publish_canary(BAD) == 2  # resolvable once a tree exists


def test_grow_with_fleetless_publisher_logs_blocked_not_crash(tmp_path):
    """An autopilot handed its own publisher= while the fleet was built
    without one: fleet.grow() would raise (IT cold-starts from the
    fleet's publisher) — the grow decision must degrade to a logged
    grow_blocked, never kill the scheduling loop mid-burn."""
    hub = get_telemetry()
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish(GOOD)  # the AUTOPILOT's publisher has weights...
    fleet = ServingFleet()  # ...but the fleet has no publisher at all
    fleet.add_replica(make_toy_batcher())
    monitor = SloMonitor(
        [SloPolicy(name="err", kind="rate", bad="serve/rejected",
                   target=0.05, window_s=5.0)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor, publisher=pub,
        replica_factory=lambda p: make_toy_batcher(params=p),
        config=AutopilotConfig(grow_after_s=2.0, cooldown_s=1.0),
        decision_log=tmp_path / "decisions.jsonl", clock=clock,
    ).attach()
    for _ in range(8):
        hub.counter("serve/rejected").add(1)
        fleet.step()
        clock.advance(1.0)
    assert fleet.live_replicas == (0,)
    actions = [
        d["action"] for d in read_decisions(tmp_path / "decisions.jsonl")
    ]
    assert actions.count("grow_blocked") == 1 and "grow" not in actions


def test_idle_shrink_never_picks_the_pending_canary_replica(tmp_path):
    """Idle shrink normally retires the highest-index live replica —
    exactly where a pending canary lives (publish_canary defaults to
    max(live)). Shrinking it mid-window would leave the comparator
    watching a retired batcher: an eternally-unobserved canary rolling
    back good weights. The shrink must pick another replica, and hold
    off entirely when only the canary replica is left."""
    hub = get_telemetry()
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish(GOOD)
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_shift_batcher(GOOD))
    fleet.add_replica(make_shift_batcher(GOOD))
    monitor = SloMonitor(
        [SloPolicy(name="gen_len_p50", metric="serve/request_tokens",
                   quantile=0.5, target=6.0, window_s=30.0,
                   burn_rate=1e18)],
        clock=clock,
    ).attach(hub)
    ap = FleetAutopilot(
        fleet, monitor,
        config=AutopilotConfig(
            scale_policies=(), canary_policies=("gen_len_p50",),
            canary_window_s=1e9, canary_min_samples=1,
            canary_max_wait_s=1e9, min_replicas=0,
            idle_after_s=2.0, cooldown_s=0.0, eval_interval_s=1.0,
        ),
        decision_log=tmp_path / "decisions.jsonl", clock=clock,
    ).attach()
    ap.publish_canary(BAD)  # lands on replica 1 (the highest live)
    for _ in range(10):
        fleet.step()
        clock.advance(1.0)
    # replica 0 was the shrink victim; the canary replica survives its
    # decision window — and with only it left, no further shrink even
    # though live (1) > min_replicas (0)
    assert fleet.live_replicas == (1,)
    assert pub.canary is not None
    shrinks = [
        d for d in read_decisions(tmp_path / "decisions.jsonl")
        if d["action"] == "shrink"
    ]
    assert len(shrinks) == 1 and shrinks[0]["detail"]["replica"] == 0


# ---------------------------------------------------------------------------
# decision log schema


def test_decision_log_jsonl_roundtrip(tmp_path):
    log = DecisionLog(tmp_path / "d.jsonl")
    log.append("grow", reason="sustained burn",
               detail={"replica": 2, "burning": {"ttft": 3.2}})
    log.append("shed", reason="queue over target")
    log.close()
    decs = read_decisions(tmp_path / "d.jsonl")
    assert [d["action"] for d in decs] == ["grow", "shed"]
    for d in decs:
        assert d["kind"] == "autopilot_decision"
        assert d["schema"] == DecisionLog.SCHEMA
        assert isinstance(d["unix_time"], float) and d["reason"]
    assert decs[0]["detail"]["burning"] == {"ttft": 3.2}
    # malformed lines are an error, not a silent skip
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"kind": "autopilot_decision", "schema": 1}\n')
    with pytest.raises(ValueError, match="missing fields"):
        read_decisions(bad)
    bad.write_text(json.dumps({
        "kind": "autopilot_decision", "schema": 99, "action": "grow",
        "unix_time": 0.0, "reason": "x",
    }) + "\n")
    with pytest.raises(ValueError, match="schema"):
        read_decisions(bad)


def test_ramp_arrivals_is_deterministic_and_exact():
    """The chaos ramp injector: fractional rates spread by an exact
    accumulator (no RNG in arrival times), same seed → same workload,
    and the tuple shape matches the bench workload builders."""
    sched = [(4, 0.5), (3, 2.0), (2, 0.0)]
    a = ramp_arrivals(sched, vocab=32, seed=7)
    b = ramp_arrivals(sched, vocab=32, seed=7)
    assert a == b
    steps = [t for t, _, _ in a]
    # rate 0.5 over steps 0-3 → arrivals at steps 1 and 3; rate 2.0
    # over steps 4-6 → two per step; rate 0 → nothing
    assert steps == [1, 3, 4, 4, 5, 5, 6, 6]
    for _, prompt, gen in a:
        assert prompt and all(0 <= t < 32 for t in prompt)
        assert gen >= 1


# ---------------------------------------------------------------------------
# the acceptance scenario: ramp + replica kill + bad canary, no human


def test_e2e_chaos_ramp_kill_and_bad_canary_recovers(tmp_path):
    """ISSUE 13 acceptance: a scripted load ramp overloads the fleet
    (burn → shed + grow), a replica is killed mid-drain (continuation
    recovery), a bad-weight canary publish is rolled back from its
    per-replica SLO deltas, and the ramp-down shrinks the fleet back —
    ending with all SLO policies non-burning, every surviving request
    token-exact, and the decision log + flight recorder explaining
    every action. Fully deterministic: shared fake clock, scripted
    arrivals, no sleeps, no human input."""
    hub = get_telemetry()
    flight_dir = tmp_path / "flight"
    hub.configure_flight_recorder(flight_dir)
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish(GOOD)
    fleet = ServingFleet(publisher=pub)
    for _ in range(2):
        fleet.add_replica(make_shift_batcher(GOOD, max_queue=3))
    monitor = SloMonitor(
        [
            # scale signal: overload rejections vs completions
            SloPolicy(name="reject_rate", kind="rate",
                      bad="serve/rejected",
                      good=("serve/requests_finished",), target=0.05,
                      window_s=8.0),
            # quality signal: tokens-per-request p50 (the canary axis)
            SloPolicy(name="gen_len_p50", metric="serve/request_tokens",
                      quantile=0.5, target=6.0, window_s=8.0,
                      burn_rate=1e18),
        ],
        clock=clock,
    ).attach(hub)
    log_path = tmp_path / "decisions.jsonl"
    ap = FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_shift_batcher(p, max_queue=3),
        config=AutopilotConfig(
            scale_policies=("reject_rate",), grow_after_s=3.0,
            cooldown_s=6.0, min_replicas=1, max_replicas=3,
            idle_after_s=4.0, idle_queue_depth=0,
            idle_slot_utilization=0.01, shed_queue_depth=4,
            canary_policies=("gen_len_p50",), canary_window_s=8.0,
            canary_tolerance=1.25, canary_min_samples=2,
            canary_max_wait_s=20.0, eval_interval_s=1.0,
        ),
        decision_log=log_path, clock=clock,
    ).attach()

    # replica 0 dies mid-drain early in the ramp (a preemption landing
    # during overload): its unfinished requests must continue elsewhere
    shrink_at_step(fleet, 0, step=4)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)

    # -- phase A: scripted overload ramp (chaos.ramp_arrivals) --------
    # odd prompts ABOVE the EOS token: good weights never hit EOS, so
    # every request runs its full budget — long-running rows keep the
    # dying replica busy for the kill and keep slots saturated so the
    # ramp actually overloads the bounded queues
    ramp = ramp_arrivals(
        [(6, 1.0), (10, 3.0), (6, 1.0)], vocab=6, seed=3,
        prompt_lo=1, prompt_hi=2, gen_lo=9, gen_hi=10,
    )
    ramp = [
        (t, [7 + 2 * (i % 3)], g) for i, (t, _p, g) in enumerate(ramp)
    ]
    frids, rejected, shed_submitted = [], 0, []
    pending = list(ramp)
    step = 0
    while pending or not all(
        fleet.finished(f) for f in list(fleet._reqs)
    ):
        while pending and pending[0][0] <= step:
            _, prompt, gen = pending.pop(0)
            # background tier rides along: the ramp's overflow should
            # land on these, not the paying traffic
            try:
                if step % 4 == 2:
                    shed_submitted.append(fleet.submit(
                        prompt, max_new_tokens=gen, priority=-1,
                    ))
                else:
                    frids.append((fleet.submit(
                        prompt, max_new_tokens=gen,
                    ), prompt, gen))
            except QueueFullError:
                rejected += 1
        fleet.step()
        clock.advance(1.0)
        step += 1
        if step > 400:
            raise AssertionError("ramp scenario did not converge")
    assert 0 in fleet.dead, "the chaos kill must have fired"
    assert rejected > 0, "the ramp must have overloaded the front door"
    assert len(fleet.live_replicas) >= 2, "the autopilot must have grown"

    # -- phase B: bad-weight canary under steady traffic ---------------
    # one request per live replica per round: least-loaded routing then
    # reaches every replica, so the canary actually serves (and shows
    # its degradation in serve/r{i}/request_tokens)
    v_bad = ap.publish_canary(BAD)
    prompts_b = [[3], [5], [1]]
    for r in range(12):
        for j in range(len(fleet.live_replicas)):
            fleet.submit(prompts_b[(r + j) % 3], max_new_tokens=10)
        fleet.step()
        clock.advance(1.0)
    fleet.drain()
    for _ in range(4):
        fleet.step()
        clock.advance(1.0)
    assert pub.canary is None, "the canary must have been decided"

    # -- phase C: ramp down → idle shrink back to the minimum ----------
    for _ in range(40):
        fleet.step()
        clock.advance(1.0)
    assert len(fleet.live_replicas) == 1

    # -- acceptance ----------------------------------------------------
    # all SLO policies non-burning at the end
    statuses = monitor.evaluate()
    assert not any(s.violating for s in statuses), [
        (s.policy.name, s.burn) for s in statuses if s.violating
    ]
    # the bad generation is rolled back on every replica: a request on
    # each live replica emits GOOD-generation tokens
    for i in fleet.live_replicas:
        b = fleet._replicas[i]
        rid = b.submit([3], max_new_tokens=10)
        b.drain()
        assert b.outputs[rid] == [4, 5, 6], (i, b.outputs[rid])
        # no replica is left on the bad stamp: untouched replicas kept
        # the prior generation, the canary was stamped PAST it
        assert b.weights_version != v_bad
    # every surviving phase-A request is token-exact (continuations
    # from the killed replica included); shed ones are explicit
    for frid, prompt, gen in frids:
        if fleet.failed.get(frid) == "shed":
            continue
        assert fleet.finished(frid)
        assert fleet.outputs(frid) == shift_expected(prompt, gen), frid
    shed_hit = [
        f for f in shed_submitted if fleet.failed.get(f) == "shed"
    ]
    assert shed_hit, "burn-driven shedding must have hit the low tier"
    # the decision log explains every action class the scenario forced
    actions = [d["action"] for d in read_decisions(log_path)]
    assert actions.count("grow") >= 1
    assert actions.count("shrink") >= 1
    assert "shed" in actions
    assert "canary_start" in actions and "canary_rollback" in actions
    for d in read_decisions(log_path):
        assert d["reason"], d  # every decision explains itself
    # flight-recorder black boxes: the replica death and the rollback
    assert (flight_dir / "flight_recorder_replica_death.json").exists()
    rb = flight_dir / "flight_recorder_autopilot_rollback.json"
    assert rb.exists()
    assert json.loads(rb.read_text())["extra"]["verdicts"]
    # /healthz autopilot block rides the fleet health payload
    health = fleet.replica_health()
    assert health["autopilot"]["burning"] == []
    assert health["autopilot"]["canary"] is None
    assert health["autopilot"]["last_decision"]["action"] == "shrink"
