"""NaN provenance + drift policies through the trainer (the ISSUE 14
acceptance leg): a ChaosScaleTask-injected NaN is attributed to the
layer/site that produced it — deterministically — in BOTH the anomaly
warning and the flight-recorder dump; drift policies page on a loss
spike and surface train_slo/* gauges; rollback forgets the numerics and
drift windows the restored state invalidates."""

import json
import logging

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import make_micro_trainer

from d9d_tpu.loop import CausalLMTask
from d9d_tpu.resilience.chaos import ChaosScaleTask
from d9d_tpu.telemetry import Telemetry, set_telemetry


def test_injected_nan_named_in_warning_and_flight_record(tmp_path, caplog):
    hub = set_telemetry(Telemetry())
    try:
        # prepared-batch call 4 = step 5 (log_every=1, prefetch off)
        task = ChaosScaleTask(CausalLMTask(), {4: float("nan")})
        trainer = make_micro_trainer(
            task, anomaly_policy="skip_step", numerics_every_steps=1,
            total_steps=8, telemetry_dir=str(tmp_path / "tele"),
        )
        with caplog.at_level(logging.WARNING, logger="d9d_tpu.resilience"):
            history = trainer.train()
        trainer.close()
        assert history[-1]["step"] == 8

        # the injection scales loss_sum, so activations stay finite and
        # the first non-finite site is the loss — the exact attribution,
        # in the one-line warning...
        warnings = [
            r.getMessage() for r in caplog.records
            if "anomaly observed" in r.getMessage()
        ]
        assert warnings and all(
            "first non-finite: loss:loss" in w for w in warnings
        )
        # ...and in the flight-recorder dump, which also carries the
        # full per-layer window of the anomalous step
        dump = json.loads(
            (tmp_path / "flight_recorder_anomaly.json").read_text()
        )
        assert dump["extra"]["first_nonfinite"] == "loss:loss"
        assert dump["extra"]["numerics_step"] == 5
        assert dump["numerics"]["step"] == 5
        assert dump["numerics"]["first_nonfinite"] == {
            "site": "loss", "name": "loss",
        }
        rows = dump["numerics"]["rows"]
        assert rows["loss"]["finite"] is False
        # NaN propagated into the backward: grad rows are marked too
        assert any(
            r["kind"] == "param" and r["finite"] is False
            for r in rows.values()
        )
    finally:
        set_telemetry(Telemetry())


def test_numerics_scalars_ride_history_and_windows_count():
    hub = set_telemetry(Telemetry())
    try:
        trainer = make_micro_trainer(
            CausalLMTask(), numerics_every_steps=1, total_steps=6,
        )
        history = trainer.train()
        assert all("numerics/grad_rms_max" in h for h in history)
        assert all(h["numerics/nonfinite_rows"] == 0.0 for h in history)
        assert hub.registry.counter("numerics/windows").value == 6
        # spec rows cover every MicroLM param leaf + the loss
        spec = trainer.step_fn.numerics_spec
        assert sum(1 for r in spec.rows if r.kind == "param") == 5
    finally:
        set_telemetry(Telemetry())


def test_cadence_windows_only_on_fetched_or_cadence_steps():
    """numerics_every_steps > log cadence: only the fetched steps carry
    decodable windows, and every fetched step does (the window the host
    decodes is always the fetched step's own)."""
    hub = set_telemetry(Telemetry())
    try:
        trainer = make_micro_trainer(
            CausalLMTask(), numerics_every_steps=3, total_steps=6,
            log_every=2,
        )
        history = trainer.train()
        # fetched steps: 2, 4, 6 — each got its own fresh window
        assert [h["step"] for h in history] == [2, 4, 6]
        assert all("numerics/grad_rms_max" in h for h in history)
        assert hub.registry.counter("numerics/windows").value == 3
        assert hub.registry.gauge("numerics/last_step").value == 6.0
    finally:
        set_telemetry(Telemetry())


def test_finite_loss_spike_pages_drift_policy():
    hub = set_telemetry(Telemetry())
    try:
        task = ChaosScaleTask(CausalLMTask(), {5: 500.0})
        trainer = make_micro_trainer(
            task, numerics_every_steps=1, total_steps=8,
        )
        trainer.train()
        assert hub.registry.counter("train_slo/violations").value >= 1
        assert (
            hub.registry.counter("train_slo/loss_spike/violations").value
            >= 1
        )
        # gauges are live for /metrics scrapes
        assert np.isfinite(
            hub.registry.gauge("train_slo/loss_spike/baseline").value
        )
        assert hub.registry.gauge("train_slo/grad_norm_drift/burn").value < 1
    finally:
        set_telemetry(Telemetry())


def test_rollback_resets_numerics_and_drift_windows(tmp_path):
    hub = set_telemetry(Telemetry())
    try:
        task = ChaosScaleTask(
            CausalLMTask(),
            {5: float("nan"), 6: float("nan"), 7: float("nan")},
        )
        trainer = make_micro_trainer(
            task,
            anomaly_policy="rollback",
            anomaly_rollback_after=2,
            numerics_every_steps=1,
            checkpoint_dir=str(tmp_path),
            checkpoint_every_steps=2,
            checkpoint_async=False,
        )
        history = trainer.train()
        trainer.close()
        assert hub.registry.counter("resilience/rollbacks").value >= 1
        assert history[-1]["step"] == trainer.config.total_steps
        assert np.isfinite(history[-1]["loss"])
        # post-rollback the provenance context was forgotten, and the
        # run finished with a clean window
        assert trainer.numerics_monitor.guard_context() is None
        assert trainer.numerics_monitor.last.first_nonfinite is None
        assert trainer.drift_monitor is not None
    finally:
        set_telemetry(Telemetry())
