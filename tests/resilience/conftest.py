"""Shared micro-scale fixtures for the chaos suite.

The fault-injection tests (docs/design/resilience.md catalogue) run in
the quick tier, so everything here is deliberately tiny: a 2-layer
MicroLM instead of a Qwen stack (compile cost ~seconds on the 2-core
rig) and a ToyDecodeLM whose next token is ``(tok + 1) % vocab`` — a
real flax decode cache (``cache_index`` + a written memory leaf) with
exactly predictable emissions, so degraded-mode scheduling asserts
exact outputs without an oracle model.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core import MeshParameters
from d9d_tpu.loop import (
    AdamWProvider,
    CausalLMTask,
    DatasetProvider,
    ModelProvider,
    StatefulDataLoader,
    Trainer,
    TrainerConfig,
)
from d9d_tpu.loop.tasks import LM_IGNORE_INDEX
from d9d_tpu.parallel import replicate_plan

VOCAB = 16


class MicroLM(nn.Module):
    """Embed → Dense → Dense next-token model returning per-token loss
    (the CausalLM contract CausalLMTask drives)."""

    vocab: int = VOCAB
    dim: int = 16

    @nn.compact
    def __call__(self, tokens, positions, labels):
        h = nn.Embed(self.vocab, self.dim)(tokens)
        h = nn.Dense(self.dim)(jax.nn.relu(h))
        logits = nn.Dense(self.vocab)(h)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(
            logp, jnp.clip(labels, 0)[..., None], axis=-1
        )[..., 0]
        valid = (labels != LM_IGNORE_INDEX).astype(jnp.float32)
        return -(ll * valid)


class MicroProvider(ModelProvider):
    def build_module(self, stage):
        return MicroLM()

    def build_plan(self, ctx):
        return replicate_plan(ctx)

    def sample_inputs(self, batch_size, seq_len):
        z = jnp.zeros((batch_size, seq_len), jnp.int32)
        return (z, z, z)


class MicroLoaderProvider(DatasetProvider):
    """Stateful (exact-resume) loader over a fixed random token table."""

    def __init__(self, n_items=64, seq=8, batch=8, dataset_wrap=None):
        self.n, self.seq, self.batch = n_items, seq, batch
        self.dataset_wrap = dataset_wrap
        self.loader_kwargs = {}

    def build(self):
        rng = np.random.RandomState(0)
        ds = [
            {"input_ids": rng.randint(0, VOCAB, self.seq + 1)}
            for _ in range(self.n)
        ]
        if self.dataset_wrap is not None:
            ds = self.dataset_wrap(ds)
        return StatefulDataLoader(
            ds, self.batch, shuffle=True, seed=0, num_epochs=100,
            **self.loader_kwargs,
        )


def make_micro_trainer(task, *, dataset_provider=None, **config_overrides):
    """A Trainer over MicroLM on the 8-device replicate mesh with
    chaos-friendly defaults (log_every=1 so the host guard observes
    every step; prefetch off unless a test opts in)."""
    ctx = MeshParameters(dp_replicate=8).build(jax.devices())
    defaults = dict(
        global_batch_size=8,
        microbatch_size=8,
        seq_len=8,
        total_steps=12,
        log_every=1,
        prefetch_batches=0,
        telemetry_console=False,
        gc_every_steps=None,
    )
    defaults.update(config_overrides)
    config = TrainerConfig(**defaults)
    return Trainer(
        ctx=ctx,
        config=config,
        model_provider=MicroProvider(),
        dataset_provider=(
            dataset_provider
            if dataset_provider is not None
            else MicroLoaderProvider()
        ),
        task=task,
        optimizer_provider=AdamWProvider(),
    )


SERVE_VOCAB = 32


class ToyDecodeLM(nn.Module):
    """Deterministic decode model: next token = (tok + 1) % vocab.

    Carries a real decode cache (scalar ``cache_index`` the batcher
    reseeds per-row, plus a written [B, L] memory leaf) so the serving
    loop's cache zeroing/pinning machinery is exercised for real.
    """

    vocab: int = SERVE_VOCAB
    decode_max_length: int = 32

    @nn.compact
    def __call__(self, tokens, positions, labels=None, mask=None):
        b = tokens.shape[0]
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        mem = self.variable(
            "cache", "mem",
            lambda: jnp.zeros((b, self.decode_max_length), jnp.int32),
        )
        i = jnp.broadcast_to(idx.value, (b,))
        mem.value = mem.value.at[
            jnp.arange(b), jnp.clip(i, 0, self.decode_max_length - 1)
        ].set(tokens[:, 0])
        idx.value = idx.value + 1
        return jax.nn.one_hot((tokens + 1) % self.vocab, self.vocab) * 20.0

    def logits(self, tokens, positions, mask=None):
        return self(tokens, positions)


@pytest.fixture
def toy_batcher_factory():
    from d9d_tpu.loop.serve import ContinuousBatcher

    model = ToyDecodeLM()
    z = jnp.zeros((2, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), z, z, z).get("params", {})

    def make(**kwargs):
        kwargs.setdefault("batch_size", 2)
        kwargs.setdefault("chunk_size", 4)
        return ContinuousBatcher(model, params, **kwargs)

    return make


def toy_expected(prompt, n):
    """The tokens ToyDecodeLM greedy-decodes after ``prompt``."""
    return [(prompt[-1] + 1 + i) % SERVE_VOCAB for i in range(n)]


class PagedToyLM(nn.Module):
    """Deterministic PAGEABLE decode model: next token = (sum of every
    token seen so far) % vocab, computed FROM the KV cache content.

    Unlike ToyDecodeLM (whose ``mem`` leaf is unpageable and whose
    output ignores the cache), this stores tokens in a ``cached_key``
    leaf written/read through the shared paged-cache helpers
    (d9d_tpu/nn/attention.py) — the serving loop pages it, the prefix
    cache stays enabled, and the emissions depend on EVERY cached slot.
    So a handoff that ships wrong/corrupt page payloads changes the
    output stream: token-identity pins here verify shipped content,
    not just bookkeeping.
    """

    vocab: int = SERVE_VOCAB
    decode_max_length: int = 32

    @nn.compact
    def __call__(self, tokens, positions, labels=None, mask=None):
        from d9d_tpu.nn.attention import (
            _decode_cache_append_heads_major,
            _decode_cache_index,
            _decode_page_table,
            _gather_pages_heads_major,
            _gather_pages_heads_major_quant,
        )

        b, t = tokens.shape
        idx = _decode_cache_index(self)
        start = idx.value
        table = _decode_page_table(self)
        v = tokens[..., None, None].astype(jnp.float32)  # [B, T, H=1, D=1]
        pool = _decode_cache_append_heads_major(
            self, v, "cached_key", self.decode_max_length, start,
            page_table=table,
        )
        if table is not None:
            if self.has_variable("cache", "cached_key_scale"):
                cache = _gather_pages_heads_major_quant(
                    pool, self.get_variable("cache", "cached_key_scale"),
                    table, jnp.float32,
                )
            else:
                cache = _gather_pages_heads_major(pool, table)
        else:
            cache = pool
        vals = cache[:, 0, :, 0]  # [B, S] cached token values
        idx.value = start + t
        # logits for each of the t new positions: the running sum over
        # all slots written so far (slot order == time order per row)
        s = jnp.broadcast_to(jnp.reshape(start, (-1, 1)), (b, t))
        end = s + jnp.arange(t)[None, :]  # inclusive last slot per pos
        slots = jnp.arange(vals.shape[1])
        valid = slots[None, None, :] <= end[..., None]  # [B, T, S]
        tot = jnp.sum(jnp.where(valid, vals[:, None, :], 0.0), axis=-1)
        # round before the mod: int8-quantized pools dequantize to the
        # token value ± float epsilon, and truncation would alias it.
        # 1 + (sum % (vocab-1)) has no absorbing state — the stream
        # keeps evolving, so any cache corruption shows up in tokens
        nxt = 1 + jnp.mod(jnp.round(tot).astype(jnp.int32), self.vocab - 1)
        return jax.nn.one_hot(nxt, self.vocab) * 20.0

    def logits(self, tokens, positions, mask=None):
        return self(tokens, positions)


def paged_toy_expected(prompt, n, vocab=SERVE_VOCAB):
    """The tokens PagedToyLM greedy-decodes after ``prompt``."""
    total = sum(prompt)
    out = []
    for _ in range(n):
        nxt = 1 + total % (vocab - 1)
        out.append(nxt)
        total += nxt
    return out


@pytest.fixture
def paged_toy_factory():
    """Factory for paged-serving batchers over PagedToyLM (prefix cache
    live, page payloads observable)."""
    from d9d_tpu.loop.serve import ContinuousBatcher

    model = PagedToyLM()
    z = jnp.zeros((2, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), z, z).get("params", {})

    def make(**kwargs):
        kwargs.setdefault("batch_size", 2)
        kwargs.setdefault("chunk_size", 4)
        kwargs.setdefault("page_size", 4)
        kwargs.setdefault("num_pages", 17)
        return ContinuousBatcher(model, dict(params), **kwargs)

    return make
