"""Data-path chaos through the trainer: transient fetch errors are
retried with backoff and counted; a dead source (prefetch-producer
death) surfaces as an explanatory DataFetchError, not a hang; finite
loss spikes are detected by the host guard."""

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import MicroLoaderProvider, make_micro_trainer

from d9d_tpu.loop import CausalLMTask, DataFetchError
from d9d_tpu.loop.components.prefetch import BatchPrefetcher
from d9d_tpu.resilience.chaos import ChaosScaleTask, FlakyDataset
from d9d_tpu.telemetry import Telemetry, set_telemetry


def test_transient_fetch_errors_retried_and_counted():
    hub = set_telemetry(Telemetry())
    try:
        provider = MicroLoaderProvider(
            dataset_wrap=lambda ds: FlakyDataset(ds, fail_calls={10, 30})
        )
        provider.loader_kwargs = dict(
            retry_attempts=2, retry_backoff_s=0.01
        )
        trainer = make_micro_trainer(
            CausalLMTask(), dataset_provider=provider, total_steps=6,
            prefetch_batches=2,
        )
        history = trainer.train()
        assert history[-1]["step"] == 6
        assert all(np.isfinite(h["loss"]) for h in history)
        assert hub.registry.counter("io/data_retries").value == 2
    finally:
        set_telemetry(Telemetry())


def test_dead_source_fails_with_position_not_timeout():
    provider = MicroLoaderProvider(
        dataset_wrap=lambda ds: FlakyDataset(ds, dead_from=20)
    )
    provider.loader_kwargs = dict(retry_attempts=1, retry_backoff_s=0.01)
    trainer = make_micro_trainer(
        CausalLMTask(), dataset_provider=provider, total_steps=10,
        prefetch_batches=2,
    )
    with pytest.raises(DataFetchError, match=r"epoch \d+ batch \d+"):
        trainer.train()


def test_prefetch_producer_death_surfaces_not_hangs():
    """A producer thread that dies without delivering batch, error, or
    end-of-data must raise promptly on the consumer."""

    class DyingPrefetcher(BatchPrefetcher):
        def _produce(self):  # silent thread death — no sentinel
            return

    pf = DyingPrefetcher(iter([]), lambda x: x, depth=1)
    pf._thread.join(timeout=5.0)
    with pytest.raises(RuntimeError, match="producer thread died"):
        next(pf)
    pf.close()


def test_finite_loss_spike_detected_and_survived():
    hub = set_telemetry(Telemetry())
    try:
        task = ChaosScaleTask(CausalLMTask(), {4: 500.0})
        trainer = make_micro_trainer(
            task, anomaly_policy="warn", anomaly_spike_factor=10.0,
            total_steps=8,
        )
        history = trainer.train()
        assert history[-1]["step"] == 8
        assert hub.registry.counter("resilience/loss_spikes").value >= 1
        # spike was finite: the device guard saw nothing anomalous
        assert history[-1].get("resilience/anomaly_total", 0.0) == 0.0
    finally:
        set_telemetry(Telemetry())
