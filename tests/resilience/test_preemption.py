"""Preemption-safe exit: SIGTERM mid-run → boundary checkpoint →
TrainingPreempted with the documented code → resume continues exactly.
"""

import signal

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import make_micro_trainer

from d9d_tpu.loop import CausalLMTask
from d9d_tpu.resilience import (
    EXIT_PREEMPTED,
    PreemptionGuard,
    TrainingPreempted,
)
from d9d_tpu.resilience.chaos import checkpoint_steps, sigterm_at_step


def test_sigterm_mid_run_checkpoints_and_resumes(tmp_path):
    trainer = make_micro_trainer(
        CausalLMTask(),
        checkpoint_dir=str(tmp_path),
        checkpoint_every_steps=50,  # only the emergency save will fire
        checkpoint_async=True,
    )
    # a REAL signal to this process when step index 5 begins; the flag
    # is honored at that step's boundary (= step 6 in 1-based history)
    sigterm_at_step(trainer.events, 5)
    with pytest.raises(TrainingPreempted) as exc:
        trainer.train()
    trainer.close()
    assert exc.value.code == EXIT_PREEMPTED
    preempt_step = exc.value.step
    assert 0 < preempt_step < trainer.config.total_steps
    # TrainingPreempted IS a SystemExit: uncaught, the process exits
    # with the documented code (no traceback) — the contract schedulers
    # key on
    assert isinstance(exc.value, SystemExit)
    # the emergency checkpoint is durable on disk at the preempt step
    assert checkpoint_steps(tmp_path) == [preempt_step]

    # existing resume picks it up: the run completes the remaining steps
    resumed = make_micro_trainer(
        CausalLMTask(),
        checkpoint_dir=str(tmp_path),
        checkpoint_every_steps=50,
        checkpoint_async=True,
    )
    history = resumed.train()
    resumed.close()
    assert history[0]["step"] == preempt_step + 1
    assert history[-1]["step"] == resumed.config.total_steps
    assert all(np.isfinite(h["loss"]) for h in history)


def test_guard_flags_without_interrupting_the_step():
    guard = PreemptionGuard()
    with guard:
        assert not guard.triggered
        guard.trip(signal.SIGTERM)
        assert guard.triggered
        assert guard.signum == signal.SIGTERM
    # handlers restored on exit; the flag persists (a preempted process
    # must not quietly start a second training run)
    assert guard.triggered
