"""Watchdog exit contract: a missed heartbeat flushes telemetry (with a
final resilience/watchdog_timeout event in the JSONL log) and hard-exits
with the configured code — pinned in a subprocess, since os._exit is
not mockable from inside."""

import json
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.chaos

_SCRIPT = r"""
import sys, time
sys.path.insert(0, {repo!r})
from d9d_tpu.loop.components.timeout_manager import TimeoutManager
from d9d_tpu.telemetry import JsonlSink, get_telemetry

tele = get_telemetry()
tele.add_sink(JsonlSink({out!r}, run_name="watchdog", process_index=0))
tele.set_step(7)
with TimeoutManager(init_timeout_s=0.2, step_timeout_s=0.2, exit_code=77):
    time.sleep(30)  # no heartbeat: the watchdog must kill us first
print("UNREACHABLE")
sys.exit(0)
"""


def test_watchdog_exits_with_configured_code_and_flushes(tmp_path):
    repo = str(pathlib.Path(__file__).resolve().parents[2])
    script = _SCRIPT.format(repo=repo, out=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 77, (proc.stdout, proc.stderr)
    assert "UNREACHABLE" not in proc.stdout
    assert "watchdog timeout" in proc.stderr

    log = tmp_path / "watchdog_proc0.jsonl"
    assert log.exists(), "watchdog exit must leave a flushed JSONL log"
    events = [json.loads(line) for line in log.read_text().splitlines()]
    spans = [e for e in events if e.get("kind") == "span"]
    assert any(
        e["name"] == "resilience/watchdog_timeout"
        and e.get("meta", {}).get("exit_code") == 77
        and e.get("step") == 7
        for e in spans
    )
    flushes = [e for e in events if e.get("kind") == "flush"]
    assert flushes and flushes[-1]["counters"].get(
        "resilience/watchdog_timeout"
    ) == 1.0


def test_exit_code_knob_defaults():
    from d9d_tpu.loop.components.timeout_manager import TimeoutManager
    from d9d_tpu.resilience import EXIT_WATCHDOG

    assert TimeoutManager().exit_code == EXIT_WATCHDOG == 42
