"""Preemption-driven serving-fleet shrink/grow
(resilience/elastic.ServingFleet, docs/design/elasticity.md): requests
route across replicas under the PR 5 backpressure contract, a shrinking
replica drains its queue into survivors, a replica killed mid-drain has
its unfinished requests recovered as continuation prompts (no committed
token lost), and a grown replica cold-starts from the latest published
weights."""

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import ToyDecodeLM, toy_expected

from d9d_tpu.loop.serve import ContinuousBatcher, QueueFullError
from d9d_tpu.resilience import PreemptionGuard, ServingFleet, WeightPublisher
from d9d_tpu.resilience.chaos import kill_replica_mid_drain, shrink_at_step
from d9d_tpu.telemetry import get_telemetry


def _make_batcher(params=None, **kwargs):
    model = ToyDecodeLM()
    if params is None:
        z = jnp.zeros((2, 1), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), z, z, z).get("params", {})
    kwargs.setdefault("batch_size", 2)
    kwargs.setdefault("chunk_size", 4)
    return ContinuousBatcher(model, params, **kwargs)


def _fleet(n_replicas=2, publisher=None, **batcher_kwargs):
    fleet = ServingFleet(publisher=publisher)
    for _ in range(n_replicas):
        fleet.add_replica(_make_batcher(**batcher_kwargs))
    return fleet


def test_fleet_routes_and_drains():
    fleet = _fleet(2)
    prompts = [[3], [7, 8], [1], [5], [9], [2, 6]]
    frids = [fleet.submit(p, max_new_tokens=4) for p in prompts]
    out = fleet.drain()
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 4), frid
    # both replicas actually served traffic (least-loaded routing)
    assert all(
        fleet._replicas[i].stats.emitted_tokens > 0 for i in (0, 1)
    )


def test_fleet_backpressure_cascades():
    """Every replica's bounded queue full → fleet-level QueueFullError
    (the PR 5 admission contract, one level up)."""
    fleet = _fleet(2, max_queue=1)
    # nothing admitted yet, so capacity = one bounded-queue slot per
    # replica; the third submit must cascade the rejection to the caller
    frids = [fleet.submit([3], max_new_tokens=8) for _ in range(2)]
    with pytest.raises(QueueFullError):
        fleet.submit([4], max_new_tokens=2)
    out = fleet.drain()
    for frid in frids:
        assert out[frid] == toy_expected([3], 8)
    # post-drain the queues are free again: the shed request retries fine
    retry = fleet.submit([4], max_new_tokens=2)
    assert fleet.drain()[retry] == toy_expected([4], 2)


def test_shrink_migrates_queue_into_survivors():
    fleet = _fleet(2, batch_size=1)
    # replica 0 least-loaded first: overload it so its queue is deep
    prompts = [[4], [8], [11], [2]]
    frids = [fleet.submit(p, max_new_tokens=5) for p in prompts]
    queued_before = sum(
        len(fleet._replicas[i]._queue) for i in (0, 1)
    )
    assert queued_before >= 1  # at least one never-admitted request
    fleet.shrink(0)
    assert fleet.live_replicas == (1,)
    assert 0 in fleet.retired
    out = fleet.drain()
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 5), frid


def test_shrink_at_step_chaos_is_deterministic():
    results = []
    for _ in range(2):
        fleet = _fleet(2)
        frids = [
            fleet.submit(p, max_new_tokens=6)
            for p in ([3], [7], [12], [1])
        ]
        shrink_at_step(fleet, 0, step=2)
        out = fleet.drain()
        results.append([out[f] for f in frids])
        assert fleet.live_replicas == (1,)
    assert results[0] == results[1]
    for toks, p in zip(results[0], ([3], [7], [12], [1])):
        assert toks == toy_expected(p, 6)


def test_kill_mid_drain_recovers_unfinished_as_continuations():
    fleet = _fleet(2)
    prompts = [[3], [7], [12], [1]]
    frids = [fleet.submit(p, max_new_tokens=10) for p in prompts]
    migrated_before = get_telemetry().counter("serve/fleet_migrated").value
    # let some chunks land so the dying replica holds partial progress
    fleet.step()
    shrink_at_step(fleet, 0, step=2)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)
    out = fleet.drain()
    assert 0 in fleet.dead
    # every request completes with its FULL expected token stream:
    # committed tokens from the dead replica survive as the prefix and
    # the survivor's greedy decode continues token-identically
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 10), frid
    assert get_telemetry().counter("serve/fleet_migrated").value \
        > migrated_before, "the kill must have migrated at least one request"
    # retired records stay readable through the bounded snapshot store
    # (and the live maps were pruned so a long-lived fleet stays flat)
    assert fleet.outputs(frids[0]) == out[frids[0]]
    assert not fleet._reqs and not fleet._by_replica


def test_kill_mid_drain_recovers_paged_requests_token_identically():
    """The chaos leg for paged KV (ISSUE 11): replicas running the
    paged cache + page allocator, one killed mid-drain — its requests
    resume on the survivor as continuation prompts, token-identical,
    and the survivor's page bookkeeping stays exact."""
    fleet = _fleet(2, page_size=4, num_pages=17)
    prompts = [[3], [7], [12], [1]]
    frids = [fleet.submit(p, max_new_tokens=10) for p in prompts]
    fleet.step()
    shrink_at_step(fleet, 0, step=2)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)
    out = fleet.drain()
    assert 0 in fleet.dead
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 10), frid
    survivor = fleet._replicas[1]
    survivor._kv.check_invariants()
    assert survivor._kv.pages_in_use == 0  # everything retired cleanly
    # the fleet-level page rollup reflects the one live paged replica
    assert fleet._kv_pages("pages_free") == survivor._kv.pages_free


def test_kill_mid_drain_quantized_fleet_token_identical():
    """The chaos leg for LOW-PRECISION serving: a fleet running int8 KV
    pages (``kv_quant="int8"``), one replica killed mid-drain — the
    continuation handoff re-prefills the committed tokens into the
    survivor's own quantized pages, so recovery must be token-identical
    to the fleet's own quantized baseline (the toy's margins make that
    baseline the exact oracle)."""
    fleet = _fleet(2, page_size=4, num_pages=17, kv_quant="int8")
    prompts = [[3], [7], [12], [1]]
    frids = [fleet.submit(p, max_new_tokens=10) for p in prompts]
    fleet.step()
    shrink_at_step(fleet, 0, step=2)
    kill_replica_mid_drain(fleet, 0, after_chunks=1)
    out = fleet.drain()
    assert 0 in fleet.dead
    for frid, p in zip(frids, prompts):
        assert out[frid] == toy_expected(p, 10), frid
    survivor = fleet._replicas[1]
    survivor._kv.check_invariants()
    assert survivor._kv.pages_in_use == 0
    assert fleet._kv_pages("pages_free") == survivor._kv.pages_free


def test_submit_validation_error_leaves_no_ghost():
    """A replica-side validation error must not strand an unplaceable
    fleet request that wedges every later drain()."""
    fleet = _fleet(1)
    with pytest.raises(ValueError):
        fleet.submit([3], max_new_tokens=10_000)  # > decode_max_length
    assert not fleet._reqs
    ok = fleet.submit([3], max_new_tokens=3)
    assert fleet.drain()[ok] == toy_expected([3], 3)


def test_shrink_fails_unmanaged_queued_requests_explicitly():
    """A request submitted DIRECTLY to a batcher that the fleet later
    shrinks can't be migrated (the caller holds that replica's rid) —
    it must surface as an explicit failure, never vanish."""
    fleet = _fleet(1, batch_size=1)
    b = ContinuousBatcher(
        ToyDecodeLM(), {}, batch_size=1, chunk_size=4
    )
    direct_busy = b.submit([4], max_new_tokens=2)
    b.step_chunk()  # admitted into the single slot
    direct_queued = b.submit([6], max_new_tokens=2)  # stays queued
    fleet.add_replica(b)
    fleet.shrink(1)  # idx 1: added after the initial replica
    assert b.failed[direct_queued] == "shrunk"
    assert direct_queued in b.done
    assert b.outputs[direct_queued] == []  # observable, just unserved
    assert b.outputs[direct_busy] == toy_expected([4], 2)
    # a shrink retirement is NOT a deadline expiry: the degraded-mode
    # expired signal must stay clean
    assert b.stats.expired == 0


def test_replica_deadline_failure_surfaces_at_fleet():
    """A deadline expiry handled BY THE REPLICA must reach fleet.failed
    — a truncated result must not read as a short success."""
    import time as _time

    fleet = _fleet(1)
    doomed = fleet.submit([5], max_new_tokens=4, deadline_s=0.005)
    ok = fleet.submit([9], max_new_tokens=4)
    _time.sleep(0.02)  # expires while queued on the replica
    out = fleet.drain()
    assert fleet.failed[doomed] == "deadline"
    assert out[doomed] == []
    assert out[ok] == toy_expected([9], 4)


def test_retention_horizon_is_graceful():
    """Past the bounded snapshot horizon, finished() still answers True
    (the request DID retire) and outputs() raises with an explanation —
    never a bare KeyError crash on a healthy long-lived fleet."""
    fleet = _fleet(1)
    f = fleet.submit([3], max_new_tokens=2)
    fleet.drain()
    assert fleet.finished(f) and fleet.outputs(f) == toy_expected([3], 2)
    fleet._MAX_FINISHED = 0  # instance override: force eviction
    fleet._retire_finished()
    assert fleet.finished(f) is True
    with pytest.raises(KeyError, match="retention horizon"):
        fleet.outputs(f)
    with pytest.raises(KeyError, match="unknown"):
        fleet.finished(10_000)


def test_weights_version_monotonic_across_publishers():
    """A publisher whose counter lags the batcher's own generation must
    not regress it: stamps stay unique per batcher."""
    from tests.resilience.conftest import ToyDecodeLM

    b = ContinuousBatcher(ToyDecodeLM(), {}, batch_size=2, chunk_size=4)
    b.submit([3], max_new_tokens=2)
    assert b.install_weights({}) == 1
    b.drain()  # applies generation 1
    pub = WeightPublisher()  # fresh counter: its first publish is "1"
    pub.attach(b)
    v = pub.publish({})
    assert v == 1
    b.submit([3], max_new_tokens=2)
    b.drain()
    # the batcher floored the lagging external version past its own
    assert b.weights_version == 2


def test_grow_cold_starts_from_published_weights():
    pub = WeightPublisher()
    fleet = _fleet(1, publisher=pub)
    model = ToyDecodeLM()
    z = jnp.zeros((2, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), z, z, z).get("params", {})
    with pytest.raises(RuntimeError):
        fleet.grow(lambda p: _make_batcher(params=p))
    pub.publish(params)
    idx = fleet.grow(lambda p: _make_batcher(params=p))
    assert fleet.live_replicas == (0, idx)
    frid = fleet.submit([5], max_new_tokens=4)
    out = fleet.drain()
    assert out[frid] == toy_expected([5], 4)


def test_preemption_signal_triggers_shrink():
    """PR 5's preemption flag is the shrink trigger: once the guard
    trips, the next scheduling round drains the bound replica."""
    fleet = _fleet(2)
    guard = PreemptionGuard(enabled=False)  # flag surface only
    fleet.bind_preemption(guard, 0)
    frids = [fleet.submit(p, max_new_tokens=6) for p in ([3], [9], [1])]
    fleet.step()
    assert fleet.live_replicas == (0, 1)  # not triggered yet
    guard.trip()
    out = fleet.drain()
    assert fleet.live_replicas == (1,)
    assert 0 in fleet.retired
    for frid, p in zip(frids, ([3], [9], [1])):
        assert out[frid] == toy_expected(p, 6), frid


def test_migration_preserves_absolute_deadline():
    """A migration must never extend a request's deadline: the fleet
    stores the ABSOLUTE deadline at submit, so a queued request whose
    contract already expired retires at migration time (partial output
    kept, counted expired) instead of getting a fresh window on the
    survivor."""
    import time as _time

    fleet = _fleet(2, batch_size=1)
    # fill replica slots+queues so a later submit stays queued
    long_frids = [fleet.submit([3], max_new_tokens=6) for _ in range(2)]
    doomed = fleet.submit([9], max_new_tokens=4, deadline_s=0.01)
    _time.sleep(0.03)  # the contract expires while still queued
    # shrink whichever replica holds the doomed request's queue entry
    holder = fleet._reqs[doomed].replica
    fleet.shrink(holder)
    assert doomed in fleet.failed and fleet.failed[doomed] == "deadline"
    out = fleet.drain()  # the rest of the fleet is unaffected
    for frid in long_frids:
        assert out[frid] == toy_expected([3], 6)
    assert out[doomed] == []  # never ran; retired cleanly


def test_shrunk_fleet_keeps_serving_new_traffic():
    fleet = _fleet(2)
    f1 = fleet.submit([4], max_new_tokens=3)
    fleet.shrink(0)
    f2 = fleet.submit([8], max_new_tokens=3)
    out = fleet.drain()
    assert out[f1] == toy_expected([4], 3)
    assert out[f2] == toy_expected([8], 3)
