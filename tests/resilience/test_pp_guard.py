"""PP-path anomaly guard: PipelinedOptimizer.step_guarded freezes every
stage's update on non-finite grad-norm/loss, carries the streak on
device, and adds no host syncs (pinned with the transfer guard)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.chaos

from d9d_tpu.pipelining.training import PipelinedOptimizer


def _setup(freeze=True):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("x",))
    sh = NamedSharding(mesh, P())
    opt = PipelinedOptimizer(
        optimizer=optax.adam(1e-2),
        scalar_shardings={0: sh, 1: sh},
        anomaly_freeze=freeze,
    )
    params = {
        0: {"w": jnp.ones((4, 4))},
        1: {"w": jnp.full((4, 4), 2.0)},
    }
    states = opt.init(params)
    return opt, params, states


def test_guarded_step_freezes_all_stages_on_nan():
    opt, params, states = _setup()
    guard = opt.init_guard_state()
    good = {s: {"w": jnp.full((4, 4), 0.1)} for s in (0, 1)}
    w = jnp.float32(1.0)

    p1, s1, _, gm, guard = opt.step_guarded(
        params, states, good, w, jnp.float32(1.0), guard
    )
    assert float(gm["resilience/anomaly"]) == 0.0
    p1_host = jax.tree.map(np.asarray, p1)

    # NaN in ONE stage's grads poisons the global norm → both freeze
    bad = {
        0: {"w": jnp.full((4, 4), jnp.nan)},
        1: {"w": jnp.full((4, 4), 0.1)},
    }
    p2, s2, _, gm, guard = opt.step_guarded(
        p1, s1, bad, w, jnp.float32(1.0), guard
    )
    assert float(gm["resilience/anomaly"]) == 1.0
    assert float(gm["resilience/anomaly_streak"]) == 1.0
    for s in (0, 1):
        np.testing.assert_array_equal(
            p1_host[s]["w"], np.asarray(p2[s]["w"])
        )

    # a NaN loss with finite grads also trips, and the streak grows
    good2 = {s: {"w": jnp.full((4, 4), 0.1)} for s in (0, 1)}
    _, _, _, gm, guard = opt.step_guarded(
        p2, s2, good2, w, jnp.float32(np.nan), guard
    )
    assert float(gm["resilience/anomaly_streak"]) == 2.0
    assert float(gm["resilience/anomaly_total"]) == 2.0


def test_guarded_step_no_device_to_host_sync():
    opt, params, states = _setup()
    guard = opt.init_guard_state()
    good = {s: {"w": jnp.full((4, 4), 0.1)} for s in (0, 1)}
    w = jnp.float32(1.0)
    # warmup compiles every jitted piece
    params, states, _, gm, guard = opt.step_guarded(
        params, states, good, w, jnp.float32(1.0), guard
    )
    jax.block_until_ready(gm["resilience/anomaly"])
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(2):
            # fresh grads per step: the update donates its grad buffers
            grads = {s: {"w": jnp.full((4, 4), 0.1)} for s in (0, 1)}
            params, states, _, gm, guard = opt.step_guarded(
                params, states, grads, w, jnp.float32(1.0), guard
            )
    jax.block_until_ready(gm["resilience/anomaly"])
