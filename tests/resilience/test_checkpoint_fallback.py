"""Checkpoint integrity & fallback: manifests written after finalize,
truncation detected, restore walks back to the newest intact step
bitwise-identically, and a kill mid-async-save never strands resume.
"""

import pathlib
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from d9d_tpu.loop.components.checkpointer import StateCheckpointer
from d9d_tpu.resilience.chaos import (
    checkpoint_steps,
    truncate_latest_checkpoint,
)
from d9d_tpu.resilience.manifest import (
    MANIFEST_NAME,
    CheckpointIntegrityError,
    validate_checkpoint_dir,
)
from d9d_tpu.telemetry import Telemetry, set_telemetry


def _arrays(step: int):
    # deterministic per-step content so fallbacks can be checked bitwise
    return {
        "w": jnp.arange(4096, dtype=jnp.float32) * step,
        "b": jnp.ones((8,), jnp.float32) * step,
    }


def _checkpointer(tmp_path, **kw):
    kw.setdefault("save_every_steps", 1)
    kw.setdefault("num_to_keep", 3)
    return StateCheckpointer(tmp_path, **kw)


def test_manifest_written_and_validated(tmp_path):
    ck = _checkpointer(tmp_path, async_save=True)
    for s in (1, 2):
        ck.save(s, _arrays(s), {"step": s})
    ck.wait_until_finished()
    for s in (1, 2):
        step_dir = pathlib.Path(tmp_path) / f"save_{s}"
        assert (step_dir / MANIFEST_NAME).exists()
        assert validate_checkpoint_dir(step_dir) is True
    ck.close()


def test_truncated_latest_falls_back_bitwise(tmp_path):
    hub = set_telemetry(Telemetry())
    try:
        ck = _checkpointer(tmp_path, async_save=True)
        for s in (1, 2, 3):
            ck.save(s, _arrays(s), {"step": s})
        ck.wait_until_finished()
        step, victim = truncate_latest_checkpoint(tmp_path)
        assert step == 3
        assert victim.stat().st_size > 0
        with pytest.raises(CheckpointIntegrityError):
            validate_checkpoint_dir(pathlib.Path(tmp_path) / f"save_{step}")
        restored = ck.restore(_arrays(0))
        assert restored is not None
        got_step, got_arrays, meta = restored
        assert got_step == 2 and meta["step"] == 2
        np.testing.assert_array_equal(
            np.asarray(got_arrays["w"]), np.asarray(_arrays(2)["w"])
        )
        np.testing.assert_array_equal(
            np.asarray(got_arrays["b"]), np.asarray(_arrays(2)["b"])
        )
        assert (
            hub.registry.counter("resilience/checkpoint_fallback").value
            == 1
        )
        # an explicit step request keeps strict semantics
        with pytest.raises(CheckpointIntegrityError):
            ck.restore(_arrays(0), step=3)
        ck.close()
    finally:
        set_telemetry(Telemetry())


def test_fallback_prunes_corrupt_steps_and_allows_resave(tmp_path):
    """After walking back past a corrupt step, that step is pruned from
    the rotation so replayed training can re-save at the same step
    number and the corrupt entry can never shadow the intact one."""
    ck = _checkpointer(tmp_path, async_save=False)
    for s in (1, 2):
        ck.save(s, _arrays(s), {"step": s})
    truncate_latest_checkpoint(tmp_path)
    restored = ck.restore(_arrays(0))
    assert restored is not None and restored[0] == 1
    assert checkpoint_steps(tmp_path) == [1]
    # the restore reset the same-step save guard: replaying to step 2
    # re-saves cleanly over the pruned slot
    ck.save(2, _arrays(2), {"step": 2})
    again = ck.restore(_arrays(0))
    assert again is not None and again[0] == 2
    np.testing.assert_array_equal(
        np.asarray(again[1]["w"]), np.asarray(_arrays(2)["w"])
    )
    ck.close()


def test_all_checkpoints_corrupt_raises_not_fresh_start(tmp_path):
    """Checkpoints exist but none restores: silently training from
    scratch (and rotating the old data away) would be quiet data loss —
    the operator gets an error instead."""
    ck = _checkpointer(tmp_path, async_save=False, num_to_keep=2)
    for s in (1, 2):
        ck.save(s, _arrays(s), {"step": s})
    for s in (1, 2):
        truncate_latest_checkpoint(tmp_path, step=s)
    with pytest.raises(RuntimeError, match="refusing to silently"):
        ck.restore(_arrays(0))
    # nothing was pruned: no intact step was found to walk back TO
    assert checkpoint_steps(tmp_path) == [1, 2]
    ck.close()


def test_empty_directory_restores_none(tmp_path):
    ck = _checkpointer(tmp_path, async_save=False)
    assert ck.restore(_arrays(0)) is None  # genuinely no checkpoints
    ck.close()


def test_pre_manifest_checkpoints_still_restore(tmp_path):
    """Back-compat: steps saved before the manifest era (no manifest
    file) restore through the unverified path."""
    ck = _checkpointer(tmp_path, async_save=False)
    ck.save(1, _arrays(1), {"step": 1})
    (pathlib.Path(tmp_path) / "save_1" / MANIFEST_NAME).unlink()
    restored = ck.restore(_arrays(0))
    assert restored is not None and restored[0] == 1
    ck.close()


_KILL_SCRIPT = r"""
import os, sys
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
sys.path.insert(0, {repo!r})
from d9d_tpu.loop.components.checkpointer import StateCheckpointer

tmp = sys.argv[1]
def arrays(step):
    return {{
        "w": jnp.arange(4096, dtype=jnp.float32) * step,
        "b": jnp.ones((8,), jnp.float32) * step,
    }}
ck = StateCheckpointer(tmp, save_every_steps=1, num_to_keep=3, async_save=True)
ck.save(1, arrays(1), {{"step": 1}})
ck.wait_until_finished()  # step 1 durable + manifest written
ck.save(2, arrays(2), {{"step": 2}})
# simulated preemption kill mid-async-save: NO wait_until_finished —
# the background write (and the step-2 manifest) may or may not land
os._exit(9)
"""


def test_kill_mid_async_save_restores_an_intact_step(tmp_path):
    """Crash consistency: a process killed mid-async-save leaves a
    directory tree from which restore ALWAYS returns an intact step
    bitwise-identically — step 1 when step 2 didn't survive, step 2 if
    its write happened to complete — and never crashes or returns
    half-written arrays."""
    repo = str(pathlib.Path(__file__).resolve().parents[2])
    script = _KILL_SCRIPT.format(repo=repo)
    proc = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 9, proc.stderr
    assert 1 in checkpoint_steps(tmp_path)

    ck = _checkpointer(tmp_path, async_save=True)
    restored = ck.restore(_arrays(0))
    assert restored is not None, "kill mid-save stranded resume entirely"
    got_step, got_arrays, meta = restored
    assert got_step in (1, 2) and meta["step"] == got_step
    np.testing.assert_array_equal(
        np.asarray(got_arrays["w"]), np.asarray(_arrays(got_step)["w"])
    )
    ck.close()
