"""Step anomaly guard: device detection/freeze, host spike detection,
policy behavior through the trainer, and the zero-extra-sync pin.

Acceptance contract (ISSUE 5): NaN grads and loss spikes are survived —
training continues with the anomaly counted in telemetry — and the
guard's happy path adds zero device dispatches/readbacks per step
(pinned here with jax's transfer guard: a device→host transfer inside
the guarded step loop would raise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import make_micro_trainer

from d9d_tpu.loop import CausalLMTask
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.loop.train_step import build_train_step
from d9d_tpu.resilience import HostAnomalyGuard
from d9d_tpu.resilience.chaos import ChaosScaleTask
from d9d_tpu.telemetry import Telemetry, set_telemetry


# -- direct step-fn level -------------------------------------------------

class _ToyTask(TrainTask):
    def prepare_batch(self, batch):
        return batch

    def loss_fn(self, module, params, mb, rng):
        y = module.apply(params, mb["x"])
        return jnp.sum((y - mb["y"]) ** 2), jnp.float32(mb["x"].shape[0]), {}


def _toy_setup(policy):
    import flax.linen as nn

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    module = Lin()
    opt = optax.adam(1e-2)
    x = jnp.ones((2, 4, 8))
    y = jnp.zeros((2, 4, 4))
    params = module.init(jax.random.PRNGKey(0), x[0])
    opt_state = jax.jit(opt.init)(params)
    step = build_train_step(
        module=module, task=_ToyTask(), optimizer=opt,
        num_microbatches=2, anomaly_policy=policy,
    )
    return step, params, opt_state, {"x": x, "y": y}


def test_skip_step_freezes_params_and_moments_bitwise():
    step, params, opt_state, batch = _toy_setup("skip_step")
    rng = jax.random.PRNGKey(1)
    params, opt_state, _ = step(params, opt_state, batch, rng)
    p_host = jax.tree.map(np.asarray, params)
    s_host = jax.tree.map(np.asarray, opt_state)
    bad = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
    params, opt_state, m = step(params, opt_state, bad, rng)
    assert float(m["resilience/anomaly"]) == 1.0
    assert float(m["resilience/anomaly_streak"]) == 1.0
    for a, b in zip(jax.tree.leaves(p_host), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    for a, b in zip(jax.tree.leaves(s_host), jax.tree.leaves(opt_state)):
        np.testing.assert_array_equal(a, np.asarray(b))
    # streak resets on the next clean step, total persists
    params, opt_state, m = step(params, opt_state, batch, rng)
    assert float(m["resilience/anomaly_streak"]) == 0.0
    assert float(m["resilience/anomaly_total"]) == 1.0
    assert np.isfinite(float(m["loss"]))


def test_warn_policy_applies_the_poisoned_update():
    step, params, opt_state, batch = _toy_setup("warn")
    rng = jax.random.PRNGKey(1)
    bad = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
    params, opt_state, m = step(params, opt_state, bad, rng)
    assert float(m["resilience/anomaly"]) == 1.0
    # warn only flags: the NaN update went through
    leaves = [np.asarray(x) for x in jax.tree.leaves(params)]
    assert any(not np.isfinite(x).all() for x in leaves)


def test_happy_path_adds_zero_dispatches_and_readbacks():
    """The serve-style pin: after warmup, guarded steps run under a
    device→host transfer guard — any readback the guard added would
    raise — and the jitted call count is exactly one per step."""
    step, params, opt_state, batch = _toy_setup("skip_step")
    rng = jax.random.PRNGKey(1)
    params, opt_state, m = step(params, opt_state, batch, rng)  # compile
    jax.block_until_ready(m["loss"])

    calls = 0
    inner = step.fn

    def counting(*args):
        nonlocal calls
        calls += 1
        return inner(*args)

    step.fn = counting
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            params, opt_state, m = step(params, opt_state, batch, rng)
    jax.block_until_ready(m["loss"])
    assert calls == 3  # one dispatch per step, nothing extra


# -- host-side spike detector --------------------------------------------

def test_host_spike_detector_rolls_and_triggers():
    tele = Telemetry()
    guard = HostAnomalyGuard(
        policy="rollback", rollback_after=2, spike_factor=10.0,
        spike_window=8, telemetry=tele,
    )
    for s in range(6):
        assert guard.observe(s, {"loss": 1.0 + 0.01 * s}) == "ok"
    # a single 100x spike warns; the second consecutive one rolls back
    assert guard.observe(6, {"loss": 100.0}) == "warn"
    assert guard.observe(7, {"loss": 100.0}) == "rollback"
    assert tele.registry.counter("resilience/loss_spikes").value == 2
    # the spike never entered the baseline window
    assert guard.observe(8, {"loss": 1.0}) == "ok"


def test_host_guard_counts_device_totals():
    tele = Telemetry()
    guard = HostAnomalyGuard(policy="skip_step", telemetry=tele)
    guard.observe(1, {"loss": float("nan"), "resilience/anomaly": 1.0,
                      "resilience/anomaly_streak": 1.0,
                      "resilience/anomaly_total": 1.0})
    # cadence gap: device total jumped by 3 — the counter keeps the delta
    guard.observe(5, {"loss": 2.0, "resilience/anomaly": 1.0,
                      "resilience/anomaly_streak": 2.0,
                      "resilience/anomaly_total": 4.0})
    assert tele.registry.counter("resilience/anomalies").value == 4.0


# -- trainer e2e ----------------------------------------------------------

def test_trainer_survives_nan_steps_with_skip_step():
    task = ChaosScaleTask(
        CausalLMTask(), {3: float("nan"), 4: float("nan")}
    )
    trainer = make_micro_trainer(task, anomaly_policy="skip_step")
    history = trainer.train()
    assert history[-1]["step"] == trainer.config.total_steps
    anomalous = [h for h in history if h.get("resilience/anomaly") == 1.0]
    assert len(anomalous) == 2
    assert history[-1]["resilience/anomaly_total"] == 2.0
    # training continued and recovered: every post-anomaly loss is finite
    post = [h["loss"] for h in history if h["step"] > anomalous[-1]["step"]]
    assert post and all(np.isfinite(v) for v in post)


def test_trainer_rollback_restores_and_completes(tmp_path):
    hub = set_telemetry(Telemetry())
    try:
        task = ChaosScaleTask(
            CausalLMTask(),
            {5: float("nan"), 6: float("nan"), 7: float("nan")},
        )
        trainer = make_micro_trainer(
            task,
            anomaly_policy="rollback",
            anomaly_rollback_after=2,
            checkpoint_dir=str(tmp_path),
            checkpoint_every_steps=2,
            checkpoint_async=False,
        )
        history = trainer.train()
        trainer.close()
        assert hub.registry.counter("resilience/rollbacks").value >= 1
        assert history[-1]["step"] == trainer.config.total_steps
        assert np.isfinite(history[-1]["loss"])
        # the rolled-back step re-ran: its step id appears twice
        steps = [h["step"] for h in history]
        assert len(steps) > len(set(steps))
    finally:
        set_telemetry(Telemetry())  # fresh hub for later tests
