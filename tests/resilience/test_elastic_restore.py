"""Topology-independent checkpoint restore (docs/design/elasticity.md):
manifest v2 records the saving mesh, restore detects a topology
mismatch and reshard-on-loads — including the e2e chaos leg the ISSUE
acceptance names: train on mesh A → SIGTERM emergency save → resume on
mesh B (different ``dp_replicate``, ZeRO on) with losses tracking the
uninterrupted run; plus the memory-bounded chunked redistribution and
the unverified-restore operator signal."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import MicroLoaderProvider, MicroProvider

from d9d_tpu.core.mesh import MeshParameters
from d9d_tpu.loop import AdamWProvider, CausalLMTask, Trainer, TrainerConfig
from d9d_tpu.loop.components.checkpointer import StateCheckpointer
from d9d_tpu.resilience import (
    ManifestVersionError,
    TrainingPreempted,
    job_mesh_spec,
    manifest_mesh,
    redistribute_tree,
    topology_mismatch,
    tree_mesh_summary,
)
from d9d_tpu.resilience.chaos import sigterm_at_step
from d9d_tpu.resilience.manifest import (
    MANIFEST_NAME,
    read_manifest,
    validate_checkpoint_dir,
    write_manifest,
)
from d9d_tpu.telemetry import get_telemetry


def _trainer(tmp_path, *, dp, zero, total_steps=6, **overrides):
    ctx = MeshParameters(dp_replicate=dp).build(jax.devices()[:dp])
    defaults = dict(
        global_batch_size=8,
        microbatch_size=8,
        seq_len=8,
        total_steps=total_steps,
        log_every=1,
        prefetch_batches=0,
        telemetry_console=False,
        gc_every_steps=None,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every_steps=100,  # only emergency/final saves fire
        checkpoint_async=False,
        zero_sharding=zero,
    )
    defaults.update(overrides)
    return Trainer(
        ctx=ctx,
        config=TrainerConfig(**defaults),
        model_provider=MicroProvider(),
        dataset_provider=MicroLoaderProvider(),
        task=CausalLMTask(),
        optimizer_provider=AdamWProvider(),
    )


# ---------------------------------------------------------------------------
# manifest v2 units


def test_manifest_v2_records_saving_mesh(tmp_path):
    step_dir = tmp_path / "save_3"
    step_dir.mkdir()
    (step_dir / "payload.bin").write_bytes(b"x" * 64)
    ctx = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
    arrays = {"w": jax.device_put(
        jnp.zeros((4, 4)), NamedSharding(ctx.mesh, P())
    )}
    spec = job_mesh_spec(ctx=ctx, zero_sharding=True, arrays=arrays)
    write_manifest(step_dir, step=3, mesh=spec)
    manifest = read_manifest(step_dir)
    assert manifest["version"] == 2
    mesh = manifest["mesh"]
    assert mesh["zero_sharding"] is True
    assert mesh["device_count"] == 2
    assert mesh["mesh_parameters"]["dp_replicate"] == 2
    assert mesh["axes"]["dp_r"] == 2
    # per-leaf shardings recorded (diagnostic block)
    assert any("w" in k for k in mesh["leaf_shardings"])
    assert validate_checkpoint_dir(step_dir) is True
    assert manifest_mesh(step_dir) == mesh


def test_manifest_v1_files_stay_readable(tmp_path):
    """≤-current rule (mirrors the telemetry schema): a v1 manifest —
    no version-gated fields beyond the inventory — validates fine."""
    step_dir = tmp_path / "save_1"
    step_dir.mkdir()
    (step_dir / "payload.bin").write_bytes(b"y" * 32)
    write_manifest(step_dir, step=1)  # no mesh block
    manifest = json.loads((step_dir / MANIFEST_NAME).read_text())
    manifest["version"] = 1
    manifest.pop("mesh", None)
    (step_dir / MANIFEST_NAME).write_text(json.dumps(manifest))
    assert validate_checkpoint_dir(step_dir) is True
    assert manifest_mesh(step_dir) is None  # pre-v2: no topology info


def test_future_manifest_version_skips_without_pruning(tmp_path):
    """A manifest from a NEWER writer raises ManifestVersionError — not
    an integrity failure: the walk-back must skip the step, never prune
    an intact checkpoint it merely cannot read."""
    step_dir = tmp_path / "save_2"
    step_dir.mkdir()
    (step_dir / "payload.bin").write_bytes(b"z" * 16)
    write_manifest(step_dir, step=2)
    manifest = json.loads((step_dir / MANIFEST_NAME).read_text())
    manifest["version"] = 99
    (step_dir / MANIFEST_NAME).write_text(json.dumps(manifest))
    from d9d_tpu.resilience import CheckpointIntegrityError

    with pytest.raises(ManifestVersionError) as exc:
        validate_checkpoint_dir(step_dir)
    assert not isinstance(exc.value, CheckpointIntegrityError)
    assert manifest_mesh(step_dir) is None  # best-effort accessor


def test_topology_mismatch_detection():
    ctx2 = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
    arrays2 = {"w": jax.device_put(
        jnp.zeros((8,)), NamedSharding(ctx2.mesh, P())
    )}
    spec2 = job_mesh_spec(ctx=ctx2, arrays=arrays2)
    assert not topology_mismatch(spec2, tree_mesh_summary(arrays2))
    ctx4 = MeshParameters(dp_replicate=4).build(jax.devices()[:4])
    arrays4 = {"w": jax.device_put(
        jnp.zeros((8,)), NamedSharding(ctx4.mesh, P())
    )}
    assert topology_mismatch(spec2, tree_mesh_summary(arrays4))
    # unknown on either side is conservative: no mismatch
    assert not topology_mismatch(None, tree_mesh_summary(arrays4))
    assert not topology_mismatch(spec2, None)


# ---------------------------------------------------------------------------
# memory-bounded redistribution


def test_redistribute_tree_chunks_under_budget():
    ctx_src = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
    src_mesh = ctx_src.mesh
    ctx_dst = MeshParameters(dp_replicate=4).build(jax.devices()[:4])
    dst_mesh = ctx_dst.mesh
    data = np.arange(64 * 16, dtype=np.float32).reshape(64, 16)
    leaf = jax.device_put(jnp.asarray(data), NamedSharding(src_mesh, P()))
    target = NamedSharding(dst_mesh, P())
    nbytes = data.nbytes  # 4 KiB
    budget = nbytes // 8  # forces 8 chunks of 8 rows
    tele = get_telemetry()
    chunks_before = tele.counter("resilience/reshard_chunks").value
    out = redistribute_tree(
        {"w": leaf}, {"w": target}, hbm_budget_bytes=budget
    )
    np.testing.assert_array_equal(np.asarray(out["w"]), data)
    assert out["w"].sharding.is_equivalent_to(target, 2)
    assert tele.counter("resilience/reshard_chunks").value \
        - chunks_before == 8
    # already-placed leaves skip entirely (no extra chunks)
    before = tele.counter("resilience/reshard_chunks").value
    out2 = redistribute_tree(out, {"w": target}, hbm_budget_bytes=budget)
    assert out2["w"] is out["w"]
    assert tele.counter("resilience/reshard_chunks").value == before


def test_cross_mesh_checkpoint_restore_with_budget(tmp_path):
    """Save on mesh A (2 devices), restore onto mesh B (4 devices) with
    a tight HBM budget: the manifest's mesh block flags the mismatch,
    the oversized replicated leaf restores through the device-sharded
    staging layout, and the chunked re-place bounds every transfer."""
    ctx_a = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
    data = np.arange(64 * 128, dtype=np.float32).reshape(64, 128)
    arrays = {
        "w": jax.device_put(
            jnp.asarray(data), NamedSharding(ctx_a.mesh, P())
        ),
        "count": jax.device_put(
            jnp.int32(7), NamedSharding(ctx_a.mesh, P())
        ),
    }
    ckpt = StateCheckpointer(tmp_path / "ckpt", async_save=False)
    ckpt.save(
        1, arrays, {"step": 1},
        mesh_spec=job_mesh_spec(ctx=ctx_a, arrays=arrays),
    )
    ckpt.close()
    saved_mesh = manifest_mesh(tmp_path / "ckpt" / "save_1")
    assert saved_mesh["device_count"] == 2

    ctx_b = MeshParameters(dp_replicate=4).build(jax.devices()[:4])
    target = {
        "w": jax.device_put(
            jnp.zeros_like(data), NamedSharding(ctx_b.mesh, P())
        ),
        "count": jax.device_put(
            jnp.int32(0), NamedSharding(ctx_b.mesh, P())
        ),
    }
    tele = get_telemetry()
    restores_before = tele.counter("resilience/reshard_restores").value
    chunks_before = tele.counter("resilience/reshard_chunks").value
    ckpt2 = StateCheckpointer(tmp_path / "ckpt", async_save=False)
    step, restored, meta = ckpt2.restore(
        target, reshard_hbm_budget_bytes=4096
    )
    ckpt2.close()
    assert step == 1 and meta["step"] == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), data)
    assert int(restored["count"]) == 7
    # final placement is the live target's, on the NEW mesh
    assert restored["w"].sharding.is_equivalent_to(
        NamedSharding(ctx_b.mesh, P()), 2
    )
    assert tele.counter("resilience/reshard_restores").value \
        - restores_before == 1
    # 32 KiB leaf over a 4 KiB budget → the chunked path actually ran
    assert tele.counter("resilience/reshard_chunks").value \
        - chunks_before >= 8
    assert tele.gauge("resilience/reshard_bytes").value >= data.nbytes


def test_unverified_restore_counts_and_restores(tmp_path):
    ctx = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
    arrays = {"w": jax.device_put(
        jnp.arange(8.0), NamedSharding(ctx.mesh, P())
    )}
    ckpt = StateCheckpointer(tmp_path / "ckpt", async_save=False)
    ckpt.save(2, arrays, {"step": 2}, mesh_spec=job_mesh_spec(ctx=ctx))
    ckpt.close()
    (tmp_path / "ckpt" / "save_2" / MANIFEST_NAME).unlink()
    tele = get_telemetry()
    before = tele.counter("resilience/unverified_restore").value
    ckpt2 = StateCheckpointer(tmp_path / "ckpt", async_save=False)
    # explicit-step restore (previously completely silent when
    # unverified) now counts the attempt — and still restores
    step, restored, _meta = ckpt2.restore(arrays, step=2)
    assert step == 2
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(8.0)
    )
    assert tele.counter("resilience/unverified_restore").value \
        - before == 1
    # resume-latest counts it too
    ckpt2.restore(arrays)
    ckpt2.close()
    assert tele.counter("resilience/unverified_restore").value \
        - before == 2


# ---------------------------------------------------------------------------
# the e2e chaos leg (ISSUE acceptance): mesh A → SIGTERM → mesh B


def _losses(history):
    return {h["step"]: h["loss"] for h in history}


def _run_cross_topology(tmp_path, *, dp_save, dp_restore, zero):
    baseline = _trainer(
        tmp_path / "base", dp=dp_save, zero=zero, checkpoint_dir=None
    )
    base_losses = _losses(baseline.train())
    baseline.close()

    interrupted = _trainer(tmp_path, dp=dp_save, zero=zero)
    sigterm_at_step(interrupted.events, 3)
    with pytest.raises(TrainingPreempted) as exc:
        interrupted.train()
    interrupted.close()
    preempt_step = exc.value.step
    assert 0 < preempt_step < 6
    # the emergency save carries the manifest v2 mesh block
    saved_mesh = manifest_mesh(
        tmp_path / "ckpt" / f"save_{preempt_step}"
    )
    assert saved_mesh is not None
    assert saved_mesh["device_count"] == dp_save
    assert saved_mesh["zero_sharding"] is zero

    tele = get_telemetry()
    reshards_before = tele.counter("resilience/reshard_restores").value
    resumed = _trainer(tmp_path, dp=dp_restore, zero=zero)
    resumed_losses = _losses(resumed.train())
    resumed.close()
    # the cross-topology restore went through the reshard path
    assert tele.counter("resilience/reshard_restores").value \
        > reshards_before
    # stateful-loader rewind + resharded params/moments: the resumed
    # run's losses track the uninterrupted run at ulp tolerance (the
    # residual is dp_r collective summation order)
    resumed_steps = sorted(resumed_losses)
    assert resumed_steps[0] == preempt_step + 1
    assert resumed_steps[-1] == 6
    for step in resumed_steps:
        np.testing.assert_allclose(
            resumed_losses[step], base_losses[step], rtol=2e-5,
            err_msg=f"step {step}",
        )


def test_sigterm_save_dp2_zero_resumes_on_dp1(tmp_path):
    """The acceptance leg: N-chip ZeRO-sharded emergency save resumes
    on fewer chips (sharding tables rebuilt for the new dp_replicate),
    losses tracking the uninterrupted run."""
    _run_cross_topology(tmp_path, dp_save=2, dp_restore=1, zero=True)


@pytest.mark.slow  # a third full micro-train; the dp1 leg covers tier-1
def test_sigterm_save_dp2_zero_resumes_on_dp4(tmp_path):
    """The grow direction: resume on MORE chips than saved."""
    _run_cross_topology(tmp_path, dp_save=2, dp_restore=4, zero=True)


@pytest.mark.slow
def test_sigterm_save_dp4_zero_resumes_on_dp2(tmp_path):
    """The inverse of the inverse: a wider ZeRO save shrinking."""
    _run_cross_topology(tmp_path, dp_save=4, dp_restore=2, zero=True)
