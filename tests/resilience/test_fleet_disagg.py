"""Disaggregated prefill→decode serving (resilience/elastic.py roles +
KV page shipment, docs/design/elasticity.md "Disaggregated serving"):
the prefill leg emits the first token and hands its filled pages off to
a decode replica token-identically; the fleet-wide prefix directory
ships a shared prompt's pages instead of recomputing them (once per
FLEET); every failure point — version skew, corrupt shipment, a prefill
replica dying mid-handoff — degrades to the continuation re-prefill
with zero leaked pages; placement is KV-capacity-aware; and the
autopilot scales the two pools independently with distinct decision
kinds. Fully deterministic: fake clock, scripted traffic, exact token
oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from tests.resilience.conftest import PagedToyLM, paged_toy_expected

from d9d_tpu.loop.serve import ContinuousBatcher
from d9d_tpu.resilience import (
    AutopilotConfig,
    FleetAutopilot,
    ServingFleet,
    WeightPublisher,
    read_decisions,
)
from d9d_tpu.resilience.chaos import (
    corrupt_handoff_payload,
    kill_prefill_mid_handoff,
)
from d9d_tpu.telemetry import (
    JsonlSink,
    SloMonitor,
    SloPolicy,
    Telemetry,
    get_telemetry,
    iter_events,
    set_telemetry,
)


@pytest.fixture(autouse=True)
def _fresh_hub():
    old = get_telemetry()
    hub = set_telemetry(Telemetry())
    yield hub
    set_telemetry(old)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


_MODEL = PagedToyLM()
_Z = jnp.zeros((2, 1), jnp.int32)
_PARAMS = _MODEL.init(jax.random.PRNGKey(0), _Z, _Z).get("params", {})


def make_paged_batcher(params=None, **kw):
    kw.setdefault("batch_size", 2)
    kw.setdefault("chunk_size", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 17)
    return ContinuousBatcher(
        _MODEL, params if params is not None else dict(_PARAMS), **kw
    )


def _drain(fleet, frids, rounds=400):
    for _ in range(rounds):
        fleet.step()
        if all(fleet.finished(f) for f in frids):
            return
    raise RuntimeError("fleet did not drain the submitted requests")


def _assert_no_leaks(fleet):
    """Zero leaked pages on every live replica: only prefix-cache
    entries may hold pages after a full drain, and the refcount audit
    must balance exactly."""
    for i in fleet.live_replicas:
        kv = fleet._replicas[i]._kv
        kv.check_invariants()
        assert kv.pages_in_use == len(kv._entries), (
            f"replica {i} leaked pages: {kv.pages_in_use} in use, "
            f"{len(kv._entries)} prefix entries"
        )


# ---------------------------------------------------------------------------
# handoff token-identity


@pytest.mark.parametrize("k", [1, 4])
def test_handoff_token_identity_vs_unified(k, tmp_path):
    """The tentpole pin: a prefill→decode fleet must emit EXACTLY what
    a single unified replica emits, across chunk sizes — the handoff
    (first-token leg, page shipment, decode continuation) is invisible
    in the token stream. The ``handoff`` trace milestone rides the
    ORIGINAL trace id."""
    prompts = [
        [3, 5, 7, 11, 2, 9, 4],
        [1, 2],
        [8, 8, 8, 8, 8, 8, 8, 8, 6],
        [13, 4, 2],
    ]
    n = 6
    unified = ServingFleet()
    unified.add_replica(make_paged_batcher(chunk_size=k))
    u_frids = [unified.submit(p, max_new_tokens=n) for p in prompts]
    u_out = unified.drain()

    hub = get_telemetry()
    sink = hub.add_sink(JsonlSink(tmp_path, run_name="disagg"))
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(chunk_size=k), role="prefill")
    fleet.add_replica(make_paged_batcher(chunk_size=k), role="decode")
    frids = [fleet.submit(p, max_new_tokens=n) for p in prompts]
    out = fleet.drain()
    for uf, f, p in zip(u_frids, frids, prompts):
        want = paged_toy_expected(p, n)
        assert u_out[uf] == want, p
        assert out[f] == want, p
    snap = hub.registry.snapshot()["counters"]
    # prompts with at least one full page ship it; shorter ones carry
    # zero pages and take the (token-identical) re-prefill path
    n_shipped = sum(1 for p in prompts if (len(p) - 1) // 4 > 0)
    assert snap["serve/fleet_handoffs"] == n_shipped
    assert snap["serve/fleet_handoffs"] \
        + snap.get("serve/fleet_handoff_fallbacks", 0) == len(prompts)
    assert snap.get("serve/handoff_checksum_failures", 0) == 0
    _assert_no_leaks(fleet)
    hub.flush(step=0)
    hub.remove_sink(sink)
    traces = {}
    for ev in iter_events(sink.path):
        if ev["kind"] == "request_trace":
            traces.setdefault(ev["trace_id"], []).append(ev["event"])
    handed = [evs for evs in traces.values() if "handoff" in evs]
    assert len(handed) == len(prompts)
    for evs in handed:
        # one continuous track under the ORIGINAL id: the prefill leg
        # (submit..first_token..finish), the handoff milestone, then
        # the decode continuation ending in the real finish
        assert evs[0] == "submit"
        assert evs.index("first_token") < evs.index("handoff")
        assert evs[-1] == "finish"


def test_prefill_role_runs_first_token_leg():
    """Stage routing: with a prefill replica live, a new request's
    first-token leg lands there (TTFT at the prefill pool), and the
    remaining budget runs on the decode replica after the handoff."""
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    prompt = [3, 5, 7, 11, 2]
    frid = fleet.submit(prompt, max_new_tokens=5)
    assert fleet._reqs[frid].stage == "prefill"
    assert fleet._reqs[frid].replica == 0
    _drain(fleet, [frid])
    assert fleet.outputs(frid) == paged_toy_expected(prompt, 5)
    # the prefill replica emitted exactly the first token; the decode
    # replica emitted the rest
    assert fleet._replicas[0].stats.emitted_tokens == 1
    assert fleet._replicas[1].stats.emitted_tokens == 4
    _assert_no_leaks(fleet)


def test_single_token_budget_finishes_at_prefill():
    """max_new_tokens=1 never hands off: the first token IS the
    request; the prefill leg retires it in place."""
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    prompt = [4, 9, 1]
    frid = fleet.submit(prompt, max_new_tokens=1)
    _drain(fleet, [frid])
    assert fleet.outputs(frid) == paged_toy_expected(prompt, 1)
    snap = get_telemetry().registry.snapshot()["counters"]
    assert snap.get("serve/fleet_handoffs", 0) == 0
    assert snap.get("serve/fleet_handoff_fallbacks", 0) == 0
    assert fleet._replicas[1].stats.emitted_tokens == 0


# ---------------------------------------------------------------------------
# weights-version pinning


def test_weights_publish_boundary_forces_reprefill():
    """A handoff whose shipment was minted under a superseded weights
    generation must NOT import (cached KV is weights-dependent): the
    continuation re-prefills instead, token-identically — same
    invariant as install_weights prefix invalidation."""
    pub = WeightPublisher()
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    prompt = [3, 5, 7, 11, 2, 9, 4, 6, 1]
    frid = fleet.submit(prompt, max_new_tokens=6)
    # run until the prefill LEG is done but the handoff has not been
    # polled yet, then move the weights generation
    for _ in range(50):
        fleet.step()
        req = fleet._reqs.get(frid)
        if req is not None and req.stage == "prefill" \
                and req.replica is not None \
                and req.local_rid in fleet._replicas[req.replica].done:
            break
    else:
        pytest.fail("prefill leg never finished")
    pub.publish(dict(_PARAMS))
    _drain(fleet, [frid])
    assert fleet.outputs(frid) == paged_toy_expected(prompt, 6)
    snap = get_telemetry().registry.snapshot()["counters"]
    # the stale-generation pages never cross: the exporter's staged
    # publish invalidates them at the boundary, so the handoff ships
    # nothing and the decode replica re-prefills under the new weights
    assert snap["serve/fleet_handoff_fallbacks"] >= 1
    assert snap.get("serve/fleet_handoffs", 0) == 0
    assert snap.get("serve/handoff_imports", 0) == 0
    _assert_no_leaks(fleet)


def test_fleet_directory_invalidated_on_publish():
    """A weight publish clears the fleet prefix directory fleet-wide
    (entries describe KV minted under the OLD generation); it
    repopulates from post-publish caches on later rounds."""
    pub = WeightPublisher()
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_paged_batcher(), role="unified")
    fleet.add_replica(make_paged_batcher(), role="unified")
    prompt = [2] * 9
    frid = fleet.submit(prompt, max_new_tokens=3)
    _drain(fleet, [frid])
    fleet.step()
    assert len(fleet._prefix_dir) >= 1
    pub.publish(dict(_PARAMS))
    fleet.step()
    assert fleet._prefix_dir == {}
    snap = get_telemetry().registry.snapshot()["counters"]
    assert snap["serve/fleet_prefix_invalidations"] == 1
    # post-publish traffic repopulates the directory under the new
    # generation (replicas applied the publish at their boundaries)
    frid2 = fleet.submit([5] * 9, max_new_tokens=3)
    _drain(fleet, [frid2])
    fleet.step()
    assert len(fleet._prefix_dir) >= 1
    assert fleet.outputs(frid2) == paged_toy_expected([5] * 9, 3)


# ---------------------------------------------------------------------------
# fleet-wide prefix cache


def test_shared_prompt_prefills_once_per_fleet():
    """Local miss + directory hit ships the prefix pages: the second
    replica's admission prefix-hits pages it never computed."""
    hub = get_telemetry()
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="unified")
    fleet.add_replica(make_paged_batcher(), role="unified")
    shared = [3, 5, 7, 11, 2, 9, 4, 6]  # two full pages
    f1 = fleet.submit(shared + [1], max_new_tokens=4)
    _drain(fleet, [f1])
    # least-loaded routing spreads the next two across both replicas:
    # the one that never saw `shared` must get its pages shipped
    f2 = fleet.submit(shared + [8], max_new_tokens=4)
    f3 = fleet.submit(shared + [13], max_new_tokens=4)
    _drain(fleet, [f2, f3])
    for f, tail in ((f1, [1]), (f2, [8]), (f3, [13])):
        assert fleet.outputs(f) == paged_toy_expected(shared + tail, 4)
    snap = hub.registry.snapshot()["counters"]
    assert snap["serve/fleet_prefix_hits"] >= 1
    assert snap.get("serve/fleet_prefix_misses", 0) == 0
    # both allocators saw prefix hits: one locally, one via shipment
    assert all(
        fleet._replicas[i]._kv.prefix_hits >= 1 for i in (0, 1)
    )
    _assert_no_leaks(fleet)


def test_dead_owner_never_wedges_a_waiter():
    """Directory entries owned by a dead replica are dropped at the
    death, and a placement that would have shipped from it falls back
    to a local prefill — never an error, never a wedge."""
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="unified")
    fleet.add_replica(make_paged_batcher(), role="unified")
    shared = [7] * 9
    f1 = fleet.submit(shared, max_new_tokens=3)
    _drain(fleet, [f1])
    fleet.step()
    owner = next(iter(fleet._prefix_dir.values()))
    # hard-kill the owner (no drain): its pages are gone with it
    fleet._live.discard(owner)
    fleet._recover_killed(owner)
    assert all(i != owner for i in fleet._prefix_dir.values())
    f2 = fleet.submit(shared, max_new_tokens=3)
    _drain(fleet, [f2])
    assert fleet.outputs(f2) == paged_toy_expected(shared, 3)
    _assert_no_leaks(fleet)


# ---------------------------------------------------------------------------
# KV-capacity-aware placement


def test_placement_ranks_full_pool_behind_capacity():
    """A paged replica with zero free pages ranks behind one with
    headroom — the request must not accept a head-of-line wait when a
    peer could run it now."""
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(num_pages=9))   # 8 allocatable
    fleet.add_replica(make_paged_batcher(num_pages=9))
    prompt = [9, 8, 7, 6, 5]
    # baseline: both pools free -> least-loaded tiebreak picks 0
    f0 = fleet.submit(prompt, max_new_tokens=2)
    assert fleet._reqs[f0].replica == 0
    _drain(fleet, [f0])
    # fill replica 0's pool completely with pinned prefix chains
    kv0 = fleet._replicas[0]._kv
    kv0.invalidate_prefix_cache()
    assert kv0.import_pages(list(range(16)), 4) is not None
    assert kv0.import_pages(list(range(100, 116)), 4) is not None
    assert kv0.pages_free_after_flush() == 0
    # same submit now ranks replica 1 first despite the index tiebreak
    f1 = fleet.submit(prompt, max_new_tokens=2)
    assert fleet._reqs[f1].replica == 1
    _drain(fleet, [f1])
    assert fleet.outputs(f1) == paged_toy_expected(prompt, 2)


# ---------------------------------------------------------------------------
# chaos: the new failure surface


def test_corrupt_handoff_payload_falls_back_token_identically(tmp_path):
    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path / "flight")
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    corrupt_handoff_payload(fleet)
    prompt = [3, 5, 7, 11, 2, 9]
    frid = fleet.submit(prompt, max_new_tokens=6)
    _drain(fleet, [frid])
    assert fleet.outputs(frid) == paged_toy_expected(prompt, 6)
    snap = hub.registry.snapshot()["counters"]
    # the checksum caught the flip BEFORE anything was written; the
    # continuation re-prefilled on the decode replica
    assert snap["serve/handoff_checksum_failures"] == 1
    assert snap["serve/fleet_handoff_fallbacks"] == 1
    assert snap.get("serve/fleet_handoffs", 0) == 0
    assert fleet.live_replicas == (0, 1)  # corruption kills no replica
    _assert_no_leaks(fleet)


def test_kill_prefill_mid_handoff_recovers_via_continuation(tmp_path):
    """The prefill replica dies with exported-but-unimported pages in
    flight: the shipment is lost, every in-flight request recovers via
    continuation onto the survivor, zero pages leak, and the flight
    recorder explains the death."""
    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path / "flight")
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    kill_prefill_mid_handoff(fleet, 0)
    prompts = [[3, 5, 7, 11, 2, 9], [8, 1]]
    frids = [fleet.submit(p, max_new_tokens=6) for p in prompts]
    _drain(fleet, frids)
    for f, p in zip(frids, prompts):
        assert fleet.outputs(f) == paged_toy_expected(p, 6), p
    assert fleet.live_replicas == (1,)
    assert 0 in fleet.dead
    snap = hub.registry.snapshot()["counters"]
    assert snap["serve/fleet_handoff_fallbacks"] >= 1
    assert snap["serve/fleet_replica_deaths"] == 1
    _assert_no_leaks(fleet)
    assert (tmp_path / "flight"
            / "flight_recorder_replica_death.json").exists()


# ---------------------------------------------------------------------------
# role-aware autopilot


def _burn_monitor(clock):
    return SloMonitor(
        [
            SloPolicy(name="ttft_p99", kind="quantile",
                      metric="serve/ttft_s", quantile=0.99,
                      target=0.5, window_s=4.0),
            SloPolicy(name="tpot_p99", kind="quantile",
                      metric="serve/tpot_s", quantile=0.99,
                      target=0.1, window_s=4.0),
        ],
        clock=clock,
    )


def test_autopilot_scales_pools_independently(tmp_path):
    """TTFT burn grows the PREFILL pool, TPOT burn grows the DECODE
    pool — distinct decision kinds in the log; idle shrink respects the
    per-role minimums."""
    hub = get_telemetry()
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish(dict(_PARAMS))
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    monitor = _burn_monitor(clock).attach(hub)
    log = tmp_path / "decisions.jsonl"
    FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_paged_batcher(params=dict(_PARAMS)),
        config=AutopilotConfig(
            grow_after_s=3.0, cooldown_s=6.0, min_replicas=2,
            max_replicas=4, idle_after_s=5.0, idle_queue_depth=0,
            idle_slot_utilization=0.5, eval_interval_s=1.0,
            prefill_policies=("ttft_p99",), decode_policies=("tpot_p99",),
            min_prefill_replicas=1, min_decode_replicas=1,
        ),
        decision_log=log, clock=clock,
    ).attach()

    def tick(rounds, *, ttft=None, tpot=None):
        for _ in range(rounds):
            if ttft is not None:
                hub.observe("serve/ttft_s", ttft)
            if tpot is not None:
                hub.observe("serve/tpot_s", tpot)
            fleet.step()
            clock.advance(1.0)

    tick(6, ttft=2.0)  # sustained TTFT burn -> prefill capacity
    assert fleet._roles[max(fleet.live_replicas)] == "prefill"
    tick(8)            # cooldown + window age-out
    tick(6, tpot=1.0)  # sustained TPOT burn -> decode capacity
    assert fleet._roles[max(fleet.live_replicas)] == "decode"
    assert len(fleet.live_replicas) == 4
    # sustained idle: shrink back down, but NEVER through a role floor
    tick(40)
    assert len(fleet.live_replicas) == 2
    roles_left = sorted(fleet._role(i) for i in fleet.live_replicas)
    assert roles_left == ["decode", "prefill"]
    actions = [d["action"] for d in read_decisions(log)]
    assert "grow_prefill" in actions and "grow_decode" in actions
    shrink_kinds = {a for a in actions if a.startswith("shrink")}
    assert shrink_kinds <= {"shrink", "shrink_prefill", "shrink_decode"}
    assert len([a for a in actions if a.startswith("shrink")]) == 2


def test_replica_health_reports_roles():
    fleet = ServingFleet()
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    fleet.add_replica(make_paged_batcher())
    health = fleet.replica_health()
    assert health["roles"] == {"prefill": 1, "decode": 1, "unified": 1}
    by_idx = {k: v["role"] for k, v in health["replicas"].items()}
    assert by_idx == {"0": "prefill", "1": "decode", "2": "unified"}


def test_add_replica_rejects_unknown_role():
    fleet = ServingFleet()
    with pytest.raises(ValueError, match="role"):
        fleet.add_replica(make_paged_batcher(), role="speculate")


# ---------------------------------------------------------------------------
# e2e acceptance: the whole story under one deterministic clock


def test_e2e_disagg_chaos_acceptance(tmp_path):
    """The ISSUE 20 acceptance leg: a role-split fleet under a mixed
    shared-prefix workload where (1) TTFT and TPOT burns are resolved
    by DIFFERENT scaling decisions, (2) the fleet prefix hit rate for
    the shared prompt is 1.0 (every shipment attempt lands), (3) every
    handoff is token-identical to the unified oracle, and (4) a
    corrupted shipment AND a prefill replica killed mid-handoff both
    recover via continuation with zero leaked pages and flight-recorder
    dumps explaining each action."""
    hub = get_telemetry()
    hub.configure_flight_recorder(tmp_path / "flight")
    clock = FakeClock()
    pub = WeightPublisher()
    pub.publish(dict(_PARAMS))
    fleet = ServingFleet(publisher=pub)
    fleet.add_replica(make_paged_batcher(), role="prefill")
    fleet.add_replica(make_paged_batcher(), role="decode")
    monitor = _burn_monitor(clock).attach(hub)
    log = tmp_path / "decisions.jsonl"
    FleetAutopilot(
        fleet, monitor,
        replica_factory=lambda p: make_paged_batcher(params=dict(_PARAMS)),
        config=AutopilotConfig(
            grow_after_s=3.0, cooldown_s=6.0, min_replicas=2,
            max_replicas=4, idle_after_s=1e9, eval_interval_s=1.0,
            prefill_policies=("ttft_p99",), decode_policies=("tpot_p99",),
            min_prefill_replicas=1, min_decode_replicas=1,
        ),
        decision_log=log, clock=clock,
    ).attach()

    shared = [3, 5, 7, 11, 2, 9, 4, 6]  # two full pages
    expected = {}

    def submit(prompt, n):
        frid = fleet.submit(prompt, max_new_tokens=n)
        expected[frid] = paged_toy_expected(prompt, n)
        return frid

    def tick(rounds, *, ttft=None, tpot=None):
        for _ in range(rounds):
            if ttft is not None:
                hub.observe("serve/ttft_s", ttft)
            if tpot is not None:
                hub.observe("serve/tpot_s", tpot)
            fleet.step()
            clock.advance(1.0)

    # phase 1: mixed-length shared-prefix ramp under a TTFT burn — the
    # autopilot must answer with PREFILL capacity
    for i, n in enumerate((3, 6, 4, 7)):
        submit(shared + [i + 1], n)
    tick(6, ttft=2.0)
    # phase 2: decode-side pressure — TPOT burn, DECODE capacity
    tick(8)
    for i, n in enumerate((5, 6)):
        submit(shared + [20 + i], n)
    tick(6, tpot=1.0)
    assert len(fleet.live_replicas) == 4
    # phase 3: corrupt the next shipment — checksum must catch it
    corrupt_handoff_payload(fleet)
    submit(shared + [27], 5)
    tick(8)
    # phase 4: kill a prefill replica at its next handoff
    prefills = [i for i in fleet.live_replicas
                if fleet._role(i) == "prefill"]
    kill_prefill_mid_handoff(fleet, prefills[0])
    # route the victim's leg onto the armed replica deterministically
    f_kill = fleet.submit(shared + [31], max_new_tokens=5)
    expected[f_kill] = paged_toy_expected(shared + [31], 5)
    if fleet._reqs[f_kill].replica != prefills[0]:
        fleet._chaos_kill_handoff = fleet._reqs[f_kill].replica
    tick(12)
    _drain(fleet, list(expected))
    # (3) every request token-identical to the unified oracle
    for frid, want in expected.items():
        assert fleet.outputs(frid) == want, frid
    # (1) different burns, different decisions
    actions = [d["action"] for d in read_decisions(log)]
    assert "grow_prefill" in actions and "grow_decode" in actions
    snap = hub.registry.snapshot()["counters"]
    # (2) shared-prefix shipments: every attempt landed
    assert snap["serve/fleet_prefix_hits"] >= 1
    assert snap.get("serve/fleet_prefix_misses", 0) == 0
    assert snap["serve/fleet_handoffs"] >= 1
    # (4) both chaos events resolved via fallback, with dumps
    assert snap["serve/handoff_checksum_failures"] >= 1
    assert snap["serve/fleet_handoff_fallbacks"] >= 2
    assert snap["serve/fleet_replica_deaths"] == 1
    assert len(fleet.dead) == 1
    _assert_no_leaks(fleet)
    assert (tmp_path / "flight"
            / "flight_recorder_replica_death.json").exists()
