"""ZeRO optimizer-state sharding (parallel/zero.py,
docs/design/zero_sharding.md): sharding-table construction, CPU
exactness of the sharded update vs the replicated path across
dp_replicate ∈ {1, 2, 4} for optax AdamW and StochasticAdamW, the PP
path with the anomaly guard firing on sharded moments, the
opt/state_bytes_per_chip gauge, and the split-update introspection mode.

Exactness contract (see the design page): dp_replicate=1 is BITWISE
(every constraint an identity); dp_replicate>1 agrees at ulp tolerance —
per-element arithmetic is order-preserved by construction, but XLA
re-partitions local reductions (grad-norm partials, CPU-backend fusion
tiling) when the program carries sharded operands.
"""

import flax.linen as nn
import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import AXIS_DP_REPLICATE, MeshParameters
from d9d_tpu.core.tree_sharding import replicate_uncommitted
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.loop.train_step import build_train_step
from d9d_tpu.optim import StochasticAdamW
from d9d_tpu.parallel.zero import (
    ZeroShardedOptimizer,
    _extend_spec,
    build_zero_sharding,
    place_tree,
    tree_bytes_per_device,
)

RTOL, ATOL = 1e-5, 1e-6


class ToyTask(TrainTask):
    def prepare_batch(self, batch):
        return batch

    def loss_fn(self, module, params, mb, rng):
        y = module.apply(params, mb["x"])
        return jnp.sum((y - mb["y"]) ** 2), jnp.float32(mb["x"].shape[0]), {}


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.Dense(16)(x)
        return nn.Dense(4)(jax.nn.relu(h))


def _make_opt(name):
    if name == "adamw":
        return optax.adamw(1e-2)
    # fp32 moments: the strict-parity recipe (bf16 moments round the
    # ulp-level re-partitioning noise across a whole bf16 ulp)
    return StochasticAdamW(1e-2, moment_dtype=jnp.float32, seed=3)


def _run(dp, zero_on, opt_name, *, steps=3, anomaly_policy=None,
         nan_at=None, split_update=False, max_grad_norm=1.0):
    """Train `steps` steps of the toy net; returns host param/state trees
    and the final metrics."""
    ctx = MeshParameters(dp_replicate=dp).build(jax.devices()[:dp])
    module = Net()
    x = jnp.ones((2, 4, 8)) * jnp.arange(8)
    y = jnp.linspace(0, 1, 2 * 4 * 4).reshape(2, 4, 4)
    params = jax.device_put(
        module.init(jax.random.PRNGKey(0), x[0]),
        NamedSharding(ctx.mesh, P()),
    )
    opt = _make_opt(opt_name)
    opt_state = replicate_uncommitted(jax.jit(opt.init)(params), ctx.mesh)
    zero = None
    if zero_on:
        zero = build_zero_sharding(
            params=params, opt_state=opt_state, mesh=ctx.mesh
        )
        opt_state = place_tree(opt_state, zero.state_shardings)
        opt = ZeroShardedOptimizer(opt, zero)
    step = build_train_step(
        module=module, task=ToyTask(), optimizer=opt, num_microbatches=2,
        anomaly_policy=anomaly_policy, zero=zero, split_update=split_update,
        max_grad_norm=max_grad_norm,
    )
    rng = jax.random.PRNGKey(1)
    metrics = None
    for i in range(steps):
        mb = {"x": x * jnp.nan, "y": y} if i == nan_at else {"x": x, "y": y}
        params, opt_state, metrics = step(params, opt_state, mb, rng)
    return (
        jax.tree.map(np.asarray, params),
        jax.tree.map(np.asarray, opt_state),
        {k: np.asarray(v) for k, v in metrics.items()},
    )


def _assert_trees(a, b, *, bitwise):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if bitwise:
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        else:
            np.testing.assert_allclose(
                np.asarray(x, np.float64), np.asarray(y, np.float64),
                rtol=RTOL, atol=ATOL,
            )


# -- sharding tables ------------------------------------------------------

class TestShardingTables:
    def test_extend_spec_picks_largest_divisible_dim(self):
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp_r", "tp"))
        # dim 1 is larger and divisible -> gets the axis
        assert _extend_spec(P(), (4, 16), mesh, "dp_r") == P(None, "dp_r")
        # existing sharding composes: dim 0 taken by tp -> extend there
        # only if divisibility after tp holds, else pick the free dim
        assert _extend_spec(P("tp"), (4, 16), mesh, "dp_r") == P(
            "tp", "dp_r"
        )
        # indivisible everywhere -> None
        assert _extend_spec(P(), (3, 5), mesh, "dp_r") is None
        # already sharded over the axis -> None (never double-shard)
        assert _extend_spec(P("dp_r"), (4, 16), mesh, "dp_r") is None

    def test_tables_skip_integer_riders(self):
        dp = 2
        ctx = MeshParameters(dp_replicate=dp).build(jax.devices()[:dp])
        module = Net()
        params = jax.device_put(
            module.init(jax.random.PRNGKey(0), jnp.ones((4, 8))),
            NamedSharding(ctx.mesh, P()),
        )
        opt = StochasticAdamW(1e-2)
        state = replicate_uncommitted(jax.jit(opt.init)(params), ctx.mesh)
        zero = build_zero_sharding(
            params=params, opt_state=state, mesh=ctx.mesh
        )
        assert zero.active and zero.axis == AXIS_DP_REPLICATE
        # count (int scalar) and the PRNG key must opt out; mu/nu shard
        flat = jax.tree.leaves(
            zero.state_shardings, is_leaf=lambda x: x is None
        )
        assert any(s is None for s in flat)
        shards = [s for s in flat if s is not None]
        assert shards, "no state leaf took the zero sharding"

        def has_axis(spec):
            return any(
                AXIS_DP_REPLICATE in (e if isinstance(e, tuple) else (e,))
                for e in spec
                if e is not None
            )

        assert all(has_axis(s.spec) for s in shards)

    def test_state_bytes_scale_with_dp(self):
        sizes = {}
        for dp in (1, 2, 4):
            ctx = MeshParameters(dp_replicate=dp).build(jax.devices()[:dp])
            module = Net()
            params = jax.device_put(
                module.init(jax.random.PRNGKey(0), jnp.ones((4, 8))),
                NamedSharding(ctx.mesh, P()),
            )
            opt = optax.adamw(1e-2)
            state = replicate_uncommitted(
                jax.jit(opt.init)(params), ctx.mesh
            )
            zero = build_zero_sharding(
                params=params, opt_state=state, mesh=ctx.mesh
            )
            state = place_tree(state, zero.state_shardings)
            sizes[dp] = tree_bytes_per_device(state)
        # moments dominate the toy state: per-chip bytes must drop by
        # roughly 1/N (scalars/odd leaves stay replicated)
        assert sizes[2] < 0.7 * sizes[1]
        assert sizes[4] < 0.7 * sizes[2]


# -- exactness vs the replicated path ------------------------------------

class TestExactness:
    @pytest.mark.parametrize("opt_name", ["adamw", "sadamw"])
    def test_dp1_bitwise(self, opt_name):
        base = _run(1, False, opt_name)
        zeroed = _run(1, True, opt_name)
        _assert_trees(base[0], zeroed[0], bitwise=True)
        _assert_trees(base[1], zeroed[1], bitwise=True)

    @pytest.mark.parametrize("opt_name", ["adamw", "sadamw"])
    def test_dp2_matches_replicated(self, opt_name):
        base = _run(2, False, opt_name)
        zeroed = _run(2, True, opt_name)
        _assert_trees(base[0], zeroed[0], bitwise=False)
        _assert_trees(base[1], zeroed[1], bitwise=False)

    @pytest.mark.slow
    @pytest.mark.parametrize("opt_name", ["adamw", "sadamw"])
    def test_dp4_matches_replicated(self, opt_name):
        base = _run(4, False, opt_name)
        zeroed = _run(4, True, opt_name)
        _assert_trees(base[0], zeroed[0], bitwise=False)
        _assert_trees(base[1], zeroed[1], bitwise=False)

    def test_guard_freezes_sharded_moments(self):
        """skip_step under ZeRO: a NaN step leaves params AND the
        sharded moments bitwise frozen, and the replicated comparison
        still holds across the anomaly."""
        base = _run(2, False, "adamw", steps=3, anomaly_policy="skip_step",
                    nan_at=1)
        zeroed = _run(2, True, "adamw", steps=3, anomaly_policy="skip_step",
                      nan_at=1)
        assert float(zeroed[2]["resilience/anomaly_total"]) == 1.0
        _assert_trees(base[0], zeroed[0], bitwise=False)
        _assert_trees(base[1], zeroed[1], bitwise=False)

    def test_guard_freeze_is_bitwise_under_zero(self):
        """The frozen step itself: state before == state after the NaN
        step, on the SHARDED trees (PR 5 freeze semantics)."""
        ctx = MeshParameters(dp_replicate=2).build(jax.devices()[:2])
        module = Net()
        x = jnp.ones((2, 4, 8))
        y = jnp.zeros((2, 4, 4))
        params = jax.device_put(
            module.init(jax.random.PRNGKey(0), x[0]),
            NamedSharding(ctx.mesh, P()),
        )
        opt = optax.adamw(1e-2)
        opt_state = replicate_uncommitted(
            jax.jit(opt.init)(params), ctx.mesh
        )
        zero = build_zero_sharding(
            params=params, opt_state=opt_state, mesh=ctx.mesh
        )
        opt_state = place_tree(opt_state, zero.state_shardings)
        opt = ZeroShardedOptimizer(opt, zero)
        step = build_train_step(
            module=module, task=ToyTask(), optimizer=opt,
            num_microbatches=2, anomaly_policy="skip_step", zero=zero,
        )
        rng = jax.random.PRNGKey(1)
        params, opt_state, _ = step(params, opt_state, {"x": x, "y": y}, rng)
        p_before = jax.tree.map(np.asarray, params)
        s_before = jax.tree.map(np.asarray, opt_state)
        params, opt_state, m = step(
            params, opt_state, {"x": x * jnp.nan, "y": y}, rng
        )
        assert float(m["resilience/anomaly"]) == 1.0
        _assert_trees(p_before, jax.tree.map(np.asarray, params), bitwise=True)
        _assert_trees(s_before, jax.tree.map(np.asarray, opt_state), bitwise=True)


# -- PP path (PipelinedOptimizer) ----------------------------------------

class TestPipelinedZero:
    def _run_pp(self, zero_axis, opt, nan_at=None, steps=2):
        from d9d_tpu.pipelining.training import PipelinedOptimizer

        mesh = Mesh(np.array(jax.devices()[:2]), (AXIS_DP_REPLICATE,))
        sh = NamedSharding(mesh, P())
        popt = PipelinedOptimizer(
            optimizer=opt,
            scalar_shardings={0: sh, 1: sh},
            anomaly_freeze=True,
            zero_axis=zero_axis,
        )
        params = {
            0: {"w": jax.device_put(jnp.linspace(0, 1, 16).reshape(4, 4), sh)},
            1: {"w": jax.device_put(jnp.linspace(1, 2, 16).reshape(4, 4), sh)},
        }
        states = popt.init(params)
        guard = popt.init_guard_state()
        w = jnp.float32(1.0)
        gm = None
        for i in range(steps):
            if i == nan_at:
                grads = {s: {"w": jnp.full((4, 4), jnp.nan)} for s in (0, 1)}
            else:
                grads = {
                    s: {"w": jnp.full((4, 4), 0.1 * (i + 1))} for s in (0, 1)
                }
            params, states, _, gm, guard = popt.step_guarded(
                params, states, grads, w, jnp.float32(1.0), guard
            )
        return (
            jax.tree.map(np.asarray, params),
            jax.tree.map(np.asarray, states),
            {k: float(v) for k, v in gm.items()},
            popt,
        )

    @pytest.mark.parametrize("opt_name", ["adamw", "sadamw"])
    def test_matches_replicated_with_guard_firing(self, opt_name):
        base = self._run_pp(None, _make_opt(opt_name), nan_at=1, steps=3)
        zeroed = self._run_pp(
            AXIS_DP_REPLICATE, _make_opt(opt_name), nan_at=1, steps=3
        )
        assert zeroed[2]["resilience/anomaly_total"] == 1.0
        _assert_trees(base[0], zeroed[0], bitwise=False)
        _assert_trees(base[1], zeroed[1], bitwise=False)

    def test_state_actually_sharded(self):
        _, states, _, popt = self._run_pp(
            AXIS_DP_REPLICATE, optax.adamw(1e-2)
        )
        assert set(popt.zero_shardings) == {0, 1}
        for z in popt.zero_shardings.values():
            assert z.active


# -- trainer gauge + split-update introspection --------------------------

def _micro_trainer(dp, zero, tmp_path, **overrides):
    from tests.resilience.conftest import MicroProvider, MicroLoaderProvider
    from d9d_tpu.loop import CausalLMTask, Trainer, TrainerConfig

    ctx = MeshParameters(dp_replicate=dp).build(jax.devices()[:dp])
    defaults = dict(
        global_batch_size=8,
        microbatch_size=8,
        seq_len=8,
        total_steps=3,
        log_every=1,
        prefetch_batches=0,
        telemetry_console=False,
        gc_every_steps=None,
        zero_sharding=zero,
    )
    defaults.update(overrides)
    return Trainer(
        ctx=ctx,
        config=TrainerConfig(**defaults),
        model_provider=MicroProvider(),
        dataset_provider=MicroLoaderProvider(),
        task=CausalLMTask(),
        optimizer_provider=__import__(
            "d9d_tpu.loop", fromlist=["AdamWProvider"]
        ).AdamWProvider(),
    )


def test_opt_state_bytes_gauge_scales(tmp_path):
    from d9d_tpu.telemetry import get_telemetry

    replicated = _micro_trainer(4, False, tmp_path)
    b_rep = replicated.opt_state_bytes_per_chip()
    assert get_telemetry().gauge("opt/state_bytes_per_chip").value == b_rep
    sharded = _micro_trainer(4, True, tmp_path)
    b_zero = sharded.opt_state_bytes_per_chip()
    assert get_telemetry().gauge("opt/state_bytes_per_chip").value == b_zero
    # MicroLM moments dominate -> ~1/4 per chip, scalars stay replicated
    assert b_zero < 0.5 * b_rep


def test_split_update_parity_and_inventory():
    from d9d_tpu.telemetry.introspect import inventory, reset_inventory

    base = _run(2, True, "adamw")
    reset_inventory()
    split = _run(2, True, "adamw", split_update=True)
    _assert_trees(base[0], split[0], bitwise=False)
    _assert_trees(base[1], split[1], bitwise=False)
    names = {rec.name for rec in inventory()}
    assert "train_opt_update" in names
    assert "train_step" in names
