"""Perf-regression gate (tools/bench_compare.py): the tier-1 tripwire.

Three layers, all pinned here:

1. pure compare() semantics (directions, tolerances, missing metrics);
2. CLI exit codes: nonzero on a synthetic regressed snapshot, zero on a
   baseline-equal one (subprocess — the rc IS the contract CI consumes);
3. the live gate: run the CPU serving microbench in-process and compare
   against the committed BENCH_BASELINE.json — every future PR that
   adds a dispatch, a steady-state compile, a recompile, or a 10x
   throughput collapse to the fused serving path fails here, even while
   the TPU tunnel is flaky.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from tests.conftest import load_repo_module

ROOT = pathlib.Path(__file__).resolve().parent.parent
BASELINE = ROOT / "BENCH_BASELINE.json"

bc = load_repo_module("bench_compare", "tools/bench_compare.py")


def test_compare_directions_and_tolerances():
    baseline = {"metrics": {
        "m.higher": {"value": 100.0, "direction": "higher", "rel_tol": 0.5},
        "m.lower": {"value": 10.0, "direction": "lower", "rel_tol": 0.0},
    }}
    ok, _ = bc.compare(
        {"metrics": {"m.higher": 51.0, "m.lower": 10.0}}, baseline
    )
    assert ok
    ok, lines = bc.compare(
        {"metrics": {"m.higher": 49.0, "m.lower": 10.0}}, baseline
    )
    assert not ok and any(
        line.startswith("FAIL m.higher") for line in lines
    )
    ok, lines = bc.compare(
        {"metrics": {"m.higher": 200.0, "m.lower": 10.1}}, baseline
    )
    assert not ok and any(
        line.startswith("FAIL m.lower") for line in lines
    )


def test_compare_fails_on_missing_metric():
    baseline = {"metrics": {
        "m.gone": {"value": 1.0, "direction": "lower", "rel_tol": 0.0},
    }}
    ok, lines = bc.compare({"metrics": {}}, baseline)
    assert not ok and "missing" in lines[0]


def test_compare_empty_baseline_gates_nothing():
    ok, _ = bc.compare({"metrics": {"x": 1.0}}, {"metrics": {}})
    assert ok


def _committed_values() -> dict:
    with open(BASELINE) as fh:
        return {
            name: spec["value"]
            for name, spec in json.load(fh)["metrics"].items()
        }


def _run_cli(tmp_path, metrics) -> subprocess.CompletedProcess:
    current = tmp_path / "current.json"
    current.write_text(json.dumps({"metrics": metrics}))
    return subprocess.run(
        [sys.executable, str(ROOT / "tools" / "bench_compare.py"),
         "--current", str(current), "--baseline", str(BASELINE)],
        capture_output=True, text=True, timeout=120,
    )


def test_cli_zero_on_committed_baseline_snapshot(tmp_path):
    """A current summary EQUAL to the committed baseline passes (every
    bound is inclusive)."""
    out = _run_cli(tmp_path, _committed_values())
    assert out.returncode == 0, out.stdout + out.stderr
    assert '"ok": true' in out.stdout


def test_cli_nonzero_on_synthetic_regression(tmp_path):
    """The acceptance pin: a regressed snapshot (extra dispatches, a
    steady-state compile, a recompile) exits nonzero."""
    regressed = _committed_values()
    regressed["serve_micro.host_dispatches"] += 5
    regressed["serve_micro.steady_state_compiles"] += 1
    regressed["serve_micro.recompiles"] += 1
    out = _run_cli(tmp_path, regressed)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "FAIL serve_micro.host_dispatches" in out.stdout
    assert "FAIL serve_micro.steady_state_compiles" in out.stdout
    assert "FAIL serve_micro.recompiles" in out.stdout


def _overhead_baseline(tmp_path) -> pathlib.Path:
    baseline = {"metrics": {
        "serve_micro.exporter_overhead_frac":
            {"value": 0.02, "direction": "lower", "rel_tol": 9.0},
        "serve_micro.host_dispatches":
            {"value": 12, "direction": "lower", "rel_tol": 0.0},
    }}
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(baseline))
    return path


def test_exporter_overhead_isolation_rerun(tmp_path, monkeypatch, capsys):
    """The contention-flake fix: when exporter_overhead_frac is the ONLY
    failing metric under --run-micro, the tool re-measures that leg once
    in isolation (and passes when the isolated number is clean)."""
    calls = {"rerun": 0}
    monkeypatch.setattr(bc, "run_micro", lambda: {"metrics": {
        "serve_micro.exporter_overhead_frac": 0.9,
        "serve_micro.host_dispatches": 12,
    }})

    def fake_rerun():
        calls["rerun"] += 1
        return 0.01

    monkeypatch.setattr(bc, "rerun_exporter_overhead", fake_rerun)
    rc = bc.main(["--run-micro", "--baseline",
                  str(_overhead_baseline(tmp_path))])
    out = capsys.readouterr().out
    assert rc == 0
    assert calls["rerun"] == 1
    assert "flaky-by-construction" in out
    assert '"exporter_rerun": true' in out


def test_exporter_rerun_fails_when_isolated_number_still_breaches(
    tmp_path, monkeypatch, capsys
):
    monkeypatch.setattr(bc, "run_micro", lambda: {"metrics": {
        "serve_micro.exporter_overhead_frac": 0.9,
        "serve_micro.host_dispatches": 12,
    }})
    monkeypatch.setattr(bc, "rerun_exporter_overhead", lambda: 0.8)
    rc = bc.main(["--run-micro", "--baseline",
                  str(_overhead_baseline(tmp_path))])
    out = capsys.readouterr().out
    assert rc == 1
    assert "FAIL serve_micro.exporter_overhead_frac" in out


def test_exporter_rerun_skipped_when_other_metrics_fail(
    tmp_path, monkeypatch, capsys
):
    """A structural failure alongside the overhead breach is real — no
    re-run, straight to rc 1."""
    monkeypatch.setattr(bc, "run_micro", lambda: {"metrics": {
        "serve_micro.exporter_overhead_frac": 0.9,
        "serve_micro.host_dispatches": 13,
    }})

    def boom():
        raise AssertionError("re-run must not trigger")

    monkeypatch.setattr(bc, "rerun_exporter_overhead", boom)
    rc = bc.main(["--run-micro", "--baseline",
                  str(_overhead_baseline(tmp_path))])
    out = capsys.readouterr().out
    assert rc == 1
    assert '"exporter_rerun": false' in out


def test_cli_current_snapshot_never_reruns(tmp_path):
    """--current snapshots stay a pure function of the file: an
    exporter_overhead_frac breach exits 1 with no isolation re-run."""
    snapshot = _committed_values()
    snapshot["serve_micro.exporter_overhead_frac"] = 1.0
    out = _run_cli(tmp_path, snapshot)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "FAIL serve_micro.exporter_overhead_frac" in out.stdout
    assert '"exporter_rerun": false' in out.stdout


def test_extract_bench_jsonl_pulls_nested_rows(tmp_path):
    rows = [
        {"leg": "x", "error": "rc=124"},  # failure line: skipped
        {"metric": "dense_lm_tokens_per_sec_per_chip", "value": 48163.0,
         "unit": "tokens/s", "vs_baseline": 1.0,
         "detail": {
             "moe": {"metric": "qwen3_moe_tokens_per_sec_per_chip",
                     "value": 25280.0},
             "serving": {"metric": "serving_tokens_per_sec_per_chip",
                         "value": 9000.0,
                         "dispatches_per_1k_tokens": 26.0},
         }},
    ]
    path = tmp_path / "bench.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    got = bc.extract_bench_jsonl(str(path))["metrics"]
    assert got["tpu.dense_lm_tokens_per_sec_per_chip"] == 48163.0
    assert got["tpu.qwen3_moe_tokens_per_sec_per_chip"] == 25280.0
    assert got["tpu.serving_dispatches_per_1k_tokens"] == 26.0


@pytest.mark.e2e
def test_live_micro_gate_against_committed_baseline(devices):
    """THE tripwire: run the CPU serving microbench and gate it against
    the committed baseline. Structural metrics (dispatches/1k tokens,
    steady-state compiles, recompiles, emitted tokens) are exact; only
    tok_per_s carries a wide collapse-only tolerance. Gates through
    gate_with_exporter_rescue — the same path as the CLI — so the
    exporter_overhead_frac 2-core-contention flake gets its one
    isolated re-measure here too instead of failing the suite on
    wall-clock noise."""
    from d9d_tpu.telemetry import Telemetry, set_telemetry, recompile_guard
    from d9d_tpu.telemetry import introspect

    set_telemetry(Telemetry())  # isolate from other tests' instruments
    recompile_guard().reset()
    current = bc.run_micro()
    with open(BASELINE) as fh:
        baseline = json.load(fh)
    ok, lines, _rerun = bc.gate_with_exporter_rescue(current, baseline)
    assert ok, "\n".join(lines)
    # and the run itself must be introspection-clean
    assert current["metrics"]["serve_micro.steady_state_compiles"] == 0
    assert current["metrics"]["serve_micro.recompiles"] == 0
