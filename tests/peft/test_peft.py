"""PEFT tests: LoRA/FullTune/PeftStack algebra + end-to-end LoRA training.

Mirrors the reference peft test coverage (d9d/peft): injection creates
correctly shaped adapters for 2-D and 3-D (grouped-expert) kernels,
injection is a forward no-op at step 0, merge == materialize, only
adapters train, and a Trainer run with LoRA lowers the loss while leaving
the base bitwise frozen.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
pytestmark = pytest.mark.e2e  # slow tier: LoRA trainer e2e


from d9d_tpu.peft import (
    FullTune,
    LoRA,
    PeftStack,
    adapter_from_state_dict,
    adapter_state_dict,
)


@pytest.fixture
def params():
    k = jax.random.PRNGKey(0)
    return {
        "attn": {"kernel": jax.random.normal(k, (16, 32))},
        "experts": {"kernel": jax.random.normal(k, (4, 16, 8))},
        "norm": {"scale": jnp.ones((16,))},
    }


class TestLoRA:
    def test_inject_shapes(self, params):
        lora = LoRA(rank=4, target_patterns=(r".*kernel",))
        base, ad = lora.inject(params, jax.random.PRNGKey(1))
        assert set(ad) == {"attn/kernel", "experts/kernel"}
        assert ad["attn/kernel"]["lora_a"].shape == (16, 4)
        assert ad["attn/kernel"]["lora_b"].shape == (4, 32)
        assert ad["experts/kernel"]["lora_a"].shape == (4, 16, 4)
        assert ad["experts/kernel"]["lora_b"].shape == (4, 4, 8)
        # norm.scale untouched (1-D never matches)
        assert "norm/scale" not in ad

    def test_injection_is_forward_noop(self, params):
        lora = LoRA(rank=4)
        base, ad = lora.inject(params, jax.random.PRNGKey(1))
        eff = lora.materialize(base, ad)
        for a, b in zip(jax.tree.leaves(eff), jax.tree.leaves(params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)

    def test_materialize_adds_scaled_delta(self, params):
        lora = LoRA(rank=2, alpha=8.0, target_patterns=(r"attn/kernel",))
        base, ad = lora.inject(params, jax.random.PRNGKey(1))
        ad["attn/kernel"]["lora_b"] = jnp.ones_like(ad["attn/kernel"]["lora_b"])
        eff = lora.materialize(base, ad)
        expected = params["attn"]["kernel"] + (8.0 / 2) * (
            ad["attn/kernel"]["lora_a"] @ ad["attn/kernel"]["lora_b"]
        )
        np.testing.assert_allclose(
            np.asarray(eff["attn"]["kernel"]), np.asarray(expected), rtol=1e-5
        )
        assert lora.merge(base, ad)["attn"]["kernel"].shape == (16, 32)

    def test_grouped_expert_delta_per_expert(self, params):
        lora = LoRA(rank=2, target_patterns=(r"experts/kernel",))
        base, ad = lora.inject(params, jax.random.PRNGKey(1))
        b = np.zeros((4, 2, 8), np.float32)
        b[2] = 1.0  # only expert 2 gets a delta
        ad["experts/kernel"]["lora_b"] = jnp.asarray(b)
        eff = lora.materialize(base, ad)
        delta = np.asarray(eff["experts"]["kernel"]) - np.asarray(
            params["experts"]["kernel"]
        )
        assert np.abs(delta[[0, 1, 3]]).max() < 1e-6
        assert np.abs(delta[2]).max() > 0

    def test_no_match_raises(self, params):
        with pytest.raises(ValueError, match="matched no params"):
            LoRA(rank=2, target_patterns=(r"nope",)).inject(
                params, jax.random.PRNGKey(0)
            )

    def test_state_dict_roundtrip(self, params):
        lora = LoRA(rank=4)
        _, ad = lora.inject(params, jax.random.PRNGKey(1))
        sd = adapter_state_dict(ad)
        assert "attn/kernel.lora_a" in sd
        back = adapter_from_state_dict(ad, sd)
        for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(ad)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFullTuneAndStack:
    def test_full_tune_selects(self, params):
        ft = FullTune(target_patterns=(r"norm/.*",))
        base, ad = ft.inject(params, jax.random.PRNGKey(0))
        assert set(ad) == {"norm/scale"}
        ad["norm/scale"] = ad["norm/scale"] * 3.0
        eff = ft.materialize(base, ad)
        np.testing.assert_allclose(np.asarray(eff["norm"]["scale"]), 3.0)

    def test_stack_composes(self, params):
        stack = PeftStack(
            methods=(
                FullTune(target_patterns=(r"norm/.*",)),
                LoRA(rank=2, target_patterns=(r"attn/kernel",)),
            )
        )
        base, (ft_ad, lora_ad) = stack.inject(params, jax.random.PRNGKey(0))
        assert set(ft_ad) == {"norm/scale"}
        assert set(lora_ad) == {"attn/kernel"}
        eff = stack.materialize(base, (ft_ad, lora_ad))
        assert jax.tree.structure(eff) == jax.tree.structure(params)


class TestLoRATrainerE2E:
    @requires_modern_jax
    def test_lora_trains_and_base_frozen(self, devices):
        from d9d_tpu.core import MeshParameters
        from d9d_tpu.loop import (
            AdamWProvider,
            CausalLMTask,
            DatasetProvider,
            ModelProvider,
            Trainer,
            TrainerConfig,
        )
        from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
        from d9d_tpu.nn.sdpa import build_sdpa_backend
        from d9d_tpu.parallel import fsdp_ep_plan

        vocab = 32

        class Provider(ModelProvider):
            def build_module(self, stage):
                return Qwen3DenseCausalLM(
                    config=Qwen3DenseConfig(
                        vocab_ranges=(("default", vocab),),
                        hidden_size=32,
                        num_layers=2,
                        num_heads=2,
                        num_kv_heads=2,
                        head_dim=16,
                        intermediate_size=64,
                        remat=False,
                    ),
                    sdpa=build_sdpa_backend(),
                    dtype=jnp.float32,
                )

            def build_plan(self, c):
                return fsdp_ep_plan(c)

            def sample_inputs(self, b, t):
                z = jnp.zeros((b, t), jnp.int32)
                return (z, z, z)

        class Data(DatasetProvider):
            def build(self):
                rng = np.random.default_rng(0)
                for _ in range(20):
                    yield {"input_ids": rng.integers(0, vocab, (8, 17))}

        ctx = MeshParameters(dp_shard=4).build(jax.devices()[:4])
        trainer = Trainer(
            ctx=ctx,
            config=TrainerConfig(
                global_batch_size=8,
                microbatch_size=8,
                seq_len=16,
                total_steps=20,
                log_every=5,
                learning_rate=5e-2,
            ),
            model_provider=Provider(),
            dataset_provider=Data(),
            task=CausalLMTask(),
            optimizer_provider=AdamWProvider(),
            peft_method=LoRA(rank=4, alpha=8.0, target_patterns=(r".*kernel",)),
        )
        base_before = jax.tree.map(lambda x: np.asarray(x).copy(), trainer.base_params)
        hist = trainer.train()
        assert hist[-1]["loss"] < hist[0]["loss"]
        # base params bitwise unchanged
        for a, b in zip(
            jax.tree.leaves(trainer.base_params), jax.tree.leaves(base_before)
        ):
            np.testing.assert_array_equal(np.asarray(a), b)
        # adapters actually moved
        moved = any(
            np.abs(np.asarray(l)).max() > 0
            for name, ad in trainer.params.items()
            for k, l in ad.items()
            if k == "lora_b"
        )
        assert moved
        # merged export has full shapes
        merged = trainer.merged_params()
        assert jax.tree.structure(merged) == jax.tree.structure(trainer.base_params)
