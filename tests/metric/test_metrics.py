"""Metric package tests (reference strategy: numeric parity vs
scikit-learn where available, SURVEY §4 category 8)."""

import numpy as np
import pytest

from d9d_tpu.metric import (
    BinaryAUROCMetric,
    ComposeMetric,
    ConfusionMatrixMetricBuilder,
    SumMetric,
    WeightedMeanMetric,
)

def _sklearn_metrics():
    return pytest.importorskip("sklearn.metrics")


def test_weighted_mean():
    m = WeightedMeanMetric()
    m.update(np.array([1.0, 2.0]), np.array([1.0, 3.0]))
    m.update(np.array([4.0]), np.array([2.0]))
    # (1*1 + 2*3 + 4*2) / (1+3+2) = 15/6
    assert float(m.compute()) == pytest.approx(15 / 6)
    assert float(m.accumulated_weight) == 6.0
    m.sync()
    assert float(m.compute()) == pytest.approx(15 / 6)
    m.reset()
    m.update(np.array([5.0]), np.array([1.0]))
    assert float(m.compute()) == 5.0


def test_sum_and_state_roundtrip():
    m = SumMetric()
    m.update(np.array([1.0, 2.0, 3.0]))
    state = m.state_dict()
    m2 = SumMetric()
    m2.load_state_dict(state)
    assert float(m2.compute()) == 6.0


def test_compose():
    m = ComposeMetric({"a": SumMetric(), "b": WeightedMeanMetric()})
    m["a"].update(np.array([2.0]))
    m["b"].update(np.array([3.0]), np.array([1.0]))
    out = m.compute()
    assert float(out["a"]) == 2.0
    assert float(out["b"]) == 3.0
    with pytest.raises(ValueError):
        m.update(1)


@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
def test_multiclass_f1_vs_sklearn(average):
    rng = np.random.default_rng(0)
    n, c = 500, 4
    preds = rng.normal(size=(n, c))
    targets = rng.integers(0, c, size=(n,))

    builder = ConfusionMatrixMetricBuilder().multiclass(c).with_f1()
    builder = getattr(builder, average)()
    m = builder.build()
    m.update(preds[:250], targets[:250])
    m.update(preds[250:], targets[250:])

    expected = _sklearn_metrics().f1_score(
        targets, preds.argmax(-1), average=average
    )
    assert float(m.compute()) == pytest.approx(expected, abs=1e-6)


def test_multiclass_accuracy_micro_vs_sklearn():
    rng = np.random.default_rng(1)
    n, c = 300, 5
    preds = rng.normal(size=(n, c))
    targets = rng.integers(0, c, size=(n,))
    m = ConfusionMatrixMetricBuilder().multiclass(c).with_accuracy().micro().build()
    m.update(preds, targets)
    # micro-averaged one-hot accuracy counts TN too; equals
    # (n*c - 2*errors)/(n*c)
    errors = (preds.argmax(-1) != targets).sum()
    expected = (n * c - 2 * errors) / (n * c)
    assert float(m.compute()) == pytest.approx(expected, abs=1e-9)


def test_binary_precision_recall_vs_sklearn():
    rng = np.random.default_rng(2)
    n = 400
    probs = rng.random(size=(n,))
    targets = rng.integers(0, 2, size=(n,))
    preds_binary = (probs > 0.5).astype(int)

    skm = _sklearn_metrics()
    for name, fn in [
        ("with_precision", skm.precision_score),
        ("with_recall", skm.recall_score),
        ("with_f1", skm.f1_score),
    ]:
        m = getattr(ConfusionMatrixMetricBuilder().binary(0.5), name)().build()
        m.update(probs, targets)
        assert float(m.compute()) == pytest.approx(
            fn(targets, preds_binary), abs=1e-9
        ), name


def test_topk_accuracy():
    preds = np.array(
        [[0.1, 0.5, 0.4], [0.8, 0.1, 0.1], [0.2, 0.3, 0.5], [0.3, 0.4, 0.3]]
    )
    targets = np.array([2, 0, 0, 1])
    m = (
        ConfusionMatrixMetricBuilder()
        .multiclass(3, top_k=2)
        .with_recall()
        .build()
    )
    m.update(preds, targets)
    # top-2 hits: [yes(2 in {1,2}), yes(0 in {0,..}), no(0 not in {2,1}), yes]
    assert float(m.compute()) == pytest.approx(3 / 4)


def test_builder_validation():
    with pytest.raises(ValueError):
        ConfusionMatrixMetricBuilder().binary().multiclass(3)
    with pytest.raises(ValueError):
        ConfusionMatrixMetricBuilder().with_f1().build()
    with pytest.raises(ValueError):
        ConfusionMatrixMetricBuilder().multiclass(3).with_f1().with_accuracy()


def test_auroc_vs_sklearn():
    rng = np.random.default_rng(3)
    n = 5000
    labels = rng.integers(0, 2, size=(n,))
    # informative but noisy scores
    probs = np.clip(
        labels * 0.35 + rng.random(size=(n,)) * 0.65, 0.0, 1.0
    )
    m = BinaryAUROCMetric(num_bins=10000)
    m.update(probs[:2500], labels[:2500])
    m.update(probs[2500:], labels[2500:])
    expected = _sklearn_metrics().roc_auc_score(labels, probs)
    assert float(m.compute()) == pytest.approx(expected, abs=5e-3)


def test_auroc_degenerate():
    m = BinaryAUROCMetric(num_bins=100)
    m.update(np.array([0.3, 0.7]), np.array([1, 1]))
    assert float(m.compute()) == 0.5


def test_zero_support_class_matches_sklearn():
    # class 3 never appears: macro/weighted must not NaN (sklearn
    # zero_division=0 semantics)
    skm = _sklearn_metrics()
    targets = np.array([0, 1, 2, 0, 1])
    preds = np.eye(4)[targets]  # perfect predictions, class 3 absent
    for average in ("macro", "weighted", "micro"):
        m = getattr(
            ConfusionMatrixMetricBuilder().multiclass(4).with_f1(), average
        )().build()
        m.update(preds, targets)
        expected = skm.f1_score(
            targets, preds.argmax(-1), average=average, labels=list(range(4)),
            zero_division=0,
        )
        got = float(m.compute())
        assert not np.isnan(got)
        if average != "micro":  # micro one-hot counts TNs (see note above)
            assert got == pytest.approx(expected, abs=1e-9), average
