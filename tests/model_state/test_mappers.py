"""Mapper algebra tests (reference: test/d9d_test/model_state/test_mappers.py
category, SURVEY §4.6)."""

import numpy as np
import pytest

from d9d_tpu.model_state.mapper import (
    ModelStateMapperChunkTensors,
    ModelStateMapperConcatenateTensors,
    ModelStateMapperIdentity,
    ModelStateMapperParallel,
    ModelStateMapperPrefixScope,
    ModelStateMapperRename,
    ModelStateMapperSelectChildModules,
    ModelStateMapperSequential,
    ModelStateMapperShard,
    ModelStateMapperStackTensors,
    ModelStateMapperTranspose,
    ModelStateMapperUnstackTensors,
    StateGroup,
)


def _run_all(mapper, state):
    """Drive a mapper like the IO layer does: fire each group when ready."""
    out = {}
    for group in mapper.state_dependency_groups():
        assert group.inputs <= state.keys(), f"missing {group.inputs}"
        result = mapper.apply({k: state[k] for k in group.inputs})
        assert set(result.keys()) == set(group.outputs)
        out.update(result)
    return out


def test_leaf_rename_transpose():
    state = {"a": np.arange(6).reshape(2, 3)}
    out = _run_all(ModelStateMapperRename("a", "b"), state)
    np.testing.assert_array_equal(out["b"], state["a"])
    out = _run_all(ModelStateMapperTranspose("a", (0, 1)), state)
    assert out["a"].shape == (3, 2)


def test_stack_unstack_roundtrip():
    state = {f"w{i}": np.full((2, 2), i) for i in range(3)}
    stacked = _run_all(
        ModelStateMapperStackTensors(["w0", "w1", "w2"], "stacked", 0), state
    )
    assert stacked["stacked"].shape == (3, 2, 2)
    unstacked = _run_all(
        ModelStateMapperUnstackTensors("stacked", ["w0", "w1", "w2"], 0),
        stacked,
    )
    for i in range(3):
        np.testing.assert_array_equal(unstacked[f"w{i}"], state[f"w{i}"])


def test_chunk_concat_roundtrip():
    state = {"big": np.arange(12).reshape(6, 2)}
    chunked = _run_all(
        ModelStateMapperChunkTensors("big", ["c0", "c1", "c2"], 0), state
    )
    assert all(chunked[f"c{i}"].shape == (2, 2) for i in range(3))
    merged = _run_all(
        ModelStateMapperConcatenateTensors(["c0", "c1", "c2"], "big", 0),
        chunked,
    )
    np.testing.assert_array_equal(merged["big"], state["big"])


def test_select_child_modules():
    m = ModelStateMapperSelectChildModules(["w", "b"], "encoder")
    state = {"encoder.w": np.ones(2), "encoder.b": np.zeros(2)}
    out = _run_all(m, state)
    assert set(out) == {"w", "b"}


def test_parallel_collision_detection():
    with pytest.raises(ValueError, match="more than one sub-mapper"):
        ModelStateMapperParallel(
            [ModelStateMapperIdentity("x"), ModelStateMapperRename("x", "y")]
        )
    with pytest.raises(ValueError, match="more than one sub-mapper"):
        ModelStateMapperParallel(
            [
                ModelStateMapperRename("a", "out"),
                ModelStateMapperRename("b", "out"),
            ]
        )


def test_sequential_chains_groups():
    # A: {x}->{y}, B: {y}->{z} reports net {x}->{z}
    seq = ModelStateMapperSequential(
        [ModelStateMapperRename("x", "y"), ModelStateMapperRename("y", "z")]
    )
    groups = seq.state_dependency_groups()
    assert groups == frozenset(
        [StateGroup(inputs=frozenset(["x"]), outputs=frozenset(["z"]))]
    )
    out = _run_all(seq, {"x": np.ones(3)})
    assert set(out) == {"z"}


def test_sequential_gap_filling():
    # stage 1 only touches 'a'; 'b' must pass through to stage 2
    seq = ModelStateMapperSequential(
        [
            ModelStateMapperRename("a", "a2"),
            ModelStateMapperConcatenateTensors(["a2", "b"], "cat", 0),
        ]
    )
    out = _run_all(seq, {"a": np.ones((1, 2)), "b": np.zeros((1, 2))})
    assert out["cat"].shape == (2, 2)


def test_sequential_transitive_merge():
    # chunk feeds two downstream groups -> one merged net group
    seq = ModelStateMapperSequential(
        [
            ModelStateMapperChunkTensors("src", ["p", "q"], 0),
            ModelStateMapperParallel(
                [
                    ModelStateMapperRename("p", "p_out"),
                    ModelStateMapperRename("q", "q_out"),
                ]
            ),
        ]
    )
    groups = seq.state_dependency_groups()
    assert groups == frozenset(
        [
            StateGroup(
                inputs=frozenset(["src"]),
                outputs=frozenset(["p_out", "q_out"]),
            )
        ]
    )
    out = _run_all(seq, {"src": np.arange(4).reshape(2, 2)})
    np.testing.assert_array_equal(out["p_out"], [[0, 1]])
    np.testing.assert_array_equal(out["q_out"], [[2, 3]])


def test_prefix_scope():
    scoped = ModelStateMapperPrefixScope(
        ModelStateMapperRename("w", "weight"),
        source_prefix="hf.",
        target_prefix="ours.",
    )
    out = _run_all(scoped, {"hf.w": np.ones(1)})
    assert set(out) == {"ours.weight"}


def test_shard_partitions_groups():
    inner = ModelStateMapperParallel(
        [ModelStateMapperIdentity(f"t{i}") for i in range(5)]
    )
    shards = [ModelStateMapperShard(inner, 2, i) for i in range(2)]
    g0 = shards[0].state_dependency_groups()
    g1 = shards[1].state_dependency_groups()
    assert len(g0) + len(g1) == 5
    assert g0.isdisjoint(g1)
