"""HF parity for the Llama-3 family (models/llama): logits vs a
transformers LlamaForCausalLM through the shared dense mapper
(qk_norm=False path), plus the llama3 rope-scaling law vs HF's
implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: full HF-roundtrip flows


from d9d_tpu.model_state import (
    identity_mapper_from_names,
    load_params,
    save_params,
    write_model_state_local,
)
from d9d_tpu.model_state.io.reader import read_model_state
from d9d_tpu.models.llama import (
    LlamaCausalLM,
    llama3_tiny,
    llama_from_hf_mapper,
    llama_to_hf_mapper,
)
from d9d_tpu.ops.attention.eager import eager_sdpa

transformers = pytest.importorskip("transformers")

VOCAB = 128


def _hf_model(rope_scaling=None):
    torch = pytest.importorskip("torch")
    cfg = transformers.LlamaConfig(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=64,
        rope_theta=500_000.0,
        rms_norm_eps=1e-6,
        attention_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        rope_scaling=rope_scaling,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


def _save_hf_state(model, tmp_path):
    state = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    write_model_state_local(
        tmp_path, identity_mapper_from_names(state.keys()), iter(state.items())
    )


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    pytest.importorskip("torch")
    tmp_path = tmp_path_factory.mktemp("hf_llama_ckpt")
    hf = _hf_model()
    _save_hf_state(hf, tmp_path)

    cfg = llama3_tiny(VOCAB)
    cfg = __import__("dataclasses").replace(
        cfg, intermediate_size=128, norm_eps=1e-6
    )
    model = LlamaCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    template = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    )
    import flax.linen as nn

    template = nn.unbox(template)
    params = load_params(
        tmp_path, template, mapper=llama_from_hf_mapper(cfg)
    )
    return hf, model, params, cfg, tmp_path


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_logits_match_hf(hf_and_ours):
    torch = pytest.importorskip("torch")
    hf, model, params, cfg, _ = hf_and_ours
    rng = np.random.default_rng(0)
    tokens_np = rng.integers(0, VOCAB, size=(2, 16))

    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens_np)).logits.numpy()

    positions = np.broadcast_to(np.arange(16), (2, 16)).astype(np.int32)
    ours = model.apply(
        params,
        jnp.asarray(tokens_np, jnp.int32),
        jnp.asarray(positions),
        method=model.logits,
    )
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow  # shares the HF-model fixture with test_logits_match_hf
def test_roundtrip_back_to_hf(hf_and_ours, tmp_path):
    torch = pytest.importorskip("torch")
    hf, model, params, cfg, _ = hf_and_ours
    save_params(tmp_path, params, mapper=llama_to_hf_mapper(cfg))

    hf_state = {k: v.numpy() for k, v in hf.state_dict().items()}
    exported = dict(
        read_model_state(
            tmp_path, identity_mapper_from_names(hf_state.keys())
        )
    )
    assert set(exported) == set(hf_state)
    for k in hf_state:
        np.testing.assert_allclose(
            exported[k], hf_state[k], rtol=1e-6, atol=1e-6, err_msg=k
        )


def test_llama3_rope_scaling_matches_hf():
    """RopeScalingLlama3 inv_freq == HF's _compute_llama3_parameters."""
    torch = pytest.importorskip("torch")
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from d9d_tpu.ops import RopeScalingLlama3, compute_rope_frequencies

    head_dim = 32
    theta = 500_000.0
    hf_cfg = transformers.LlamaConfig(
        hidden_size=128,
        num_attention_heads=4,
        head_dim=head_dim,
        rope_theta=theta,
        max_position_embeddings=4096,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "original_max_position_embeddings": 512,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
        },
    )
    hf_inv_freq, hf_scale = ROPE_INIT_FUNCTIONS["llama3"](
        hf_cfg, device="cpu"
    )
    ours, scale = compute_rope_frequencies(
        head_dim,
        theta,
        RopeScalingLlama3(
            factor=8.0,
            original_max_position=512,
            low_freq_factor=1.0,
            high_freq_factor=4.0,
        ),
    )
    assert scale == hf_scale == 1.0
    np.testing.assert_allclose(
        np.asarray(ours), hf_inv_freq.numpy(), rtol=1e-6, atol=1e-9
    )
