"""Streaming IO round-trip tests (reference: model_state/test_dist_io.py
category, SURVEY §4.6)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)

from d9d_tpu.model_state import (
    MODEL_STATE_INDEX_FILE_NAME,
    ModelStateMapperRename,
    ModelStateMapperParallel,
    identity_mapper_from_names,
    load_params,
    read_model_state,
    save_params,
    write_model_state_local,
)


def test_write_read_roundtrip(tmp_path):
    state = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.ones((2, 2), dtype=np.float16),
        "c": np.array(3, dtype=np.int32),
    }
    mapper = identity_mapper_from_names(state.keys())
    write_model_state_local(tmp_path, mapper, iter(state.items()))

    index = json.loads((tmp_path / MODEL_STATE_INDEX_FILE_NAME).read_text())
    assert set(index["weight_map"].keys()) == {"a", "b", "c"}

    out = dict(read_model_state(tmp_path, mapper))
    for k, v in state.items():
        np.testing.assert_array_equal(out[k], v)
        assert out[k].dtype == v.dtype


def test_shard_spilling(tmp_path):
    # 4 x 1MB tensors with a ~2MB shard limit -> at least 2 shard files
    state = {
        f"t{i}": np.zeros((256, 1024), dtype=np.float32) for i in range(4)
    }
    mapper = identity_mapper_from_names(state.keys())
    write_model_state_local(
        tmp_path, mapper, iter(state.items()), shard_size_gb=2 / 1024
    )
    files = {p.name for p in tmp_path.glob("*.safetensors")}
    assert len(files) >= 2
    index = json.loads((tmp_path / MODEL_STATE_INDEX_FILE_NAME).read_text())
    assert set(index["weight_map"].values()) <= files
    out = dict(read_model_state(tmp_path, mapper))
    assert set(out) == set(state)


def test_writer_rejects_oversized_tensor(tmp_path):
    state = {"huge": np.zeros((1024, 1024), dtype=np.float32)}
    mapper = identity_mapper_from_names(state.keys())
    with pytest.raises(ValueError, match="larger than the shard size cap"):
        write_model_state_local(
            tmp_path, mapper, iter(state.items()), shard_size_gb=1 / 1024
        )


def test_writer_detects_missing_inputs(tmp_path):
    mapper = identity_mapper_from_names(["present", "absent"])
    with pytest.raises(ValueError, match="still waiting for inputs"):
        write_model_state_local(
            tmp_path, mapper, iter({"present": np.ones(1)}.items())
        )


def test_reader_applies_mapper(tmp_path):
    state = {"old_name": np.arange(4, dtype=np.float32)}
    write_model_state_local(
        tmp_path, identity_mapper_from_names(state.keys()), iter(state.items())
    )
    renamed = dict(
        read_model_state(
            tmp_path,
            ModelStateMapperParallel(
                [ModelStateMapperRename("old_name", "new_name")]
            ),
        )
    )
    assert set(renamed) == {"new_name"}


def test_param_tree_roundtrip(tmp_path):
    params = {
        "params": {
            "dense": {"kernel": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
            "norm": {"weight": jnp.ones(3)},
        }
    }
    save_params(tmp_path, params)
    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    loaded = load_params(tmp_path, template)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        loaded,
    )


@requires_modern_jax
def test_load_params_with_shardings(tmp_path):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from d9d_tpu.core import MeshParameters

    ctx = MeshParameters(dp_shard=4, tp=2).build(jax.devices()[:8])
    params = {"params": {"w": jnp.arange(32, dtype=jnp.float32).reshape(8, 4)}}
    save_params(tmp_path, params)
    shardings = {
        "params": {"w": NamedSharding(ctx.mesh, P("dp_s", "tp"))}
    }
    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    loaded = load_params(tmp_path, template, shardings=shardings)
    w = loaded["params"]["w"]
    assert w.sharding.spec == P("dp_s", "tp")
    np.testing.assert_array_equal(np.asarray(w), np.asarray(params["params"]["w"]))


def test_load_params_shape_mismatch(tmp_path):
    params = {"params": {"w": jnp.ones((2, 2))}}
    save_params(tmp_path, params)
    template = {"params": {"w": jax.ShapeDtypeStruct((3, 3), jnp.float32)}}
    with pytest.raises(ValueError, match="shape mismatch"):
        load_params(tmp_path, template)
