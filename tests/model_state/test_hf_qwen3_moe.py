"""HF parity for Qwen3-MoE: load a transformers checkpoint through the
mapper, compare logits; roundtrip back (reference huggingface.py:118,290)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: full training/IO flows


from d9d_tpu.model_state import (
    identity_mapper_from_names,
    load_params,
    read_model_state,
    save_params,
    write_model_state_local,
)
from d9d_tpu.models.qwen3 import Qwen3MoeCausalLM, Qwen3MoeConfig
from d9d_tpu.models.qwen3.huggingface import (
    qwen3_moe_from_hf_mapper,
    qwen3_moe_to_hf_mapper,
)
from d9d_tpu.ops.attention.eager import eager_sdpa

transformers = pytest.importorskip("transformers")

VOCAB = 128


def _hf_model():
    torch = pytest.importorskip("torch")
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=96,
        moe_intermediate_size=48,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        num_experts=8,
        num_experts_per_tok=2,
        norm_topk_prob=True,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        max_position_embeddings=64,
        rope_theta=1_000_000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        router_aux_loss_coef=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3MoeForCausalLM(cfg)
    model.eval()
    return model


def _our_config():
    return Qwen3MoeConfig(
        vocab_ranges=(("default", VOCAB),),
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        moe_intermediate_size=48,
        num_experts=8,
        num_experts_per_tok=2,
        norm_topk_prob=True,
        rope_theta=1_000_000.0,
        remat=False,
    )


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    import flax.linen as nn

    tmp_path = tmp_path_factory.mktemp("hf_moe_ckpt")
    hf = _hf_model()
    state = {k: v.detach().cpu().numpy() for k, v in hf.state_dict().items()}
    write_model_state_local(
        tmp_path, identity_mapper_from_names(state.keys()), iter(state.items())
    )

    cfg = _our_config()
    model = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    template = nn.unbox(
        jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        )
    )
    template = {"params": template["params"]}
    params = load_params(
        tmp_path, template, mapper=qwen3_moe_from_hf_mapper(cfg)
    )
    return hf, model, params, cfg


def test_logits_match_hf(hf_and_ours):
    torch = pytest.importorskip("torch")
    hf, model, params, cfg = hf_and_ours
    rng = np.random.default_rng(0)
    tokens_np = rng.integers(0, VOCAB, size=(2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens_np)).logits.numpy()
    positions = np.broadcast_to(np.arange(16), (2, 16)).astype(np.int32)
    ours = model.apply(
        params,
        jnp.asarray(tokens_np, jnp.int32),
        jnp.asarray(positions),
        method=model.logits,
    )
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4
    )


def test_roundtrip_back_to_hf(hf_and_ours, tmp_path):
    hf, model, params, cfg = hf_and_ours
    save_params(tmp_path, params, mapper=qwen3_moe_to_hf_mapper(cfg))
    hf_state = {k: v.numpy() for k, v in hf.state_dict().items()}
    exported = dict(
        read_model_state(tmp_path, identity_mapper_from_names(hf_state.keys()))
    )
    assert set(exported) == set(hf_state)
    for k in hf_state:
        np.testing.assert_allclose(
            exported[k], hf_state[k], rtol=1e-6, atol=1e-6, err_msg=k
        )


def test_fused_v5_format_roundtrips_against_v4(hf_and_ours, tmp_path):
    """The v5 fused-experts mapper (reference huggingface.py:60-81,240-263)
    exports ``experts.gate_up_proj``/``experts.down_proj`` tensors whose
    re-import equals the v4 ModuleList import bit-for-bit."""
    hf, model, params, cfg = hf_and_ours

    # export in the fused layout
    fused_dir = tmp_path / "fused"
    save_params(
        fused_dir, params, mapper=qwen3_moe_to_hf_mapper(cfg, experts_format="fused")
    )
    fused_names = [
        f"model.layers.{i}.mlp.experts.{n}"
        for i in range(cfg.num_layers)
        for n in ("gate_up_proj", "down_proj")
    ]
    fused_state = {
        k: v
        for k, v in read_model_state(
            fused_dir, identity_mapper_from_names(fused_names)
        )
        if k in fused_names
    }
    # fused shapes: gate_up [E, 2i, h], down [E, h, i]
    e, i_dim, h = cfg.num_experts, cfg.moe_intermediate_size, cfg.hidden_size
    assert fused_state[fused_names[0]].shape == (e, 2 * i_dim, h)
    assert fused_state[fused_names[1]].shape == (e, h, i_dim)

    # re-import through the fused mapper == original grouped params
    import flax.linen as nn

    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    template = nn.unbox(
        jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        )
    )
    template = {"params": template["params"]}
    params_back = load_params(
        fused_dir,
        template,
        mapper=qwen3_moe_from_hf_mapper(cfg, experts_format="fused"),
    )
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=0, atol=0
        ),
        params["params"],
        params_back["params"],
    )
