"""HF parity for the Qwen3-Next hybrid family (GDN + gated attention + MoE
with gated shared expert): load a transformers checkpoint through the
mapper, compare logits; roundtrip back. Beyond-reference capability — the
reference ships no hybrid family (SURVEY §2.4); the interop target is
transformers' Qwen3Next directly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: full training/IO flows


from d9d_tpu.model_state import (
    identity_mapper_from_names,
    load_params,
    read_model_state,
    save_params,
    write_model_state_local,
)
from d9d_tpu.models.qwen3 import Qwen3MoeCausalLM, Qwen3MoeConfig
from d9d_tpu.models.qwen3.huggingface_next import (
    qwen3_next_from_hf_mapper,
    qwen3_next_to_hf_mapper,
)
from d9d_tpu.nn.moe import SharedExpertParameters
from d9d_tpu.ops.attention.eager import eager_sdpa

transformers = pytest.importorskip("transformers")
pytest.importorskip("torch")

VOCAB = 128


def _hf_model():
    import torch

    cfg = transformers.Qwen3NextConfig(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        partial_rotary_factor=0.25,
        rope_theta=1_000_000.0,
        linear_num_value_heads=4,
        linear_num_key_heads=2,
        linear_key_head_dim=16,
        linear_value_head_dim=16,
        linear_conv_kernel_dim=4,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=48,
        shared_expert_intermediate_size=32,
        decoder_sparse_step=1,
        norm_topk_prob=True,
        layer_types=[
            "linear_attention",
            "full_attention",
            "linear_attention",
            "full_attention",
        ],
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        router_aux_loss_coef=0.0,
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3NextForCausalLM(cfg)
    model.eval()
    return model


def _our_config():
    return Qwen3MoeConfig(
        vocab_ranges=(("default", VOCAB),),
        hidden_size=64,
        num_layers=4,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        moe_intermediate_size=48,
        num_experts=8,
        num_experts_per_tok=2,
        shared_expert=SharedExpertParameters(
            intermediate_size=32, enable_gate=True
        ),
        norm_topk_prob=True,
        rope_theta=1_000_000.0,
        remat=False,
        linear_attention_layers=(0, 2),
        gdn_qk_heads=2,
        gdn_v_heads=4,
        gdn_head_qk_dim=16,
        gdn_head_v_dim=16,
        use_output_gate=True,
        rope_fraction=0.25,
        zero_centered_norms=True,
    )


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    import flax.linen as nn

    tmp_path = tmp_path_factory.mktemp("hf_next_ckpt")
    hf = _hf_model()
    state = {k: v.detach().cpu().numpy() for k, v in hf.state_dict().items()}
    write_model_state_local(
        tmp_path, identity_mapper_from_names(state.keys()), iter(state.items())
    )

    cfg = _our_config()
    model = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    template = nn.unbox(
        jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        )
    )
    template = {"params": template["params"]}
    params = load_params(
        tmp_path, template, mapper=qwen3_next_from_hf_mapper(cfg)
    )
    return hf, model, params, cfg


def test_logits_match_hf(hf_and_ours):
    import torch

    hf, model, params, cfg = hf_and_ours
    rng = np.random.default_rng(0)
    tokens_np = rng.integers(0, VOCAB, size=(2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens_np)).logits.numpy()
    positions = np.broadcast_to(np.arange(16), (2, 16)).astype(np.int32)
    ours = model.apply(
        params,
        jnp.asarray(tokens_np, jnp.int32),
        jnp.asarray(positions),
        method=model.logits,
    )
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=5e-4, atol=5e-4
    )


def test_roundtrip_back_to_hf(hf_and_ours, tmp_path):
    hf, model, params, cfg = hf_and_ours
    save_params(tmp_path, params, mapper=qwen3_next_to_hf_mapper(cfg))
    hf_state = {k: v.numpy() for k, v in hf.state_dict().items()}
    exported = dict(
        read_model_state(tmp_path, identity_mapper_from_names(hf_state.keys()))
    )
    assert set(exported) == set(hf_state)
    for k in hf_state:
        np.testing.assert_allclose(
            exported[k], hf_state[k], rtol=1e-6, atol=1e-6, err_msg=k
        )
