"""HF parity: load a transformers Qwen3 checkpoint through the mapper and
compare logits (reference strategy: block/model-level HF parity tests,
SURVEY §4.2/§4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: full training/IO flows


from d9d_tpu.model_state import save_params, load_params, write_model_state_local, identity_mapper_from_names
from d9d_tpu.model_state.io.reader import read_model_state
from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
from d9d_tpu.models.qwen3.huggingface import (
    qwen3_dense_from_hf_mapper,
    qwen3_dense_to_hf_mapper,
)
from d9d_tpu.ops.attention.eager import eager_sdpa

transformers = pytest.importorskip("transformers")


VOCAB = 128


def _hf_model():
    torch = pytest.importorskip("torch")
    cfg = transformers.Qwen3Config(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=64,
        rope_theta=1_000_000.0,
        rms_norm_eps=1e-6,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(cfg)
    model.eval()
    return model


def _our_config():
    return Qwen3DenseConfig(
        vocab_ranges=(("default", VOCAB),),
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=96,
        rope_theta=1_000_000.0,
        remat=False,
    )


def _save_hf_state(model, tmp_path):
    state = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    write_model_state_local(
        tmp_path, identity_mapper_from_names(state.keys()), iter(state.items())
    )


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    torch = pytest.importorskip("torch")
    tmp_path = tmp_path_factory.mktemp("hf_ckpt")
    hf = _hf_model()
    _save_hf_state(hf, tmp_path)

    cfg = _our_config()
    model = Qwen3DenseCausalLM(
        config=cfg, sdpa=eager_sdpa, dtype=jnp.float32
    )
    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    template = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    )
    import flax.linen as nn

    template = nn.unbox(template)
    params = load_params(
        tmp_path, template, mapper=qwen3_dense_from_hf_mapper(cfg)
    )
    return hf, model, params, cfg, tmp_path


def test_logits_match_hf(hf_and_ours):
    torch = pytest.importorskip("torch")
    hf, model, params, cfg, _ = hf_and_ours
    rng = np.random.default_rng(0)
    tokens_np = rng.integers(0, VOCAB, size=(2, 16))

    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens_np)).logits.numpy()

    positions = np.broadcast_to(np.arange(16), (2, 16)).astype(np.int32)
    ours = model.apply(
        params,
        jnp.asarray(tokens_np, jnp.int32),
        jnp.asarray(positions),
        method=model.logits,
    )
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4
    )


def test_roundtrip_back_to_hf(hf_and_ours, tmp_path):
    """Export through the to_hf mapper and compare tensors with the source."""
    torch = pytest.importorskip("torch")
    hf, model, params, cfg, _ = hf_and_ours
    save_params(tmp_path, params, mapper=qwen3_dense_to_hf_mapper(cfg))

    hf_state = {k: v.numpy() for k, v in hf.state_dict().items()}
    exported = dict(
        read_model_state(
            tmp_path, identity_mapper_from_names(hf_state.keys())
        )
    )
    assert set(exported) == set(hf_state)
    for k in hf_state:
        np.testing.assert_allclose(
            exported[k], hf_state[k], rtol=1e-6, atol=1e-6, err_msg=k
        )
