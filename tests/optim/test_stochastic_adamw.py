"""StochasticAdamW + stochastic rounding tests.

Mirrors the reference test strategy for kernel/stochastic/* and
optim/stochastic/adamw.py: (a) rounding is mean-preserving and lands only on
the two bf16 neighbours; (b) the bf16 optimizer tracks an fp32 optax.adamw
trajectory; (c) RNG state lives in the optimizer state (reproducible).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
pytestmark = pytest.mark.e2e  # slow tier: long fp32-tracking sweep


from d9d_tpu.ops.stochastic import (
    stochastic_round_to_bf16,
    stochastic_round_to_bf16_pallas,
)
from d9d_tpu.optim import StochasticAdamW


class TestStochasticRounding:
    def test_lands_on_neighbours(self):
        x = jnp.array([1.0 + 1 / 256.0] * 1024, jnp.float32)  # between bf16 grid pts
        out = stochastic_round_to_bf16(x, jax.random.PRNGKey(0))
        lo = np.float32(jnp.asarray(x[0]).astype(jnp.bfloat16))  # nearest = 1.0
        vals = set(np.unique(np.asarray(out.astype(jnp.float32))))
        grid = {1.0, 1.0 + 1 / 128.0}
        assert vals <= grid, (vals, grid, lo)
        assert len(vals) == 2  # both neighbours hit

    def test_mean_preserving(self):
        # value 1/4 of the way between two bf16 neighbours -> P(up) = 0.25
        lo, hi = 1.0, 1.0 + 1 / 128.0
        x = jnp.full((200_000,), lo + (hi - lo) * 0.25, jnp.float32)
        out = stochastic_round_to_bf16(x, jax.random.PRNGKey(1))
        frac_up = float(jnp.mean((out.astype(jnp.float32) > lo).astype(jnp.float32)))
        assert abs(frac_up - 0.25) < 0.01
        mean = float(jnp.mean(out.astype(jnp.float32)))
        assert abs(mean - float(x[0])) < 1e-5

    def test_exact_values_unchanged(self):
        x = jnp.array([0.0, 1.0, -2.0, 0.5], jnp.float32)  # exact in bf16
        out = stochastic_round_to_bf16(x, jax.random.PRNGKey(2))
        np.testing.assert_array_equal(
            np.asarray(out.astype(jnp.float32)), np.asarray(x)
        )

    def test_nonfinite_passthrough(self):
        x = jnp.array([jnp.inf, -jnp.inf, jnp.nan], jnp.float32)
        out = stochastic_round_to_bf16(x, jax.random.PRNGKey(3))
        o = np.asarray(out.astype(jnp.float32))
        assert np.isposinf(o[0]) and np.isneginf(o[1]) and np.isnan(o[2])

    def test_pallas_kernel_matches_semantics(self):
        try:
            x = jnp.full((8, 128), 1.0 + 1 / 512.0, jnp.float32)
            out = stochastic_round_to_bf16_pallas(
                x, jnp.int32(42), interpret=True
            )
        except Exception as e:  # pragma: no cover - interpret-mode gaps
            pytest.skip(f"pallas interpret mode unavailable for prng: {e}")
        vals = set(np.unique(np.asarray(out.astype(jnp.float32))))
        assert vals <= {1.0, 1.0 + 1 / 128.0}


def _tree_close(a, b, tol):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32), atol=tol, rtol=tol
        )


class TestStochasticAdamW:
    def _problem(self, dtype):
        params = {
            "w": jnp.linspace(-1, 1, 64, dtype=jnp.float32).astype(dtype),
            "b": jnp.zeros((8,), dtype),
        }
        def grads_at(step):
            g = jax.random.normal(jax.random.PRNGKey(100 + step), (64,))
            return {"w": g.astype(jnp.float32), "b": jnp.ones((8,), jnp.float32)}
        return params, grads_at

    @pytest.mark.slow  # compile-bound minutes-class on the 2-core rig; e2e tier covers it
    def test_tracks_fp32_adamw(self):
        lr, wd = 1e-2, 0.1
        params_bf, grads_at = self._problem(jnp.bfloat16)
        params_32 = jax.tree.map(lambda p: p.astype(jnp.float32), params_bf)

        opt = StochasticAdamW(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
        ref = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
        state = opt.init(params_bf)
        ref_state = ref.init(params_32)

        for step in range(50):
            g = grads_at(step)
            new_p, state = jax.jit(opt.update)(g, state, params_bf)
            params_bf = opt.apply_updates(params_bf, new_p)
            upd, ref_state = ref.update(g, ref_state, params_32)
            params_32 = optax.apply_updates(params_32, upd)

        # bf16 stochastic trajectory stays near the fp32 one; individual
        # elements random-walk a few bf16 grid points, the mean error is tight
        _tree_close(params_bf, params_32, tol=8e-2)
        err = np.asarray(params_bf["w"].astype(jnp.float32)) - np.asarray(
            params_32["w"]
        )
        assert abs(err.mean()) < 5e-3
        assert jax.tree.leaves(params_bf)[0].dtype == jnp.bfloat16

    def test_reproducible_from_state(self):
        params, grads_at = self._problem(jnp.bfloat16)
        opt = StochasticAdamW(1e-2, seed=7)
        s0 = opt.init(params)
        p1, s1 = opt.update(grads_at(0), s0, params)
        p2, s2 = opt.update(grads_at(0), s0, params)
        _tree_close(p1, p2, tol=0.0)
        assert int(s1.count) == 1

    def test_moment_dtype_bf16(self):
        params, grads_at = self._problem(jnp.bfloat16)
        opt = StochasticAdamW(1e-2, moment_dtype=jnp.bfloat16)
        state = opt.init(params)
        assert jax.tree.leaves(state.mu)[0].dtype == jnp.bfloat16
        new_p, state = opt.update(grads_at(0), state, params)
        assert jax.tree.leaves(state.mu)[0].dtype == jnp.bfloat16
        assert jax.tree.leaves(new_p)[0].dtype == jnp.bfloat16

    def test_in_trainer_loop_loss_decreases(self):
        # tiny quadratic: params should descend
        params = {"w": jnp.full((128,), 2.0, jnp.bfloat16)}
        opt = StochasticAdamW(5e-2)
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(p["w"].astype(jnp.float32) ** 2)

        losses = []
        for _ in range(100):
            g = jax.grad(loss_fn)(params)
            g = {"w": g["w"].astype(jnp.float32)}
            new_p, state = opt.update(g, state, params)
            params = opt.apply_updates(params, new_p)
            losses.append(float(loss_fn(params)))
        assert losses[-1] < losses[0] * 0.2
