"""Global test configuration.

Tests run on a virtual 8-device CPU mesh (the TPU analogue of the
reference's 8-process `torchrun` rig — reference Makefile:9-12). The env
vars must be set before jax initializes its backends.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Must use config.update (not the env var): the environment may have already
# imported jax and registered an accelerator plugin at interpreter startup.
jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable the persistent compilation cache
# (jax_compilation_cache_dir) here to speed repeat runs: on this rig's
# jaxlib 0.4.37 CPU backend, executables deserialized from the cache
# segfault when re-run with donated buffers (reproduced on the trainer
# step + checkpoint-restore path). Revisit after a jaxlib upgrade.


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _fixed_seed():
    import random

    import numpy as np

    random.seed(0)
    np.random.seed(0)
    yield


def load_repo_module(name, relpath):
    """Load a repo-root script (bench.py, tools/*.py) by path — shared by
    the harness tests so the spec/exec boilerplate lives once."""
    import importlib.util
    import pathlib
    import sys

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(name, root / relpath)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod
