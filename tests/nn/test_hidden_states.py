"""Hidden-state aggregator tests (reference hidden_states_aggregator/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.nn.hidden_states import (
    HiddenStatesAggregationMode,
    create_hidden_states_aggregator,
    masked_mean_pool,
)


def test_masked_mean_pool():
    h = jnp.arange(2 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 3)
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]])
    out = masked_mean_pool(h, mask)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(h[0, :2].mean(0)), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out[1]), np.asarray(h[1].mean(0)), atol=1e-6
    )


def test_mean_aggregator_pack_and_snapshot_chain():
    mask = jnp.ones((2, 4))
    agg = create_hidden_states_aggregator(HiddenStatesAggregationMode.mean, mask)
    agg.add_hidden_states(jnp.ones((2, 4, 3)))
    agg.add_hidden_states(jnp.full((2, 4, 3), 2.0))
    packed = agg.pack_with_snapshot(None)
    assert packed.shape == (2, 2, 3)  # [layers, batch, dim]
    # next stage prepends the previous snapshot
    agg2 = create_hidden_states_aggregator(HiddenStatesAggregationMode.mean, mask)
    agg2.add_hidden_states(jnp.full((2, 4, 3), 3.0))
    packed2 = agg2.pack_with_snapshot(packed)
    assert packed2.shape == (3, 2, 3)
    np.testing.assert_allclose(np.asarray(packed2[0]), 1.0)
    np.testing.assert_allclose(np.asarray(packed2[2]), 3.0)
    # pack clears the buffer
    assert agg2.pack_with_snapshot(None) is None


def test_noop_and_errors():
    agg = create_hidden_states_aggregator(HiddenStatesAggregationMode.no, None)
    agg.add_hidden_states(jnp.ones((1, 2, 3)))
    assert agg.pack_with_snapshot(jnp.ones((1, 1, 3))) is None
    with pytest.raises(ValueError, match="aggregation mask"):
        create_hidden_states_aggregator(HiddenStatesAggregationMode.mean, None)
