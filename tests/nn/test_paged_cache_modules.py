"""Module-level paged-cache parity (nn/attention.py paged write/gather
against the dense slot caches): a paged cache whose gathered view
equals the dense cache must produce BITWISE-identical decode outputs —
for GQA (heads-major pools) and MLA (latent/rope-key pools, absorbed
and decompressed forms) — and the paged-mode contracts must fail
loudly. Quantized pools (int8 + sibling scale leaves, ``kv_quant``)
are parity-checked with a drift bound instead: int8 KV is lossy by
design, but the flash kernel's in-VMEM dequant and the eager gather's
dequant must agree with each other almost exactly. The serving-loop
integration is pinned in tests/loop/test_serve_paged.py; this file
isolates the module layer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.traverse_util import flatten_dict, unflatten_dict

from d9d_tpu.nn.attention import (
    GroupedQueryAttention,
    MultiHeadLatentAttention,
)
from d9d_tpu.nn.decode_flags import (
    PAGE_TABLE_LEAF,
    PAGED_CACHE_LEAVES,
    PAGED_SCALE_SUFFIX,
)
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.rope import compute_rope_frequencies, make_rope_cos_sin

B, DML, PS = 2, 16, 4


def _rope(b, start, t, d_rope):
    inv, scale = compute_rope_frequencies(d_rope, 10000.0)
    pos = jnp.broadcast_to(jnp.arange(start, start + t), (b, t))
    return make_rope_cos_sin(pos, inv, scale)


def _paged_cache(dense_cache, quant=False):
    """Convert a (zeroed) dense cache dict into pools + page tables —
    identity page assignment, exactly what loop/serve.py seeds; with
    ``quant`` the pools are int8 and sibling f32 scale pools ride next
    to them (the ``kv_quant="int8"`` layout)."""
    n_pages = DML // PS
    pool_n = B * n_pages + 1
    pt = np.zeros((B, n_pages), np.int32)
    nxt = 1
    for bi in range(B):
        for pi in range(n_pages):
            pt[bi, pi] = nxt
            nxt += 1
    out = {}
    for p, leaf in flatten_dict(dense_cache).items():
        name = p[-1]
        if name == "cache_index":
            out[p] = jnp.zeros((B,), jnp.int32)
        elif name in PAGED_CACHE_LEAVES:
            axis = PAGED_CACHE_LEAVES[name]
            shape = (
                (pool_n,) + leaf.shape[1:axis] + (PS,)
                + leaf.shape[axis + 1:]
            )
            if quant:
                out[p] = jnp.zeros(shape, jnp.int8)
                out[p[:-1] + (name + PAGED_SCALE_SUFFIX,)] = jnp.zeros(
                    shape[:-1], jnp.float32
                )
            else:
                out[p] = jnp.zeros(shape, leaf.dtype)
            out[p[:-1] + (PAGE_TABLE_LEAF,)] = jnp.asarray(pt)
        else:
            out[p] = leaf
    return unflatten_dict(out)


def _per_row_cache(dense_cache):
    out = {}
    for p, leaf in flatten_dict(dense_cache).items():
        out[p] = (
            jnp.zeros((B,), jnp.int32) if p[-1] == "cache_index" else leaf
        )
    return unflatten_dict(out)


def _drive(blk, params, cache, d_rope, steps=6, dim=None):
    dim = dim if dim is not None else blk.hidden_size
    outs = []
    for i in range(steps):
        x = jax.random.normal(jax.random.PRNGKey(100 + i), (B, 1, dim))
        cos, sin = _rope(B, i, 1, d_rope)
        o, st = blk.apply(
            {"params": params, "cache": cache}, x, cos, sin,
            mutable=["cache"],
        )
        cache = st["cache"]
        outs.append(o)
    return jnp.concatenate(outs, axis=1)


def test_gqa_paged_bitwise_matches_dense():
    blk = GroupedQueryAttention(
        hidden_size=32, num_heads=4, num_kv_heads=2, head_dim=8,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
        use_sinks=True, window_size=6,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 32))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x, cos, sin)
    zero = jax.tree.map(jnp.zeros_like, variables["cache"])
    want = _drive(blk, variables["params"], _per_row_cache(zero), 8)
    got = _drive(blk, variables["params"], _paged_cache(zero), 8)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


@pytest.mark.parametrize("absorbed", [True, False])
def test_mla_paged_bitwise_matches_dense(absorbed):
    blk = MultiHeadLatentAttention(
        hidden_size=64, num_heads=4, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=12, kv_lora_rank=32,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
        decode_absorbed=absorbed,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 64))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x, cos, sin)
    zero = jax.tree.map(jnp.zeros_like, variables["cache"])
    want = _drive(blk, variables["params"], _per_row_cache(zero), 8)
    got = _drive(blk, variables["params"], _paged_cache(zero), 8)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_gqa_paged_quant_drift_bounded(monkeypatch):
    """Int8 paged KV vs the dense f32 cache: lossy but bounded — and
    the pallas kernel's in-VMEM dequant must agree with the eager
    gather's dequant almost exactly (same int8*scale widening, only
    accumulation order differs)."""
    blk = GroupedQueryAttention(
        hidden_size=32, num_heads=4, num_kv_heads=2, head_dim=8,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
        use_sinks=True, window_size=6,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 32))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x, cos, sin)
    zero = jax.tree.map(jnp.zeros_like, variables["cache"])
    want = _drive(blk, variables["params"], _per_row_cache(zero), 8)
    monkeypatch.setenv("D9D_TPU_DECODE_ATTN", "eager")
    got_eager = _drive(
        blk, variables["params"], _paged_cache(zero, quant=True), 8
    )
    # int8 per-slot-per-head scales keep attention outputs close to the
    # full-precision reference; the bound is loose on purpose (lossy)
    np.testing.assert_allclose(
        np.asarray(want), np.asarray(got_eager), atol=0.05, rtol=0.05
    )
    monkeypatch.setenv("D9D_TPU_DECODE_ATTN", "pallas")
    got_flash = _drive(
        blk, variables["params"], _paged_cache(zero, quant=True), 8
    )
    # kernel dequant vs eager dequant: the SAME quantized bytes widen
    # through both paths — near-bitwise, not drift-bounded
    np.testing.assert_allclose(
        np.asarray(got_eager), np.asarray(got_flash), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("absorbed", [True, False])
def test_mla_paged_quant_drift_bounded(absorbed):
    blk = MultiHeadLatentAttention(
        hidden_size=64, num_heads=4, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=12, kv_lora_rank=32,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
        decode_absorbed=absorbed,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 64))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x, cos, sin)
    zero = jax.tree.map(jnp.zeros_like, variables["cache"])
    want = _drive(blk, variables["params"], _per_row_cache(zero), 8)
    got = _drive(
        blk, variables["params"], _paged_cache(zero, quant=True), 8
    )
    np.testing.assert_allclose(
        np.asarray(want), np.asarray(got), atol=0.05, rtol=0.05
    )


def test_paged_quant_pools_stay_int8():
    """The write path must keep quantized pools int8 (a silent f32
    resurrection would double the bytes and void the audit census) and
    actually land scales for written slots."""
    blk = GroupedQueryAttention(
        hidden_size=32, num_heads=4, num_kv_heads=2, head_dim=8,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 32))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x, cos, sin)
    cache = _paged_cache(
        jax.tree.map(jnp.zeros_like, variables["cache"]), quant=True
    )
    _, st = blk.apply(
        {"params": variables["params"], "cache": cache}, x, cos, sin,
        mutable=["cache"],
    )
    flat = flatten_dict(st["cache"])
    for p, leaf in flat.items():
        if p[-1] in PAGED_CACHE_LEAVES:
            assert leaf.dtype == jnp.int8, p
        if p[-1].endswith(PAGED_SCALE_SUFFIX):
            assert leaf.dtype == jnp.float32, p
            assert np.abs(np.asarray(leaf)).max() > 0.0, p


def test_paged_contracts_fail_loudly():
    blk = GroupedQueryAttention(
        hidden_size=32, num_heads=4, num_kv_heads=2, head_dim=8,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=DML,
    )
    x1 = jax.random.normal(jax.random.PRNGKey(0), (B, 1, 32))
    cos, sin = _rope(B, 0, 1, 8)
    variables = blk.init(jax.random.PRNGKey(1), x1, cos, sin)
    paged = _paged_cache(jax.tree.map(jnp.zeros_like, variables["cache"]))
    # multi-token calls never reach a paged cache (the serving loop
    # teacher-forces prompts token-by-token)
    x3 = jax.random.normal(jax.random.PRNGKey(2), (B, 3, 32))
    cos3, sin3 = _rope(B, 0, 3, 8)
    with pytest.raises(NotImplementedError, match="single-token"):
        blk.apply(
            {"params": variables["params"], "cache": paged},
            x3, cos3, sin3, mutable=["cache"],
        )
    # slot masks don't compose with paging
    with pytest.raises(NotImplementedError, match="slot mask"):
        blk.apply(
            {"params": variables["params"], "cache": paged},
            x1, cos, sin, mask=jnp.ones((B, 1, 1, DML), bool),
            mutable=["cache"],
        )