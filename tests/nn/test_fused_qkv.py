"""fused_qkv parity: one-matmul q/k/v must be numerically identical to
three Dense projections with the SAME parameter pytree (r4 dense-MFU
lever; checkpoints/plans see no difference)."""

import pytest

# slow tier (r5 quick-tier trim): whole-model double-build parity
pytestmark = pytest.mark.e2e

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
from d9d_tpu.ops.attention.eager import eager_sdpa


def _cfg(fused):
    return Qwen3DenseConfig(
        vocab_ranges=(("default", 64),), hidden_size=32, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, intermediate_size=64,
        remat=False, fused_qkv=fused,
    )


@pytest.mark.slow  # >10s compile-bound on the 2-core rig; e2e tier covers it
def test_fused_qkv_matches_unfused_params_and_outputs():
    from d9d_tpu.core import MeshParameters

    # a previous test may leave a tp>1 ambient mesh (MeshParameters.build
    # sets it globally), which the fused path rightfully rejects — pin the
    # single-device mesh this test is about
    MeshParameters().build(jax.devices()[:1])
    b, t = 2, 16
    tokens = jnp.zeros((b, t), jnp.int32).at[:, 5:].set(3)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    labels = jnp.ones((b, t), jnp.int32)

    m_ref = Qwen3DenseCausalLM(config=_cfg(False), sdpa=eager_sdpa,
                               dtype=jnp.float32)
    m_fused = Qwen3DenseCausalLM(config=_cfg(True), sdpa=eager_sdpa,
                                 dtype=jnp.float32)
    p_ref = m_ref.init(jax.random.PRNGKey(0), tokens, pos, labels)
    p_fused = m_fused.init(jax.random.PRNGKey(0), tokens, pos, labels)

    # identical parameter pytree: same paths, shapes, and init values
    ref_leaves = jax.tree_util.tree_leaves_with_path(p_ref)
    fused_leaves = jax.tree_util.tree_leaves_with_path(p_fused)
    assert [k for k, _ in ref_leaves] == [k for k, _ in fused_leaves]
    for (k, a), (_, b_) in zip(ref_leaves, fused_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_), err_msg=str(k))

    def loss(m, p):
        out = m.apply(p, tokens, pos, labels)
        leaf = jax.tree.leaves(out)[0]
        return jnp.sum(leaf.astype(jnp.float32))

    l_ref, g_ref = jax.value_and_grad(lambda p: loss(m_ref, p))(p_ref)
    l_fused, g_fused = jax.value_and_grad(lambda p: loss(m_fused, p))(p_ref)
    np.testing.assert_allclose(np.asarray(l_fused), np.asarray(l_ref),
                               rtol=1e-6, atol=1e-6)
    for a, b_ in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_fused)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6)


def test_fused_qkv_rejects_tp_mesh():
    import jax
    import pytest

    from d9d_tpu.core import MeshParameters

    ctx = MeshParameters(tp=2).build(jax.devices()[:2])
    del ctx  # MeshParameters.build sets the ambient mesh
    b, t = 1, 8
    tokens = jnp.zeros((b, t), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    labels = jnp.zeros((b, t), jnp.int32)
    m = Qwen3DenseCausalLM(config=_cfg(True), sdpa=eager_sdpa,
                           dtype=jnp.float32)
    with pytest.raises(ValueError, match="fused_qkv"):
        m.init(jax.random.PRNGKey(0), tokens, pos, labels)


def test_cce_auto_respects_vocab_budget():
    """auto must keep chunking when n*V exceeds the swept slab even at
    small n (large-vocab models never materialize [N, V])."""
    from unittest import mock

    import d9d_tpu.ops.linear_ce as lce

    h = jnp.ones((1024, 8), jnp.float32)
    w = jnp.ones((131072, 8), jnp.float32)  # n*V = 2^27 >> swept budget
    labels = jnp.zeros((1024,), jnp.int32)
    with mock.patch.object(
        lce, "_chunk_loss", wraps=lce._chunk_loss
    ) as spy:
        lce.linear_cross_entropy(h, w, labels)
    # chunked path: _chunk_loss is called via lax.map body trace, with a
    # [512, ...] chunk — never the full 1024-token slab
    assert spy.called
    for call in spy.call_args_list:
        assert call.args[0].shape[0] == 512
