"""TopKRouter group-limited routing (DeepSeek group_limited_greedy):
experts partition into groups scored by their best member; only the top
``topk_group`` groups are eligible for the global top-k. Quick-tier
oracle checks against a numpy reimplementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.nn.moe import TopKRouter


def _route(n_group, topk_group, e=8, k=2, seed=0):
    router = TopKRouter(
        dim=16, num_experts=e, top_k=k,
        renormalize_probabilities=False,
        n_group=n_group, topk_group=topk_group,
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(seed), (3, 5, 16))
    params = router.init(jax.random.PRNGKey(1), x)
    ids, probs = router.apply(params, x)
    # recover the full softmax for the oracle
    import flax.linen as fnn

    kernel = fnn.unbox(params)["params"]["gate"]["kernel"]
    full = jax.nn.softmax(x @ kernel, axis=-1)
    return np.asarray(ids), np.asarray(probs), np.asarray(full)


def test_plain_topk_unchanged():
    ids, probs, full = _route(n_group=1, topk_group=1)
    want_ids = np.argsort(-full, axis=-1)[..., :2]
    np.testing.assert_array_equal(np.sort(ids, -1), np.sort(want_ids, -1))
    np.testing.assert_allclose(
        probs, np.take_along_axis(full, ids, -1), rtol=1e-6
    )


def test_group_limited_oracle():
    e, k, n_group, topk_group = 8, 2, 4, 2
    ids, probs, full = _route(n_group, topk_group, e=e, k=k, seed=3)
    per = e // n_group
    for idx in np.ndindex(full.shape[:-1]):
        row = full[idx]
        gscore = row.reshape(n_group, per).max(-1)
        top_groups = np.argsort(-gscore)[:topk_group]
        eligible = np.zeros(e, bool)
        for g in top_groups:
            eligible[g * per:(g + 1) * per] = True
        masked = np.where(eligible, row, -np.inf)
        want = set(np.argsort(-masked)[:k])
        assert set(ids[idx]) == want, (idx, ids[idx], want)
        # returned weights are the RAW softmax probs of the selection
        np.testing.assert_allclose(
            probs[idx], row[ids[idx]], rtol=1e-6
        )


def test_group_routing_can_differ_from_plain():
    """With a tight group budget, at least one token must route
    differently than plain top-k (otherwise the test proves nothing)."""
    ids_g, _, full = _route(n_group=4, topk_group=1, e=8, k=2, seed=5)
    want_plain = np.argsort(-full, axis=-1)[..., :2]
    assert (np.sort(ids_g, -1) != np.sort(want_plain, -1)).any()


def test_invalid_group_divisibility():
    router = TopKRouter(
        dim=8, num_experts=6, top_k=2, n_group=4, topk_group=2,
        dtype=jnp.float32,
    )
    x = jnp.zeros((2, 3, 8))
    with pytest.raises(ValueError, match="not divisible"):
        router.init(jax.random.PRNGKey(0), x)
