"""MLA + GatedDeltaNet block tests: shapes, causality, grads, variants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
pytestmark = pytest.mark.e2e  # slow tier: heavy kernel/e2e parity


from d9d_tpu.nn.attention import MultiHeadLatentAttention
from d9d_tpu.nn.linear_attention import DecayGateKind, GatedDeltaNet
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.rope import compute_rope_frequencies, make_rope_cos_sin


def _rope(b, t, d_rope):
    inv, scale = compute_rope_frequencies(d_rope, 10000.0)
    pos = jnp.broadcast_to(jnp.arange(t), (b, t))
    return make_rope_cos_sin(pos, inv, scale)


class TestMLA:
    def _block(self, q_lora=None):
        return MultiHeadLatentAttention(
            hidden_size=64,
            num_heads=4,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=12,
            kv_lora_rank=32,
            q_lora_rank=q_lora,
            sdpa=eager_sdpa,
            dtype=jnp.float32,
        )

    @pytest.mark.parametrize("q_lora", [None, 24])
    def test_shapes_and_grads(self, q_lora):
        blk = self._block(q_lora)
        b, t = 2, 10
        x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
        cos, sin = _rope(b, t, 8)
        params = blk.init(jax.random.PRNGKey(1), x, cos, sin)
        out = blk.apply(params, x, cos, sin)
        assert out.shape == (b, t, 64)
        if q_lora is not None:
            assert "down_proj" in params["params"]["q_proj"]

        g = jax.grad(lambda p: jnp.sum(blk.apply(p, x, cos, sin) ** 2))(params)
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))

    def test_causality(self):
        blk = self._block()
        b, t = 1, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
        cos, sin = _rope(b, t, 8)
        params = blk.init(jax.random.PRNGKey(1), x, cos, sin)
        out1 = blk.apply(params, x, cos, sin)
        x2 = x.at[:, -1].set(99.0)  # perturb the future
        out2 = blk.apply(params, x2, cos, sin)
        np.testing.assert_allclose(
            np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
        )

    @pytest.mark.parametrize("absorbed", [True, False])
    def test_latent_cache_decode_matches_full_forward(self, absorbed):
        """MLA decode caches (latent, rotated rope key) per token; prefill
        + teacher-forced single-token steps must reproduce the full
        forward at every position — in BOTH the absorbed (rank-space)
        form and the decompressed oracle (``decode_absorbed=False``,
        which re-expands every cache slot through kv_up per step)."""
        b, t, p = 2, 12, 8
        full = self._block()
        dec = MultiHeadLatentAttention(
            hidden_size=64,
            num_heads=4,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=12,
            kv_lora_rank=32,
            sdpa=eager_sdpa,
            dtype=jnp.float32,
            decode_max_length=16,
            decode_absorbed=absorbed,
        )
        x = jax.random.normal(jax.random.PRNGKey(3), (b, t, 64))
        cos, sin = _rope(b, t, 8)
        params = full.init(jax.random.PRNGKey(1), x, cos, sin)
        want = full.apply(params, x, cos, sin)

        got_pre, state = dec.apply(
            params, x[:, :p], cos[:, :p], sin[:, :p], mutable=["cache"]
        )
        np.testing.assert_allclose(
            np.asarray(got_pre), np.asarray(want[:, :p]),
            rtol=2e-5, atol=2e-5,
        )
        cache = state["cache"]
        for i in range(p, t):
            got_i, state = dec.apply(
                {**params, "cache": cache},
                x[:, i : i + 1], cos[:, i : i + 1], sin[:, i : i + 1],
                mutable=["cache"],
            )
            cache = state["cache"]
            np.testing.assert_allclose(
                np.asarray(got_i[:, 0]), np.asarray(want[:, i]),
                rtol=2e-5, atol=2e-5,
            )
        # the cache really is the compressed form: latent + rope key only
        slot_bytes = sum(
            np.prod(v.shape[2:])
            for k, v in cache.items()
            if k.startswith("cached")
        )
        assert slot_bytes == 32 + 8  # kv_lora_rank + d_rope per token


class TestGatedDeltaNet:
    def _block(self, gate=DecayGateKind.mamba, hqk=2, hv=4):
        return GatedDeltaNet(
            hidden_size=64,
            num_qk_heads=hqk,
            num_v_heads=hv,
            head_qk_dim=16,
            head_v_dim=8,
            conv_size=4,
            decay_gate=gate,
            chunk_size=8,
            dtype=jnp.float32,
        )

    @pytest.mark.parametrize("gate", [DecayGateKind.mamba, DecayGateKind.logsigmoid])
    @pytest.mark.parametrize("hqk,hv", [(2, 4), (4, 4)])
    def test_shapes_and_grads(self, gate, hqk, hv):
        blk = self._block(gate, hqk, hv)
        b, t = 2, 24
        x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
        params = blk.init(jax.random.PRNGKey(1), x)
        out = blk.apply(params, x)
        assert out.shape == (b, t, 64)
        g = jax.grad(lambda p: jnp.sum(blk.apply(p, x) ** 2))(params)
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))

    def test_causality(self):
        blk = self._block()
        b, t = 1, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
        params = blk.init(jax.random.PRNGKey(1), x)
        out1 = blk.apply(params, x)
        x2 = x.at[:, -1].set(7.0)
        out2 = blk.apply(params, x2)
        np.testing.assert_allclose(
            np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
        )

    def test_mask_zeroes_padding_influence(self):
        blk = self._block()
        b, t = 1, 12
        x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
        params = blk.init(jax.random.PRNGKey(1), x)
        mask = jnp.ones((b, t)).at[:, 6:].set(0.0)
        out_masked = blk.apply(params, x, mask)
        x_zeroed = x * mask[..., None]
        out_zeroed = blk.apply(params, x_zeroed, mask)
        np.testing.assert_allclose(
            np.asarray(out_masked[:, :6]), np.asarray(out_zeroed[:, :6]), atol=1e-5
        )

    def test_dt_bias_init_is_inverse_softplus(self):
        blk = self._block()
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 64))
        import flax.linen as nn

        params = nn.unbox(blk.init(jax.random.PRNGKey(1), x))
        dt_bias = params["params"]["decay_gate"]["dt_bias"]
        dt = np.asarray(jax.nn.softplus(dt_bias))
        assert (dt >= 1e-4 - 1e-9).all() and (dt <= 0.2).all()


@requires_modern_jax
def test_mla_with_ring_attention_matches_eager(devices):
    """MLA composes with context-parallel ring attention (long-context
    path for the latent-attention family): same outputs and grads as the
    eager backend on the gathered sequence."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from d9d_tpu.core import MeshParameters
    from d9d_tpu.ops.attention.ring import make_ring_sdpa

    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(
        ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=()
    )

    def block(sdpa):
        return MultiHeadLatentAttention(
            hidden_size=64,
            num_heads=4,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=12,
            kv_lora_rank=32,
            sdpa=sdpa,
            dtype=jnp.float32,
        )

    b, t = 2, 32
    x = jax.random.normal(jax.random.PRNGKey(0), (b, t, 64))
    cos, sin = _rope(b, t, 8)
    params = block(eager_sdpa).init(jax.random.PRNGKey(1), x, cos, sin)

    def loss_eager(p, x):
        return jnp.sum(jnp.sin(block(eager_sdpa).apply(p, x, cos, sin)))

    x_sharded = jax.device_put(
        x, NamedSharding(ctx.mesh, P(None, "cp_s", None))
    )

    def loss_ring(p, x):
        return jnp.sum(jnp.sin(block(ring).apply(p, x, cos, sin)))

    l_e, g_e = jax.value_and_grad(loss_eager)(params, x)
    l_r, g_r = jax.jit(jax.value_and_grad(loss_ring))(params, x_sharded)
    np.testing.assert_allclose(float(l_r), float(l_e), rtol=1e-4, atol=1e-4)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5
        ),
        g_r,
        g_e,
    )
