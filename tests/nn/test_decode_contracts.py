"""Decode cache contract assertions (ADVICE r4).

Two contracts are traced and therefore unverifiable by shape alone:
the multi-token prefill fast path requires an EMPTY cache (start == 0),
and the cache must never overflow (``dynamic_update_slice`` clamps past
capacity and attention silently degrades). ``_decode_contract_checks``
expresses both as ``checkify.debug_check`` — a no-op in plain jit, a
loud error when the caller functionalizes with ``checkify.checkify``.
These tests prove the violations ARE caught that way, and that the
valid flow stays silent.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import checkify

from d9d_tpu.nn.attention import GroupedQueryAttention
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.rope import compute_rope_frequencies, make_rope_cos_sin


def _rope(b, t, d, start=0):
    inv, scale = compute_rope_frequencies(d, 10000.0)
    pos = jnp.broadcast_to(jnp.arange(start, start + t), (b, t))
    return make_rope_cos_sin(pos, inv, scale)


@pytest.fixture(scope="module")
def gqa_setup():
    blk = GroupedQueryAttention(
        hidden_size=32,
        num_heads=4,
        num_kv_heads=2,
        head_dim=8,
        sdpa=eager_sdpa,
        dtype=jnp.float32,
        decode_max_length=8,
    )
    b = 1
    x4 = jax.random.normal(jax.random.PRNGKey(0), (b, 4, 32))
    cos, sin = _rope(b, 4, 8)
    variables = blk.init(jax.random.PRNGKey(1), x4, cos, sin)
    # init ran a forward, so its cache is warm — tests start from zeros
    fresh = jax.tree.map(jnp.zeros_like, variables["cache"])
    return blk, x4, cos, sin, {"params": variables["params"],
                               "cache": fresh}


def _checked_apply(blk, params, cache, x, cos, sin):
    def fn(x):
        out, state = blk.apply(
            {"params": params, "cache": cache}, x, cos, sin,
            mutable=["cache"],
        )
        return out, state

    err, (out, state) = checkify.checkify(
        jax.jit(fn), errors=checkify.user_checks
    )(x)
    return err, out, state


def test_valid_prefill_then_steps_pass_checks(gqa_setup):
    blk, x4, cos, sin, variables = gqa_setup
    params = variables["params"]
    err, _, state = _checked_apply(
        blk, params, variables["cache"], x4, cos, sin
    )
    err.throw()  # no error on an empty-cache prefill
    c1, s1 = _rope(1, 1, 8, start=4)
    err, _, _ = _checked_apply(
        blk, params, state["cache"], x4[:, :1], c1, s1
    )
    err.throw()  # single-token step within capacity: silent


def test_prefill_on_warm_cache_fails_loudly(gqa_setup):
    blk, x4, cos, sin, variables = gqa_setup
    params = variables["params"]
    _, _, state = _checked_apply(
        blk, params, variables["cache"], x4, cos, sin
    )
    err, _, _ = _checked_apply(
        blk, params, state["cache"], x4, cos, sin
    )
    with pytest.raises(checkify.JaxRuntimeError, match="empty cache"):
        err.throw()


def test_cache_overflow_fails_loudly(gqa_setup):
    blk, x4, cos, sin, variables = gqa_setup
    params = variables["params"]
    cache = variables["cache"]
    state = {"cache": cache}
    # capacity 8: two 4-token prefills fill it; the second call violates
    # the prefill contract too, so drive with single-token steps instead
    _, _, state = _checked_apply(blk, params, cache, x4, cos, sin)
    for i in range(4, 8):
        c1, s1 = _rope(1, 1, 8, start=i)
        err, _, state = _checked_apply(
            blk, params, state["cache"], x4[:, :1], c1, s1
        )
        err.throw()
    c1, s1 = _rope(1, 1, 8, start=8)
    err, _, _ = _checked_apply(
        blk, params, state["cache"], x4[:, :1], c1, s1
    )
    with pytest.raises(checkify.JaxRuntimeError, match="overflow"):
        err.throw()
