"""tools/roofline.py: the analytic attribution must stay runnable and
keep telling the story BASELINE.md cites (quick tier — pure numpy-free
arithmetic, no jax backend)."""
import json
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _rows():
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "roofline.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-800:]
    return {r["scenario"]: r for r in map(json.loads, out.stdout.splitlines())}


def test_roofline_scenarios():
    rows = _rows()
    dense = rows["dense_256m"]
    # calibration anchor: within +-25% of the measured dense row (48,127)
    assert 0.75 * 48127 < dense["predicted_tokens_per_sec"] < 1.25 * 48127

    moe1 = rows["qwen3_moe_ub1_fp32"]
    # the ceiling explanation: ub1/fp32 lands in the measured row's band
    assert 0.8 * 25280 < moe1["predicted_tokens_per_sec"] < 1.4 * 25280
    # ...and the top component is the HBM-bound expert gate+up matmul
    top_name, top = next(iter(moe1["top_components"].items()))
    assert top_name == "moe.experts_gate_up"
    assert top["bound"] == "hbm"

    # the queued recovery levers must rank correctly: ub2+bf16 > ub1,
    # ub4+bf16 > ub2, and ub4 clears the VERDICT 0.25-MFU target
    moe2 = rows["qwen3_moe_ub2_bf16"]
    moe4 = rows["qwen3_moe_ub4_bf16"]
    assert (moe2["predicted_mfu"] > moe1["predicted_mfu"])
    assert (moe4["predicted_mfu"] > moe2["predicted_mfu"])
    assert moe4["predicted_mfu"] >= 0.25

    # ZeRO pre-registrations (docs/design/zero_sharding.md): sharding
    # the optimizer stream + grad accumulator over 4 replicas must beat
    # every same-µBS replicated row, and ub2+zero already clears 0.25
    for ub, base in (("1_fp32", moe1), ("2_bf16", moe2), ("4_bf16", moe4)):
        z = rows[f"qwen3_moe_ub{ub}_zero4"]
        assert z["predicted_mfu"] > base["predicted_mfu"]
    assert rows["qwen3_moe_ub2_bf16_zero4"]["predicted_mfu"] >= 0.25
