"""Piecewise LR schedule tests (parity with the reference builder/engine
semantics: warmup→hold→decay shapes, clamping, jit traceability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.lr_scheduler import (
    CurveCosine,
    CurveExponential,
    CurveLinear,
    CurvePoly,
    PiecewiseSchedulerConfig,
    piecewise_schedule,
    piecewise_scheduler_from_config,
    sample_schedule,
)


def test_linear_warmup_and_clamp():
    sched = (
        piecewise_schedule(0.0, total_steps=100)
        .for_steps(10, 1.0, CurveLinear())
        .fill_rest(0.0, CurveLinear())
        .build()
    )
    assert float(sched(-5)) == 0.0  # clamps below
    assert float(sched(0)) == 0.0
    assert float(sched(5)) == pytest.approx(0.5)
    assert float(sched(10)) == pytest.approx(1.0)
    assert float(sched(55)) == pytest.approx(0.5)
    assert float(sched(100)) == pytest.approx(0.0)
    assert float(sched(1000)) == pytest.approx(0.0)  # clamps above


def test_cosine_hits_midpoint():
    sched = piecewise_schedule(1.0).for_steps(100, 0.0, CurveCosine()).build()
    assert float(sched(0)) == pytest.approx(1.0)
    assert float(sched(50)) == pytest.approx(0.5, abs=1e-6)
    assert float(sched(100)) == pytest.approx(0.0)


def test_poly_and_exponential_curves():
    poly = piecewise_schedule(0.0).for_steps(10, 1.0, CurvePoly(2.0)).build()
    assert float(poly(5)) == pytest.approx(0.25)

    exp = (
        piecewise_schedule(1.0).for_steps(10, 0.01, CurveExponential()).build()
    )
    assert float(exp(5)) == pytest.approx(0.1, rel=1e-4)


def test_multi_phase_continuity():
    sched = (
        piecewise_schedule(0.0, total_steps=1000)
        .for_steps(100, 1.0, CurveLinear())
        .until_percentage(0.5, 1.0, CurveLinear())
        .fill_rest(0.1, CurveCosine())
        .build()
    )
    ys = sample_schedule(sched, 1000)
    assert np.all(np.abs(np.diff(ys)) < 0.05)  # no jumps
    assert ys[100] == pytest.approx(1.0)
    assert ys[300] == pytest.approx(1.0)
    assert ys[999] == pytest.approx(0.1, abs=1e-2)


def test_builder_validation():
    with pytest.raises(ValueError):
        piecewise_schedule(0.0).until_percentage(0.5, 1.0, CurveLinear())
    with pytest.raises(ValueError):
        (
            piecewise_schedule(0.0, total_steps=10)
            .for_steps(20, 1.0, CurveLinear())
            .build()
        )
    with pytest.raises(ValueError):
        (
            piecewise_schedule(0.0, total_steps=100)
            .until_percentage(0.5, 1.0, CurveLinear())
            .until_percentage(0.1, 1.0, CurveLinear())
        )


def test_jit_traceable():
    sched = (
        piecewise_schedule(0.0, total_steps=100)
        .for_steps(10, 1.0, CurveLinear())
        .fill_rest(0.0, CurveCosine())
        .build()
    )

    @jax.jit
    def f(step):
        return sched(step)

    for s in (0, 5, 10, 50, 99):
        assert float(f(jnp.asarray(s))) == pytest.approx(float(sched(s)))


def test_from_config_matches_builder():
    config = PiecewiseSchedulerConfig.model_validate(
        {
            "initial_multiplier": 0.0,
            "phases": [
                {
                    "mode": "steps",
                    "steps": 10,
                    "target_multiplier": 1.0,
                    "curve": {"type": "linear"},
                },
                {
                    "mode": "percentage",
                    "percentage": 0.5,
                    "target_multiplier": 0.5,
                    "curve": {"type": "poly", "power": 2.0},
                },
                {
                    "mode": "rest",
                    "target_multiplier": 0.0,
                    "curve": {"type": "cosine"},
                },
            ],
        }
    )
    sched = piecewise_scheduler_from_config(config, total_steps=100)
    manual = (
        piecewise_schedule(0.0, total_steps=100)
        .for_steps(10, 1.0, CurveLinear())
        .until_percentage(0.5, 0.5, CurvePoly(2.0))
        .fill_rest(0.0, CurveCosine())
        .build()
    )
    np.testing.assert_allclose(
        sample_schedule(sched, 100), sample_schedule(manual, 100), rtol=1e-6
    )


def test_build_lr_scales():
    sched = (
        piecewise_schedule(0.0)
        .for_steps(10, 1.0, CurveLinear())
        .build_lr(3e-4)
    )
    assert float(sched(10)) == pytest.approx(3e-4)
