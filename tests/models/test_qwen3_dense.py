import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: heavy kernel/e2e parity


from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.pipelining import PipelineStageInfo


@pytest.fixture(scope="module")
def tiny_cfg():
    return Qwen3DenseConfig.tiny(vocab_size=128)


def make_model(cfg, stage=PipelineStageInfo(), dtype=jnp.float32):
    return Qwen3DenseCausalLM(
        config=cfg, sdpa=eager_sdpa, stage=stage, dtype=dtype, param_dtype=jnp.float32
    )


def test_forward_loss_shape(tiny_cfg):
    model = make_model(tiny_cfg)
    tokens = jnp.arange(24).reshape(2, 12) % 128
    positions = jnp.broadcast_to(jnp.arange(12), (2, 12))
    labels = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), tokens, positions, labels)
    loss = model.apply(params, tokens, positions, labels)
    assert loss.shape == (2, 12)
    assert np.isfinite(np.asarray(loss)).all()


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_grads_flow(tiny_cfg):
    model = make_model(tiny_cfg)
    tokens = jnp.arange(16).reshape(2, 8) % 128
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
    labels = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), tokens, positions, labels)

    def loss_fn(p):
        return model.apply(p, tokens, positions, labels).mean()

    grads = jax.grad(loss_fn)(params)
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    assert any(np.abs(np.asarray(g)).max() > 0 for g in leaves)


def test_pipeline_stage_split_matches_full(tiny_cfg):
    """Two chained stages with the full model's params must reproduce the
    single-stage forward exactly (global layer naming contract)."""
    full = make_model(tiny_cfg)
    tokens = jnp.arange(16).reshape(2, 8) % 128
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
    labels = jnp.roll(tokens, -1, axis=1)
    params = full.init(jax.random.PRNGKey(0), tokens, positions, labels)
    full_loss = full.apply(params, tokens, positions, labels)

    s0 = make_model(tiny_cfg, PipelineStageInfo(0, 2))
    s1 = make_model(tiny_cfg, PipelineStageInfo(1, 2))
    p = params["params"]
    p0 = {"params": {"model": {
        "embed_tokens": p["model"]["embed_tokens"],
        "layers_0": p["model"]["layers_0"],
    }}}
    p1 = {"params": {
        "model": {"layers_1": p["model"]["layers_1"], "norm": p["model"]["norm"]},
        "lm_head": p["lm_head"],
    }}
    h = s0.apply(p0, tokens, positions)
    assert h.shape == (2, 8, tiny_cfg.hidden_size)
    loss = s1.apply(p1, h, positions, labels)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(full_loss), rtol=1e-5)


def test_hf_parity(tiny_cfg):
    """Numerical parity vs transformers Qwen3ForCausalLM with copied weights.

    Mirrors the reference's block-level HF parity tests
    (test/d9d_test/modules/block/attention/grouped_query/test_hf_qwen3.py).
    """
    torch = pytest.importorskip("torch")
    from transformers import Qwen3Config, Qwen3ForCausalLM

    cfg = tiny_cfg
    hf_cfg = Qwen3Config(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rope_theta,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    torch.manual_seed(0)
    hf = Qwen3ForCausalLM(hf_cfg).eval()

    def t2j(t):
        return jnp.asarray(t.detach().numpy())

    hfm = hf.model
    layers = {}
    for i, hl in enumerate(hfm.layers):
        layers[f"layers_{i}"] = {
            "input_layernorm": {"weight": t2j(hl.input_layernorm.weight)},
            "post_attention_layernorm": {
                "weight": t2j(hl.post_attention_layernorm.weight)
            },
            "self_attn": {
                "q_proj": {"kernel": t2j(hl.self_attn.q_proj.weight).T},
                "k_proj": {"kernel": t2j(hl.self_attn.k_proj.weight).T},
                "v_proj": {"kernel": t2j(hl.self_attn.v_proj.weight).T},
                "o_proj": {"kernel": t2j(hl.self_attn.o_proj.weight).T},
                "q_norm": {"weight": t2j(hl.self_attn.q_norm.weight)},
                "k_norm": {"weight": t2j(hl.self_attn.k_norm.weight)},
            },
            "mlp": {
                "gate_proj": {"kernel": t2j(hl.mlp.gate_proj.weight).T},
                "up_proj": {"kernel": t2j(hl.mlp.up_proj.weight).T},
                "down_proj": {"kernel": t2j(hl.mlp.down_proj.weight).T},
            },
        }
    params = {"params": {
        "model": {
            "embed_tokens": {"embedding_default": t2j(hfm.embed_tokens.weight)},
            "norm": {"weight": t2j(hfm.norm.weight)},
            **layers,
        },
        "lm_head": {"head_default": t2j(hf.lm_head.weight)},
    }}

    model = make_model(cfg)
    tokens_np = np.arange(20).reshape(2, 10) % cfg.vocab_size
    positions = jnp.broadcast_to(jnp.arange(10), (2, 10))
    ours = model.apply(
        params, jnp.asarray(tokens_np), positions, method=model.logits
    )
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens_np)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_remat_policy_variants_match(devices):
    """remat off / full / dots_no_batch compute identical losses."""
    import dataclasses

    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.ops.attention.eager import eager_sdpa

    base = dataclasses.replace(Qwen3DenseConfig.tiny(), remat=False)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 16)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))

    def loss_and_grad(cfg):
        model = Qwen3DenseCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        params = {"params": variables["params"]}
        return jax.value_and_grad(
            lambda p: model.apply(p, tokens, positions, tokens).mean()
        )(params)

    l0, g0 = loss_and_grad(base)
    for policy in ("full", "dots_no_batch"):
        cfg = dataclasses.replace(base, remat=True, remat_policy=policy)
        l, g = loss_and_grad(cfg)
        np.testing.assert_allclose(float(l), float(l0), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            g,
            g0,
        )
