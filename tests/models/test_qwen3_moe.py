"""Qwen3-MoE model tests: forward shapes, EP==local parity on the mesh,
HF parity (reference strategy: moe block + model HF tests, SURVEY §4.2-4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
# slow tier: heavy kernel/e2e parity
pytestmark = [pytest.mark.e2e, requires_modern_jax]


from d9d_tpu.core import MeshParameters
from d9d_tpu.models.qwen3 import Qwen3MoeCausalLM, Qwen3MoeConfig
from d9d_tpu.nn.moe import MoELayer
from d9d_tpu.nn.sdpa import build_sdpa_backend
from d9d_tpu.ops.attention.eager import eager_sdpa

B, T = 4, 16


@pytest.fixture(scope="module")
def ctx():
    return MeshParameters(dp_shard=4, tp=2, ep_shard=8).build(jax.devices())


def _model(ep_axes=None):
    return Qwen3MoeCausalLM(
        config=Qwen3MoeConfig.tiny(ep_axes=ep_axes),
        sdpa=eager_sdpa,
        dtype=jnp.float32,
    )


def _inputs(vocab=256):
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    return tokens, positions


def test_forward_loss_shape(ctx):
    model = _model()
    tokens, positions = _inputs()
    variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss = model.apply(params, tokens, positions, tokens)
    assert loss.shape == (B, T)
    assert np.isfinite(np.asarray(loss)).all()


def test_ep_matches_local(ctx):
    tokens, positions = _inputs()
    local = _model()
    variables = local.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss_local = local.apply(params, tokens, positions, tokens)

    ep = _model(ep_axes=ctx.ep_shard_axes)
    loss_ep = jax.jit(ep.apply)(params, tokens, positions, tokens)
    np.testing.assert_allclose(
        np.asarray(loss_ep), np.asarray(loss_local), rtol=2e-4, atol=2e-5
    )

    g_local = jax.grad(
        lambda p: local.apply(p, tokens, positions, tokens).sum()
    )(params)
    g_ep = jax.jit(
        jax.grad(lambda p: ep.apply(p, tokens, positions, tokens).sum())
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4
        ),
        g_local,
        g_ep,
    )


def test_mlp_only_layers_are_dense(ctx):
    cfg = Qwen3MoeConfig(
        vocab_ranges=(("default", 64),),
        hidden_size=32,
        num_layers=2,
        num_heads=2,
        num_kv_heads=1,
        head_dim=16,
        moe_intermediate_size=32,
        num_experts=4,
        num_experts_per_tok=2,
        intermediate_size=48,
        mlp_only_layers=(0,),
        remat=False,
    )
    model = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    tokens, positions = _inputs(vocab=64)
    variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    layers = variables["params"]["model"]
    assert "gate_proj" in layers["layers_0"]["mlp"]  # dense SwiGLU
    assert "router" in layers["layers_1"]["mlp"]  # MoE


def test_moe_layer_tokens_per_expert_stats(ctx):
    layer = MoELayer(
        hidden_dim=16,
        intermediate_dim_grouped=32,
        num_grouped_experts=8,
        top_k=2,
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    variables = layer.init(jax.random.PRNGKey(0), x)
    _, stats = layer.apply(
        {"params": variables["params"]}, x, mutable=["moe_stats"]
    )
    tpe = stats["moe_stats"]["tokens_per_expert"]
    tpe = tpe[0] if isinstance(tpe, tuple) else tpe
    assert int(np.asarray(tpe).sum()) == 2 * 8 * 2


@pytest.mark.parametrize(
    "mesh_kw",
    [
        {"dp_shard": 4, "tp": 2, "ep_shard": 8},
        # cp in the token axes AND the ep suffix: t@cp_s flatten path
        {"dp_shard": 2, "cp_shard": 2, "tp": 2, "ep_shard": 4},
    ],
    ids=["dp_tp", "dp_cp_tp"],
)
def test_ep_token_layout_matches_local(mesh_kw):
    """The token-layout EP flow (shard_map riding the residual
    [B@dp, T@cp, D] sharding, non-token ep axes subdividing ownership)
    computes the same loss/grads as the local path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ctx = MeshParameters(**mesh_kw).build(jax.devices())
    tokens, positions = _inputs()
    local = _model()
    variables = local.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss_local = local.apply(params, tokens, positions, tokens)

    import dataclasses

    # thread the residual layout (batch over dp; t over cp_s when present)
    cfg = dataclasses.replace(
        Qwen3MoeConfig.tiny(ep_axes=ctx.ep_shard_axes),
        moe_token_axes=(ctx.batch_axes, ctx.sequence_axes),
    )
    ep = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    sharded_tokens = jax.device_put(
        tokens, NamedSharding(ctx.mesh, P(ctx.batch_axes, ctx.sequence_axes))
    )
    loss_ep = jax.jit(ep.apply)(params, sharded_tokens, positions, tokens)
    np.testing.assert_allclose(
        np.asarray(loss_ep), np.asarray(loss_local), rtol=2e-4, atol=2e-5
    )

    g_local = jax.grad(
        lambda p: local.apply(p, tokens, positions, tokens).sum()
    )(params)
    g_ep = jax.jit(
        jax.grad(lambda p: ep.apply(p, sharded_tokens, positions, tokens).sum())
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4
        ),
        g_local,
        g_ep,
    )


class TestHybridLinearAttention:
    """Hybrid GDN:attention stacks (beyond-reference; BASELINE config 5)."""

    def test_param_structure_and_forward(self, ctx):
        model = Qwen3MoeCausalLM(
            config=Qwen3MoeConfig.hybrid_tiny(), sdpa=eager_sdpa,
            dtype=jnp.float32,
        )
        tokens, positions = _inputs()
        variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        layers = variables["params"]["model"]
        for i in (0, 1, 2):
            assert "linear_attn" in layers[f"layers_{i}"], i
            assert "self_attn" not in layers[f"layers_{i}"], i
        assert "self_attn" in layers["layers_3"]
        params = {"params": variables["params"]}
        loss = model.apply(params, tokens, positions, tokens)
        assert loss.shape == (B, T)
        assert np.isfinite(np.asarray(loss)).all()

    def test_hybrid_trains(self, ctx):
        """Loss decreases on a memorizable batch through GDN + MoE layers."""
        import optax

        model = Qwen3MoeCausalLM(
            config=Qwen3MoeConfig.hybrid_tiny(), sdpa=eager_sdpa,
            dtype=jnp.float32,
        )
        tokens, positions = _inputs()
        variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
        params = {"params": variables["params"]}
        opt = optax.adam(3e-3)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(
                lambda p: model.apply(p, tokens, positions, tokens).mean()
            )(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, l

        losses = []
        for _ in range(20):
            params, state, l = step(params, state)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.7, losses


def test_hybrid_padding_mask_blocks_contamination(ctx):
    """Padded positions must not leak into later tokens through the GDN
    conv/recurrent state (HF apply_mask_to_padding_states semantics)."""
    model = Qwen3MoeCausalLM(
        config=Qwen3MoeConfig.hybrid_tiny(), sdpa=eager_sdpa,
        dtype=jnp.float32,
    )
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 256, (1, 12)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(12, dtype=jnp.int32), (1, 12))
    variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}

    # garbage in the first 4 (padded) positions must not change outputs at
    # the real positions when both masks exclude them: padding_mask zeroes
    # GDN inputs, the sdpa mask blocks attention to the padded keys (the
    # same split HF makes — attention_mask drives both)
    pad_mask = jnp.asarray([[0, 0, 0, 0] + [1] * 8], jnp.int32)
    attn_mask = pad_mask[:, None, None, :].astype(bool)
    corrupted = tokens.at[:, :4].set(7)
    out_a = model.apply(
        params, tokens, positions, method=model.logits,
        mask=attn_mask, padding_mask=pad_mask,
    )
    out_b = model.apply(
        params, corrupted, positions, method=model.logits,
        mask=attn_mask, padding_mask=pad_mask,
    )
    np.testing.assert_allclose(
        np.asarray(out_a[:, 4:]), np.asarray(out_b[:, 4:]), atol=1e-5
    )
    # sdpa mask alone is NOT enough — without padding_mask the pad tokens
    # still flow through the GDN conv/recurrence (the bug being pinned)
    out_c = model.apply(
        params, corrupted, positions, method=model.logits, mask=attn_mask
    )
    assert not np.allclose(np.asarray(out_a[:, 4:]), np.asarray(out_c[:, 4:]),
                           atol=1e-5)


class TestRematPolicies:
    """All remat policies must produce identical gradients — they differ
    only in what gets recomputed vs saved (models/qwen3/dense.py
    _remat_policy; "save_expensive" keeps named flash/grouped-dot outputs)."""

    def test_grad_parity_across_policies(self):
        toks = jnp.ones((2, 16), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
        grads = {}
        for policy in ("full", "dots_no_batch", "save_expensive"):
            cfg = Qwen3MoeConfig(
                vocab_ranges=(("default", 64),), hidden_size=32,
                num_layers=2, num_heads=2, num_kv_heads=1, head_dim=16,
                moe_intermediate_size=32, num_experts=4,
                num_experts_per_tok=2, remat=True, remat_policy=policy,
            )
            m = Qwen3MoeCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), dtype=jnp.float32
            )
            variables = m.init(jax.random.PRNGKey(0), toks, pos, toks)
            params = variables["params"]
            rest = {k: v for k, v in variables.items() if k != "params"}

            def loss(p):
                out = m.apply(
                    {"params": p, **rest}, toks, pos, toks,
                    mutable=["moe_stats", "moe_buffers"],
                )[0]
                return sum(
                    jnp.sum(leaf.astype(jnp.float32))
                    for leaf in jax.tree.leaves(out)
                )

            grads[policy] = jax.jit(jax.grad(loss))(params)

        ref = jax.tree.leaves(grads["full"])
        for policy in ("dots_no_batch", "save_expensive"):
            for a, b in zip(ref, jax.tree.leaves(grads[policy])):
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
