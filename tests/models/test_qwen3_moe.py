"""Qwen3-MoE model tests: forward shapes, EP==local parity on the mesh,
HF parity (reference strategy: moe block + model HF tests, SURVEY §4.2-4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core import MeshParameters
from d9d_tpu.models.qwen3 import Qwen3MoeCausalLM, Qwen3MoeConfig
from d9d_tpu.nn.moe import MoELayer
from d9d_tpu.ops.attention.eager import eager_sdpa

B, T = 4, 16


@pytest.fixture(scope="module")
def ctx():
    return MeshParameters(dp_shard=4, tp=2, ep_shard=8).build(jax.devices())


def _model(ep_axes=None):
    return Qwen3MoeCausalLM(
        config=Qwen3MoeConfig.tiny(ep_axes=ep_axes),
        sdpa=eager_sdpa,
        dtype=jnp.float32,
    )


def _inputs(vocab=256):
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    return tokens, positions


def test_forward_loss_shape(ctx):
    model = _model()
    tokens, positions = _inputs()
    variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss = model.apply(params, tokens, positions, tokens)
    assert loss.shape == (B, T)
    assert np.isfinite(np.asarray(loss)).all()


def test_ep_matches_local(ctx):
    tokens, positions = _inputs()
    local = _model()
    variables = local.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss_local = local.apply(params, tokens, positions, tokens)

    ep = _model(ep_axes=ctx.ep_shard_axes)
    loss_ep = jax.jit(ep.apply)(params, tokens, positions, tokens)
    np.testing.assert_allclose(
        np.asarray(loss_ep), np.asarray(loss_local), rtol=2e-4, atol=2e-5
    )

    g_local = jax.grad(
        lambda p: local.apply(p, tokens, positions, tokens).sum()
    )(params)
    g_ep = jax.jit(
        jax.grad(lambda p: ep.apply(p, tokens, positions, tokens).sum())
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4
        ),
        g_local,
        g_ep,
    )


def test_mlp_only_layers_are_dense(ctx):
    cfg = Qwen3MoeConfig(
        vocab_ranges=(("default", 64),),
        hidden_size=32,
        num_layers=2,
        num_heads=2,
        num_kv_heads=1,
        head_dim=16,
        moe_intermediate_size=32,
        num_experts=4,
        num_experts_per_tok=2,
        intermediate_size=48,
        mlp_only_layers=(0,),
        remat=False,
    )
    model = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    tokens, positions = _inputs(vocab=64)
    variables = model.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    layers = variables["params"]["model"]
    assert "gate_proj" in layers["layers_0"]["mlp"]  # dense SwiGLU
    assert "router" in layers["layers_1"]["mlp"]  # MoE


def test_moe_layer_tokens_per_expert_stats(ctx):
    layer = MoELayer(
        hidden_dim=16,
        intermediate_dim_grouped=32,
        num_grouped_experts=8,
        top_k=2,
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    variables = layer.init(jax.random.PRNGKey(0), x)
    _, stats = layer.apply(
        {"params": variables["params"]}, x, mutable=["moe_stats"]
    )
    tpe = stats["moe_stats"]["tokens_per_expert"]
    tpe = tpe[0] if isinstance(tpe, tuple) else tpe
    assert int(np.asarray(tpe).sum()) == 2 * 8 * 2


def test_ep_token_layout_matches_local(ctx):
    """The token-layout EP flow (shard_map riding the residual
    [B@dp, T@cp, D] sharding, non-token ep axes subdividing ownership)
    computes the same loss/grads as the local path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, positions = _inputs()
    local = _model()
    variables = local.init(jax.random.PRNGKey(0), tokens, positions, tokens)
    params = {"params": variables["params"]}
    loss_local = local.apply(params, tokens, positions, tokens)

    import dataclasses

    # thread the residual layout: batch over dp, no cp in this mesh
    cfg = dataclasses.replace(
        Qwen3MoeConfig.tiny(ep_axes=ctx.ep_shard_axes),
        moe_token_axes=(ctx.batch_axes, ctx.sequence_axes),
    )
    ep = Qwen3MoeCausalLM(config=cfg, sdpa=eager_sdpa, dtype=jnp.float32)
    sharded_tokens = jax.device_put(
        tokens, NamedSharding(ctx.mesh, P(ctx.batch_axes, ctx.sequence_axes))
    )
    loss_ep = jax.jit(ep.apply)(params, sharded_tokens, positions, tokens)
    np.testing.assert_allclose(
        np.asarray(loss_ep), np.asarray(loss_local), rtol=2e-4, atol=2e-5
    )

    g_local = jax.grad(
        lambda p: local.apply(p, tokens, positions, tokens).sum()
    )(params)
    g_ep = jax.jit(
        jax.grad(lambda p: ep.apply(p, sharded_tokens, positions, tokens).sum())
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4
        ),
        g_local,
        g_ep,
    )
