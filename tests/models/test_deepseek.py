"""DeepSeek-V2 family (models/deepseek): MLA attention + shared-expert
MoE riding the Qwen3-MoE backbone — training forward/grads, decode
parity against the full forward, and the serving loops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.e2e  # slow tier: whole-model loops

from d9d_tpu.loop.generate import generate
from d9d_tpu.loop.serve import ContinuousBatcher
from d9d_tpu.loop.speculative import speculative_generate
from d9d_tpu.models.deepseek import DeepseekCausalLM, deepseek_v2_tiny
from d9d_tpu.ops.attention.eager import eager_sdpa

VOCAB = 64


def _models(dml=0):
    cfg = deepseek_v2_tiny(VOCAB)
    model = DeepseekCausalLM(
        config=cfg, sdpa=eager_sdpa, dtype=jnp.float32,
        decode_max_length=dml,
    )
    b, t = 2, 8
    z = jnp.zeros((b, t), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    full = model.clone(decode_max_length=0)
    params = full.init(jax.random.PRNGKey(0), z, pos, z)["params"]
    return full, model, params


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_forward_loss_and_grads():
    full, _, params = _models()
    b, t = 2, 8
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (b, t)), jnp.int32
    )
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    loss = full.apply({"params": params}, ids, pos, ids)
    assert np.isfinite(float(loss.sum()))
    # MLA params exist where GQA's would not
    layer = params["model"]["layers_1"]["self_attn"]
    assert "kv_down_proj" in layer and "kv_up_proj" in layer
    g = jax.grad(
        lambda xp: float_sum(full, xp, ids, pos)
    )(params)
    assert all(
        np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g)
    )


def float_sum(model, params, ids, pos):
    return jnp.sum(
        model.apply({"params": params}, ids, pos, ids).astype(jnp.float32)
    )


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_greedy_generate_matches_full_forward_argmax():
    """Teacher-forced rollout through the FULL forward must equal the
    cached decode loop token for token (MLA latent-cache + absorbed
    decode correctness at the model level)."""
    full, dec, params = _models(dml=20)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, (1, 5)), jnp.int32
    )
    n = 6
    got = np.asarray(generate(dec, params, prompt, max_new_tokens=n))[0]

    seq = list(np.asarray(prompt)[0])
    for _ in range(n):
        ids = jnp.asarray([seq], jnp.int32)
        pos = jnp.broadcast_to(
            jnp.arange(len(seq), dtype=jnp.int32), (1, len(seq))
        )
        logits = full.apply(
            {"params": params}, ids, pos, method=full.logits
        )
        seq.append(int(jnp.argmax(logits[0, -1])))
    want = seq[5:]
    assert got.tolist() == want


@pytest.mark.slow  # compile-bound on the 2-core rig; e2e tier covers it
def test_serving_and_speculative():
    full, dec, params = _models(dml=24)
    prompts = [
        np.random.RandomState(s).randint(0, VOCAB, 3 + s % 3).tolist()
        for s in range(3)
    ]
    n = 5

    def oracle(p):
        out = generate(
            dec, params, jnp.asarray([p], jnp.int32), max_new_tokens=n
        )
        return np.asarray(out)[0].tolist()

    batcher = ContinuousBatcher(dec, params, batch_size=2)
    rids = [batcher.submit(p, max_new_tokens=n) for p in prompts]
    outputs = batcher.drain()
    for rid, p in zip(rids, prompts):
        assert outputs[rid] == oracle(p), rid

    # speculative with a perfect draft: MLA verify (decompressed
    # continuation chunks) + index rewind must stay exact
    prompt2 = jnp.asarray([prompts[0], prompts[0]], jnp.int32)
    want = np.asarray(generate(dec, params, prompt2, max_new_tokens=n))
    got = np.asarray(speculative_generate(
        dec, params, dec, params, prompt2,
        max_new_tokens=n, speculate_k=3,
    ))
    np.testing.assert_array_equal(got, want)


def test_first_layer_dense_rest_sparse():
    _, _, params = _models()
    l0 = params["model"]["layers_0"]["mlp"]
    l1 = params["model"]["layers_1"]["mlp"]
    assert "gate_proj" in l0  # dense SwiGLU (first_k_dense_replace)
    assert "router" in l1 and "shared_expert_module" in l1
    # ungated shared expert (DeepSeek style)
    assert "gate" not in l1["shared_expert_module"]
