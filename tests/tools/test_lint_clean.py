"""The tier-1 lint gate: ``d9d-lint`` over the live repo must be clean.

Same shape as ``tools/bench_compare``'s live gate — run the real tool
in-process against the committed ``tools/lint/baseline.json`` and fail
on any NEW finding. Every future PR that bakes params into a jit,
sneaks a host sync into a hot loop, bare-jits a hot path, or registers
an undocumented metric name fails here, with the finding text naming
the file and the contract it broke (docs/design/static_analysis.md).

Budget-pinned: the linter is stdlib-only (no jax import) and must stay
a few-seconds tool so the gate costs tier-1 nothing.
"""

import pathlib
import time

from tools.lint import baseline as baseline_mod
from tools.lint.cli import DEFAULT_BASELINE, DEFAULT_TARGETS, REPO_ROOT
from tools.lint.engine import lint_paths
from tools.lint.rules import ALL_RULES

ROOT = pathlib.Path(__file__).resolve().parents[2]


def test_repo_is_lint_clean_and_fast():
    t0 = time.perf_counter()
    errors = []
    findings = lint_paths(
        REPO_ROOT,
        [REPO_ROOT / t for t in DEFAULT_TARGETS],
        list(ALL_RULES),
        on_error=lambda e: errors.append(str(e)),
    )
    diff = baseline_mod.diff_against_baseline(
        findings, baseline_mod.load(DEFAULT_BASELINE), REPO_ROOT
    )
    wall = time.perf_counter() - t0

    assert not errors, f"unparseable files: {errors}"
    assert diff.ok, (
        "NEW d9d-lint findings (fix, suppress inline with a reason, or — "
        "last resort — refresh tools/lint/baseline.json):\n"
        + "\n".join(f.render() for f in diff.new)
    )
    assert not diff.stale, (
        "stale baseline entries (the debt was paid — refresh with "
        "`d9d-lint --write-baseline` so the file shrinks):\n"
        + "\n".join(str(e) for e in diff.stale)
    )
    # budget pin: stdlib-only AST pass over ~250 files; 30s is ~10x
    # headroom on the 2-core CPU rig
    assert wall < 30.0, f"d9d-lint took {wall:.1f}s — budget blown"


def test_gate_paths_are_the_committed_ones():
    """The gate must scan the real package surfaces against the real
    committed baseline — a drifted default would hollow out the gate."""
    assert REPO_ROOT == ROOT
    assert set(DEFAULT_TARGETS) == {"d9d_tpu", "tools"}
    assert DEFAULT_BASELINE == ROOT / "tools/lint/baseline.json"
    assert DEFAULT_BASELINE.exists()


def test_console_entry_declared():
    pyproject = (ROOT / "pyproject.toml").read_text(encoding="utf-8")
    assert 'd9d-lint = "tools.lint.cli:main"' in pyproject
