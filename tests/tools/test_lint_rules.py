"""Per-rule fixture coverage for the d9d-lint engine
(tools/lint/, docs/design/static_analysis.md).

One true-positive and one true-negative snippet per rule, plus the
suppression-comment semantics (reason mandatory → D9D000) and the
committed-baseline diff semantics (new vs baselined vs stale). The
snippets are tiny synthetic repos in tmp_path — the engine resolves
hot-path scopes and the observability doc relative to its root, so
fixtures exercise the exact production configuration paths.
"""

import textwrap

import pytest

from tools.lint import baseline as baseline_mod
from tools.lint.engine import lint_paths
from tools.lint.rules import (
    ALL_RULES,
    RULES_BY_ID,
)

DOC = textwrap.dedent(
    """
    # Observability

    | prefix | source | examples |
    |---|---|---|
    | `serve/*` | serving | `serve/ttft_s`, `serve/tokens` |
    | `slo/*` | slo | `slo/{policy}/burn` |
    | `train/*` | trainer | `train/phase/*` spans |
    """
)


def make_repo(tmp_path, files, doc=DOC):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    docp = tmp_path / "docs/design/observability.md"
    docp.parent.mkdir(parents=True, exist_ok=True)
    docp.write_text(doc, encoding="utf-8")
    return tmp_path


def run(tmp_path, rules=None, subdir="d9d_tpu"):
    rules = rules if rules is not None else list(ALL_RULES)
    return lint_paths(tmp_path, [tmp_path / subdir], rules)


# -- D9D001 ---------------------------------------------------------------


def test_d9d001_bare_jit_in_hot_module_fires(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/hot.py": """
            import functools
            import jax

            def g(x):
                return x

            f = jax.jit(g)

            @functools.partial(jax.jit, static_argnames=("k",))
            def h(x, k):
                return x
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D001"]])
    assert len(found) == 2
    assert {f.rule for f in found} == {"D9D001"}


def test_d9d001_tracked_jit_and_cold_modules_clean(tmp_path):
    make_repo(tmp_path, {
        # tracked_jit in a hot module: the sanctioned form
        "d9d_tpu/loop/hot.py": """
            from d9d_tpu.telemetry import tracked_jit

            def g(x):
                return x

            f = tracked_jit(g, name="loop/g")
        """,
        # bare jit OUTSIDE the hot-module surface: allowed
        "d9d_tpu/core/cold.py": """
            import jax

            def g(x):
                return x

            f = jax.jit(g)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D001"]]) == []


# -- D9D002 ---------------------------------------------------------------


def test_d9d002_param_closure_fires(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/closure.py": """
            import jax

            def build(self):
                params = self.load()
                def step(x):
                    return params["w"] * x
                return jax.jit(step)

            def build_attr(self):
                def step(x):
                    return self._params["w"] * x
                return jax.jit(step)
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D002"]])
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "closes over 'params'" in msgs
    assert "self._params" in msgs


def test_d9d002_traced_args_and_scan_bodies_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/closure_ok.py": """
            import jax

            def build():
                def step(params, x):
                    return params["w"] * x
                return jax.jit(step)

            def scan_user(params, xs):
                # a scan BODY may close over params: it re-traces with
                # its enclosing jit, so the capture refreshes
                def body(c, x):
                    return c + params["w"] * x, x
                return jax.lax.scan(body, 0.0, xs)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D002"]]) == []


# -- D9D003 ---------------------------------------------------------------


def test_d9d003_host_sync_in_registered_hot_scope_fires(tmp_path):
    # the file path matches the production hot-scope registration
    make_repo(tmp_path, {
        "d9d_tpu/loop/serve.py": """
            import jax
            import numpy as np

            class ContinuousBatcher:
                def _harvest_one(self):
                    toks_d = self._dispatch()
                    toks = np.asarray(toks_d)
                    loss = jax.numpy.sum(toks_d)
                    x = float(loss)
                    y = toks_d.item()
                    return toks, x, y
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D003"]])
    assert len(found) == 3  # np.asarray(from-call), float(device), .item()
    assert {f.rule for f in found} == {"D9D003"}


def test_d9d003_host_marshalling_and_cold_scopes_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/serve.py": """
            import numpy as np

            class ContinuousBatcher:
                def _harvest_one(self):
                    # np.asarray on host lists is marshalling, not a sync
                    pos = np.asarray([s.pos for s in self._slots])
                    n = float(len(pos))
                    return pos, n

                def cold_debug_helper(self):
                    # not a registered hot scope: syncs allowed
                    return self._tokens.item()
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D003"]]) == []


# -- D9D004 ---------------------------------------------------------------


def test_d9d004_uncommitted_jit_init_fires(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/init_state.py": """
            import jax

            def build(opt, params):
                return jax.jit(opt.init)(params)
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D004"]])
    assert len(found) == 1
    assert "replicate_uncommitted" in found[0].message


def test_d9d004_normalized_inits_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/init_state_ok.py": """
            import jax
            from d9d_tpu.core.tree_sharding import replicate_uncommitted

            def wrapped(opt, params, mesh):
                return replicate_uncommitted(jax.jit(opt.init)(params), mesh)

            def sharded(init_fn, shardings):
                return jax.jit(init_fn, out_shardings=shardings)()

            def named_then_normalized(opt, params, mesh):
                state = jax.jit(opt.init)(params)
                return replicate_uncommitted(state, mesh)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D004"]]) == []


# -- D9D005 ---------------------------------------------------------------


def test_d9d005_nondeterminism_in_traced_fn_fires(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/chaos.py": """
            import time
            import numpy as np
            import jax

            def step(x):
                return x * time.time()

            jitted = jax.jit(step)

            def outer(xs):
                # traced transitively: scan body calls a helper that
                # draws host randomness
                def noise():
                    return np.random.rand()
                def body(c, x):
                    return c + noise(), x
                return jax.lax.scan(body, 0.0, xs)
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D005"]])
    assert len(found) == 2
    assert any("time.time" in f.message for f in found)
    assert any("numpy.random.rand" in f.message for f in found)


def test_d9d005_host_code_and_callback_escapes_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/host_time.py": """
            import time
            import jax

            def host_loop(step, x):
                t0 = time.perf_counter()   # host telemetry: fine
                y = step(x)
                return y, time.perf_counter() - t0

            def traced_with_escape(x):
                # the callback payload runs on the HOST by contract
                jax.debug.callback(lambda v: print(time.time(), v), x)
                return x * 2

            jitted = jax.jit(traced_with_escape)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D005"]]) == []


# -- D9D006 ---------------------------------------------------------------


def test_d9d006_undocumented_name_and_path_label_fire(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/telemetry/user.py": """
            def instrument(tele, batcher):
                tele.counter("serve/bogus_counter").add(1)
                batcher.set_replica_label("east/1")
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D006"]])
    assert len(found) == 2
    msgs = "\n".join(f.message for f in found)
    assert "serve/bogus_counter" in msgs
    assert "path-free-label" in msgs


def test_d9d006_documented_names_templates_and_probes_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/telemetry/user_ok.py": """
            def instrument(tele, policies, batcher):
                tele.counter("serve/tokens").add(1)
                tele.observe("serve/ttft_s", 0.1)
                for p in policies:
                    tele.gauge(f"slo/{p.name}/burn").set(0.0)
                tele.span("train/phase/data_wait")
                batcher.set_replica_label("east1")
                # variable-named instruments are out of static reach
                name = compute_name()
                tele.counter(name).add(1)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D006"]]) == []


# -- suppressions (engine, D9D000) ---------------------------------------


def test_suppression_with_reason_applies(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/sup.py": """
            import jax

            def g(x):
                return x

            # d9d-lint: disable=D9D001 — cold one-shot helper, test fixture
            f = jax.jit(g)
        """,
    })
    assert run(tmp_path) == []


def test_suppression_without_reason_files_d9d000(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/sup_bad.py": """
            import jax

            def g(x):
                return x

            f = jax.jit(g)  # d9d-lint: disable=D9D001
        """,
    })
    found = run(tmp_path)
    # the D9D001 is suppressed, but the reason-less comment is itself
    # a finding — discipline stays enforced
    assert [f.rule for f in found] == ["D9D000"]


def test_suppression_only_covers_named_rule(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/sup_other.py": """
            import jax

            def g(x):
                return x

            # d9d-lint: disable=D9D005 — wrong rule named
            f = jax.jit(g)
        """,
    })
    assert [f.rule for f in run(tmp_path)] == ["D9D001"]


# -- baseline diff semantics ---------------------------------------------


def _one_finding_repo(tmp_path):
    return make_repo(tmp_path, {
        "d9d_tpu/loop/hot.py": """
            import jax

            def g(x):
                return x

            f = jax.jit(g)
        """,
    })


def test_baseline_diff_new_vs_baselined_vs_stale(tmp_path):
    root = _one_finding_repo(tmp_path)
    findings = run(root, [RULES_BY_ID["D9D001"]])
    assert len(findings) == 1

    # accept the debt: the finding becomes baselined, the gate passes
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, findings, root)
    diff = baseline_mod.diff_against_baseline(
        findings, baseline_mod.load(bl_path), root
    )
    assert diff.ok and len(diff.baselined) == 1 and not diff.stale

    # a NEW violation fails even though the old one is baselined
    hot = root / "d9d_tpu/loop/hot.py"
    hot.write_text(
        hot.read_text() + "\nf2 = jax.jit(lambda x: x)\n", encoding="utf-8"
    )
    findings2 = run(root, [RULES_BY_ID["D9D001"]])
    diff2 = baseline_mod.diff_against_baseline(
        findings2, baseline_mod.load(bl_path), root
    )
    assert not diff2.ok
    assert len(diff2.new) == 1 and len(diff2.baselined) == 1

    # fixing the baselined site leaves a STALE entry (reported, not fatal)
    hot.write_text(
        "import jax\n\ndef g(x):\n    return x\n\n"
        "f2 = jax.jit(lambda x: x)\n",
        encoding="utf-8",
    )
    findings3 = run(root, [RULES_BY_ID["D9D001"]])
    baseline_mod.write(bl_path, findings3, root)  # refresh accepts f2
    diff3 = baseline_mod.diff_against_baseline(
        findings3, baseline_mod.load(bl_path), root
    )
    assert diff3.ok and not diff3.stale and len(diff3.baselined) == 1


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    root = _one_finding_repo(tmp_path)
    findings = run(root, [RULES_BY_ID["D9D001"]])
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, findings, root)

    # insert unrelated lines ABOVE the finding: fingerprint must hold
    hot = root / "d9d_tpu/loop/hot.py"
    hot.write_text(
        "# a comment\n# another\n" + hot.read_text(), encoding="utf-8"
    )
    findings2 = run(root, [RULES_BY_ID["D9D001"]])
    diff = baseline_mod.diff_against_baseline(
        findings2, baseline_mod.load(bl_path), root
    )
    assert diff.ok and len(diff.baselined) == 1


# -- CLI ------------------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path, capsys):
    import json

    from tools.lint.cli import main

    root = _one_finding_repo(tmp_path)
    bl = tmp_path / "bl.json"

    # no baseline: the finding fails the gate
    rc = main(["--root", str(root), "--baseline", str(bl),
               "--json", "d9d_tpu"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and not report["ok"] and len(report["new"]) == 1

    # --write-baseline accepts it; the next run is clean
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--write-baseline", "d9d_tpu"]) == 0
    capsys.readouterr()
    rc = main(["--root", str(root), "--baseline", str(bl),
               "--json", "d9d_tpu"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"] and report["new"] == []

    # --no-baseline ignores the acceptance
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--no-baseline", "d9d_tpu"]) == 1
    capsys.readouterr()

    # unknown rule id is a usage error
    assert main(["--select", "D9D999"]) == 2
    capsys.readouterr()


def test_cli_partial_run_cannot_corrupt_baseline(tmp_path, capsys):
    """--select + --write-baseline would erase the un-run rules'
    entries; --select alone must not report them as stale."""
    from tools.lint.cli import main

    root = make_repo(tmp_path, {
        "d9d_tpu/loop/two.py": """
            import time
            import jax

            def g(x):
                return x * time.time()

            f = jax.jit(g)
        """,
    })
    bl = tmp_path / "bl.json"
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--write-baseline", "d9d_tpu"]) == 0  # D9D001 + D9D005
    capsys.readouterr()

    # refusing the partial rewrite: rc 2, baseline untouched
    before = bl.read_text()
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--select", "D9D001", "--write-baseline", "d9d_tpu"]) == 2
    assert bl.read_text() == before
    capsys.readouterr()

    # a partial run: the D9D005 entry is unknown, NOT stale
    import json

    rc = main(["--root", str(root), "--baseline", str(bl),
               "--select", "D9D001", "--json", "d9d_tpu"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"] and report["stale"] == []


def test_cli_nonexistent_target_is_an_error_not_clean(tmp_path, capsys):
    from tools.lint.cli import main

    root = make_repo(tmp_path, {"d9d_tpu/ok.py": "x = 1\n"})
    rc = main(["--root", str(root), "--baseline",
               str(tmp_path / "bl.json"), "no_such_dir"])
    out = capsys.readouterr()
    assert rc == 1
    assert "no such file or directory" in out.err


def test_cli_target_outside_root_is_an_error_not_a_traceback(
    tmp_path, capsys
):
    from tools.lint.cli import main

    root = make_repo(tmp_path / "root", {"d9d_tpu/ok.py": "x = 1\n"})
    outside = tmp_path / "elsewhere.py"
    outside.write_text("x = 1\n")
    rc = main(["--root", str(root), "--baseline",
               str(tmp_path / "bl.json"), str(outside)])
    err = capsys.readouterr().err
    assert rc == 1 and "outside the lint root" in err


def test_cli_write_baseline_refuses_on_analysis_errors(tmp_path, capsys):
    """A refresh over a partial scan must not silently drop entries
    for files the engine could not parse."""
    from tools.lint.cli import main

    root = _one_finding_repo(tmp_path)
    bl = tmp_path / "bl.json"
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--write-baseline", "d9d_tpu"]) == 0
    capsys.readouterr()
    before = bl.read_text()

    (root / "d9d_tpu/loop/broken.py").write_text("def f(:\n")
    rc = main(["--root", str(root), "--baseline", str(bl),
               "--write-baseline", "d9d_tpu"])
    err = capsys.readouterr().err
    assert rc == 2 and "refuses" in err and "syntax error" in err
    assert bl.read_text() == before  # untouched


def test_cli_missing_observability_doc_is_a_usage_error(tmp_path, capsys):
    from tools.lint.cli import main

    (tmp_path / "d9d_tpu").mkdir(parents=True)
    (tmp_path / "d9d_tpu/ok.py").write_text("x = 1\n")
    rc = main(["--root", str(tmp_path), "--baseline",
               str(tmp_path / "bl.json"), "d9d_tpu"])
    err = capsys.readouterr().err
    assert rc == 2 and "D9D006" in err
    # the other rules still run without the doc
    assert main(["--root", str(tmp_path), "--baseline",
                 str(tmp_path / "bl.json"), "--select",
                 "D9D001,D9D005", "d9d_tpu"]) == 0
    capsys.readouterr()


def test_cli_list_rules(capsys):
    from tools.lint.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("D9D000", "D9D001", "D9D002", "D9D003", "D9D004",
                "D9D005", "D9D006"):
        assert rid in out


def test_d9d003_nested_helper_in_hot_scope_still_covered(tmp_path):
    """Wrapping a readback in a local def must not escape the rule."""
    make_repo(tmp_path, {
        "d9d_tpu/loop/serve.py": """
            import numpy as np

            class ContinuousBatcher:
                def _harvest_one(self):
                    def fetch():
                        toks_d = self._dispatch()
                        return np.asarray(toks_d)
                    return fetch()
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D003"]])
    assert len(found) == 1 and found[0].rule == "D9D003"


def test_d9d005_keyword_form_tracing_entries_covered(tmp_path):
    """scan(f=body, ...) / jit(fun=step) must seed the traced set."""
    make_repo(tmp_path, {
        "d9d_tpu/loop/kwform.py": """
            import time
            import jax

            def outer(xs):
                def body(c, x):
                    return c + time.time(), x
                return jax.lax.scan(f=body, init=0.0, xs=xs)
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D005"]])
    assert len(found) == 1 and "time.time" in found[0].message


def test_cli_non_python_file_target_is_an_error(tmp_path, capsys):
    from tools.lint.cli import main

    root = make_repo(tmp_path, {"d9d_tpu/ok.py": "x = 1\n"})
    (root / "README.md").write_text("# readme\n")
    rc = main(["--root", str(root), "--baseline",
               str(tmp_path / "bl.json"), "README.md"])
    err = capsys.readouterr().err
    assert rc == 1 and "not a Python file" in err


def test_cli_select_excludes_and_includes_d9d000(tmp_path, capsys):
    from tools.lint.cli import main

    root = make_repo(tmp_path, {
        "d9d_tpu/loop/sup_bad.py": """
            import jax

            def g(x):
                return x

            f = jax.jit(g)  # d9d-lint: disable=D9D001
        """,
    })
    bl = tmp_path / "bl.json"
    # selecting another rule must not fail on the reason-less
    # suppression (D9D001 itself is suppressed, reason or not)
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--select", "D9D001", "d9d_tpu"]) == 0
    capsys.readouterr()
    # but D9D000 is itself selectable
    assert main(["--root", str(root), "--baseline", str(bl),
                 "--select", "D9D000", "d9d_tpu"]) == 1
    out = capsys.readouterr().out
    assert "D9D000" in out


def test_syntax_error_reported_not_crash(tmp_path):
    root = make_repo(tmp_path, {
        "d9d_tpu/loop/broken.py": "def f(:\n",
    })
    errors = []
    findings = lint_paths(
        root, [root / "d9d_tpu"], list(ALL_RULES),
        on_error=lambda e: errors.append(str(e)),
    )
    assert findings == []
    assert len(errors) == 1 and "syntax error" in errors[0]

    with pytest.raises(Exception):
        lint_paths(root, [root / "d9d_tpu"], list(ALL_RULES))


# -- D9D007 (tracked_jit name uniqueness, cross-file) ---------------------


def test_d9d007_duplicate_literal_names_fire_across_files(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": """
            from d9d_tpu.telemetry import tracked_jit

            f = tracked_jit(lambda x: x, name="shared/step")
        """,
        "d9d_tpu/loop/b.py": """
            from d9d_tpu.telemetry import tracked_jit

            g = tracked_jit(lambda x: x + 1, name="shared/step")
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D007"]])
    # every site of the duplicated name is flagged, and each message
    # names the other collision sites
    assert len(found) == 2
    assert {f.rule for f in found} == {"D9D007"}
    assert {f.path for f in found} == {
        "d9d_tpu/loop/a.py", "d9d_tpu/loop/b.py",
    }
    assert all("shared/step" in f.message for f in found)
    assert all("a.py" in f.message and "b.py" in f.message for f in found)


def test_d9d007_identical_fstring_templates_fire(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": """
            from d9d_tpu.telemetry import tracked_jit

            def build(stage):
                return tracked_jit(lambda x: x, name=f"pp/s{stage}/update")

            def build2(stage):
                return tracked_jit(lambda x: x, name=f"pp/s{stage}/update")
        """,
    })
    found = run(tmp_path, [RULES_BY_ID["D9D007"]])
    # two SITES with the same template collide for every formatted
    # value — the blended-gauge bug the per-stage factories fixed
    assert len(found) == 2


def test_d9d007_distinct_names_and_single_factory_clean(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": """
            from d9d_tpu.telemetry import tracked_jit

            f = tracked_jit(lambda x: x, name="serve/step")
            g = tracked_jit(lambda x: x, name="serve/reset_row")

            def per_stage(sid, label):
                # ONE site formatted many ways is a single factory, not
                # a collision
                return tracked_jit(lambda x: x, name=f"pp_s{sid}/{label}")

            def dynamic(name):
                # non-static name: out of the rule's reach, never flagged
                return tracked_jit(lambda x: x, name=name)
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D007"]]) == []


def test_d9d007_suppression_with_reason_applies(tmp_path):
    make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": """
            from d9d_tpu.telemetry import tracked_jit

            # d9d-lint: disable=D9D007 — deliberate share, one of the two is ever built
            f = tracked_jit(lambda x: x, name="shared/step")
            g = tracked_jit(lambda x: x, name="shared/step")  # d9d-lint: disable=D9D007 — deliberate share, one of the two is ever built
        """,
    })
    assert run(tmp_path, [RULES_BY_ID["D9D007"]]) == []


def test_d9d007_lint_file_single_file_still_checks(tmp_path):
    from tools.lint.engine import lint_file

    make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": """
            from d9d_tpu.telemetry import tracked_jit

            f = tracked_jit(lambda x: x, name="shared/step")
            g = tracked_jit(lambda x: x, name="shared/step")
        """,
    })
    found = lint_file(
        tmp_path, tmp_path / "d9d_tpu/loop/a.py",
        [RULES_BY_ID["D9D007"]],
    )
    assert len(found) == 2


def test_rule_raised_linterror_routes_to_on_error(tmp_path):
    """A LintError raised by a rule's check() (not just a parse
    failure) reports via on_error and the scan continues — the
    documented no-raise contract library callers rely on."""
    from tools.lint.engine import LintError as LE

    root = make_repo(tmp_path, {
        "d9d_tpu/loop/a.py": "x = 1\n",
        "d9d_tpu/loop/b.py": "y = 2\n",
    })

    class ExplodingRule:
        rule_id = "D9DX99"
        summary = "always raises"

        @classmethod
        def check(cls, ctx):
            raise LE(f"{ctx.path}: rule blew up")
            yield  # pragma: no cover

    errors = []
    findings = lint_paths(
        root, [root / "d9d_tpu"], [ExplodingRule],
        on_error=lambda e: errors.append(str(e)),
    )
    assert findings == []
    assert len(errors) == 2  # every file reported, scan never aborted
    with pytest.raises(LE):
        lint_paths(root, [root / "d9d_tpu"], [ExplodingRule])
