"""The tier-1 audit gate: ``d9d-audit`` over the registered hot
executables must be clean against the committed ``AUDIT_BASELINE.json``.

Same shape as the lint gate (test_lint_clean.py) one layer down the
stack: the trace harness compiles every registered executable shape —
non-PP train step, ZeRO dp_replicate>1 train step, the fused-K and
legacy serving paths, the speculative round, the PipelinedOptimizer
per-stage programs — at tiny config on the CPU rig with artifact
capture on, and the rule set certifies the *compiled artifacts*: the
ZeRO collective schedule and the serve zero-collective contract at the
HLO level, donation coverage 100%-or-baselined-with-reasons, no baked
constants over threshold, no f64, no host callbacks. Every future PR
that changes an executable's shape (MPMD stages, quantized decode)
re-certifies here or fails with the contract named.

Budget-pinned: the harness is a handful of tiny-config compiles
(~20-40s on the 2-core rig); the pin keeps it from growing into a
second bench suite.
"""

import pathlib
import time

import pytest

from tools.audit import manifest as manifest_mod
from tools.audit.cli import DEFAULT_BASELINE, REPO_ROOT
from tools.audit.rules import run_rules

ROOT = pathlib.Path(__file__).resolve().parents[2]

pytestmark = pytest.mark.e2e  # compiles real (tiny) executables


@pytest.fixture
def fresh_hub():
    """Isolate the harness's compiles from the process hub other tests
    assert against (recompile counters, hbm gauges), and restore."""
    from d9d_tpu.telemetry import Telemetry, get_telemetry, set_telemetry
    from d9d_tpu.telemetry.introspect import recompile_guard

    prev = get_telemetry()
    guard = recompile_guard()
    set_telemetry(Telemetry())
    guard.reset()
    yield
    guard.reset()
    set_telemetry(prev)


def test_repo_executables_are_audit_clean(fresh_hub):
    from tools.audit.harness import LEGS, trace_registered_executables

    t0 = time.perf_counter()
    facts = trace_registered_executables()
    wall = time.perf_counter() - t0

    manifest = manifest_mod.load(DEFAULT_BASELINE)
    report = run_rules(facts, manifest)
    diff = manifest_mod.diff_against_baseline(report.violations, manifest)

    # every leg captured facts (the harness raises otherwise) and every
    # committed expectation found its executable — the gate cannot be
    # hollowed out by a rename or a dropped leg
    contexts = {f["context"] for f in facts}
    assert contexts == set(LEGS)
    assert report.unmatched_expectations == [], (
        "expectations that matched no captured executable: "
        f"{report.unmatched_expectations}"
    )
    assert report.unchecked_contexts == []

    assert diff.ok, (
        "NEW d9d-audit violations (fix the artifact, or accept into "
        "AUDIT_BASELINE.json with a reason):\n"
        + "\n".join(v.render() for v in diff.new)
    )
    assert not diff.stale, (
        "stale AUDIT_BASELINE.json entries (the debt was paid — "
        "refresh with `d9d-audit --write-baseline`):\n"
        + "\n".join(str(e) for e in diff.stale)
    )

    # the headline contracts, asserted against the raw facts so a
    # manifest edit can't silently weaken them:
    # (a) the ZeRO step's update collectives exist and were verified at
    # the HLO level on a >1-partition program
    zero_facts = [
        f for f in facts
        if f["context"] == "train_zero" and f["name"] == "train_step"
    ]
    assert zero_facts and all(
        f["num_partitions"] > 1 and f["collectives"] for f in zero_facts
    )
    # (b) every serving-path executable is collective-free on the
    # 1-replica mesh — decode never pays a cross-replica hop
    serve_facts = [
        f for f in facts if f["context"] in ("serve", "spec_decode")
    ]
    assert serve_facts and all(
        not f["collectives"] for f in serve_facts
    )
    # (c) donation coverage: 100% everywhere or baselined with a reason
    baselined = {
        e["fingerprint"]: e for e in manifest.get("baseline", [])
    }
    for v in report.violations:
        if v.rule == "D9D101":
            entry = baselined[v.fingerprint()]
            assert entry["reason"].strip()
    # (d) no f64, no callbacks, no over-threshold consts anywhere in
    # the registered set (none are currently baselined)
    assert all(not f["f64_ops"] for f in facts)
    assert all(not f["callbacks"] for f in facts)

    # budget pin: a handful of tiny compiles, generous 4x headroom on
    # the 2-core rig
    assert wall < 120.0, f"audit harness took {wall:.1f}s — budget blown"


def test_capture_adds_zero_runtime_work(fresh_hub):
    """The acceptance pin that audit facts are harvested at compile
    time only: with capture forced on, a tracked executable compiles
    once, its steady-state calls hit the compiled-executable cache, and
    an off-compile call completes under a device→host transfer guard
    (any capture-added readback would raise)."""
    import jax
    import jax.numpy as jnp

    from d9d_tpu.telemetry import audit_capture, introspect, tracked_jit

    audit_capture.enable(True)
    try:
        mark = len(introspect.inventory())
        tj = tracked_jit(
            lambda x, s: (x * 2 + 1, s + 1),
            name="audit_gate/pin", donate_argnums=(1,),
        )
        x = jnp.ones((8, 8))
        s = jnp.zeros((), jnp.int32)
        _, s = tj(x, s)
        with jax.transfer_guard_device_to_host("disallow"):
            for _ in range(3):
                out, s = tj(x, s)
        jax.block_until_ready(out)
        recs = introspect.inventory()[mark:]
        assert len(recs) == 1, "steady-state calls must not re-capture"
        assert recs[0].calls == 4
        assert recs[0].audit is not None
        assert recs[0].audit["donated_declared"] == 1
        assert recs[0].audit["aliased_pairs"] == 1
    finally:
        audit_capture.enable(None)


def test_gate_paths_are_the_committed_ones():
    """The gate must audit against the real committed manifest — a
    drifted default would hollow out the gate."""
    assert REPO_ROOT == ROOT
    assert DEFAULT_BASELINE == ROOT / "AUDIT_BASELINE.json"
    assert DEFAULT_BASELINE.exists()
    manifest = manifest_mod.load(DEFAULT_BASELINE)
    # the committed contracts this PR pre-registered stay committed
    for context in ("train", "train_zero", "serve", "serve_disagg",
                    "spec_decode", "pp_opt", "pp_fused"):
        assert context in manifest["expectations"], context
    # every baseline entry carries a human reason (load enforces it; the
    # explicit loop keeps the failure message naming the entry)
    for entry in manifest["baseline"]:
        assert entry["reason"].strip(), entry


def test_console_entry_declared():
    pyproject = (ROOT / "pyproject.toml").read_text(encoding="utf-8")
    assert 'd9d-audit = "tools.audit.cli:main"' in pyproject


def test_cli_list_surfaces():
    from tools.audit.cli import main

    assert main(["--list-rules"]) == 0
    assert main(["--list-legs"]) == 0
    # --facts with no files is a usage error, not a clean run
    assert main(["--facts"]) == 2
