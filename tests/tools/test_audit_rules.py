"""Per-rule fixture coverage for the d9d-audit compiled-artifact
checker (tools/audit/, docs/design/static_analysis.md).

Two layers, mirroring how the checker is built:

- **rule units** over synthetic fact dicts: one true-positive and one
  true-negative per rule (D9D100–D9D104), the manifest's
  new/baselined/stale diff semantics, the mandatory-reason policy, and
  fingerprint stability;
- **real-artifact fixtures**: tiny programs compiled through
  ``tracked_jit`` with capture on — a deliberately un-donatable buffer,
  a baked-constant closure, a collective-bearing fake serve step, a
  host-callback program — asserting the facts extracted from the
  actual jaxpr/HLO drive the same rules, plus the opt-in and
  compile-time-only contracts of the capture layer itself.
"""

import json

import numpy as np
import pytest

from tools.audit import manifest as manifest_mod
from tools.audit.rules import Violation, run_rules


def fact(**over) -> dict:
    base = {
        "name": "x/step",
        "context": "ctx",
        "collectives": {},
        "num_partitions": 1,
        "donated_declared": 0,
        "donated_bytes": 0,
        "aliased_pairs": 0,
        "consts": [],
        "const_bytes_total": 0,
        "n_consts": 0,
        "dtype_ops": {},
        "f64_ops": [],
        "f32_matmuls": 0,
        "callbacks": [],
    }
    base.update(over)
    return base


def manifest(expectations=None, baseline=None, defaults=None) -> dict:
    return {
        "version": 1,
        "defaults": defaults or {},
        "expectations": expectations or {},
        "baseline": baseline or [],
    }


def rules_of(report):
    return [v.rule for v in report.violations]


# -- D9D100 collective census --------------------------------------------


class TestCollectiveCensus:
    def test_no_collectives_contract_fires_on_any_collective(self):
        exp = {"ctx": {"x/step": {"no_collectives": True}}}
        report = run_rules(
            [fact(collectives={"all-gather": 1})], manifest(exp)
        )
        assert rules_of(report) == ["D9D100"]
        assert "all-gather" in report.violations[0].message

    def test_no_collectives_contract_clean(self):
        exp = {"ctx": {"x/step": {"no_collectives": True}}}
        report = run_rules([fact()], manifest(exp))
        assert report.violations == []

    def test_exact_census_mismatch_fires(self):
        exp = {"ctx": {"x/step": {
            "collectives": {"all-reduce": 6, "all-gather": 9},
        }}}
        report = run_rules(
            [fact(collectives={"all-reduce": 6, "all-gather": 8})],
            manifest(exp),
        )
        assert rules_of(report) == ["D9D100"]

    def test_exact_census_match_clean(self):
        exp = {"ctx": {"x/step": {
            "collectives": {"all-reduce": 6, "all-gather": 9},
        }}}
        report = run_rules(
            [fact(collectives={"all-reduce": 6, "all-gather": 9})],
            manifest(exp),
        )
        assert report.violations == []

    def test_glob_pattern_matches(self):
        exp = {"ctx": {"serve/fused_k*": {"no_collectives": True}}}
        report = run_rules(
            [fact(name="serve/fused_k4", collectives={"all-reduce": 1})],
            manifest(exp),
        )
        assert rules_of(report) == ["D9D100"]
        assert report.unmatched_expectations == []

    def test_census_checks_last_signature_only(self):
        """A warmup variant's census is not the contract: the last
        compiled signature is the program the loop keeps dispatching
        (the PipelinedOptimizer first-step case)."""
        exp = {"ctx": {"x/step": {
            "collectives": {"all-gather": 2},
        }}}
        warmup = fact(collectives={"all-gather": 1})
        steady = fact(collectives={"all-gather": 2})
        assert run_rules([warmup, steady], manifest(exp)).violations == []
        # and the reversed order DOES fire — order is meaningful
        assert rules_of(
            run_rules([steady, warmup], manifest(exp))
        ) == ["D9D100"]

    def test_unmatched_expectation_reported(self):
        """A contract whose executable was renamed (or whose leg was
        dropped) must not silently stop being checked."""
        exp = {"ctx": {"x/renamed_step": {"no_collectives": True}}}
        report = run_rules([fact()], manifest(exp))
        assert report.unmatched_expectations == [("ctx", "x/renamed_step")]
        # contexts with no facts at all are notes, not failures
        exp2 = {"other_ctx": {"y": {"no_collectives": True}}}
        report2 = run_rules([fact()], manifest(exp2))
        assert report2.unmatched_expectations == []
        assert report2.unchecked_contexts == ["other_ctx"]

    def test_no_expectation_means_unchecked(self):
        report = run_rules(
            [fact(collectives={"all-reduce": 3})], manifest()
        )
        assert report.violations == []


# -- D9D101 donation coverage --------------------------------------------


class TestDonationCoverage:
    def test_dropped_donation_fires(self):
        report = run_rules(
            [fact(donated_declared=3, donated_bytes=1024, aliased_pairs=2)],
            manifest(),
        )
        assert rules_of(report) == ["D9D101"]
        assert "double-buffered" in report.violations[0].message

    def test_full_coverage_clean(self):
        report = run_rules(
            [fact(donated_declared=3, aliased_pairs=3)], manifest()
        )
        assert report.violations == []

    def test_undonated_executable_clean(self):
        report = run_rules([fact()], manifest())
        assert report.violations == []


# -- D9D102 baked constants ----------------------------------------------


class TestBakedConstants:
    def test_large_const_fires(self):
        c = {"bytes": 400_000, "shape": [100, 1000], "dtype": "float32"}
        report = run_rules(
            [fact(consts=[c], const_bytes_total=400_000, n_consts=1)],
            manifest(),
        )
        assert rules_of(report) == ["D9D102"]
        assert "install_weights" in report.violations[0].message

    def test_small_const_clean(self):
        c = {"bytes": 64, "shape": [16], "dtype": "float32"}
        report = run_rules(
            [fact(consts=[c], const_bytes_total=64, n_consts=1)],
            manifest(),
        )
        assert report.violations == []

    def test_per_executable_threshold_override(self):
        c = {"bytes": 4096, "shape": [1024], "dtype": "float32"}
        exp = {"ctx": {"x/step": {"max_const_bytes": 1024}}}
        report = run_rules([fact(consts=[c])], manifest(exp))
        assert rules_of(report) == ["D9D102"]
        # default threshold would have let it through
        assert run_rules([fact(consts=[c])], manifest()).violations == []

    def test_defaults_threshold_from_manifest(self):
        c = {"bytes": 4096, "shape": [1024], "dtype": "float32"}
        report = run_rules(
            [fact(consts=[c])],
            manifest(defaults={"max_const_bytes": 100}),
        )
        assert rules_of(report) == ["D9D102"]


# -- D9D103 dtype discipline ---------------------------------------------


class TestDtypeDiscipline:
    def test_f64_always_fires(self):
        report = run_rules([fact(f64_ops=["add", "mul"])], manifest())
        assert rules_of(report) == ["D9D103"]
        assert "x64" in report.violations[0].message

    def test_f32_matmuls_fire_only_under_bf16_policy(self):
        f = fact(f32_matmuls=5)
        assert run_rules([f], manifest()).violations == []
        exp = {"ctx": {"x/step": {"dtype_policy": "bf16_compute"}}}
        report = run_rules([f], manifest(exp))
        assert rules_of(report) == ["D9D103"]

    def test_bf16_program_clean_under_policy(self):
        exp = {"ctx": {"x/step": {"dtype_policy": "bf16_compute"}}}
        report = run_rules(
            [fact(dtype_ops={"bfloat16": 40, "float32": 6})],
            manifest(exp),
        )
        assert report.violations == []

    def test_require_dtypes_fires_when_census_widens(self):
        # the low-precision serving contract: a quantized program whose
        # census lost int8 silently resurrected wide pools
        exp = {"ctx": {"x/step": {"require_dtypes": ["int8", "float32"]}}}
        report = run_rules(
            [fact(dtype_ops={"float32": 40, "int32": 3})], manifest(exp)
        )
        assert rules_of(report) == ["D9D103"]
        v = report.violations[0]
        assert "int8" in v.message and v.key == "require_dtypes:int8"

    def test_require_dtypes_clean_when_present(self):
        exp = {"ctx": {"x/step": {"require_dtypes": ["int8", "float32"]}}}
        report = run_rules(
            [fact(dtype_ops={"int8": 4, "float32": 40, "int32": 3})],
            manifest(exp),
        )
        assert report.violations == []
        # and without an expectation the census is unconstrained
        assert run_rules(
            [fact(dtype_ops={"float32": 40})], manifest()
        ).violations == []


# -- D9D104 host callbacks -----------------------------------------------


class TestHostCallbacks:
    def test_callback_fires(self):
        report = run_rules(
            [fact(callbacks=["pure_callback"])], manifest()
        )
        assert rules_of(report) == ["D9D104"]

    def test_no_callback_clean(self):
        assert run_rules([fact()], manifest()).violations == []


# -- manifest / baseline semantics ---------------------------------------


class TestManifestSemantics:
    def _violation(self, key="k") -> Violation:
        return Violation(
            rule="D9D101", context="ctx", executable="x/step",
            message="m", key=key,
        )

    def test_fingerprint_stable_and_key_sensitive(self):
        a, b = self._violation(), self._violation()
        assert a.fingerprint() == b.fingerprint()
        assert a.fingerprint() != self._violation("other").fingerprint()

    def test_diff_new_baselined_stale(self):
        v = self._violation()
        m = manifest(baseline=[{
            "fingerprint": v.fingerprint(), "rule": v.rule,
            "reason": "accepted for a reason",
        }])
        diff = manifest_mod.diff_against_baseline([v], m)
        assert diff.ok and diff.baselined == [v] and diff.stale == []
        # a baselined entry that stopped firing is stale
        diff2 = manifest_mod.diff_against_baseline([], m)
        assert diff2.ok and diff2.stale == m["baseline"]
        # an unknown violation is new
        diff3 = manifest_mod.diff_against_baseline(
            [self._violation("fresh")], m
        )
        assert not diff3.ok and len(diff3.new) == 1

    def test_load_rejects_reasonless_baseline(self, tmp_path):
        p = tmp_path / "AUDIT_BASELINE.json"
        p.write_text(json.dumps({
            "version": 1, "expectations": {},
            "baseline": [{"fingerprint": "abc", "rule": "D9D101"}],
        }))
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)
        p.write_text(json.dumps({
            "version": 1, "expectations": {},
            "baseline": [{
                "fingerprint": "abc", "rule": "D9D101",
                "reason": manifest_mod.FILL_ME,
            }],
        }))
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)

    def test_load_rejects_non_manifest(self, tmp_path):
        p = tmp_path / "AUDIT_BASELINE.json"
        p.write_text("{\"metrics\": {}}")
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)
        p.write_text("not json")
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)

    def test_write_baseline_carries_reasons_and_marks_new(self, tmp_path):
        p = tmp_path / "AUDIT_BASELINE.json"
        v_old, v_new = self._violation("old"), self._violation("new")
        p.write_text(json.dumps({
            "version": 1,
            "expectations": {"ctx": {"x/step": {"no_collectives": True}}},
            "baseline": [{
                "fingerprint": v_old.fingerprint(), "rule": v_old.rule,
                "reason": "the old reason",
            }],
        }))
        data = manifest_mod.write_baseline(p, [v_old, v_new])
        by_fp = {e["fingerprint"]: e for e in data["baseline"]}
        assert by_fp[v_old.fingerprint()]["reason"] == "the old reason"
        assert by_fp[v_new.fingerprint()]["reason"].startswith("FILL-ME")
        # expectations survive the rewrite, and the FILL-ME entry keeps
        # the file un-loadable until a human writes the reason
        assert json.loads(p.read_text())["expectations"]
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)


# -- real-artifact fixtures (capture on actual compiles) -----------------


@pytest.fixture
def capture():
    from d9d_tpu.telemetry import audit_capture, introspect

    audit_capture.enable(True)
    mark = len(introspect.inventory())
    yield introspect, mark
    audit_capture.enable(None)


def _facts_since(introspect, mark):
    return [
        r.audit
        for r in introspect.inventory()[mark:]
        if r.audit is not None
    ]


class TestRealArtifacts:
    def test_dropped_donation_detected(self, capture):
        import jax.numpy as jnp

        from d9d_tpu.telemetry import tracked_jit

        introspect, mark = capture

        def f(x, dead):
            return x + 1.0  # `dead` has no matching output to alias

        tj = tracked_jit(f, name="fix/undonated", donate_argnums=(1,))
        tj(jnp.ones((4, 4)), jnp.ones((7,)))
        (facts,) = _facts_since(introspect, mark)
        assert facts["donated_declared"] == 1
        assert facts["aliased_pairs"] == 0
        report = run_rules([facts], manifest())
        assert rules_of(report) == ["D9D101"]

    def test_full_donation_clean(self, capture):
        import jax.numpy as jnp

        from d9d_tpu.telemetry import tracked_jit

        introspect, mark = capture
        tj = tracked_jit(
            lambda x: x + 1.0, name="fix/donated", donate_argnums=(0,)
        )
        tj(jnp.ones((4, 4)))
        (facts,) = _facts_since(introspect, mark)
        assert facts["donated_declared"] == 1
        assert facts["aliased_pairs"] == 1
        assert run_rules([facts], manifest()).violations == []

    def test_baked_constant_closure_detected(self, capture):
        import jax.numpy as jnp

        from d9d_tpu.telemetry import tracked_jit

        introspect, mark = capture
        baked = np.ones((128, 128), np.float32)  # 64 KiB > threshold

        def f(x):
            return x @ jnp.asarray(baked)

        tj = tracked_jit(f, name="fix/baked")
        tj(jnp.ones((2, 128)))
        (facts,) = _facts_since(introspect, mark)
        assert facts["n_consts"] == 1
        assert facts["consts"][0]["bytes"] == 128 * 128 * 4
        report = run_rules([facts], manifest())
        assert rules_of(report) == ["D9D102"]

    def test_collective_bearing_fake_serve_step(self, capture):
        import jax
        import jax.numpy as jnp
        from jax.sharding import (
            Mesh,
            NamedSharding,
            PartitionSpec as P,
        )

        from d9d_tpu.telemetry import audit_capture, tracked_jit

        introspect, mark = capture
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))

        def fake_step(x, y):
            g = jax.lax.with_sharding_constraint(
                x * 2.0 + 1.0, NamedSharding(mesh, P("dp"))
            )
            p = jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, P())
            )
            return p + y

        with audit_capture.context("serve"):
            tj = tracked_jit(fake_step, name="serve/step")
            rep = NamedSharding(mesh, P())
            tj(
                jax.device_put(jnp.ones((8, 4)), rep),
                jax.device_put(jnp.ones((8, 4)), rep),
            )
        (facts,) = _facts_since(introspect, mark)
        assert facts["context"] == "serve"
        assert facts["collectives"], "expected a collective in the HLO"
        exp = {"serve": {"serve/step": {"no_collectives": True}}}
        report = run_rules([facts], manifest(exp))
        assert rules_of(report) == ["D9D100"]

    def test_host_callback_detected(self, capture):
        import jax
        import jax.numpy as jnp

        from d9d_tpu.telemetry import tracked_jit

        introspect, mark = capture

        def f(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                x,
            )
            return y + 1
        # host-callback payloads are allowed in COLD paths; this fixture
        # deliberately puts one in a tracked executable to pin detection
        tj = tracked_jit(f, name="fix/callback")
        tj(jnp.ones((4,)))
        (facts,) = _facts_since(introspect, mark)
        assert facts["callbacks"]
        report = run_rules([facts], manifest())
        assert rules_of(report) == ["D9D104"]

    def test_f64_census_from_jaxpr(self):
        """f64 detection at the jaxpr layer (no x64 compile needed):
        the census walks sub-jaxprs, so an f64 inside a scan body is
        seen too."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from d9d_tpu.telemetry.audit_capture import _jaxpr_census

        with jax.experimental.enable_x64():
            def body(c, _):
                return c * np.float64(1.5), None

            def f(x):
                out, _ = lax.scan(body, x, None, length=3)
                return out

            jaxpr = jax.make_jaxpr(f)(np.ones((4,), np.float64))
        census = _jaxpr_census(jaxpr)
        assert census["f64_ops"]
        report = run_rules([fact(**{
            "f64_ops": census["f64_ops"],
        })], manifest())
        assert rules_of(report) == ["D9D103"]
        # and the default f32 path is f64-free
        jaxpr32 = jax.make_jaxpr(lambda x: x * 2.0)(
            np.ones((4,), np.float32)
        )
        assert _jaxpr_census(jaxpr32)["f64_ops"] == []

    def test_capture_is_opt_in_and_compile_time_only(self):
        import jax
        import jax.numpy as jnp

        from d9d_tpu.telemetry import audit_capture, introspect, tracked_jit

        # opt-in: no facts without the flag
        audit_capture.enable(False)
        try:
            mark = len(introspect.inventory())
            tj = tracked_jit(lambda x: x * 2, name="fix/optout")
            tj(jnp.ones((4,)))
            recs = introspect.inventory()[mark:]
            assert len(recs) == 1 and recs[0].audit is None
        finally:
            audit_capture.enable(None)

        # compile-time only: with capture on, repeated calls reuse the
        # ONE compiled executable (no re-trace, no readback — the call
        # runs under a device→host transfer guard to prove it)
        audit_capture.enable(True)
        try:
            mark = len(introspect.inventory())
            tj = tracked_jit(lambda x: x * 3, name="fix/zerocost")
            x = jnp.ones((4,))
            tj(x)  # compile + capture happen here
            with jax.transfer_guard_device_to_host("disallow"):
                out = tj(x)
            jax.block_until_ready(out)
            recs = introspect.inventory()[mark:]
            assert len(recs) == 1
            assert recs[0].audit is not None
            assert recs[0].calls == 2
        finally:
            audit_capture.enable(None)

    def test_facts_are_json_serializable(self, capture):
        import jax.numpy as jnp

        from d9d_tpu.telemetry import tracked_jit

        introspect, mark = capture
        tj = tracked_jit(lambda x: x.sum(), name="fix/json")
        tj(jnp.ones((4, 4)))
        (facts,) = _facts_since(introspect, mark)
        assert json.loads(json.dumps(facts)) == facts


class TestReviewHardening:
    def test_same_shape_consts_get_distinct_fingerprints(self):
        """Two distinct over-threshold consts sharing dtype+shape must
        not collapse to one fingerprint — one baseline entry would
        otherwise cover any number of smuggled same-shape arrays."""
        c = {"bytes": 400_000, "shape": [100, 1000], "dtype": "float32"}
        report = run_rules(
            [fact(consts=[dict(c), dict(c)], n_consts=2)], manifest()
        )
        assert rules_of(report) == ["D9D102", "D9D102"]
        fps = {v.fingerprint() for v in report.violations}
        assert len(fps) == 2

    def test_write_baseline_refused_on_partial_runs(self, capsys):
        """--write-baseline with --legs/--facts would rebuild the
        baseline from a partial capture, erasing the other contexts'
        entries and their hand-written reasons (the d9d-lint --select
        refusal, one layer down)."""
        from tools.audit.cli import main

        assert main(["--legs", "serve", "--write-baseline"]) == 2
        err = capsys.readouterr().err
        assert "refuses" in err
        assert main(
            ["--facts", "whatever.jsonl", "--write-baseline"]
        ) == 2

    def test_census_counts_async_and_variadic_collectives(self):
        """Async (-start/-done pairs, tuple result types with spaces)
        and variadic collectives must census correctly — on TPU HLO the
        async form is the norm, and undercounting reads as 'no
        collectives' (verified miss before the type-match fix)."""
        from d9d_tpu.telemetry.audit_capture import _collective_census

        hlo = "\n".join([
            "HloModule jit_f",
            "  %ag = (f32[2]{0}, f32[4]{0}) all-gather-start(f32[2]{0} %p), dimensions={0}",
            "  %agd = f32[4]{0} all-gather-done((f32[2]{0}, f32[4]{0}) %ag)",
            "  %ar = (f32[4]{0}, f32[8]{0}) all-reduce(f32[4]{0} %a, f32[8]{0} %b), to_apply=%add",
            "  %rs = f32[2]{0} reduce-scatter(f32[4]{0} %c), dimensions={0}",
            "  ROOT %r = f32[4]{0} add(f32[4]{0} %agd, f32[4]{0} %ar)",
        ])
        assert _collective_census(hlo) == {
            "all-gather": 1, "all-reduce": 1, "reduce-scatter": 1,
        }

    def test_manifest_rejects_fingerprintless_baseline_entry(
        self, tmp_path
    ):
        """A hand-edited entry that drops its fingerprint must be an
        rc-2 manifest error at load, not a KeyError downstream."""
        p = tmp_path / "AUDIT_BASELINE.json"
        p.write_text(json.dumps({
            "version": 1, "expectations": {},
            "baseline": [{"rule": "D9D101", "reason": "a fine reason"}],
        }))
        with pytest.raises(manifest_mod.AuditManifestError):
            manifest_mod.load(p)

    def test_census_tolerates_tpu_tiled_layout_tuple_types(self):
        """TPU optimized HLO prints tiled-layout annotations with
        NESTED parens inside async tuple types — the census must still
        see the op (a drifted chip schedule must not read as 'no
        collectives')."""
        from d9d_tpu.telemetry.audit_capture import _collective_census

        hlo = (
            "%ag = (bf16[1024,8192]{1,0:T(8,128)(2,1)}, "
            "bf16[8192,8192]{1,0:T(8,128)}) "
            "all-gather-start(bf16[1024,8192]{1,0:T(8,128)} %p), "
            "dimensions={0}"
        )
        assert _collective_census(hlo) == {"all-gather": 1}

    def test_cli_full_run_fails_on_unchecked_context(
        self, monkeypatch, capsys
    ):
        """On a FULL harness run (no --legs/--facts) an expectation
        context with zero captured facts is a dropped/renamed leg
        retiring its whole contract table — rc 1, not a note."""
        import tools.audit.harness as harness_mod
        from tools.audit.cli import main

        monkeypatch.setattr(
            harness_mod, "trace_registered_executables",
            lambda legs=None: [fact(context="train")],
        )
        import json as _json
        import pathlib
        import tempfile

        p = pathlib.Path(tempfile.mkdtemp()) / "m.json"
        p.write_text(_json.dumps({
            "version": 1,
            "expectations": {
                "train": {"x/step": {"no_collectives": True}},
                "spec_decode": {"serve/spec_round": {
                    "no_collectives": True,
                }},
            },
            "baseline": [],
        }))
        assert main(["--baseline", str(p)]) == 1
        out = capsys.readouterr().out
        assert "FULL harness run" in out
        # the same gap on an explicit partial run is a note, rc 0
        assert main(["--baseline", str(p), "--legs", "train"]) == 0
        assert "partial run" in capsys.readouterr().out

    def test_trace_failure_keeps_tracked_path(self, monkeypatch):
        """A capture-only trace() failure must not trip the permanent
        plain-jit fallback: compile accounting stays, only the audit
        facts are omitted."""
        import jax.numpy as jnp

        from d9d_tpu.telemetry import audit_capture, introspect, tracked_jit

        audit_capture.enable(True)
        try:
            mark = len(introspect.inventory())
            tj = tracked_jit(lambda x: x + 1, name="fix/tracefail")
            real = tj._jit

            class _QuirkyJit:
                # trace() raises where the plain lower() succeeds —
                # the capture-specific failure mode under test
                def trace(self, *a, **k):
                    raise RuntimeError("capture-path quirk")

                def __getattr__(self, name):
                    return getattr(real, name)

                def __call__(self, *a, **k):
                    return real(*a, **k)

            tj._jit = _QuirkyJit()
            out = tj(jnp.ones((4,)))
            assert float(out[0]) == 2.0
            recs = introspect.inventory()[mark:]
            assert len(recs) == 1, "compile accounting must survive"
            # the jaxpr-derived blocks degrade to empty; the HLO-derived
            # facts (collectives, aliasing) still land off the plain
            # lower() path
            assert recs[0].audit is not None
            assert recs[0].audit["dtype_ops"] == {}
            assert recs[0].audit["collectives"] == {}
            assert not tj._fallback, (
                "capture failure must not degrade the tracked path"
            )
        finally:
            audit_capture.enable(None)

    def test_print_audit_names_omitted_rows(self, capsys):
        from pathlib import Path

        from tools.trace_summary import print_audit

        evs = [
            (Path("x.jsonl"), {"name": f"e{i}", "audit": fact()})
            for i in range(5)
        ]
        print_audit(evs, top=1)
        out = capsys.readouterr().out
        assert "+3 more" in out
