"""bench.py's wall-clock watchdog (round-4 tunnel-wedge hardening).

Quick tier: the watchdog path never touches a jax backend — it exists
precisely for the case where the backend accepted a program and went
silent, so it must work (and be tested) without one.
"""
import json
import pathlib
import subprocess
import sys


ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run_child(code: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env={"PYTHONPATH": str(ROOT), "PATH": "/usr/bin:/bin"},
    )


def test_watchdog_fires_with_partial_results():
    """A wedge after the dense row finished must still deliver that row:
    exit 4 with one JSON line carrying error + partial."""
    child = _run_child(
        "import sys, time\n"
        f"sys.path.insert(0, {str(ROOT)!r})\n"
        "import bench\n"
        "bench._partial_results['dense'] = {'value': 123.0}\n"
        "bench._arm_watchdog(0.5)\n"
        "time.sleep(30)\n"
    )
    assert child.returncode == 4, (child.returncode, child.stderr[-500:])
    out = json.loads(child.stdout.strip())
    assert "watchdog" in out["error"]
    assert out["partial"]["dense"]["value"] == 123.0


def test_watchdog_fires_empty():
    """No rows finished: the error line must not carry a partial key
    (the driver should see an unambiguous no-data outage record)."""
    child = _run_child(
        "import sys, time\n"
        f"sys.path.insert(0, {str(ROOT)!r})\n"
        "import bench\n"
        "bench._arm_watchdog(0.5)\n"
        "time.sleep(30)\n"
    )
    assert child.returncode == 4
    out = json.loads(child.stdout.strip())
    assert "watchdog" in out["error"]
    assert "partial" not in out


def test_watchdog_cancellable():
    """A finished bench must be able to outlive its armed watchdog: the
    timer is a daemon and cancel() prevents the exit-4 path."""
    child = _run_child(
        "import sys, time\n"
        f"sys.path.insert(0, {str(ROOT)!r})\n"
        "import bench\n"
        "t = bench._arm_watchdog(0.5)\n"
        "t.cancel()\n"
        "time.sleep(1.0)\n"
        "print('survived')\n"
    )
    assert child.returncode == 0, child.stderr[-500:]
    assert "survived" in child.stdout
