"""Per-stage numerics executables (PipelinedOptimizer.stage_numerics):
param-space rows in build_param_spec order, the update:param column NaN
by contract (the stats dispatch runs BEFORE the donating update), and a
per-stage NaN marked on exactly the producing stage's rows. The full
trainer-driven PP parity leg is tests/loop/test_pp_numerics.py (slow
tier — whole-model compiles)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.pipelining.training import PipelinedOptimizer
from d9d_tpu.telemetry.numerics import build_param_spec, decode_window


def _setup():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("x",))
    sh = NamedSharding(mesh, P())
    opt = PipelinedOptimizer(
        optimizer=optax.adam(1e-2),
        scalar_shardings={0: sh, 1: sh},
    )
    params = {
        0: {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))},
        1: {"w": jnp.full((4, 4), 2.0)},
    }
    states = opt.init(params)
    return opt, params, states


def test_stage_rows_decode_against_param_spec():
    opt, params, states = _setup()
    grads = {s: jax.tree.map(lambda p: p * 0 + 0.5, params[s]) for s in params}
    for s in (0, 1):
        spec = build_param_spec(params[s])
        vec = np.asarray(opt.stage_numerics(s, params[s], grads[s], states[s]))
        assert vec.shape == (spec.flat_size,)
        rows = decode_window(spec, vec, prefix=f"pp/s{s}/")
        assert set(rows) == {f"pp/s{s}/{n}" for n in params[s]}
        for name, r in rows.items():
            assert r["finite_ok"], name
            assert r["rms"] == pytest.approx(0.5)
            assert r["param_rms"] >= 0  # the zero-init bias reads 0
            # pre-update dispatch: no old/new pair → the ratio column
            # is NaN under PP by contract
            assert math.isnan(r["update_ratio"])
            # Adam second moments found through the per-stage state
            assert np.isfinite(r["moment2_max"])


def test_stage_nan_lands_on_the_producing_stage_only():
    opt, params, states = _setup()
    bad = {"w": jnp.full((4, 4), jnp.nan), "b": jnp.zeros((4,))}
    good = {"w": jnp.full((4, 4), 0.1)}
    rows0 = decode_window(
        build_param_spec(params[0]),
        np.asarray(opt.stage_numerics(0, params[0], bad, states[0])),
    )
    rows1 = decode_window(
        build_param_spec(params[1]),
        np.asarray(opt.stage_numerics(1, params[1], good, states[1])),
    )
    assert not rows0["w"]["grad_finite"] and rows0["b"]["grad_finite"]
    assert rows0["w"]["moment_finite"]  # moments untouched
    assert all(r["finite_ok"] for r in rows1.values())


def test_stage_executables_are_cached_per_stage():
    opt, params, states = _setup()
    grads = {s: jax.tree.map(jnp.zeros_like, params[s]) for s in params}
    for _ in range(3):
        for s in (0, 1):
            opt.stage_numerics(s, params[s], grads[s], states[s])
    assert set(opt._numerics_fns) == {0, 1}
