"""Fused-executor exactness: the compiled-run runtime must be BIT-identical
to the legacy per-action interpreter (runtime/fused.py's parity contract).

The fused runtime replays the legacy handlers symbolically and traces the
same raw stage impls into per-rank programs — so equality here is exact
(``assert_array_equal``), not tolerance-based: any divergence means the
schedule compiler reordered or rewired the math. (Known boundary of the
bitwise contract, documented in fused.py: ``cache_acts`` W-slot grads on
graphs XLA compiles differently once the replayed jaxpr shares a program
with its I slot — on real models the long f32 dW reductions can
reassociate at ~1e-4 relative; on these toy stages both contexts compile
identically and the pins below hold exactly.) The suite pins loss,
weight, every metric, per-stage grads, eval outputs, and the
``pp_numerics/s{S}`` stats vector against the legacy oracle across 1F1B
and zero-bubble schedules; the tiny 1F1B config additionally pins the
structural acceptance: the whole step fuses into ONE program and real
dispatches drop ≥5× (the ISSUE 16 gate, also enforced continuously by
``tools/bench_compare.py``).

Compile-heavy schedule×policy sweeps live in the ``slow`` tier; tier-1
keeps one representative per contract.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

# unlike test_e2e's SPMD tier this suite is mesh-free by construction
# (single-device stages, shardings None), so it runs — and the parity
# contract is enforced — on the legacy-jax CPU rig too
pytestmark = [pytest.mark.e2e]


from d9d_tpu.pipelining import (
    FusedPipelineExecutor,
    PipelineScheduleExecutor,
    PipelineStageInfo,
    PipelineStageRuntime,
)
from d9d_tpu.pipelining.program import add_communication_ops
from d9d_tpu.pipelining.program.builders import (
    DualPipeVProgramBuilder,
    GPipeProgramBuilder,
    Interleaved1F1BProgramBuilder,
    InferenceProgramBuilder,
    ZeroBubbleVProgramBuilder,
)
from d9d_tpu.telemetry.introspect import TrackedJit

HID = 8


class StageBlock(nn.Module):
    """One pipeline stage: dense + tanh (nonlinear so dI/dW split is honest)."""

    n_layers: int = 1

    @nn.compact
    def __call__(self, x):
        for _ in range(self.n_layers):
            x = jnp.tanh(nn.Dense(HID, use_bias=True)(x))
        return x


class TinyTask:
    """StageTask impl: carry = activations; loss = masked square error."""

    def split_microbatch(self, micro):
        return micro["x"], {}, {"y": micro["y"], "w": micro["w"]}

    def stage_forward(self, module, params, carry, kwargs):
        return module.apply(params, carry)

    def last_stage_loss(self, module, params, carry, kwargs, state):
        out = module.apply(params, carry)
        err = ((out - state["y"]) ** 2).sum(-1)
        loss_sum = (err * state["w"]).sum()
        weight = state["w"].sum()
        return loss_sum, weight, {"examples": weight}


def make_stages(num_stages, key, residual_policy="remat"):
    task = TinyTask()
    stages = {}
    for s in range(num_stages):
        info = PipelineStageInfo(stage_index=s, num_stages=num_stages)
        module = StageBlock()
        key, sub = jax.random.split(key)
        params = module.init(sub, jnp.zeros((1, HID)))
        stages[s] = PipelineStageRuntime(
            info=info, module=module, params=params, task=task,
            residual_policy=residual_policy,
        )
    return stages


def make_microbatches(m, key, mb_size=4):
    out = []
    for _ in range(m):
        key, k1, k2 = jax.random.split(key, 3)
        out.append({
            "x": jax.random.normal(k1, (mb_size, HID)),
            "y": jax.random.normal(k2, (mb_size, HID)),
            "w": jnp.ones((mb_size,)),
        })
    return out


def build_pair(builder, m, residual_policy="remat", train=True,
               fused_numerics=False):
    """Legacy + fused executors over independently-built but identical
    stage sets (same PRNG seed → identical params; separate objects so
    neither runtime can lean on the other's caches)."""
    stages_l = make_stages(
        builder.num_stages, jax.random.PRNGKey(0), residual_policy
    )
    stages_f = make_stages(
        builder.num_stages, jax.random.PRNGKey(0), residual_policy
    )
    program = add_communication_ops(
        builder.compose(m), num_stages=builder.num_stages,
        stage_owner=builder.stage_owner,
    )
    legacy = PipelineScheduleExecutor(
        stages=stages_l, program=program, stage_owner=builder.stage_owner,
        num_microbatches=m, train=train,
    )
    fused = FusedPipelineExecutor(
        stages=stages_f, program=program, stage_owner=builder.stage_owner,
        num_microbatches=m, train=train, numerics=fused_numerics,
    )
    return legacy, fused, stages_l, stages_f


def tree_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


def assert_results_identical(rl, rf, train=True):
    if train:
        assert set(rl.grads) == set(rf.grads)
        for s in rl.grads:
            tree_equal(rl.grads[s], rf.grads[s])
    else:
        assert len(rl.outputs) == len(rf.outputs)
        for a, b in zip(rl.outputs, rf.outputs):
            tree_equal(a, b)
    np.testing.assert_array_equal(
        np.asarray(rl.loss_sum), np.asarray(rf.loss_sum)
    )
    np.testing.assert_array_equal(
        np.asarray(rl.weight_sum), np.asarray(rf.weight_sum)
    )
    assert set(rl.metrics) == set(rf.metrics)
    for k in rl.metrics:
        np.testing.assert_array_equal(
            np.asarray(rl.metrics[k]), np.asarray(rf.metrics[k])
        )


def run_parity(builder, m, residual_policy="remat", train=True):
    legacy, fused, _, _ = build_pair(
        builder, m, residual_policy=residual_policy, train=train
    )
    mbs = make_microbatches(m, jax.random.PRNGKey(1))
    rl = legacy.step(list(mbs))
    rf = fused.step(list(mbs))
    assert_results_identical(rl, rf, train=train)
    # a second step reuses the compiled runs: donation / buffer
    # lifetime bugs surface as deleted-buffer errors or drift here
    rf2 = fused.step(list(mbs))
    np.testing.assert_array_equal(
        np.asarray(rl.loss_sum), np.asarray(rf2.loss_sum)
    )
    return fused


class _DispatchCounter:
    """Counts real executable dispatches through TrackedJit.__call__ —
    the single dispatch point both runtimes share, so the ratio is
    measured symmetrically."""

    def __init__(self, monkeypatch):
        self.count = 0
        orig = TrackedJit.__call__

        def counting(tj, *args, **kwargs):
            self.count += 1
            return orig(tj, *args, **kwargs)

        monkeypatch.setattr(TrackedJit, "__call__", counting)

    def take(self):
        n, self.count = self.count, 0
        return n


# -- tier-1: one representative per contract ---------------------------


def test_1f1b_bitwise():
    run_parity(Interleaved1F1BProgramBuilder(2), 4)


def test_zb1p_cache_acts_bitwise():
    run_parity(
        Interleaved1F1BProgramBuilder(2, zero_bubble=True), 4,
        residual_policy="cache_acts",
    )


def test_single_stage_zero_bubble_bitwise():
    """pp=1 zero-bubble: the stage is first AND last; loss statistics
    must surface identically from the fused BackwardInput slot."""
    fused = run_parity(Interleaved1F1BProgramBuilder(1, zero_bubble=True), 3)
    assert fused.num_fused_programs == 1


def test_tiny_1f1b_fuses_and_drops_dispatches(monkeypatch):
    """The ISSUE 16 acceptance config (tools/bench_pp_overhead.py --tiny:
    one rank, two virtual stages, m=8): the whole step must fuse into a
    single device program, and real dispatches must drop ≥5×."""
    builder = Interleaved1F1BProgramBuilder(1, 2)
    m = 8
    legacy, fused, _, _ = build_pair(builder, m)
    mbs = make_microbatches(m, jax.random.PRNGKey(1))
    counter = _DispatchCounter(monkeypatch)
    rl = legacy.step(list(mbs))
    legacy_dispatches = counter.take()
    rf = fused.step(list(mbs))
    fused_dispatches = counter.take()
    assert_results_identical(rl, rf)
    assert fused.num_fused_programs == 1
    assert fused_dispatches == 1
    assert legacy_dispatches >= 5 * fused_dispatches, (
        f"dispatch reduction {legacy_dispatches}/{fused_dispatches} < 5x"
    )


def test_numerics_stats_vector_bitwise():
    """The in-program pp_numerics/s{S} fold must reproduce the
    PipelinedOptimizer.stage_numerics oracle bit-for-bit on cadence and
    NaN-fill off cadence — from the SAME fused program (the traced flag
    flips a cond branch, never the signature)."""
    import optax

    from d9d_tpu.pipelining.training import PipelinedOptimizer
    from d9d_tpu.telemetry import numerics as numerics_mod

    builder = Interleaved1F1BProgramBuilder(2)
    m = 4
    legacy, fused, stages_l, stages_f = build_pair(
        builder, m, fused_numerics=True
    )
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("x",))
    scalar = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    opt = PipelinedOptimizer(
        optimizer=optax.adam(1e-3),
        scalar_shardings={s: scalar for s in stages_l},
    )
    opt_states = opt.init({s: rt.params for s, rt in stages_l.items()})

    mbs = make_microbatches(m, jax.random.PRNGKey(1))
    rl = legacy.step(list(mbs))
    moments = {
        s: numerics_mod.find_second_moments(opt_states[s], rt.params)
        for s, rt in stages_f.items()
    }
    rf_on = fused.step(list(mbs), numerics_on=True, numerics_moments=moments)
    rf_off = fused.step(list(mbs), numerics_on=False, numerics_moments=moments)
    assert_results_identical(rl, rf_on)
    for s in stages_l:
        oracle = opt.stage_numerics(
            s, stages_l[s].params, rl.grads[s], opt_states[s]
        )
        np.testing.assert_array_equal(
            np.asarray(rf_on.numerics[s]), np.asarray(oracle)
        )
        assert np.all(np.isnan(np.asarray(rf_off.numerics[s])))


def test_inference_outputs_bitwise():
    run_parity(InferenceProgramBuilder(2), 4, train=False)


def test_timeline_attribution_parity_tiny_1f1b():
    """ISSUE 19 acceptance: on the tiny 1F1B config a timeline-cadence
    fused step populates pp/s{S}/busy_s|bubble_s|bubble_frac for EVERY
    stage, and the fused busy-share vector (per-run wall apportioned by
    kind-weighted op shares) agrees with the legacy interpreter's
    host-attributed shares within a pinned tolerance. The tolerance is
    loose by design — legacy attribution includes per-action dispatch
    overhead the fused runtime abolished, so the two measure the same
    schedule through different clocks; what must agree is the SHAPE
    (which stage dominates, roughly by how much), not the microseconds.
    """
    from d9d_tpu.telemetry import Telemetry, set_telemetry

    set_telemetry(Telemetry())
    builder = Interleaved1F1BProgramBuilder(1, 2)
    m = 8
    legacy, fused, _, _ = build_pair(builder, m)
    mbs = make_microbatches(m, jax.random.PRNGKey(1))
    # warm both executors: compiles must not pollute the timed steps
    legacy.step(list(mbs))
    fused.step(list(mbs))

    from d9d_tpu.telemetry import get_telemetry

    tele = get_telemetry()
    num_stages = builder.num_stages

    def busy_shares():
        gauges = tele.registry.snapshot()["gauges"]
        busy = [gauges[f"pp/s{s}/busy_s"] for s in range(num_stages)]
        total = sum(busy)
        assert total > 0
        return [b / total for b in busy]

    legacy.step(list(mbs))
    legacy_shares = busy_shares()
    fused.step(list(mbs), timeline=True)
    fused_shares = busy_shares()
    gauges = tele.registry.snapshot()["gauges"]
    # the acceptance surface: every stage's gauge triple on the cadence
    # step, plus the rollup and the per-run wall
    for s in range(num_stages):
        assert gauges[f"pp/s{s}/busy_s"] > 0
        assert gauges[f"pp/s{s}/bubble_s"] >= 0
        assert 0 <= gauges[f"pp/s{s}/bubble_frac"] <= 1
    assert 0 <= gauges["pp/bubble_frac"] <= 1
    assert gauges["pp/run/r0/k0/wall_s"] > 0
    # shape agreement vs the legacy oracle (pinned tolerance: 0.25
    # absolute per-stage share — wide enough for dispatch-overhead skew
    # and CPU-CI timing noise, tight enough that swapped or uniform
    # attribution fails)
    for s in range(num_stages):
        assert abs(legacy_shares[s] - fused_shares[s]) <= 0.25, (
            f"stage {s}: legacy share {legacy_shares[s]:.3f} vs "
            f"fused share {fused_shares[s]:.3f}"
        )


def test_timeline_off_by_default_no_gauges():
    """Without timeline=True the fused step must emit NO pp/s{S}/* or
    pp/run/* gauges (the off-cadence byte-identical contract's
    telemetry face)."""
    from d9d_tpu.telemetry import Telemetry, set_telemetry

    tele = set_telemetry(Telemetry())
    legacy, fused, _, _ = build_pair(Interleaved1F1BProgramBuilder(1, 2), 8)
    del legacy
    fused.step(make_microbatches(8, jax.random.PRNGKey(1)))
    gauges = tele.registry.snapshot()["gauges"]
    assert not any(
        k.startswith("pp/s") or k.startswith("pp/run/") for k in gauges
    ), sorted(gauges)


# -- slow tier: the compile-heavy schedule × policy sweep ---------------


@pytest.mark.slow
@pytest.mark.parametrize("residual_policy", ["remat", "cache_full", "cache_acts"])
@pytest.mark.parametrize("m", [4, 7])
def test_zb1p_policies_bitwise_slow(residual_policy, m):
    run_parity(
        Interleaved1F1BProgramBuilder(2, zero_bubble=True), m,
        residual_policy=residual_policy,
    )


@pytest.mark.slow
@pytest.mark.parametrize("residual_policy", ["cache_full", "cache_acts"])
def test_zbv_bitwise_slow(residual_policy):
    run_parity(
        ZeroBubbleVProgramBuilder(2), 4, residual_policy=residual_policy
    )


@pytest.mark.slow
def test_dual_pipe_v_bitwise_slow():
    run_parity(DualPipeVProgramBuilder(2), 4, residual_policy="cache_full")


@pytest.mark.slow
@pytest.mark.parametrize("pp,m", [(2, 4), (4, 8)])
def test_gpipe_bitwise_slow(pp, m):
    run_parity(GPipeProgramBuilder(pp), m)


@pytest.mark.slow
def test_interleaved_virtual_stages_bitwise_slow():
    run_parity(Interleaved1F1BProgramBuilder(2, 2), 8)
