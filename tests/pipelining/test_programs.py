"""Schedule builder validity: every schedule × microbatch count must produce
a deadlock-free, complete program after comm injection.

Mirrors the reference's exhaustive schedule sweep (test_e2e.py:49-66) at
the program level — numeric e2e parity is covered separately.
"""

import pytest

from d9d_tpu.pipelining.program import (
    BackwardWeight,
    Compose,
    DualPipeVProgramBuilder,
    GPipeProgramBuilder,
    Interleaved1F1BProgramBuilder,
    InferenceProgramBuilder,
    LoopedBFSProgramBuilder,
    ScheduleStyle,
    ZeroBubbleVProgramBuilder,
    add_communication_ops,
    ranks_to_stages,
    stage_to_rank,
    validate_program,
)


def _validate(builder, m, train=True):
    program = builder.compose(m)
    program = add_communication_ops(
        program, num_stages=builder.num_stages, stage_owner=builder.stage_owner
    )
    return validate_program(
        program,
        num_stages=builder.num_stages,
        num_microbatches=m,
        stage_owner=builder.stage_owner,
        train=train,
    )


MB_COUNTS = [1, 2, 3, 4, 8, 13, 32]


class TestTopology:
    def test_loop(self):
        assert [stage_to_rank(s, 4, ScheduleStyle.LOOP) for s in range(8)] == [
            0, 1, 2, 3, 0, 1, 2, 3,
        ]

    def test_v_snake(self):
        # stages 0..7 over 4 ranks: down then up — rank r owns r and 7-r
        assert [stage_to_rank(s, 4, ScheduleStyle.V) for s in range(8)] == [
            0, 1, 2, 3, 3, 2, 1, 0,
        ]

    def test_ranks_to_stages(self):
        assert ranks_to_stages(8, 4, ScheduleStyle.V)[0] == [0, 7]


@pytest.mark.parametrize("m", MB_COUNTS)
@pytest.mark.parametrize("pp", [1, 2, 4])
class TestSimpleSchedules:
    def test_gpipe(self, pp, m):
        _validate(GPipeProgramBuilder(pp), m)

    def test_1f1b(self, pp, m):
        _validate(Interleaved1F1BProgramBuilder(pp), m)

    def test_zb1p(self, pp, m):
        sim = _validate(Interleaved1F1BProgramBuilder(pp, zero_bubble=True), m)
        assert any(isinstance(a, BackwardWeight) for _, a in sim.order)

    def test_inference(self, pp, m):
        _validate(InferenceProgramBuilder(pp), m, train=False)


@pytest.mark.parametrize("m", MB_COUNTS)
@pytest.mark.parametrize("pp,v", [(2, 2), (2, 3), (4, 2)])
def test_looped_bfs(pp, v, m):
    _validate(LoopedBFSProgramBuilder(pp, v), m)


@pytest.mark.parametrize("m", [2, 4, 8, 12, 32])
@pytest.mark.parametrize("pp,v", [(2, 2), (4, 2), (4, 3)])
def test_interleaved_1f1b(pp, v, m):
    if m % pp != 0:
        pytest.skip("megatron constraint")
    _validate(Interleaved1F1BProgramBuilder(pp, v), m)


def test_interleaved_rejects_bad_microbatches():
    with pytest.raises(ValueError, match="num_microbatches"):
        Interleaved1F1BProgramBuilder(4, 2).compose(6)


@pytest.mark.parametrize("m", MB_COUNTS)
@pytest.mark.parametrize("pp", [1, 2, 4])
def test_zero_bubble_v(pp, m):
    sim = _validate(ZeroBubbleVProgramBuilder(pp), m)
    assert any(isinstance(a, BackwardWeight) for _, a in sim.order)


@pytest.mark.parametrize("m", MB_COUNTS)
@pytest.mark.parametrize("pp", [1, 2, 4])
def test_dual_pipe_v(pp, m):
    program = DualPipeVProgramBuilder(pp).compose(m)
    _validate(DualPipeVProgramBuilder(pp), m)
    if pp > 1 and m >= 2 * pp:
        has_compose = any(
            isinstance(a, Compose) for acts in program.values() for a in acts
        )
        assert has_compose, "DualPipeV should emit overlapped F+B slots"


def test_zb1p_defers_weight_grads():
    """ZB1P must not run W immediately after its I during steady state."""
    program = Interleaved1F1BProgramBuilder(4, zero_bubble=True).compose(8)
    acts = [str(a) for a in program[0]]
    first_i = next(i for i, a in enumerate(acts) if a.startswith("I"))
    first_w = next(i for i, a in enumerate(acts) if a.startswith("W"))
    assert first_w > first_i + 1
