"""Pipeline runtime e2e: every schedule must be grad-exact vs a sequential
single-program baseline.

Mirrors the reference's e2e sweep (test/d9d_test/pipelining/test_e2e.py:49-66):
a tiny multi-stage matmul model, each schedule × microbatch counts, grads
compared against running the composed model directly.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
# slow tier: heavy kernel/e2e parity
pytestmark = [pytest.mark.e2e, requires_modern_jax]


from d9d_tpu.pipelining import (
    PipelineScheduleExecutor,
    PipelineStageInfo,
    PipelineStageRuntime,
)
from d9d_tpu.pipelining.program import add_communication_ops
from d9d_tpu.pipelining.program.builders import (
    DualPipeVProgramBuilder,
    GPipeProgramBuilder,
    Interleaved1F1BProgramBuilder,
    InferenceProgramBuilder,
    LoopedBFSProgramBuilder,
    ZeroBubbleVProgramBuilder,
)

HID = 8


class StageBlock(nn.Module):
    """One pipeline stage: dense + tanh (nonlinear so dI/dW split is honest)."""

    n_layers: int = 1

    @nn.compact
    def __call__(self, x):
        for _ in range(self.n_layers):
            x = jnp.tanh(nn.Dense(HID, use_bias=True)(x))
        return x


class TinyTask:
    """StageTask impl: carry = activations; loss = masked square error."""

    def split_microbatch(self, micro):
        return micro["x"], {}, {"y": micro["y"], "w": micro["w"]}

    def stage_forward(self, module, params, carry, kwargs):
        return module.apply(params, carry)

    def last_stage_loss(self, module, params, carry, kwargs, state):
        out = module.apply(params, carry)
        err = ((out - state["y"]) ** 2).sum(-1)
        loss_sum = (err * state["w"]).sum()
        weight = state["w"].sum()
        return loss_sum, weight, {"examples": weight}


def make_stages(num_stages, key, residual_policy="remat"):
    """Build per-stage modules+params and the composed baseline function."""
    task = TinyTask()
    stages = {}
    all_params = []
    for s in range(num_stages):
        info = PipelineStageInfo(stage_index=s, num_stages=num_stages)
        module = StageBlock()
        key, sub = jax.random.split(key)
        params = module.init(sub, jnp.zeros((1, HID)))
        stages[s] = PipelineStageRuntime(
            info=info, module=module, params=params, task=task,
            residual_policy=residual_policy,
        )
        all_params.append(params)
    return stages, all_params, task


def baseline_grads(stages, all_params, microbatches):
    """Σ_mb grads of loss_sum via one composed jax.grad per microbatch."""

    def total_loss(params_list, micro):
        h = micro["x"]
        for s in range(len(params_list) - 1):
            h = stages[s].module.apply(params_list[s], h)
        out = stages[len(params_list) - 1].module.apply(params_list[-1], h)
        err = ((out - micro["y"]) ** 2).sum(-1)
        return (err * micro["w"]).sum()

    grads = None
    loss = 0.0
    for micro in microbatches:
        l, g = jax.value_and_grad(total_loss)(all_params, micro)
        loss = loss + l
        grads = g if grads is None else jax.tree.map(jnp.add, grads, g)
    return loss, grads


def make_microbatches(m, key, mb_size=4):
    out = []
    for i in range(m):
        key, k1, k2 = jax.random.split(key, 3)
        out.append(
            {
                "x": jax.random.normal(k1, (mb_size, HID)),
                "y": jax.random.normal(k2, (mb_size, HID)),
                "w": jnp.ones((mb_size,)),
            }
        )
    return out


def run_schedule(builder, m, seed=0, residual_policy="remat"):
    stages, all_params, _ = make_stages(
        builder.num_stages, jax.random.PRNGKey(seed),
        residual_policy=residual_policy,
    )
    program = add_communication_ops(
        builder.compose(m),
        num_stages=builder.num_stages,
        stage_owner=builder.stage_owner,
    )
    ex = PipelineScheduleExecutor(
        stages=stages,
        program=program,
        stage_owner=builder.stage_owner,
        num_microbatches=m,
    )
    microbatches = make_microbatches(m, jax.random.PRNGKey(seed + 1))
    result = ex.step(microbatches)
    ref_loss, ref_grads = baseline_grads(stages, all_params, microbatches)
    return result, ref_loss, ref_grads


def assert_close(result, ref_loss, ref_grads, num_stages):
    np.testing.assert_allclose(
        np.asarray(result.loss_sum), np.asarray(ref_loss), rtol=1e-5
    )
    for s in range(num_stages):
        got = result.grads[s]
        want = ref_grads[s]
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            got,
            want,
        )


@pytest.mark.parametrize("m", [1, 2, 4, 7])
@pytest.mark.parametrize("pp", [1, 2, 4])
def test_gpipe(pp, m):
    b = GPipeProgramBuilder(pp)
    assert_close(*run_schedule(b, m), b.num_stages)


@pytest.mark.parametrize("m", [1, 4, 7])
@pytest.mark.parametrize("pp", [2, 4])
def test_1f1b(pp, m):
    b = Interleaved1F1BProgramBuilder(pp)
    assert_close(*run_schedule(b, m), b.num_stages)


@pytest.mark.parametrize("m", [4, 8])
@pytest.mark.parametrize("pp,v", [(2, 2), (4, 2)])
def test_interleaved_1f1b(pp, v, m):
    b = Interleaved1F1BProgramBuilder(pp, v)
    assert_close(*run_schedule(b, m), b.num_stages)


@pytest.mark.parametrize("m", [4, 8])
@pytest.mark.parametrize("pp", [2, 4])
@pytest.mark.parametrize("residual_policy", ["remat", "cache_full", "cache_acts"])
def test_zb1p(pp, m, residual_policy):
    b = Interleaved1F1BProgramBuilder(pp, zero_bubble=True)
    assert_close(
        *run_schedule(b, m, residual_policy=residual_policy), b.num_stages
    )


@pytest.mark.parametrize("m", [1, 4, 6])
@pytest.mark.parametrize("pp,v", [(2, 2), (2, 3), (4, 2)])
def test_looped_bfs(pp, v, m):
    b = LoopedBFSProgramBuilder(pp, v)
    assert_close(*run_schedule(b, m), b.num_stages)


@pytest.mark.parametrize("m", [2, 4, 7])
@pytest.mark.parametrize("pp", [2, 4])
@pytest.mark.parametrize("residual_policy", ["remat", "cache_full", "cache_acts"])
def test_zero_bubble_v(pp, m, residual_policy):
    b = ZeroBubbleVProgramBuilder(pp)
    assert_close(
        *run_schedule(b, m, residual_policy=residual_policy), b.num_stages
    )


@pytest.mark.parametrize("m", [2, 4, 7])
@pytest.mark.parametrize("pp", [2, 4])
@pytest.mark.parametrize("residual_policy", ["remat", "cache_full", "cache_acts"])
def test_dual_pipe_v(pp, m, residual_policy):
    b = DualPipeVProgramBuilder(pp)
    assert_close(
        *run_schedule(b, m, residual_policy=residual_policy), b.num_stages
    )


@pytest.mark.parametrize("pp", [1, 4])
def test_inference_forward_only(pp):
    m = 4
    b = InferenceProgramBuilder(pp)
    stages, all_params, _ = make_stages(b.num_stages, jax.random.PRNGKey(0))
    program = add_communication_ops(
        b.compose(m), num_stages=b.num_stages, stage_owner=b.stage_owner
    )
    ex = PipelineScheduleExecutor(
        stages=stages,
        program=program,
        stage_owner=b.stage_owner,
        num_microbatches=m,
        train=False,
    )
    microbatches = make_microbatches(m, jax.random.PRNGKey(1))
    result = ex.step(microbatches)
    ref_loss, _ = baseline_grads(stages, all_params, microbatches)
    assert result.grads is None
    np.testing.assert_allclose(
        np.asarray(result.loss_sum), np.asarray(ref_loss), rtol=1e-5
    )
    assert len(result.outputs) == m


def test_single_stage_split_backward_reports_loss():
    """pp=1 with a zero-bubble schedule: the stage is both first and last;
    loss statistics must still surface from the BackwardInput action."""
    b = Interleaved1F1BProgramBuilder(1, zero_bubble=True)
    result, ref_loss, ref_grads = run_schedule(b, 3)
    assert result.loss_sum is not None
    assert_close(result, ref_loss, ref_grads, 1)


def test_frozen_backbone_first_stage():
    """dI no-op on first stage must not break schedules (reference frozen-
    param variants, test_e2e.py)."""
    b = ZeroBubbleVProgramBuilder(2)
    result, ref_loss, ref_grads = run_schedule(b, 4)
    assert_close(result, ref_loss, ref_grads, b.num_stages)
