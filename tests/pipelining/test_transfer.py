"""put_compat / _shardwise_put contracts (VERDICT r3 item 8).

Multi-host PP safety: when a stage-boundary transfer needs a global slice
this process does not own, the shard-wise fallback must fail with the
documented layout-guidance error — not hang mid-step or produce garbage.
The legal-layout contract is documented in
docs/design/multihost_pp_layouts.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d9d_tpu.pipelining.runtime.transfer import _shardwise_put, put_compat


def _mesh(devs):
    return Mesh(np.array(devs), ("x",))


def test_shardwise_put_moves_matching_slices(devices):
    src = NamedSharding(_mesh(devices[:2]), P("x"))
    dst = NamedSharding(_mesh(devices[2:4]), P("x"))
    x = jax.device_put(jnp.arange(16.0).reshape(4, 4), src)
    out = _shardwise_put(x, dst)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert out.sharding.device_set == dst.device_set


def test_shardwise_put_missing_slice_raises_documented_error(devices):
    """A consumer slice no owned source shard covers (the multi-host
    boundary-crossing case) fails fast with layout guidance."""
    src = NamedSharding(_mesh(devices[:2]), P("x"))  # halves on dev 0/1
    # destination wants the FULL array replicated per device — neither
    # source shard matches the full-array slice, exactly the situation of
    # a pp boundary whose consumer slice lives on another process
    dst = NamedSharding(_mesh(devices[2:4]), P())
    x = jax.device_put(jnp.arange(16.0).reshape(4, 4), src)
    with pytest.raises(ValueError, match="interleave processes"):
        _shardwise_put(x, dst)


def test_put_compat_same_set_is_direct(devices):
    sh = NamedSharding(_mesh(devices[:2]), P("x"))
    x = jax.device_put(jnp.arange(8.0), sh)
    out = put_compat({"a": x}, sh)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(x))


def test_put_compat_none_sharding_passthrough(devices):
    x = jnp.arange(4.0)
    assert put_compat(x, None) is x
