"""ragged-all-to-all EP dispatch: routing math, compute-scaling contract,
capacity clamping, differentiability (VERDICT r1 item 3).

Uses a transparent expert_fn (adds a per-expert constant) so routing
errors can't hide inside GEMM numerics. The local oracle computes the same
top-k combine on unsharded arrays.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
# slow tier: heavy kernel/e2e parity
pytestmark = [pytest.mark.e2e, requires_modern_jax]

from jax.sharding import Mesh, PartitionSpec as P

from d9d_tpu.core import compat
from d9d_tpu.ops.ep_dispatch import ep_buffer_rows, ep_dispatch_compute_combine

W = 4  # ep world
E = 8  # global experts
E_LOC = E // W
K = 2
N_LOC = 6  # tokens per shard
D = 16


def _mesh(devices):
    return Mesh(np.array(devices[:W]), ("ep",))


def _expert_fn_factory(shard_offset, seen_rows):
    """Expert e transforms rows as x * (2 + global_e). Records GEMM size."""

    def fn(rows, group_sizes):
        seen_rows.append(rows.shape[0])
        # build per-row scale from group membership
        bounds = jnp.cumsum(group_sizes)
        local_e = (jnp.arange(rows.shape[0])[:, None] >= bounds[None, :]).sum(1)
        global_e = shard_offset + jnp.clip(local_e, 0, group_sizes.shape[0] - 1)
        return rows * (2.0 + global_e[:, None])

    return fn


def _run_dispatch(devices, x, ids, probs, capacity_factor):
    mesh = _mesh(devices)
    seen: list[int] = []

    def body(x_loc, ids_loc, probs_loc):
        shard_offset = jax.lax.axis_index(("ep",)) * E_LOC
        return ep_dispatch_compute_combine(
            x_loc,
            ids_loc,
            probs_loc,
            _expert_fn_factory(shard_offset, seen),
            ep_axes=("ep",),
            e_loc=E_LOC,
            ep_world=W,
            capacity_factor=capacity_factor,
        )

    run = jax.jit(
        compat.shard_map(
            body,
            mesh=mesh,
            in_specs=(P("ep"), P("ep"), P("ep")),
            out_specs=P("ep"),
            check_vma=False,
        )
    )
    # scope the mesh: earlier tests may have left a process-wide full mesh
    # (MeshParameters.build calls jax.set_mesh) that would conflict
    with jax.set_mesh(mesh):
        out = run(x, ids, probs)
    return np.asarray(out), seen


def _oracle(x, ids, probs):
    """Unsharded top-k combine with the same transparent experts."""
    scale = 2.0 + ids.astype(np.float32)  # [N, K]
    return (x[:, None, :] * scale[..., None] * probs[..., None]).sum(axis=1)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    n = W * N_LOC
    x = rng.randn(n, D).astype(np.float32)
    ids = rng.randint(0, E, size=(n, K)).astype(np.int32)
    # distinct experts per row keep the oracle simple
    ids[:, 1] = (ids[:, 0] + 1 + ids[:, 1] % (E - 1)) % E
    probs = rng.rand(n, K).astype(np.float32)
    return x, ids.astype(np.int32), probs


def test_dropless_matches_oracle(devices):
    x, ids, probs = _data()
    out, seen = _run_dispatch(devices, x, ids, probs, capacity_factor=None)
    np.testing.assert_allclose(out, _oracle(x, ids, probs), rtol=1e-5, atol=1e-5)


def test_gemm_rows_follow_capacity_contract(devices):
    """Per-shard GEMM row count must be the static buffer size, i.e.
    capacity_factor × N_global·k/ep — not the all-gather's N_global·k."""
    x, ids, probs = _data()
    m = N_LOC * K
    _, seen = _run_dispatch(devices, x, ids, probs, capacity_factor=2.0)
    expected = ep_buffer_rows(m, W, 2.0)
    assert all(s == expected for s in seen)
    assert expected < m * W  # strictly below the all-gather row count

    _, seen_dropless = _run_dispatch(devices, x, ids, probs, None)
    assert all(s == ep_buffer_rows(m, W, None) for s in seen_dropless)


def test_generous_capacity_matches_oracle(devices):
    """A capacity that no shard overflows must be numerically dropless."""
    x, ids, probs = _data(seed=3)
    out, _ = _run_dispatch(devices, x, ids, probs, capacity_factor=float(W))
    np.testing.assert_allclose(out, _oracle(x, ids, probs), rtol=1e-5, atol=1e-5)


def test_capacity_drops_are_deterministic_zeros(devices):
    """Force overflow: all assignments target shard 0's experts. The kept
    rows must match the oracle; dropped ones contribute exactly zero."""
    rng = np.random.RandomState(1)
    n = W * N_LOC
    x = rng.randn(n, D).astype(np.float32)
    ids = np.zeros((n, K), np.int32)
    ids[:, 1] = 1  # all rows → experts 0 and 1 (both shard 0)
    probs = np.full((n, K), 0.5, np.float32)

    out, _ = _run_dispatch(devices, x, ids, probs, capacity_factor=1.0)
    m = N_LOC * K
    cap = ep_buffer_rows(m, W, 1.0)  # 16: shard 0's whole 12 + 4 of shard 1
    assert cap == 16
    full = _oracle(x, ids, probs)
    # earliest source wins: shard 0's tokens fully kept
    np.testing.assert_allclose(out[:N_LOC], full[:N_LOC], rtol=1e-5, atol=1e-5)
    # shard 1 got 4 rows in — the expert-0 assignments of its first 4
    # tokens (its block is expert-sorted); expert 0 scales by 2.0
    np.testing.assert_allclose(
        out[N_LOC : N_LOC + 4], x[N_LOC : N_LOC + 4] * 2.0 * 0.5,
        rtol=1e-5, atol=1e-5,
    )
    # everything else dropped → exact zeros
    np.testing.assert_array_equal(out[N_LOC + 4 :], 0.0)


def test_dispatch_is_differentiable(devices):
    x, ids, probs = _data(seed=5)
    mesh = _mesh(devices)

    def loss(x, probs):
        def body(x_loc, ids_loc, probs_loc):
            shard_offset = jax.lax.axis_index(("ep",)) * E_LOC
            return ep_dispatch_compute_combine(
                x_loc, ids_loc, probs_loc,
                _expert_fn_factory(shard_offset, []),
                ep_axes=("ep",), e_loc=E_LOC, ep_world=W,
                capacity_factor=None,
            )

        out = compat.shard_map(
            body, mesh=mesh, in_specs=(P("ep"), P("ep"), P("ep")),
            out_specs=P("ep"), check_vma=False,
        )(x, ids, probs)
        return (out ** 2).sum()

    with jax.set_mesh(mesh):
        gx, gp = jax.grad(loss, argnums=(0, 1))(
            jnp.asarray(x), jnp.asarray(probs)
        )

    def oracle_loss(x, probs):
        scale = 2.0 + jnp.asarray(ids, jnp.float32)
        out = (x[:, None, :] * scale[..., None] * probs[..., None]).sum(axis=1)
        return (out ** 2).sum()

    egx, egp = jax.grad(oracle_loss, argnums=(0, 1))(
        jnp.asarray(x), jnp.asarray(probs)
    )
    np.testing.assert_allclose(gx, egx, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gp, egp, rtol=1e-4, atol=1e-4)
