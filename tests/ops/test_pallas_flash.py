"""Pallas flash attention vs eager oracle (interpret mode on CPU).

Mirrors the reference's kernel-correctness strategy (SURVEY §4.1): every
feature combination checked numerically against the eager implementation,
forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: heavy kernel/e2e parity


from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.attention.pallas_flash import make_pallas_flash_sdpa


def rng(*shape, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=jnp.float32)


flash = make_pallas_flash_sdpa(block_q=16, block_kv=16)


def check(q, k, v, rtol=2e-3, atol=2e-3, **kw):
    out_f = flash(q, k, v, **kw)
    out_e = eager_sdpa(q, k, v, **kw)
    np.testing.assert_allclose(out_f, out_e, rtol=rtol, atol=atol)


class TestForward:
    def test_causal(self):
        check(rng(2, 64, 4, 32), rng(2, 64, 4, 32, seed=1), rng(2, 64, 4, 32, seed=2))

    def test_non_causal(self):
        check(
            rng(1, 32, 2, 16), rng(1, 32, 2, 16, seed=1), rng(1, 32, 2, 16, seed=2),
            causal=False,
        )

    def test_gqa(self):
        check(rng(2, 48, 8, 16), rng(2, 48, 2, 16, seed=1), rng(2, 48, 2, 16, seed=2))

    def test_unaligned_seq_len(self):
        # 50 is not a multiple of block 16 — exercises padding/masking
        check(rng(1, 50, 2, 16), rng(1, 50, 2, 16, seed=1), rng(1, 50, 2, 16, seed=2))

    def test_window(self):
        check(
            rng(1, 64, 2, 16), rng(1, 64, 2, 16, seed=1), rng(1, 64, 2, 16, seed=2),
            window_size=20,
        )

    def test_sinks(self):
        sinks = jnp.array([0.5, -1.0])
        check(
            rng(1, 32, 2, 16), rng(1, 32, 2, 16, seed=1), rng(1, 32, 2, 16, seed=2),
            sinks=sinks,
        )

    def test_softmax_scale(self):
        check(
            rng(1, 32, 2, 16), rng(1, 32, 2, 16, seed=1), rng(1, 32, 2, 16, seed=2),
            softmax_scale=0.5,
        )

    def test_mask_falls_back_to_eager(self):
        q = rng(1, 8, 1, 8)
        k, v = rng(1, 8, 1, 8, seed=1), rng(1, 8, 1, 8, seed=2)
        mask = jnp.ones((1, 1, 8, 8), bool)
        out = flash(q, k, v, mask=mask)
        np.testing.assert_allclose(out, eager_sdpa(q, k, v, mask=mask), rtol=1e-5)


class TestBackward:
    @pytest.mark.parametrize(
        "case",
        ["causal", "gqa", "window", "sinks", "unaligned"],
    )
    def test_grads_match_eager(self, case):
        kw = {}
        t = 48
        hq = hkv = 2
        sinks = None
        if case == "gqa":
            hq = 4
        elif case == "window":
            kw["window_size"] = 17
        elif case == "sinks":
            sinks = jnp.array([0.3, -0.7])
        elif case == "unaligned":
            t = 37
        q = rng(2, t, hq, 16)
        k, v = rng(2, t, hkv, 16, seed=1), rng(2, t, hkv, 16, seed=2)

        def loss_flash(q, k, v, s):
            return (flash(q, k, v, sinks=s, **kw) ** 2).sum()

        def loss_eager(q, k, v, s):
            return (eager_sdpa(q, k, v, sinks=s, **kw) ** 2).sum()

        argnums = (0, 1, 2, 3) if sinks is not None else (0, 1, 2)
        gf = jax.grad(loss_flash, argnums=argnums)(q, k, v, sinks)
        ge = jax.grad(loss_eager, argnums=argnums)(q, k, v, sinks)
        for a, b in zip(gf, ge):
            np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-3)


def _packed_segments(b, t, n_docs, seed=3):
    """Random packed-document segment ids: non-decreasing ints per row."""
    key = jax.random.PRNGKey(seed)
    cuts = jax.random.randint(key, (b, t), 0, n_docs)
    return jnp.sort(cuts, axis=1).astype(jnp.int32)


class TestSegments:
    """Packed-sequence (varlen) parity — reference flash_attn_varlen_func
    (d9d/kernel/flash_attn/function.py:384)."""

    def test_forward_matches_eager(self):
        q = rng(2, 48, 2, 16)
        k, v = rng(2, 48, 2, 16, seed=1), rng(2, 48, 2, 16, seed=2)
        seg = _packed_segments(2, 48, 3)
        check(q, k, v, q_segments=seg, kv_segments=seg)

    def test_forward_unaligned(self):
        q = rng(1, 37, 2, 16)
        k, v = rng(1, 37, 2, 16, seed=1), rng(1, 37, 2, 16, seed=2)
        seg = _packed_segments(1, 37, 4)
        check(q, k, v, q_segments=seg, kv_segments=seg)

    def test_forward_with_window_and_gqa(self):
        q = rng(2, 64, 4, 16)
        k, v = rng(2, 64, 2, 16, seed=1), rng(2, 64, 2, 16, seed=2)
        seg = _packed_segments(2, 64, 3)
        check(q, k, v, q_segments=seg, kv_segments=seg, window_size=20)

    def test_sinks_with_segments(self):
        q = rng(2, 48, 2, 16)
        k, v = rng(2, 48, 2, 16, seed=1), rng(2, 48, 2, 16, seed=2)
        seg = _packed_segments(2, 48, 3)
        check(q, k, v, q_segments=seg, kv_segments=seg,
              sinks=jnp.array([0.4, -0.9]))

    @pytest.mark.parametrize("case", ["plain", "gqa_window", "sinks"])
    def test_grads_match_eager(self, case):
        kw = {}
        hq = hkv = 2
        sinks = None
        if case == "gqa_window":
            hq, kw["window_size"] = 4, 19
        elif case == "sinks":
            sinks = jnp.array([0.3, -0.7])
        q = rng(2, 48, hq, 16)
        k, v = rng(2, 48, hkv, 16, seed=1), rng(2, 48, hkv, 16, seed=2)
        seg = _packed_segments(2, 48, 3)

        def loss_flash(q, k, v, s):
            return (flash(q, k, v, sinks=s, q_segments=seg,
                          kv_segments=seg, **kw) ** 2).sum()

        def loss_eager(q, k, v, s):
            return (eager_sdpa(q, k, v, sinks=s, q_segments=seg,
                               kv_segments=seg, **kw) ** 2).sum()

        argnums = (0, 1, 2, 3) if sinks is not None else (0, 1, 2)
        gf = jax.grad(loss_flash, argnums=argnums)(q, k, v, sinks)
        ge = jax.grad(loss_eager, argnums=argnums)(q, k, v, sinks)
        for a, b in zip(gf, ge):
            np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-3)

    def test_mismatched_segments_raise(self):
        q = rng(1, 16, 1, 8)
        with pytest.raises(ValueError):
            flash(q, q, q, q_segments=_packed_segments(1, 16, 2))


class TestAttentionBlock:
    """flash_attention_block: the (o, lse) chunk primitive ring attention
    composes. Chunked calls at global offsets + an lse-combine must equal
    one full-sequence attention, fwd and bwd (the bwd exercises the dlse
    cotangent folding into delta)."""

    def _combine(self, parts):
        from d9d_tpu.ops.attention.pallas_flash import combine_attention_chunks

        o, lse = parts[0]
        for o2, lse2 in parts[1:]:
            o, lse = combine_attention_chunks(o, lse, o2, lse2)
        return o

    @pytest.mark.parametrize("n_chunks,kw", [
        (2, {}),
        (4, {"window_size": 13}),
        (2, {"causal": False}),
    ])
    @pytest.mark.slow  # ~10s/param compile-bound on the 2-core rig
    def test_chunked_matches_full(self, n_chunks, kw):
        from d9d_tpu.ops.attention.pallas_flash import flash_attention_block

        b, t, hq, hkv, d = 2, 64, 4, 2, 16
        q = rng(b, t, hq, d)
        k, v = rng(b, t, hkv, d, seed=1), rng(b, t, hkv, d, seed=2)
        seg = _packed_segments(b, t, 3)
        c = t // n_chunks

        def loss_chunked(q, k, v):
            parts = [
                flash_attention_block(
                    q, k[:, i * c:(i + 1) * c], v[:, i * c:(i + 1) * c],
                    q_offset=0, k_offset=i * c,
                    q_segments=seg, kv_segments=seg[:, i * c:(i + 1) * c],
                    block_q=16, block_kv=16, **kw)
                for i in range(n_chunks)
            ]
            return (self._combine(parts) ** 2).sum()

        def loss_full(q, k, v):
            return (eager_sdpa(q, k, v, q_segments=seg,
                               kv_segments=seg, **kw) ** 2).sum()

        lc, gc = jax.value_and_grad(loss_chunked, (0, 1, 2))(q, k, v)
        le, ge = jax.value_and_grad(loss_full, (0, 1, 2))(q, k, v)
        np.testing.assert_allclose(lc, le, rtol=2e-3, atol=2e-3)
        for a, b_ in zip(gc, ge):
            np.testing.assert_allclose(a, b_, rtol=5e-3, atol=5e-3)

    def test_fully_future_chunk_is_weightless(self):
        from d9d_tpu.ops.attention.pallas_flash import flash_attention_block

        q = rng(1, 16, 2, 8)
        k, v = rng(1, 16, 2, 8, seed=1), rng(1, 16, 2, 8, seed=2)
        # keys sit entirely in the causal future of every query
        o, lse = flash_attention_block(
            q, k, v, q_offset=0, k_offset=1024, block_q=16, block_kv=16)
        assert np.all(np.asarray(lse) < -1e29)


class TestFusedBackward:
    """One-pass backward (D9D_TPU_FLASH_BWD=fused): dq/dk/dv must match
    the split two-kernel backward (and hence the eager oracle) across the
    feature matrix. The fused kernel accumulates dq in a full-[g*Tq, d]
    VMEM scratch across the kv grid dim."""

    @pytest.mark.parametrize("case", [
        "causal", "gqa", "window", "segments", "sinks", "unaligned",
        "noncausal",
    ])
    def test_grads_match_split(self, case):
        kw = {}
        t, hq, hkv = 48, 2, 2
        sinks = None
        seg = None
        if case == "gqa":
            hq = 4
        elif case == "window":
            kw["window_size"] = 17
        elif case == "segments":
            seg = _packed_segments(2, 48, 3)
        elif case == "sinks":
            sinks = jnp.array([0.3, -0.7])
        elif case == "unaligned":
            t = 37
        elif case == "noncausal":
            kw["causal"] = False
        fused = make_pallas_flash_sdpa(
            block_q=16, block_kv=16, fused_bwd=True
        )
        split = make_pallas_flash_sdpa(
            block_q=16, block_kv=16, fused_bwd=False
        )
        q = rng(2, t, hq, 16)
        k, v = rng(2, t, hkv, 16, seed=1), rng(2, t, hkv, 16, seed=2)

        def loss(f, q, k, v, s):
            return (f(q, k, v, sinks=s, q_segments=seg,
                      kv_segments=seg, **kw) ** 2).sum()

        argnums = (0, 1, 2, 3) if sinks is not None else (0, 1, 2)
        gf = jax.grad(lambda *a: loss(fused, *a), argnums)(q, k, v, sinks)
        gs = jax.grad(lambda *a: loss(split, *a), argnums)(q, k, v, sinks)
        for a, b in zip(gf, gs):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("q_offset,k_offset", [(0, 0), (64, 32)])
    def test_ring_block_grads_with_fused(self, q_offset, k_offset):
        """flash_attention_block's VJP routes through the fused backward
        (exercising its offsets branch and the lse-cotangent path) and
        matches the split backward at nonzero global offsets."""
        from d9d_tpu.ops.attention import pallas_flash as pf

        q = rng(1, 32, 2, 16)
        k, v = rng(1, 32, 2, 16, seed=1), rng(1, 32, 2, 16, seed=2)

        def loss(q, k, v, fused):
            o, lse = pf.flash_attention_block(
                q, k, v, q_offset=q_offset, k_offset=k_offset,
                block_q=16, block_kv=16, fused_bwd=fused,
            )
            return (o.astype(jnp.float32) ** 2).sum() + lse.sum()

        g_split = jax.grad(loss, (0, 1, 2))(q, k, v, False)
        g_fused = jax.grad(loss, (0, 1, 2))(q, k, v, True)
        for a, b in zip(g_fused, g_split):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
