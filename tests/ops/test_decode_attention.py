"""Flash-decode kernel parity vs the eager slot-mask oracle.

The kernel (ops/attention/pallas_decode.py) must reproduce
``eager_sdpa(q, cache, cache, causal=False, mask=_decode_slot_mask(...))``
bit-for-bit in semantics (fp32 accumulation both sides) across start
positions, windows, sinks, GQA grouping, ragged key validity, and
non-lane-aligned cache lengths. Runs in Pallas interpret mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.nn.attention import _decode_slot_mask
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.attention.pallas_decode import flash_decode_attention


def _mk(b, t, hq, hkv, d, s, seed=0):
    """q plus a HEADS-MAJOR [B, Hkv, S, D] slot cache (the kernel's —
    and the GQA decode cache's — native layout)."""
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, hkv, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, hkv, s, d), jnp.float32)
    return q, k, v


def _oracle(q, k, v, start, window, sinks, kv_valid):
    s_max = k.shape[2]
    t = q.shape[1]
    mask = None
    if kv_valid is not None:
        mask = kv_valid[:, None, None, :].astype(bool)
    dec = _decode_slot_mask(jnp.asarray(start), t, s_max, window, mask)
    return eager_sdpa(
        q,
        jnp.transpose(k, (0, 2, 1, 3)),
        jnp.transpose(v, (0, 2, 1, 3)),
        causal=False, sinks=sinks, mask=dec,
    )


@pytest.mark.parametrize("t", [1, 3])
@pytest.mark.parametrize("start", [0, 5, 60])
@pytest.mark.parametrize("window", [None, 7])
def test_parity_start_window(t, start, window):
    b, hq, hkv, d, s = 2, 4, 2, 16, 64
    if start + t > s:
        pytest.skip("overflows cache")
    q, k, v = _mk(b, t, hq, hkv, d, s)
    got = flash_decode_attention(
        q, k, v, start=jnp.asarray(start), window_size=window,
        interpret=True, block_kv=32,
    )
    want = _oracle(q, k, v, start, window, None, None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_parity_sinks_and_validity():
    b, t, hq, hkv, d, s = 2, 1, 8, 2, 32, 96  # g=4, s not %128
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=3)
    rng = np.random.RandomState(7)
    sinks = jnp.asarray(rng.randn(hq), jnp.float32)
    start = 40
    # left-padded ragged: row 0 valid from slot 10, row 1 from slot 0
    valid = np.ones((b, s), np.int32)
    valid[0, :10] = 0
    kv_valid = jnp.asarray(valid)
    got = flash_decode_attention(
        q, k, v, start=jnp.asarray(start), sinks=sinks, kv_valid=kv_valid,
        interpret=True, block_kv=32,
    )
    want = _oracle(q, k, v, start, None, sinks, kv_valid)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_parity_per_row_start():
    """Continuous batching: each row carries its own write index; the
    kernel must mask slot-causally per row (oracle: per-row slot mask)."""
    b, t, hq, hkv, d, s = 3, 1, 4, 2, 16, 64
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=9)
    starts = jnp.asarray([0, 17, 63], jnp.int32)
    got = flash_decode_attention(
        q, k, v, start=starts, interpret=True, block_kv=32
    )
    for i in range(b):
        want_i = _oracle(
            q[i : i + 1], k[i : i + 1], v[i : i + 1],
            int(starts[i]), None, None, None,
        )
        np.testing.assert_allclose(
            np.asarray(got[i : i + 1]), np.asarray(want_i),
            rtol=2e-5, atol=2e-5,
        )


def test_fully_masked_rows_emit_zeros():
    """ADVICE r5 #3: a row whose EVERY key is masked must produce exact
    zeros (guarded softmax), not the silent mean-of-V that an unclamped
    online softmax yields when m never leaves its sentinel. Partially
    masked rows in the same batch must stay oracle-exact."""
    b, t, hq, hkv, d, s = 2, 1, 4, 2, 16, 64
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=11)
    start = 40
    valid = np.ones((b, s), np.int32)
    valid[0, :] = 0        # row 0: nothing visible at all
    valid[1, :10] = 0      # row 1: ordinary left-padded raggedness
    got = np.asarray(flash_decode_attention(
        q, k, v, start=jnp.asarray(start),
        kv_valid=jnp.asarray(valid), interpret=True, block_kv=32,
    ))
    np.testing.assert_array_equal(got[0], np.zeros_like(got[0]))
    want = _oracle(q, k, v, start, None, None, jnp.asarray(valid))
    np.testing.assert_allclose(
        got[1:], np.asarray(want)[1:], rtol=2e-5, atol=2e-5
    )


def _paginate(k, v, page_size, seed=0):
    """Scatter a contiguous [B, Hkv, S, D] cache into a permuted page
    pool + page table whose gathered view equals the original — the
    paged call must then match the contiguous call exactly."""
    rng = np.random.RandomState(seed)
    b, hkv, s, d = k.shape
    n_pages = s // page_size
    pool_n = b * n_pages + 1  # page 0 = reserved garbage
    pool_k = np.zeros((pool_n, hkv, page_size, d), np.float32)
    pool_v = np.zeros((pool_n, hkv, page_size, d), np.float32)
    perm = rng.permutation(np.arange(1, pool_n))
    pt = np.zeros((b, n_pages), np.int32)
    i = 0
    for bi in range(b):
        for pi in range(n_pages):
            page = perm[i]
            i += 1
            pt[bi, pi] = page
            sl = slice(pi * page_size, (pi + 1) * page_size)
            pool_k[page] = np.asarray(k)[bi, :, sl]
            pool_v[page] = np.asarray(v)[bi, :, sl]
    return jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(pt)


@pytest.mark.parametrize("window", [None, 7])
def test_paged_parity_per_row_start(window):
    """The paged block-index gather (scalar-prefetch index map) must
    reproduce the contiguous kernel bit-for-bit in semantics: same
    per-row starts, same windows, pages deliberately scattered through
    the pool in permuted order."""
    b, t, hq, hkv, d, s, ps = 3, 1, 4, 2, 16, 64, 16
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=21)
    starts = jnp.asarray([0, 17, 63], jnp.int32)
    want = flash_decode_attention(
        q, k, v, start=starts, window_size=window, interpret=True,
        block_kv=ps,
    )
    pool_k, pool_v, pt = _paginate(k, v, ps, seed=4)
    got = flash_decode_attention(
        q, pool_k, pool_v, start=starts, window_size=window,
        page_table=pt, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_paged_parity_sinks_and_gqa():
    b, t, hq, hkv, d, s, ps = 2, 1, 8, 2, 32, 96, 16  # g=4
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=23)
    rng = np.random.RandomState(5)
    sinks = jnp.asarray(rng.randn(hq), jnp.float32)
    starts = jnp.asarray([40, 95], jnp.int32)
    want = flash_decode_attention(
        q, k, v, start=starts, sinks=sinks, interpret=True, block_kv=ps
    )
    pool_k, pool_v, pt = _paginate(k, v, ps, seed=6)
    got = flash_decode_attention(
        q, pool_k, pool_v, start=starts, sinks=sinks, page_table=pt,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    with pytest.raises(NotImplementedError, match="kv_valid"):
        flash_decode_attention(
            q, pool_k, pool_v, start=starts, page_table=pt,
            kv_valid=jnp.ones((b, pt.shape[1] * ps), jnp.int32),
            interpret=True,
        )


def test_parity_under_jit_traced_start():
    """start is traced in real decode loops (lax.scan carry)."""
    b, t, hq, hkv, d, s = 1, 1, 4, 4, 16, 64
    q, k, v = _mk(b, t, hq, hkv, d, s, seed=5)

    @jax.jit
    def step(start):
        return flash_decode_attention(
            q, k, v, start=start, interpret=True, block_kv=32
        )

    for start in (0, 17, 63):
        got = step(jnp.asarray(start))
        want = _oracle(q, k, v, start, None, None, None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


@pytest.mark.e2e  # slow tier: whole-module prefill+decode loop ×2 backends
def test_gqa_module_routes_pallas(monkeypatch):
    """GroupedQueryAttention decode through the kernel (env-forced on
    CPU → interpret mode) must match the default eager routing."""
    from d9d_tpu.nn.attention import GroupedQueryAttention
    from d9d_tpu.ops.rope import (
        compute_rope_frequencies,
        make_rope_cos_sin,
    )

    blk = GroupedQueryAttention(
        hidden_size=32, num_heads=4, num_kv_heads=2, head_dim=8,
        sdpa=eager_sdpa, dtype=jnp.float32, decode_max_length=16,
        window_size=6, use_sinks=True,
    )
    b = 2
    inv, sc = compute_rope_frequencies(8, 10000.0)

    def rope(start, t):
        pos = jnp.broadcast_to(jnp.arange(start, start + t), (b, t))
        return make_rope_cos_sin(pos, inv, sc)

    x4 = jax.random.normal(jax.random.PRNGKey(0), (b, 4, 32))
    cos, sin = rope(0, 4)
    variables = blk.init(jax.random.PRNGKey(1), x4, cos, sin)
    params = variables["params"]
    fresh = jax.tree.map(jnp.zeros_like, variables["cache"])

    def drive():
        _, st = blk.apply({"params": params, "cache": fresh},
                          x4, cos, sin, mutable=["cache"])
        outs = []
        for i in range(4, 7):
            c1, s1 = rope(i, 1)
            o, st = blk.apply(
                {"params": params, "cache": st["cache"]},
                x4[:, :1], c1, s1, mutable=["cache"],
            )
            outs.append(o)
        return jnp.concatenate(outs, axis=1)

    monkeypatch.setenv("D9D_TPU_DECODE_ATTN", "eager")
    want = drive()
    monkeypatch.setenv("D9D_TPU_DECODE_ATTN", "pallas")
    got = drive()
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
