"""Ring attention parity vs the eager oracle.

The reference has NO context-parallel attention (SURVEY.md §2.9) — this is
the beyond-reference capability, so the test bar is the same as the other
kernels: numerical parity (fwd + grads) against eager_sdpa on the gathered
sequence, across mesh layouts (cp alone, cp×dp, cp×tp), causal/window/sink
variants.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.core.compat import HAS_MODERN_JAX

# the SPMD/multiprocess e2e tier needs the modern jax runtime
# (core/compat.py emulates only ambient-mesh bookkeeping)
requires_modern_jax = pytest.mark.skipif(
    not HAS_MODERN_JAX, reason="needs the modern-jax SPMD runtime"
)
# slow tier: heavy kernel/e2e parity
pytestmark = [pytest.mark.e2e, requires_modern_jax]

from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core import compat
from d9d_tpu.core import MeshParameters
from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.attention.ring import make_ring_sdpa, ring_attention


def _rand_qkv(key, b, t, hq, hkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), dtype)
    k = jax.random.normal(kk, (b, t, hkv, d), dtype)
    v = jax.random.normal(kv, (b, t, hkv, d), dtype)
    return q, k, v


def _assert_close(a, b, atol=2e-5, rtol=2e-5):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32), atol=atol, rtol=rtol
    )


@pytest.mark.parametrize(
    "mesh_kw,batch_axes,head_axes",
    [
        ({"cp_shard": 8}, (), ()),
        ({"dp_shard": 2, "cp_shard": 4}, ("dp_s",), ()),
        ({"cp_shard": 4, "tp": 2}, (), ("tp",)),
        ({"dp_shard": 2, "cp_shard": 2, "tp": 2}, ("dp_s",), ("tp",)),
    ],
)
def test_ring_matches_eager_fwd_bwd(devices, mesh_kw, batch_axes, head_axes):
    ctx = MeshParameters(**mesh_kw).build(devices)
    ring = make_ring_sdpa(
        ctx.mesh, seq_axis="cp_s", batch_axes=batch_axes, head_axes=head_axes
    )
    b, t, hq, hkv, d = 2, 32, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), b, t, hq, hkv, d)
    qkv_sharding = NamedSharding(
        ctx.mesh, P(tuple(batch_axes) or None, "cp_s", tuple(head_axes) or None, None)
    )
    qs, ks, vs = (jax.device_put(x, qkv_sharding) for x in (q, k, v))

    def loss_ring(q, k, v):
        o = ring(q, k, v, causal=True)
        return jnp.sum(jnp.sin(o)), o

    def loss_eager(q, k, v):
        o = eager_sdpa(q, k, v, causal=True)
        return jnp.sum(jnp.sin(o)), o

    (l_r, o_r), g_r = jax.jit(jax.value_and_grad(loss_ring, (0, 1, 2), has_aux=True))(qs, ks, vs)
    (l_e, o_e), g_e = jax.jit(jax.value_and_grad(loss_eager, (0, 1, 2), has_aux=True))(q, k, v)

    _assert_close(o_r, o_e)
    _assert_close(l_r, l_e)
    for gr, ge in zip(g_r, g_e):
        _assert_close(gr, ge, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_noncausal_and_window(devices, causal):
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=())
    b, t, hq, hkv, d = 1, 32, 2, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b, t, hq, hkv, d)
    sh = NamedSharding(ctx.mesh, P(None, "cp_s", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    o_r = jax.jit(lambda a, b_, c: ring(a, b_, c, causal=causal, window_size=9))(qs, ks, vs)
    o_e = eager_sdpa(q, k, v, causal=causal, window_size=9)
    _assert_close(o_r, o_e)


def test_ring_with_sinks(devices):
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=())
    b, t, hq, hkv, d = 1, 16, 4, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b, t, hq, hkv, d)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (hq,))
    sh = NamedSharding(ctx.mesh, P(None, "cp_s", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    def loss_r(q, k, v, s):
        return jnp.sum(jnp.sin(ring(q, k, v, causal=True, sinks=s)))

    def loss_e(q, k, v, s):
        return jnp.sum(jnp.sin(eager_sdpa(q, k, v, causal=True, sinks=s)))

    l_r, g_r = jax.jit(jax.value_and_grad(loss_r, (0, 3)))(qs, ks, vs, sinks)
    l_e, g_e = jax.value_and_grad(loss_e, (0, 3))(q, k, v, sinks)
    _assert_close(l_r, l_e)
    _assert_close(g_r[0], g_e[0], atol=1e-4, rtol=1e-4)
    _assert_close(g_r[1], g_e[1], atol=1e-4, rtol=1e-4)


def test_ring_rejects_mask(devices):
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=())
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 1, 8, 2, 2, 4)
    with pytest.raises(NotImplementedError):
        ring(q, k, v, mask=jnp.ones((1, 2, 8, 8), bool))


def test_ring_raw_inside_shard_map(devices):
    """ring_attention composes with a user shard_map directly."""
    ctx = MeshParameters(cp_shard=8).build(devices)
    b, t, h, d = 1, 64, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), b, t, h, h, d)
    sh = NamedSharding(ctx.mesh, P(None, "cp_s", None, None))

    run = jax.jit(
        compat.shard_map(
            functools.partial(ring_attention, axis_name="cp_s", causal=True),
            mesh=ctx.mesh,
            in_specs=(sh.spec, sh.spec, sh.spec),
            out_specs=sh.spec,
            check_vma=False,
        )
    )
    o = run(jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
    _assert_close(o, eager_sdpa(q, k, v, causal=True))


@pytest.mark.parametrize(
    "mesh_kw,batch_axes,head_axes",
    [
        ({"cp_shard": 2, "dp_shard": 4}, ("dp_s",), ()),
        ({"cp_shard": 4, "tp": 2}, (), ("tp",)),
    ],
)
def test_ring_packed_segments_match_eager(devices, mesh_kw, batch_axes, head_axes):
    """Packed-batch parity (VERDICT r2 item 10): segment ids ride the ring
    alongside their K/V blocks and cross-segment attention is masked, fwd
    and bwd, matching eager_sdpa's packed semantics on the full sequence."""
    ctx = MeshParameters(**mesh_kw).build(devices)
    ring = make_ring_sdpa(
        ctx.mesh, seq_axis="cp_s", batch_axes=batch_axes, head_axes=head_axes
    )
    b, t, hq, hkv, d = 4, 32, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b, t, hq, hkv, d)
    # ragged packed layout: row i packs sequences with boundaries every
    # (5 + i) tokens, so segment edges fall on both sides of cp shards
    seg = np.stack(
        [np.arange(t) // (5 + i) for i in range(b)]
    ).astype(np.int32)
    seg = jnp.asarray(seg)

    qkv_sharding = NamedSharding(
        ctx.mesh, P(tuple(batch_axes) or None, "cp_s", tuple(head_axes) or None, None)
    )
    seg_sharding = NamedSharding(ctx.mesh, P(tuple(batch_axes) or None, "cp_s"))
    qs, ks, vs = (jax.device_put(x, qkv_sharding) for x in (q, k, v))
    segs = jax.device_put(seg, seg_sharding)

    def loss_ring(q, k, v):
        o = ring(q, k, v, causal=True, q_segments=segs, kv_segments=segs)
        return jnp.sum(jnp.sin(o)), o

    def loss_eager(q, k, v):
        o = eager_sdpa(
            q, k, v, causal=True, q_segments=seg, kv_segments=seg
        )
        return jnp.sum(jnp.sin(o)), o

    (l_r, o_r), g_r = jax.jit(
        jax.value_and_grad(loss_ring, (0, 1, 2), has_aux=True)
    )(qs, ks, vs)
    (l_e, o_e), g_e = jax.jit(
        jax.value_and_grad(loss_eager, (0, 1, 2), has_aux=True)
    )(q, k, v)

    _assert_close(o_r, o_e)
    _assert_close(l_r, l_e)
    for gr, ge in zip(g_r, g_e):
        _assert_close(gr, ge, atol=1e-4, rtol=1e-4)


def test_ring_flash_matches_eager_impl(devices):
    """The two ring block implementations (Pallas flash blocks with the
    lse-combine vs the fp32 einsum oracle) agree fwd+bwd on a config
    exercising causal+window+sinks together."""
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    b, t, hq, hkv, d = 2, 32, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), b, t, hq, hkv, d)
    sinks = jax.random.normal(jax.random.PRNGKey(8), (hq,))
    sh = NamedSharding(ctx.mesh, P(None, "cp_s", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    def make_loss(impl):
        ring = make_ring_sdpa(
            ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=(), impl=impl
        )

        def loss(q, k, v, s):
            o = ring(q, k, v, causal=True, window_size=11, sinks=s)
            return jnp.sum(jnp.sin(o)), o

        return jax.jit(jax.value_and_grad(loss, (0, 1, 2, 3), has_aux=True))

    (l_f, o_f), g_f = make_loss("flash")(qs, ks, vs, sinks)
    (l_e, o_e), g_e = make_loss("eager")(qs, ks, vs, sinks)
    _assert_close(o_f, o_e)
    _assert_close(l_f, l_e)
    for gf, ge in zip(g_f, g_e):
        _assert_close(gf, ge, atol=1e-4, rtol=1e-4)


def test_ring_rejects_unknown_impl(devices):
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(
        ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=(), impl="nope"
    )
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 1, 8, 2, 2, 4)
    with pytest.raises(ValueError, match="ring block impl"):
        jax.jit(lambda a, b_, c: ring(a, b_, c))(q, k, v)


def test_ring_segments_require_both(devices):
    ctx = MeshParameters(cp_shard=4).build(devices[:4])
    ring = make_ring_sdpa(ctx.mesh, seq_axis="cp_s", batch_axes=(), head_axes=())
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 1, 8, 2, 2, 4)
    with pytest.raises(ValueError, match="together"):
        ring(q, k, v, q_segments=jnp.zeros((1, 8), jnp.int32))
