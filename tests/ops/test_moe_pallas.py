"""Fused aligned-layout MoE FFN kernel (ops/moe_pallas.py): exact parity
with the reference XLA chain, forward and backward, on the CPU rig
(interpret mode)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from d9d_tpu.ops.moe import (
    permute_tokens,
    sort_tokens_by_expert,
    unpermute_combine,
    grouped_matmul,
)
from d9d_tpu.ops.moe_pallas import (
    aligned_metadata,
    fused_moe_ffn_apply,
)
from d9d_tpu.ops.swiglu import silu_mul


def _reference(x, probs, sort, wg, wu, wd, dtype):
    permuted_x, permuted_probs = permute_tokens(x, probs, sort)
    xx = permuted_x.astype(dtype)
    inter = wg.shape[-1]
    gate_up = jnp.concatenate([wg.astype(dtype), wu.astype(dtype)], axis=-1)
    h_gu = grouped_matmul(xx, gate_up, sort.group_sizes)
    hidden = silu_mul(h_gu[..., :inter], h_gu[..., inter:])
    y = grouped_matmul(hidden, wd.astype(dtype), sort.group_sizes)
    y = y * permuted_probs[:, None].astype(dtype)
    return unpermute_combine(y, sort, x.shape[0]).astype(x.dtype)


def _problem(seed=0, n=96, h=64, inter=32, e=8, k=2, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, h), dtype)
    wg = jnp.asarray(rng.randn(e, h, inter) * 0.1, dtype)
    wu = jnp.asarray(rng.randn(e, h, inter) * 0.1, dtype)
    wd = jnp.asarray(rng.randn(e, inter, h) * 0.1, dtype)
    ids = jnp.asarray(
        np.stack([rng.choice(e, size=k, replace=False) for _ in range(n)]),
        jnp.int32,
    )
    probs = jnp.asarray(rng.rand(n, k) + 0.1, jnp.float32)
    return x, ids, probs, wg, wu, wd


class TestAlignedMetadata:
    def test_layout_invariants(self):
        _, ids, _, *_ = _problem()
        e, bm = 8, 16
        sort = sort_tokens_by_expert(ids, e)
        meta = aligned_metadata(sort, e, bm)
        m = int(sort.dest.shape[0])
        assert meta.m_pad % bm == 0
        dest_aligned = np.asarray(meta.dest_aligned)
        # aligned rows are unique and in range
        assert len(set(dest_aligned.tolist())) == m
        assert dest_aligned.max() < meta.m_pad
        # each aligned row sits in a tile owned by its pair's expert
        gid = np.asarray(meta.gid)
        flat_ids = np.asarray(ids).reshape(-1)
        for pair, row in enumerate(dest_aligned.tolist()):
            assert gid[row // bm] == flat_ids[pair]
        # pair_src is the inverse map
        pair_src = np.asarray(meta.pair_src)
        for pair, row in enumerate(dest_aligned.tolist()):
            assert pair_src[row] == pair
        # pad rows marked -1
        assert (pair_src < 0).sum() == meta.m_pad - m

    def test_empty_and_full_groups(self):
        # all tokens on expert 3: other groups are empty, still consistent
        n, e, k, bm = 24, 6, 1, 8
        ids = jnp.full((n, k), 3, jnp.int32)
        sort = sort_tokens_by_expert(ids, e)
        meta = aligned_metadata(sort, e, bm)
        dest_aligned = np.asarray(meta.dest_aligned)
        assert len(set(dest_aligned.tolist())) == n
        gid = np.asarray(meta.gid)
        for row in dest_aligned.tolist():
            assert gid[row // bm] == 3


class TestFusedParity:
    @pytest.mark.parametrize("block_m", [8, 16, 64])
    @pytest.mark.parametrize("backend", ["pallas", "pallas_gather"])
    def test_forward_matches_reference(self, block_m, backend, monkeypatch):
        monkeypatch.setenv("D9D_TPU_MOE_FFN", backend)
        x, ids, probs, wg, wu, wd = _problem()
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        ref = _reference(x, probs, sort, wg, wu, wd, jnp.float32)
        got = fused_moe_ffn_apply(
            x, probs, sort, wg, wu, wd, jnp.float32,
            num_experts=e, block_m=block_m, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.slow  # >10s compile-bound on the 2-core rig
    def test_gather_variant_gradients(self, monkeypatch):
        """The gather variant shares the reference backward; its custom
        fwd must still produce exact grads end to end."""
        monkeypatch.setenv("D9D_TPU_MOE_FFN", "pallas_gather")
        x, ids, probs, wg, wu, wd = _problem(seed=11)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)

        def loss(fn):
            def run(x_, wg_):
                return (fn(x_, wg_) ** 2).sum()
            return run

        fused = loss(lambda x_, wg_: fused_moe_ffn_apply(
            x_, probs, sort, wg_, wu, wd, jnp.float32,
            num_experts=e, block_m=16, interpret=True,
        ))
        ref = loss(lambda x_, wg_: _reference(
            x_, probs, sort, wg_, wu, wd, jnp.float32
        ))
        gf = jax.grad(fused, argnums=(0, 1))(x, wg)
        gr = jax.grad(ref, argnums=(0, 1))(x, wg)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            )

    def test_gather_fit_gate_falls_back(self, monkeypatch):
        """Unaligned token counts (n % 8 != 0) must silently use the
        two-step aligned path, not the resident-x kernel."""
        from d9d_tpu.ops.moe_pallas import _gather_fits

        assert _gather_fits(96, 192, 64, 32, 16, 4, num_experts=8)
        assert not _gather_fits(
            97, 194, 64, 32, 16, 4, num_experts=8  # misaligned
        )
        # the SMEM estimate must count aligned_metadata's real pair_src
        # length ((ceil(m/bm) + E) * bm), so a huge expert count alone
        # can veto even when VMEM residency fits
        assert not _gather_fits(96, 192, 64, 32, 16, 4, num_experts=8192)
        monkeypatch.setenv("D9D_TPU_MOE_FFN_VMEM_BUDGET", "1024")
        assert not _gather_fits(
            96, 192, 64, 32, 16, 4, num_experts=8  # over budget
        )

    @pytest.mark.slow  # ~10s compile-bound on the 2-core rig
    def test_gradients_match_reference(self):
        x, ids, probs, wg, wu, wd = _problem(seed=3)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        cot = jnp.asarray(
            np.random.RandomState(9).randn(*x.shape), jnp.float32
        )

        def loss_ref(x_, probs_, wg_, wu_, wd_):
            return (
                _reference(x_, probs_, sort, wg_, wu_, wd_, jnp.float32)
                * cot
            ).sum()

        def loss_fused(x_, probs_, wg_, wu_, wd_):
            return (
                fused_moe_ffn_apply(
                    x_, probs_, sort, wg_, wu_, wd_, jnp.float32,
                    num_experts=e, block_m=16, interpret=True,
                )
                * cot
            ).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
            x, probs, wg, wu, wd
        )
        g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(
            x, probs, wg, wu, wd
        )
        for a, b in zip(g_fused, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            )

    @pytest.mark.slow  # ~9s compile-bound on the 2-core rig
    def test_under_remat(self):
        """jax.checkpoint replays the custom fwd; grads stay exact."""
        x, ids, probs, wg, wu, wd = _problem(seed=5)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)

        def f(x_):
            return fused_moe_ffn_apply(
                x_, probs, sort, wg, wu, wd, jnp.float32,
                num_experts=e, block_m=16, interpret=True,
            ).sum()

        g_plain = jax.grad(f)(x)
        g_remat = jax.grad(jax.checkpoint(f))(x)
        np.testing.assert_allclose(
            np.asarray(g_remat), np.asarray(g_plain), rtol=1e-6, atol=1e-6
        )

    def test_bf16_path(self):
        x, ids, probs, wg, wu, wd = _problem(seed=7, dtype=jnp.float32)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        ref = _reference(
            x.astype(jnp.bfloat16), probs, sort, wg, wu, wd, jnp.bfloat16
        )
        got = fused_moe_ffn_apply(
            x.astype(jnp.bfloat16), probs, sort, wg, wu, wd, jnp.bfloat16,
            num_experts=e, block_m=16, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            rtol=3e-2, atol=3e-2,
        )


@pytest.mark.e2e  # slow tier: 4-seed randomized sweep (r5 quick trim)
@pytest.mark.parametrize("seed", range(4))
def test_fused_parity_random_geometry(seed):
    """Randomized geometry sweep: token counts not divisible by block_m,
    skewed expert loads (including empty experts), k=1..3 — the aligned
    layout must stay exact everywhere."""
    rng = np.random.RandomState(100 + seed)
    e = int(rng.choice([3, 5, 8, 13]))
    k = int(rng.randint(1, min(4, e + 1)))
    n = int(rng.randint(17, 140))
    h = int(rng.choice([16, 48]))
    inter = int(rng.choice([8, 24]))
    block_m = int(rng.choice([8, 32]))
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    wg = jnp.asarray(rng.randn(e, h, inter) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.randn(e, h, inter) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.randn(e, inter, h) * 0.1, jnp.float32)
    # skewed routing: concentrate most tokens on few experts (hot set at
    # least k wide so the skew branch fires for EVERY seed — with
    # hot < e, some experts also stay empty)
    hot = rng.choice(e, size=max(k, e // 3), replace=False)
    ids_np = np.stack([
        rng.choice(hot, size=k, replace=False)
        if rng.rand() < 0.8
        else rng.choice(e, size=k, replace=False)
        for _ in range(n)
    ])
    ids = jnp.asarray(ids_np, jnp.int32)
    probs = jnp.asarray(rng.rand(n, k) + 0.05, jnp.float32)
    sort = sort_tokens_by_expert(ids, e)
    ref = _reference(x, probs, sort, wg, wu, wd, jnp.float32)
    got = fused_moe_ffn_apply(
        x, probs, sort, wg, wu, wd, jnp.float32,
        num_experts=e, block_m=block_m, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=3e-5, atol=3e-5
    )


class TestFusedCombine:
    """r7 gather-fused combine (ops/moe_pallas.py): the kernel emits the
    token-major combined [N, h] directly — parity fwd + grads vs the
    existing combine, the default-on env knob, and the fit gate."""

    @pytest.mark.parametrize("block_m", [8, 16, 64])
    def test_forward_matches_reference(self, block_m):
        x, ids, probs, wg, wu, wd = _problem(seed=21)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        ref = _reference(x, probs, sort, wg, wu, wd, jnp.float32)
        got = fused_moe_ffn_apply(
            x, probs, sort, wg, wu, wd, jnp.float32,
            num_experts=e, block_m=block_m, interpret=True,
            gather=True, combine=True,
        )
        # the in-kernel K-sum accumulates in expert-sorted order vs the
        # XLA path's slot order: ulp tolerance, same as the other paths
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_matches_uncombined_gather_variant(self):
        """combine on vs off over the SAME gather kernel inputs."""
        x, ids, probs, wg, wu, wd = _problem(seed=23)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        off = fused_moe_ffn_apply(
            x, probs, sort, wg, wu, wd, jnp.float32,
            num_experts=e, block_m=16, interpret=True,
            gather=True, combine=False,
        )
        on = fused_moe_ffn_apply(
            x, probs, sort, wg, wu, wd, jnp.float32,
            num_experts=e, block_m=16, interpret=True,
            gather=True, combine=True,
        )
        np.testing.assert_allclose(
            np.asarray(on), np.asarray(off), rtol=2e-5, atol=2e-5
        )

    def test_gradients_match_reference(self):
        """The combine variant rides the same custom_vjp backward (the
        XLA reference chain) — grads must match end to end."""
        x, ids, probs, wg, wu, wd = _problem(seed=25)
        e = wg.shape[0]
        sort = sort_tokens_by_expert(ids, e)
        cot = jnp.asarray(
            np.random.RandomState(9).randn(*x.shape), jnp.float32
        )

        def loss(fn):
            def run(x_, probs_, wg_, wu_, wd_):
                return (fn(x_, probs_, wg_, wu_, wd_) * cot).sum()
            return run

        ref = loss(lambda x_, p_, g_, u_, d_: _reference(
            x_, p_, sort, g_, u_, d_, jnp.float32
        ))
        fused = loss(lambda x_, p_, g_, u_, d_: fused_moe_ffn_apply(
            x_, p_, sort, g_, u_, d_, jnp.float32,
            num_experts=e, block_m=16, interpret=True,
            gather=True, combine=True,
        ))
        g_ref = jax.grad(ref, argnums=(0, 1, 2, 3, 4))(x, probs, wg, wu, wd)
        g_fused = jax.grad(fused, argnums=(0, 1, 2, 3, 4))(
            x, probs, wg, wu, wd
        )
        for a, b in zip(g_fused, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            )

    def test_env_knob_defaults_on(self, monkeypatch):
        from d9d_tpu.ops.moe import fused_combine_enabled

        monkeypatch.delenv("D9D_TPU_MOE_COMBINE", raising=False)
        assert fused_combine_enabled()
        monkeypatch.setenv("D9D_TPU_MOE_COMBINE", "unfused")
        assert not fused_combine_enabled()

    def test_combine_fit_gate(self, monkeypatch):
        from d9d_tpu.ops.moe_pallas import _combine_fits, _gather_fits

        assert _combine_fits(96, 192, 64, 32, 16, 4, num_experts=8)
        # anything the gather gate rejects, the combine gate rejects
        assert not _combine_fits(97, 194, 64, 32, 16, 4, num_experts=8)
        # a budget that fits the gather residency but not the extra
        # [N, h] output residency routes to the uncombined variant
        gather_only = None
        for budget in range(20_000, 400_000, 10_000):
            monkeypatch.setenv("D9D_TPU_MOE_FFN_VMEM_BUDGET", str(budget))
            if _gather_fits(96, 192, 64, 32, 16, 4, num_experts=8):
                gather_only = budget
                break
        assert gather_only is not None
        assert not _combine_fits(96, 192, 64, 32, 16, 4, num_experts=8)

    def test_skewed_and_empty_experts(self):
        """Every token on one expert: pad tiles and the scatter loop's
        branchless pad handling must stay exact."""
        n, e, k = 32, 6, 2
        rng = np.random.RandomState(31)
        x = jnp.asarray(rng.randn(n, 64), jnp.float32)
        wg = jnp.asarray(rng.randn(e, 64, 32) * 0.1, jnp.float32)
        wu = jnp.asarray(rng.randn(e, 64, 32) * 0.1, jnp.float32)
        wd = jnp.asarray(rng.randn(e, 32, 64) * 0.1, jnp.float32)
        ids = jnp.stack(
            [jnp.full((n,), 3, jnp.int32), jnp.full((n,), 5, jnp.int32)],
            axis=1,
        )
        probs = jnp.asarray(rng.rand(n, k), jnp.float32)
        sort = sort_tokens_by_expert(ids, e)
        ref = _reference(x, probs, sort, wg, wu, wd, jnp.float32)
        got = fused_moe_ffn_apply(
            x, probs, sort, wg, wu, wd, jnp.float32,
            num_experts=e, block_m=8, interpret=True,
            gather=True, combine=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_unfused_gate_up_env_knob_exact(monkeypatch):
    """D9D_TPU_MOE_FUSED_GATE_UP=0 (two grouped matmuls, no runtime
    weight concat — the ub1/fp32 A/B tools/roofline.py motivates) must be
    numerically identical to the fused default."""
    import jax.numpy as jnp

    from d9d_tpu.nn.moe import grouped_swiglu_apply

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(48, 32), jnp.float32)
    wg = jnp.asarray(rng.randn(4, 32, 16) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.randn(4, 32, 16) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.randn(4, 16, 32) * 0.1, jnp.float32)
    ids = jnp.asarray(rng.randint(0, 4, (48, 2)), jnp.int32)
    probs = jnp.asarray(rng.rand(48, 2), jnp.float32)
    sort = sort_tokens_by_expert(ids, 4)
    px, pp = permute_tokens(x, probs, sort)

    # pin the fused default so a leaked env var can't make this vacuous
    monkeypatch.setenv("D9D_TPU_MOE_FUSED_GATE_UP", "1")
    fused = grouped_swiglu_apply(
        px, pp, sort.group_sizes, wg, wu, wd, jnp.float32
    )
    monkeypatch.setenv("D9D_TPU_MOE_FUSED_GATE_UP", "0")
    unfused = grouped_swiglu_apply(
        px, pp, sort.group_sizes, wg, wu, wd, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(unfused), np.asarray(fused), rtol=1e-6, atol=1e-6
    )


@pytest.mark.e2e  # slow tier: whole-layer double-run (r5 quick trim)
class TestLayerIntegration:
    def test_moe_layer_env_switch(self, monkeypatch):
        """MoELayer output is identical (to tolerance) with the pallas
        FFN backend selected."""
        from d9d_tpu.nn.moe import MoELayer

        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 12, 64), jnp.float32)
        layer = MoELayer(
            hidden_dim=64,
            intermediate_dim_grouped=32,
            num_grouped_experts=8,
            top_k=2,
            dtype=jnp.float32,
        )
        params = layer.init(jax.random.PRNGKey(0), x)
        base = layer.apply(params, x)
        monkeypatch.setenv("D9D_TPU_MOE_FFN", "pallas")
        fused = layer.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(base), rtol=2e-5, atol=2e-5
        )
