"""Gated delta rule: chunked-WY vs recurrent-oracle parity + sanity.

Mirrors the reference's kernel test strategy (fla kernels tested against
naive recurrence): the chunked form must match the exact lax.scan
recurrence for every (chunk size, l2norm, GQA shape, ragged length)
combination, fwd and grads.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.e2e  # slow tier: heavy kernel/e2e parity


from d9d_tpu.ops.gated_delta import (
    gated_delta_rule_chunked,
    gated_delta_rule_recurrent,
)


def _inputs(key, b=2, t=33, h=2, dk=16, dv=8):
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, t, h, dk))
    k = jax.random.normal(ks[1], (b, t, h, dk))
    v = jax.random.normal(ks[2], (b, t, h, dv))
    g = -jax.nn.softplus(jax.random.normal(ks[3], (b, t, h)))  # ≤ 0
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (b, t, h)))
    return q, k, v, g, beta


def test_recurrent_matches_python_loop():
    q, k, v, g, beta = _inputs(jax.random.PRNGKey(0), b=1, t=5, h=1, dk=4, dv=3)
    o, s = gated_delta_rule_recurrent(q, k, v, g, beta, use_qk_l2norm=False)

    # plain numpy re-implementation of the recurrence
    qn, kn, vn = (np.asarray(x[0, :, 0]) for x in (q, k, v))
    gn, bn = np.asarray(g[0, :, 0]), np.asarray(beta[0, :, 0])
    qn = qn * (qn.shape[-1] ** -0.5)
    S = np.zeros((4, 3))
    outs = []
    for i in range(5):
        S = S * np.exp(gn[i])
        err = (vn[i] - S.T @ kn[i]) * bn[i]
        S = S + np.outer(kn[i], err)
        outs.append(S.T @ qn[i])
    np.testing.assert_allclose(np.asarray(o[0, :, 0]), np.array(outs), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s[0, 0]), S, atol=1e-5)


@pytest.mark.parametrize("chunk_size", [4, 8, 64])
@pytest.mark.parametrize("l2norm", [True, False])
def test_chunked_matches_recurrent(chunk_size, l2norm):
    q, k, v, g, beta = _inputs(jax.random.PRNGKey(1), t=37)
    o_r, s_r = gated_delta_rule_recurrent(q, k, v, g, beta, use_qk_l2norm=l2norm)
    o_c, s_c = gated_delta_rule_chunked(
        q, k, v, g, beta, use_qk_l2norm=l2norm, chunk_size=chunk_size
    )
    # rtol covers large-magnitude elements: at chunk_size=64 > t=37 the
    # whole sequence is one chunk, and the WY-form matmul accumulation
    # order diverges maximally from the scan recurrence — measured worst
    # case (l2norm=False): |Δ|=2.31e-5 on O(1) outputs at rel 5.96e-6,
    # i.e. pure fp32 summation-order noise, not an algorithmic error
    # (pre-PR-6 this was tier-1's single standing failure: atol-only
    # 2e-5 sat below the observed 2.31e-5)
    np.testing.assert_allclose(
        np.asarray(o_c), np.asarray(o_r), rtol=1e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(s_c), np.asarray(s_r), rtol=1e-5, atol=2e-5
    )


def test_chunked_grads_match_recurrent():
    q, k, v, g, beta = _inputs(jax.random.PRNGKey(2), t=16)

    def loss(fn, *args):
        o, _ = fn(*args)
        return jnp.sum(jnp.sin(o))

    g_r = jax.grad(lambda *a: loss(gated_delta_rule_recurrent, *a), (0, 1, 2, 3, 4))(
        q, k, v, g, beta
    )
    g_c = jax.grad(
        lambda *a: loss(
            lambda *b: gated_delta_rule_chunked(*b, chunk_size=8), *a
        ),
        (0, 1, 2, 3, 4),
    )(q, k, v, g, beta)
    for a, b in zip(g_c, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_initial_state_carries():
    q, k, v, g, beta = _inputs(jax.random.PRNGKey(3), t=32)
    # running two halves with state handoff == full run
    o_full, s_full = gated_delta_rule_chunked(q, k, v, g, beta, chunk_size=8)
    o1, s1 = gated_delta_rule_chunked(
        q[:, :16], k[:, :16], v[:, :16], g[:, :16], beta[:, :16], chunk_size=8
    )
    o2, s2 = gated_delta_rule_chunked(
        q[:, 16:], k[:, 16:], v[:, 16:], g[:, 16:], beta[:, 16:],
        chunk_size=8, initial_state=s1,
    )
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([o1, o2], 1)), np.asarray(o_full), atol=2e-5
    )
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s_full), atol=2e-5)


def test_decay_extremes():
    q, k, v, g, beta = _inputs(jax.random.PRNGKey(4), t=8)
    # g = -inf-ish (full decay): each step only sees its own write
    g_hard = jnp.full_like(g, -30.0)
    o, _ = gated_delta_rule_chunked(q, k, v, g_hard, beta, chunk_size=4)
    assert np.isfinite(np.asarray(o)).all()
    # g = 0 (no decay): plain delta rule — still finite and causal
    o0, _ = gated_delta_rule_chunked(q, k, v, jnp.zeros_like(g), beta, chunk_size=4)
    assert np.isfinite(np.asarray(o0)).all()
