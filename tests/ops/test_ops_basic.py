import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d9d_tpu.ops import (
    LM_IGNORE_INDEX,
    RopeStyle,
    apply_rope,
    compute_rope_frequencies,
    eager_sdpa,
    linear_cross_entropy,
    make_rope_cos_sin,
    rms_norm,
    silu_mul,
)


def rng(*shape, seed=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


class TestRmsNorm:
    def test_matches_manual(self):
        x = rng(4, 16)
        w = rng(16, seed=1) * 0.1 + 1.0
        out = rms_norm(x, w)
        expected = (
            np.asarray(x)
            / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True) + 1e-6)
            * np.asarray(w)
        )
        np.testing.assert_allclose(out, expected, rtol=1e-5)

    def test_zero_centered(self):
        x = rng(4, 16)
        w = jnp.zeros(16)
        out = rms_norm(x, w, zero_centered=True)
        base = rms_norm(x, jnp.ones(16))
        np.testing.assert_allclose(out, base, rtol=1e-6)

    def test_preserves_dtype(self):
        x = rng(4, 16).astype(jnp.bfloat16)
        assert rms_norm(x, jnp.ones(16)).dtype == jnp.bfloat16


class TestSiluMul:
    def test_matches_torch(self):
        import torch

        g, u = rng(8, 32), rng(8, 32, seed=1)
        out = silu_mul(g, u)
        tg = torch.tensor(np.asarray(g))
        tu = torch.tensor(np.asarray(u))
        expected = (torch.nn.functional.silu(tg) * tu).numpy()
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


class TestRope:
    def test_half_style_matches_hf(self):
        """HALF layout must match the HuggingFace Llama/Qwen implementation."""
        import torch

        b, t, h, d = 2, 5, 3, 8
        q = rng(b, t, h, d)
        inv_freq, scale = compute_rope_frequencies(d, 10000.0)
        assert scale == 1.0
        positions = jnp.arange(t)
        cos, sin = make_rope_cos_sin(positions, inv_freq, scale)
        out = apply_rope(q, cos[None], sin[None], RopeStyle.HALF)

        # HF oracle: rotate_half with cos/sin duplicated across both halves
        tq = torch.tensor(np.asarray(q)).permute(0, 2, 1, 3)  # [B,H,T,D]
        t_inv = torch.tensor(np.asarray(inv_freq))
        ang = torch.arange(t)[:, None].float() * t_inv[None, :]
        tcos = torch.cat([ang.cos(), ang.cos()], dim=-1)[None, None]
        tsin = torch.cat([ang.sin(), ang.sin()], dim=-1)[None, None]

        def rotate_half(x):
            x1, x2 = x.chunk(2, dim=-1)
            return torch.cat((-x2, x1), dim=-1)

        expected = (tq * tcos + rotate_half(tq) * tsin).permute(0, 2, 1, 3).numpy()
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)

    def test_interleaved_rotation_is_norm_preserving(self):
        q = rng(1, 7, 2, 16)
        inv_freq, s = compute_rope_frequencies(16, 1e6)
        cos, sin = make_rope_cos_sin(jnp.arange(7), inv_freq, s)
        out = apply_rope(q, cos[None], sin[None], RopeStyle.INTERLEAVED)
        np.testing.assert_allclose(
            jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(q, axis=-1), rtol=1e-5
        )

    @pytest.mark.parametrize("name", ["linear", "ntk", "yarn"])
    def test_scalings(self, name):
        from d9d_tpu.ops import RopeScalingLinear, RopeScalingNtk, RopeScalingYarn

        scaling = {
            "linear": RopeScalingLinear(factor=4.0),
            "ntk": RopeScalingNtk(factor=4.0),
            "yarn": RopeScalingYarn(factor=4.0, original_max_position=128),
        }[name]
        inv_freq, scale = compute_rope_frequencies(32, 10000.0, scaling)
        base, _ = compute_rope_frequencies(32, 10000.0)
        assert inv_freq.shape == (16,)
        # scaled frequencies must not exceed base (context extension slows rotation)
        assert (np.asarray(inv_freq) <= np.asarray(base) + 1e-9).all()
        if name == "yarn":
            assert scale > 1.0


class TestEagerSdpa:
    def test_causal_matches_torch(self):
        import torch

        b, t, h, d = 2, 9, 4, 16
        q, k, v = rng(b, t, h, d), rng(b, t, h, d, seed=1), rng(b, t, h, d, seed=2)
        out = eager_sdpa(q, k, v, causal=True)
        tq, tk, tv = (
            torch.tensor(np.asarray(x)).permute(0, 2, 1, 3) for x in (q, k, v)
        )
        expected = (
            torch.nn.functional.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
            .permute(0, 2, 1, 3)
            .numpy()
        )
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    def test_gqa_matches_torch(self):
        import torch

        q = rng(1, 6, 8, 8)
        k, v = rng(1, 6, 2, 8, seed=1), rng(1, 6, 2, 8, seed=2)
        out = eager_sdpa(q, k, v, causal=True)
        tq = torch.tensor(np.asarray(q)).permute(0, 2, 1, 3)
        tk = torch.tensor(np.asarray(k)).permute(0, 2, 1, 3)
        tv = torch.tensor(np.asarray(v)).permute(0, 2, 1, 3)
        expected = (
            torch.nn.functional.scaled_dot_product_attention(
                tq, tk, tv, is_causal=True, enable_gqa=True
            )
            .permute(0, 2, 1, 3)
            .numpy()
        )
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    def test_sliding_window(self):
        q = rng(1, 8, 1, 4)
        k, v = rng(1, 8, 1, 4, seed=1), rng(1, 8, 1, 4, seed=2)
        out_full = eager_sdpa(q, k, v, causal=True)
        out_win = eager_sdpa(q, k, v, causal=True, window_size=3)
        # early tokens (window not yet binding) identical, later differ
        np.testing.assert_allclose(out_win[:, :3], out_full[:, :3], rtol=1e-5)
        assert not np.allclose(out_win[:, 5:], out_full[:, 5:])

    def test_sinks_reduce_attention_mass(self):
        q = rng(1, 4, 2, 8)
        k, v = rng(1, 4, 2, 8, seed=1), rng(1, 4, 2, 8, seed=2)
        out_nosink = eager_sdpa(q, k, v, causal=True)
        out_sink = eager_sdpa(q, k, v, causal=True, sinks=jnp.full((2,), 10.0))
        # huge sink logit absorbs almost all probability mass
        assert np.abs(np.asarray(out_sink)).max() < np.abs(np.asarray(out_nosink)).max()

    def test_explicit_mask(self):
        q = rng(1, 4, 1, 4)
        k, v = rng(1, 4, 1, 4, seed=1), rng(1, 4, 1, 4, seed=2)
        mask = jnp.ones((1, 1, 4, 4), dtype=bool).at[..., 0].set(False)
        out = eager_sdpa(q, k, v, causal=True, mask=mask)
        assert np.isfinite(np.asarray(out)).all()

    def test_cross_attention_alignment(self):
        """T < S: last query aligns with last key (decode-style)."""
        q = rng(1, 1, 1, 4)
        k, v = rng(1, 6, 1, 4, seed=1), rng(1, 6, 1, 4, seed=2)
        out = eager_sdpa(q, k, v, causal=True)
        full_q = jnp.concatenate([rng(1, 5, 1, 4, seed=9), q], axis=1)
        out_full = eager_sdpa(full_q, k, v, causal=True)
        np.testing.assert_allclose(out[:, 0], out_full[:, -1], rtol=1e-5)


class TestLinearCrossEntropy:
    def _oracle(self, hidden, weight, labels):
        logits = np.asarray(hidden, np.float64) @ np.asarray(weight, np.float64).T
        lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + logits.max(-1)
        correct = np.take_along_axis(logits, np.maximum(labels, 0)[:, None], -1)[:, 0]
        loss = lse - correct
        loss[np.asarray(labels) == LM_IGNORE_INDEX] = 0.0
        return loss

    def test_matches_oracle(self):
        h, w = rng(10, 8), rng(32, 8, seed=1)
        labels = jnp.array([0, 5, 31, LM_IGNORE_INDEX, 2, 7, 1, 0, 30, LM_IGNORE_INDEX])
        out = linear_cross_entropy(h, w, labels)  # fp32 inputs → exact path
        np.testing.assert_allclose(out, self._oracle(h, w, np.asarray(labels)), rtol=1e-5)

    def test_bf16_matmul_policy_close_to_fp32(self):
        """bf16 inputs select the bf16-in/fp32-accum MXU policy by default
        and stay within bf16 rounding of the fp32 path (the softmax math is
        fp32 in both)."""
        h, w = rng(64, 32), rng(128, 32, seed=1)
        labels = jnp.arange(64) % 128
        ref = linear_cross_entropy(h, w, labels)  # fp32 path
        out = linear_cross_entropy(
            h.astype(jnp.bfloat16), w.astype(jnp.bfloat16), labels
        )
        np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)
        # and the dtype-inferred default equals the explicit policy
        explicit = linear_cross_entropy(
            h.astype(jnp.bfloat16), w.astype(jnp.bfloat16), labels,
            matmul_dtype="bf16",
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(explicit))

    def test_chunked_equals_unchunked(self):
        h, w = rng(100, 8), rng(64, 8, seed=1)
        labels = jnp.arange(100) % 64
        full = linear_cross_entropy(h, w, labels, chunk_size=1024)
        chunked = linear_cross_entropy(h, w, labels, chunk_size=16)
        np.testing.assert_allclose(full, chunked, rtol=1e-5)

    def test_grads_flow_and_match(self):
        h, w = rng(48, 8), rng(16, 8, seed=1)
        labels = jnp.arange(48) % 16

        def mean_loss(chunk):
            return lambda h, w: linear_cross_entropy(
                h, w, labels, chunk_size=chunk
            ).mean()

        g_full = jax.grad(mean_loss(1024), argnums=(0, 1))(h, w)
        g_chunk = jax.grad(mean_loss(8), argnums=(0, 1))(h, w)
        for a, b in zip(g_full, g_chunk):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)

    def test_softcap(self):
        h, w = rng(4, 8), rng(16, 8, seed=1)
        labels = jnp.array([0, 1, 2, 3])
        out = linear_cross_entropy(h, w, labels, logit_softcap=5.0)
        assert out.shape == (4,)
        assert np.isfinite(np.asarray(out)).all()


class TestPartialRope:
    def test_gqa_partial_rope_runs_and_passes_through(self):
        """rope_fraction=0.5: second half of head dims must be untouched by rotation."""
        import flax.linen as nn

        from d9d_tpu.nn.attention import GroupedQueryAttention

        d = 16
        module = GroupedQueryAttention(
            hidden_size=32, num_heads=2, num_kv_heads=2, head_dim=d,
            sdpa=eager_sdpa, rope_fraction=0.5, dtype=jnp.float32,
        )
        x = rng(1, 6, 32)
        inv_freq, s = compute_rope_frequencies(d // 2, 10000.0)
        cos, sin = make_rope_cos_sin(jnp.arange(6), inv_freq, s)
        params = module.init(jax.random.PRNGKey(0), x, cos[None], sin[None])
        out = module.apply(params, x, cos[None], sin[None])
        assert out.shape == (1, 6, 32)
        assert np.isfinite(np.asarray(out)).all()


class TestStableExpertOrder:
    """The sort-free grouping permutation must reproduce stable argsort
    exactly (ops/moe.py: one-hot -> cumsum -> scatter replaces the bitonic
    sort the MoE layer would otherwise run per layer per microbatch)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_stable_argsort(self, seed):
        from d9d_tpu.ops.moe import sort_tokens_by_expert, stable_expert_order

        r = np.random.RandomState(seed)
        n, k, e = r.randint(1, 200), r.randint(1, 9), r.randint(1, 65)
        ids = jnp.asarray(r.randint(0, e, size=(n, k)), jnp.int32)
        flat = ids.reshape(-1)
        got_idx, got_dest, got_sizes = stable_expert_order(flat, e)
        np.testing.assert_array_equal(
            np.asarray(got_dest)[np.asarray(got_idx)], np.arange(flat.shape[0])
        )
        np.testing.assert_array_equal(got_idx, jnp.argsort(flat, stable=True))
        np.testing.assert_array_equal(got_sizes, jnp.bincount(flat, length=e))
        ts = sort_tokens_by_expert(ids, e)
        np.testing.assert_array_equal(ts.token_idx, got_idx // k)

    def test_empty_experts_and_single_expert(self):
        from d9d_tpu.ops.moe import stable_expert_order

        # all pairs on one expert; other experts empty
        flat = jnp.full((7,), 3, jnp.int32)
        idx, _, sizes = stable_expert_order(flat, 8)
        np.testing.assert_array_equal(idx, np.arange(7))
        assert int(sizes[3]) == 7 and int(sizes.sum()) == 7


def test_stable_expert_order_argsort_fallback_matches(monkeypatch):
    """Above the M*E threshold the grouping falls back to a stable argsort
    (ADVICE r3: the one-hot's O(M*E) HBM traffic inverts at large expert
    counts); both paths must produce identical permutations."""
    import d9d_tpu.ops.moe as moe_ops

    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 13, 2048).astype(np.int32))
    fast = moe_ops.stable_expert_order(ids, 13)
    monkeypatch.setattr(moe_ops, "_ONE_HOT_GROUPING_LIMIT", 0)
    slow = moe_ops.stable_expert_order(ids, 13)
    for a, b in zip(fast, slow):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
